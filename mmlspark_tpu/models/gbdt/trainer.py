"""Histogram-GBDT training engine — the flagship compute path.

This is the TPU-native replacement for everything the reference drives
through LightGBM C++: histogram building, split finding, tree growth and
the distributed histogram reduction
(SURVEY.md §2.7 row 1; lightgbm/.../TrainUtils.scala:98-135 iteration
loop, StreamingPartitionTask.scala data push, NetworkManager ring
allreduce). Design:

  - rows live sharded over the mesh ``dp`` axis; bin boundaries and tree
    state are replicated (the "reference dataset" broadcast analog);
  - per-level histograms are built with one `segment_sum` scatter over
    all rows — when inputs are row-sharded, XLA GSPMD turns the segment
    reduction into per-device partials + an ICI all-reduce, which *is*
    LightGBM's ``data_parallel`` histogram allreduce with no rendezvous;
  - trees grow level-wise over a fixed ``max_depth`` (static shapes for
    XLA), with a traced ``num_leaves`` budget that gates splits by
    within-level gain rank — the budgeted analog of LightGBM's leaf-wise
    growth;
  - the per-iteration loop stays in Python (one compiled ``build_tree``
    reused every iteration), matching the reference's driver-side loop
    shape while keeping all math on device.

GOSS / bagging / feature-fraction / DART semantics follow
params/LightGBMParams.scala; voting/feature parallel variants live in
``mmlspark_tpu.parallel``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.env import (env_flag, env_int, env_override,
                                   env_raw, env_str)
from mmlspark_tpu.core import sanitizer
from mmlspark_tpu.core.faults import fault_point
from mmlspark_tpu.parallel import resilience
from mmlspark_tpu.models.gbdt import metrics as metrics_mod
from mmlspark_tpu.models.gbdt import objectives as obj_mod
from mmlspark_tpu.models.gbdt.booster import BoosterArrays


@dataclass(frozen=True)
class TrainConfig:
    """Static training configuration (hashable: becomes jit static arg).

    Field names mirror the reference's param surface
    (lightgbm/.../params/LightGBMParams.scala:1) in snake_case.
    """

    objective: str = "regression"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = 5            # full-tree layout depth (2^d leaves max)
    max_bin: int = 255
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    boosting_type: str = "gbdt"   # gbdt | rf | dart | goss
    top_rate: float = 0.2         # goss
    other_rate: float = 0.1       # goss
    drop_rate: float = 0.1        # dart
    skip_drop: float = 0.5        # dart
    num_class: int = 1
    sigmoid: float = 1.0
    alpha: float = 0.9            # huber / quantile
    tweedie_variance_power: float = 1.5
    poisson_max_delta_step: float = 0.7
    fair_c: float = 1.0
    early_stopping_round: int = 0
    metric: Optional[str] = None
    eval_at: Any = 5              # NDCG@k position(s): int or list of ints
    # distributed tree learner (LightGBMParams.scala:25-29):
    # serial | data | voting | feature — "data" is the default sharded
    # path (XLA-derived histogram all-reduce); voting/feature use the
    # explicit shard_map builders in parallel_modes.py
    tree_learner: str = "serial"
    top_k: int = 20               # voting_parallel local vote size
    seed: int = 0
    deterministic: bool = True
    boost_from_average: bool = True
    # categorical split handling (params/LightGBMParams.scala categorical
    # group; core/schema/Categoricals.scala): features listed here split
    # by set membership over category bins, not ordered thresholds
    categorical_features: Any = ()
    cat_smooth: float = 10.0      # added to hessian in the sort ratio
    cat_l2: float = 10.0          # extra L2 when evaluating cat splits
    max_cat_threshold: int = 32   # max categories on the scanned side
    max_cat_to_onehot: int = 4    # <=: one-vs-rest instead of sorted scan
    # monotone constraints (LightGBM monotone_constraints, "basic"
    # method): per-feature -1/0/+1; +1 forces predictions non-decreasing
    # in the feature. Direction-violating splits are rejected and child
    # subtrees are clamped to the split midpoint bound.
    monotone_constraints: Any = ()
    # LightGBM path_smooth: child outputs shrink toward the parent's by
    # n/(n+path_smooth); applied at value recording (split selection
    # still uses unsmoothed scores)
    path_smooth: float = 0.0
    # LightGBM max_delta_step: clamp |leaf output| (0 = off)
    max_delta_step: float = 0.0
    # LightGBM pos/neg_bagging_fraction: per-class bagging rates for
    # binary labels (both default 1.0 = plain bagging_fraction)
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    # LightGBM extra_trees: evaluate ONE random threshold per
    # (node, feature) instead of scanning every bin
    extra_trees: bool = False
    # DART extras (BaseTrainParams.scala DartModeParams): cap on trees
    # dropped per iteration (<=0 = unlimited), uniform vs
    # weight-proportional drop selection, and a dedicated drop RNG
    # stream (None = derived from seed)
    max_drop: int = 50
    uniform_drop: bool = False
    drop_seed: Optional[int] = None
    # seed family (LightGBM derives per-purpose streams; defaults match
    # its conventions: bagging 3, feature_fraction 2, extra 6)
    bagging_seed: int = 3
    feature_fraction_seed: int = 2
    extra_seed: int = 6
    # lambdarank (RankerTrainParams maxPosition / labelGain)
    lambdarank_truncation_level: int = 30
    label_gain: Any = ()
    # LightGBM zero_as_missing: zeros are binned as missing (the
    # estimator maps 0.0 -> NaN pre-binning) and trained nodes stamp
    # zero-missing decision bits so raw scoring routes zeros the same
    zero_as_missing: bool = False
    # LightGBM feature_fraction_bynode: re-sample the feature subset at
    # every tree node instead of once per tree
    feature_fraction_by_node: float = 1.0
    # early-stopping improvement tolerance (TrainUtils.scala:143-169:
    # an eval counts as improved iff cur-best > tol for higher-better
    # metrics, cur-best < tol for lower-better)
    improvement_tolerance: float = 0.0
    # LightGBM min_data_per_group: categories below this count are
    # excluded from the sorted categorical scan (one-hot mode keeps
    # its per-bin min_data_in_leaf guard)
    min_data_per_group: int = 100
    # LightGBM min_data_in_bin: consumed by BinMapper at fit time (the
    # trainer itself sees only binned codes); lives here so
    # passThroughArgs can reach it
    min_data_in_bin: int = 3

    def __post_init__(self):
        # eval_at may arrive as a list; the config is used as a cache key
        # for compiled functions, so every field must be hashable.
        # Sequence fields also accept a bare scalar ('label_gain=1' via
        # passThroughArgs, or direct construction — ADVICE r4): wrap it
        # in a 1-tuple here so tuple(cfg.label_gain) consumers never see
        # an opaque TypeError. eval_at stays scalar-or-tuple (a scalar
        # is a documented value for it).
        if isinstance(self.eval_at, list):
            object.__setattr__(self, "eval_at", tuple(self.eval_at))
        if isinstance(self.label_gain, (int, float)):
            object.__setattr__(self, "label_gain",
                               (float(self.label_gain),))
        elif isinstance(self.label_gain, (list, np.ndarray)):
            object.__setattr__(self, "label_gain",
                               tuple(float(g) for g in self.label_gain))
        if isinstance(self.categorical_features, (int, np.integer)):
            object.__setattr__(self, "categorical_features",
                               (int(self.categorical_features),))
        elif isinstance(self.categorical_features, (list, np.ndarray)):
            object.__setattr__(self, "categorical_features",
                               tuple(int(i) for i in self.categorical_features))
        if isinstance(self.monotone_constraints, (int, np.integer)):
            object.__setattr__(self, "monotone_constraints",
                               (int(self.monotone_constraints),))
        elif isinstance(self.monotone_constraints, (list, np.ndarray)):
            object.__setattr__(self, "monotone_constraints",
                               tuple(int(i) for i in self.monotone_constraints))

    @property
    def effective_depth(self) -> int:
        # enough depth for num_leaves leaves, capped by max_depth if set
        need = max(1, math.ceil(math.log2(max(self.num_leaves, 2))))
        if self.max_depth and self.max_depth > 0:
            return min(need, self.max_depth) if self.num_leaves > 0 else self.max_depth
        return need


def _objective_kwargs(cfg: TrainConfig) -> Dict[str, Any]:
    name = cfg.objective
    if name == "binary":
        return {"sigmoid": cfg.sigmoid}
    if name in ("multiclass", "softmax", "multiclassova"):
        return {"num_class": cfg.num_class}
    if name == "huber":
        return {"alpha": cfg.alpha}
    if name == "quantile":
        return {"alpha": cfg.alpha}
    if name == "fair":
        return {"fair_c": cfg.fair_c}
    if name == "tweedie":
        return {"tweedie_variance_power": cfg.tweedie_variance_power}
    if name == "poisson":
        return {"max_delta_step": cfg.poisson_max_delta_step}
    if name == "lambdarank":
        kw: Dict[str, Any] = {
            "sigmoid": cfg.sigmoid,
            "truncation_level": cfg.lambdarank_truncation_level}
        if cfg.label_gain:
            kw["label_gain"] = tuple(cfg.label_gain)
        return kw
    return {}


# ---------------------------------------------------------------------------
# Tree building (device side)
# ---------------------------------------------------------------------------

_WARNED_BAD_FORMULATION = False
_WARNED_SHARD_DOWNGRADE = False
_WARNED_NATIVE_DOWNGRADE = False

_VALID_FORMULATIONS = ("per_feature", "separate", "fused", "onehot",
                       "native")


def native_histogram_available() -> bool:
    """Is the C++ level-histogram kernel loadable (builds lazily)?"""
    from mmlspark_tpu.native import bindings
    return bindings.is_available()


def _native_hist_default_enabled() -> bool:
    """Native kernel as the DEFAULT formulation: CPU backend only (on
    TPU the data never visits the host; under GSPMD the callback is not
    partitionable — callers gate that via ``allow_native``), only when
    the compiled library actually loaded (the numpy fallback is for
    correctness tests, not a default), and — on jax versions where the
    op goes through ``jax.pure_callback`` instead of the raw-callback
    primitive — only when synchronous CPU dispatch is guaranteed
    (pure_callback's impl issues jax dispatches on the callback
    thread, which deadlock against in-flight executions;
    ensure_sync_cpu_dispatch's docstring has the full story).
    MMLSPARK_TPU_NATIVE_HIST=0 is the kill switch back to the XLA
    formulations."""
    if not env_flag("MMLSPARK_TPU_NATIVE_HIST", default=True):
        return False
    if not _raw_callback_needed():
        from mmlspark_tpu.core.jax_compat import ensure_sync_cpu_dispatch
        if not ensure_sync_cpu_dispatch():
            return False
    import jax
    return jax.default_backend() == "cpu" and native_histogram_available()


def resolve_histogram_formulation(b: int, in_shard_map: bool = False,
                                  allow_pallas: bool = True,
                                  allow_native: bool = True,
                                  warn: bool = True) -> str:
    """Single best-available histogram-kernel policy, shared by the
    trainer dispatch, the shard_map builders and bench attribution:

      1. the Pallas TPU kernel when opted in (MMLSPARK_TPU_PALLAS_HIST,
         pending the on-TPU A/B that may make it the TPU default) and
         the caller allows it (single-program or per-shard, <=256 bins);
      2. an explicit MMLSPARK_TPU_HIST_FORMULATION override, with
         constraint downgrades warned once per process so A/B labels
         stay honest: per_feature -> separate inside shard_map (the
         fori_loop carry is not shard_map-safe), native -> XLA default
         under GSPMD auto-partitioning (host callbacks cannot be
         partitioned);
      3. the native cache-blocked C++ kernel on the CPU backend
         (mmls_level_hist_*, via a host callback) — the competitive
         CPU path, also selected per-shard inside the explicit
         shard_map tree learners;
      4. the XLA segment_sum formulations otherwise: per_feature
         outside shard_map, separate under shard_map on TPU (fused does
         not compile there), fused under shard_map on CPU.
    """
    import jax

    from mmlspark_tpu.models.gbdt.hist_pallas import (
        pallas_histogram_enabled,
    )

    global _WARNED_BAD_FORMULATION, _WARNED_SHARD_DOWNGRADE, \
        _WARNED_NATIVE_DOWNGRADE
    if pallas_histogram_enabled() and allow_pallas and b <= 256:
        return "pallas"
    forced = env_str("MMLSPARK_TPU_HIST_FORMULATION", "").strip()
    if forced and forced not in _VALID_FORMULATIONS:
        # a mistyped value silently running the default would mislabel
        # an A/B measurement — warn loudly (once per process)
        if warn and not _WARNED_BAD_FORMULATION:
            _WARNED_BAD_FORMULATION = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_HIST_FORMULATION={forced!r} is not one "
                "of per_feature|separate|fused|onehot|native; using the "
                "default formulation instead", stacklevel=2)
        forced = ""
    if forced == "native" and not allow_native:
        if warn and not _WARNED_NATIVE_DOWNGRADE:
            _WARNED_NATIVE_DOWNGRADE = True
            import warnings
            warnings.warn(
                "MMLSPARK_TPU_HIST_FORMULATION=native cannot run under "
                "GSPMD auto-partitioning (host callbacks are not "
                "partitionable); this builder uses the XLA default — "
                "label A/B measurements accordingly", stacklevel=2)
        forced = ""
    if forced == "per_feature" and in_shard_map:
        # ADVICE r5: this downgrade used to be silent while mistyped
        # values warned loudly — inconsistent for A/B labeling
        if warn and not _WARNED_SHARD_DOWNGRADE:
            _WARNED_SHARD_DOWNGRADE = True
            import warnings
            warnings.warn(
                "MMLSPARK_TPU_HIST_FORMULATION=per_feature is not "
                "shard_map-safe (fori_loop carry); running the "
                "'separate' formulation inside shard_map — label A/B "
                "measurements accordingly", stacklevel=2)
        forced = "separate"
    if forced:
        return forced
    if allow_native and _native_hist_default_enabled():
        return "native"
    if not in_shard_map:
        return "per_feature"
    return "separate" if jax.default_backend() == "tpu" else "fused"


_WARNED_BAD_QUANT = False
_WARNED_QUANT_SHARD = False

_VALID_QUANT = ("off", "q16", "q8")


def resolve_hist_quant(in_shard_map: bool = False,
                       warn: bool = True) -> str:
    """Gradient/hessian histogram-quantization policy
    (MMLSPARK_TPU_HIST_QUANT, default off): per-round grad/hess
    quantized to int16 (q16) or int8 (q8) with a shared power-of-two
    scale, accumulated in int32 with periodic rescale into wide
    accumulators, dequantized only at split-gain evaluation
    (arXiv:2011.02022's quantized training scheme). Follows the same
    bad-value contract as ``resolve_histogram_formulation``: a mistyped
    value warns once and runs unquantized rather than mislabeling a
    measurement. Single-program only — the shard_map builders keep f32
    histograms (the native quant kernel is a host callback and the
    chunked-scan XLA mirror's carry is not shard_map-safe), downgrading
    with a warning so A/B labels stay honest."""
    global _WARNED_BAD_QUANT, _WARNED_QUANT_SHARD
    raw = (env_str("MMLSPARK_TPU_HIST_QUANT", "") or "").strip().lower()
    if not raw:
        return "off"
    if raw not in _VALID_QUANT:
        if warn and not _WARNED_BAD_QUANT:
            _WARNED_BAD_QUANT = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_HIST_QUANT={raw!r} is not one of "
                "off|q16|q8; histograms run unquantized", stacklevel=2)
        return "off"
    if raw != "off" and in_shard_map:
        if warn and not _WARNED_QUANT_SHARD:
            _WARNED_QUANT_SHARD = True
            import warnings
            warnings.warn(
                "MMLSPARK_TPU_HIST_QUANT is single-program only; "
                "sharded (data/voting/feature-parallel) fits build f32 "
                "histograms — label A/B measurements accordingly",
                stacklevel=2)
        return "off"
    return raw


_WARNED_BAD_GROW = False
_WARNED_LEAFWISE_DOWNGRADE = False

_VALID_GROW = ("depthwise", "leafwise")


def resolve_grow_policy(warn: bool = True) -> str:
    """Tree growth policy (MMLSPARK_TPU_GROW_POLICY, default
    depthwise): ``leafwise`` grows each tree by a max-gain priority
    queue capped by ``num_leaves`` (LightGBM's native policy;
    arXiv:1706.08359 §2) over the same level-histogram kernels with
    sibling subtraction; ``depthwise`` is the compiled full-level
    builder with the within-level leaf budget. Bad values warn once
    and run depthwise (core.env contract)."""
    global _WARNED_BAD_GROW
    raw = (env_str("MMLSPARK_TPU_GROW_POLICY", "") or "").strip().lower()
    if not raw:
        return "depthwise"
    if raw not in _VALID_GROW:
        if warn and not _WARNED_BAD_GROW:
            _WARNED_BAD_GROW = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_GROW_POLICY={raw!r} is not one of "
                "depthwise|leafwise; growing depthwise", stacklevel=2)
        return "depthwise"
    return raw


def _leafwise_supported(cfg: "TrainConfig", mesh) -> Optional[str]:
    """None when leaf-wise growth can honor this config, else the
    human-readable reason for the depthwise fallback."""
    if mesh is not None:
        return "a device mesh is attached (leafwise is single-program)"
    if cfg.tree_learner in ("voting", "feature"):
        return f"tree_learner={cfg.tree_learner!r}"
    if cfg.categorical_features:
        return "categorical_features"
    if any(cfg.monotone_constraints or ()):
        return "monotone_constraints"
    if cfg.extra_trees:
        return "extra_trees"
    if cfg.feature_fraction_by_node < 1.0:
        return "feature_fraction_by_node"
    return None


_WARNED_BAD_OOC = False
_WARNED_OOC_DOWNGRADE = False

_VALID_OOC = ("auto", "off", "on")


def resolve_ooc(warn: bool = True) -> str:
    """Out-of-core training policy (MMLSPARK_TPU_OOC, default auto):
    ``auto`` streams a supported fit through the chunked spill plane
    once the row count reaches MMLSPARK_TPU_OOC_ROWS; ``on`` forces it
    (downgrading with one warning when the fit shape is unsupported);
    ``off`` disables. Bad values warn once and run auto (core.env
    contract)."""
    global _WARNED_BAD_OOC
    raw = (env_str("MMLSPARK_TPU_OOC", "") or "").strip().lower()
    if not raw:
        return "auto"
    if raw not in _VALID_OOC:
        if warn and not _WARNED_BAD_OOC:
            _WARNED_BAD_OOC = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_OOC={raw!r} is not one of auto|off|on; "
                "using auto", stacklevel=2)
        return "auto"
    return raw


def resolve_ooc_chunk_rows() -> int:
    return env_int("MMLSPARK_TPU_OOC_CHUNK_ROWS", 262_144, minimum=1024)


def _ooc_supported(cfg: "TrainConfig", mesh, k: int, has_valid: bool,
                   has_custom: bool, has_groups: bool,
                   total_bins: int) -> Optional[str]:
    """None when the chunked out-of-core loop can reproduce this fit
    exactly, else the human-readable reason for staying in-core.

    The supported surface is the serial depthwise numeric plane whose
    histograms merge exactly across row chunks: the native kernel's
    integer-quantized accumulation is row-partition invariant, so a
    chunk-merged histogram is bitwise the in-core one. Anything that
    samples rows/features per iteration, needs resident full-N state
    (validation scoring, lambdarank groups), or runs a different
    builder stays in-core."""
    if mesh is not None:
        return "a device mesh is attached (out-of-core is single-program)"
    if resolve_grow_policy(warn=False) == "leafwise":
        return "leafwise growth"
    if cfg.tree_learner in ("voting", "feature"):
        return f"tree_learner={cfg.tree_learner!r}"
    if cfg.boosting_type != "gbdt":
        return f"boosting_type={cfg.boosting_type!r}"
    if has_custom:
        return "a custom objective"
    if k > 1:
        return "multiclass objectives"
    if cfg.objective == "lambdarank" or has_groups:
        return "lambdarank / grouped fits"
    if has_valid or cfg.early_stopping_round > 0:
        return "validation sets / early stopping"
    if cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0:
        return "bagging"
    if cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0:
        return "pos/neg bagging"
    if cfg.feature_fraction < 1.0 or cfg.feature_fraction_by_node < 1.0:
        return "feature sampling"
    if cfg.extra_trees:
        return "extra_trees"
    if cfg.categorical_features:
        return "categorical_features"
    if any(cfg.monotone_constraints or ()):
        return "monotone_constraints"
    if resolve_histogram_formulation(total_bins, warn=False) != "native":
        return ("the native histogram kernel is unavailable (chunk-exact "
                "merges need its integer accumulation)")
    return None


_WARNED_BAD_SHARD = False
_WARNED_SHARD_DOWNGRADE_DP = False

_VALID_SHARD = ("auto", "off", "on")


def resolve_hist_shard(warn: bool = True) -> str:
    """Raw MMLSPARK_TPU_HIST_SHARD policy value (auto|off|on, default
    auto). ``auto`` turns the sharded reduction on exactly when the fit
    is data-parallel over dp>1 and :func:`_hist_shard_supported` allows
    the config; ``on`` forces it, downgrading with one warning when the
    config cannot honor it; ``off`` keeps the legacy full-psum GSPMD
    path. Bad values warn once and run auto (core.env contract)."""
    global _WARNED_BAD_SHARD
    raw = (env_str("MMLSPARK_TPU_HIST_SHARD", "") or "").strip().lower()
    if not raw:
        return "auto"
    if raw not in _VALID_SHARD:
        if warn and not _WARNED_BAD_SHARD:
            _WARNED_BAD_SHARD = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_HIST_SHARD={raw!r} is not one of "
                "auto|off|on; using auto", stacklevel=2)
        return "auto"
    return raw


def _hist_shard_supported(cfg: "TrainConfig", mesh) -> Optional[str]:
    """None when the reduce-scatter data-parallel builder can honor
    this config bitwise-identically to the full-psum path, else the
    human-readable reason for staying on the GSPMD path."""
    if mesh is None:
        return "no device mesh is attached"
    if cfg.tree_learner in ("voting", "feature"):
        return f"tree_learner={cfg.tree_learner!r}"
    from mmlspark_tpu.parallel.mesh import axis_size
    if axis_size(mesh, "dp") < 2:
        return "dp axis size is 1"
    if cfg.categorical_features:
        return "categorical_features"
    if any(cfg.monotone_constraints or ()):
        return "monotone_constraints"
    if cfg.extra_trees:
        return "extra_trees"
    if cfg.feature_fraction_by_node < 1.0:
        return "feature_fraction_by_node"
    return None


def resolve_hist_shard_mode(cfg: "TrainConfig", mesh,
                            warn: bool = True
                            ) -> Tuple[str, Optional[str]]:
    """(resolved mode, downgrade reason): ``("on", None)`` routes the
    fit through the explicit reduce-scatter shard_map builder,
    ``("off", reason-or-None)`` keeps the full-psum path. A forced
    ``on`` that the config cannot honor warns once (honest A/B
    labeling, as the leafwise/quant downgrades); ``auto`` downgrades
    silently — off is simply its resolution for unsupported fits."""
    global _WARNED_SHARD_DOWNGRADE_DP
    raw = resolve_hist_shard(warn=warn)
    if raw == "off":
        return "off", None
    reason = _hist_shard_supported(cfg, mesh)
    if reason is None:
        return "on", None
    if raw == "on":
        if warn and not _WARNED_SHARD_DOWNGRADE_DP:
            _WARNED_SHARD_DOWNGRADE_DP = True
            import warnings
            warnings.warn(
                "MMLSPARK_TPU_HIST_SHARD=on cannot shard the histogram "
                f"reduction for this fit ({reason}); running the "
                "full-psum path — label A/B measurements accordingly",
                stacklevel=2)
    return "off", reason


_WARNED_ASYNC_CALLBACK = False


def _warn_async_callback_hazard() -> None:
    """A forced ``native`` formulation is honored even when synchronous
    CPU dispatch could not be guaranteed (parity tests run tiny arrays
    and are safe), but at >~1 MB operands the callback WILL deadlock —
    say so once instead of hanging silently."""
    from mmlspark_tpu.core.jax_compat import ensure_sync_cpu_dispatch
    global _WARNED_ASYNC_CALLBACK
    if ensure_sync_cpu_dispatch() or _WARNED_ASYNC_CALLBACK:
        return
    _WARNED_ASYNC_CALLBACK = True
    import warnings
    warnings.warn(
        "the native histogram callback is running under asynchronous "
        "XLA:CPU dispatch (the CPU client was created before "
        "mmlspark_tpu could disable it, or "
        "MMLSPARK_TPU_SYNC_CPU_DISPATCH=0 is set); executions over "
        ">~1 MB operands will deadlock — import mmlspark_tpu before "
        "running any jax computation", stacklevel=2)


_NATIVE_HIST_PRIM = None

# XLA swallows exceptions raised inside the raw emit_python_callback
# host callbacks (the runtime logs them and leaves the result buffer
# uninitialized), so a failing native kernel would otherwise surface
# much later as an anonymous crash on garbage data. The latch records
# the first failure, the callback hands XLA a benign zero histogram,
# and the boosting loops re-raise the latched error — attributed, with
# the original exception chained — at the next per-iteration host sync
# (and once more after the loop, so a failure on the final iteration
# cannot be checkpointed into a poisoned segment).
_CALLBACK_FAILURE: List[BaseException] = []


class CallbackFailed(RuntimeError):
    """A native-histogram host callback raised mid-execution; the fit
    aborts at the next host sync with the original error chained."""


def _latch_callback_failure(e: BaseException) -> None:
    if not _CALLBACK_FAILURE:
        _CALLBACK_FAILURE.append(e)


def _clear_callback_failure() -> None:
    _CALLBACK_FAILURE.clear()


def _check_callback_failure() -> None:
    if _CALLBACK_FAILURE:
        e = _CALLBACK_FAILURE[0]
        _CALLBACK_FAILURE.clear()
        raise CallbackFailed(
            "[native.callback] native histogram host callback failed "
            f"mid-fit ({type(e).__name__}: {e}); aborting before the "
            "zero-histogram fallback tree can be committed") from e


def _native_hist_primitive():
    """Raw-callback primitive for the native histogram on jax 0.4.x.

    ``jax.pure_callback`` is NOT usable for this op there: its
    compiled-mode lowering routes every invocation through
    ``pure_callback_impl``, which ``jax.device_put``s the operands and
    ``np.asarray``s them ON THE CALLBACK THREAD — jax dispatches
    issued while the main thread is blocked inside the very execution
    the callback is serving. On the single-stream XLA:CPU runtime
    that circular wait deadlocks: reproduced with the cached training
    step's second execution at bench shape (2M rows; the first,
    compile-carrying execution survives — the hang is
    scheduling-dependent, which is worse than deterministic).

    ``mlir.emit_python_callback`` — the layer pure_callback itself
    lowers through — hands the callback raw numpy views of the
    runtime buffers instead: no jax ops on the callback thread,
    nothing to deadlock, and none of pure_callback_impl's round-trip
    copies (~2x cheaper per call at 2M rows)."""
    global _NATIVE_HIST_PRIM
    if _NATIVE_HIST_PRIM is not None:
        return _NATIVE_HIST_PRIM
    import jax.numpy as jnp
    from jax._src import core as jcore
    from jax._src.interpreters import mlir as jmlir

    prim = jcore.Primitive("mmlspark_native_level_hist")

    def _run(bn, g, h, lv, lo, width, n_bins):
        # host-callback boundary: an armed delay here simulates a hung
        # native kernel (the failure mode the raw-callback redesign
        # exists to avoid), a corrupt simulates bad kernel output
        try:
            fault_point("native.callback")
            from mmlspark_tpu.native import bindings
            with resilience.boundary("host_callback",
                                     "native.level_histogram"):
                return bindings.level_histogram(bn, g, h, lv, lo, width,
                                                n_bins)
        except BaseException as e:  # XLA would swallow it — latch it
            _latch_callback_failure(e)
            return np.zeros((width, bn.shape[1], n_bins, 3), np.float32)

    def _abstract(binned, grad, hess, live, local, *, width, n_bins):
        return jcore.ShapedArray((width, binned.shape[1], n_bins, 3),
                                 np.float32)

    def _impl(binned, grad, hess, live, local, *, width, n_bins):
        # eager (outside-jit) path
        return jnp.asarray(_run(np.asarray(binned), np.asarray(grad),
                                np.asarray(hess), np.asarray(live),
                                np.asarray(local), width, n_bins))

    def _lowering(ctx, *args, width, n_bins):
        def _cb(bn, g, h, lv, lo):
            return (_run(bn, g, h, lv, lo, width, n_bins),)
        result, _, _ = jmlir.emit_python_callback(
            ctx, _cb, None, list(args), ctx.avals_in, ctx.avals_out,
            has_side_effect=False)
        return result

    prim.def_abstract_eval(_abstract)
    prim.def_impl(_impl)
    jmlir.register_lowering(prim, _lowering)
    _NATIVE_HIST_PRIM = prim
    return prim


def _raw_callback_needed() -> bool:
    """jax 0.4.x needs the raw-callback primitive (see
    ``_native_hist_primitive``); 0.5+ reworked the callback runtime
    and carries the vma-typed avals the pure_callback path declares."""
    import jax
    major, minor = jax.__version__.split(".")[:2]
    return (int(major), int(minor)) < (0, 5)


def _native_level_histogram(binned, grad, hess, live, local, width, f, b):
    """The C++ cache-blocked level-histogram kernel
    (native/data_plane.cpp mmls_level_hist_*) as a host callback: the
    CPU-backend twin of the Pallas kernel's VMEM restructuring. Inside
    jit on the CPU backend the buffers are already host-resident, so
    the callback costs one (width, F, B, 3) result copy. Falls back to
    a numpy bincount implementation when the library isn't built
    (bindings.level_histogram), so the formulation stays selectable in
    compiler-less environments."""
    import jax
    import jax.numpy as jnp

    if _raw_callback_needed():
        return _native_hist_primitive().bind(
            binned, grad, hess, live, local.astype(jnp.int32),
            width=width, n_bins=b)

    # the pure_callback path is only safe under synchronous CPU
    # dispatch (see _native_hist_primitive / ensure_sync_cpu_dispatch)
    _warn_async_callback_hazard()

    def _cb(bn, g, h, lv, lo, _w=width, _b=b):
        try:
            fault_point("native.callback")
            from mmlspark_tpu.native import bindings
            with resilience.boundary("host_callback",
                                     "native.level_histogram"):
                return bindings.level_histogram(
                    np.asarray(bn), np.asarray(g), np.asarray(h),
                    np.asarray(lv), np.asarray(lo), _w, _b)
        except BaseException as e:
            # latch AND re-raise: pure_callback propagates on some jax
            # versions and swallows on others — both end attributed
            _latch_callback_failure(e)
            raise

    # under shard_map the per-shard result varies over whatever mesh
    # axes the inputs vary over; declare the union when this jax
    # exposes vma-typed avals (mirrors hist_pallas's out_shape; on
    # older jax the shard_map builders run with check_vma off instead,
    # see parallel_modes._check_vma)
    from mmlspark_tpu.core.jax_compat import (operand_vma,
                                              shape_dtype_struct)
    out_type = shape_dtype_struct(
        (width, f, b, 3), jnp.float32,
        vma=operand_vma(binned, grad, hess, live, local))
    return jax.pure_callback(_cb, out_type, binned, grad, hess, live,
                             local.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Host-binned registry: the binned matrix is host-resident numpy for the
# whole fit, so the native-histogram callback can read it directly
# instead of receiving it as a traced operand. At bench shape the
# operand marshal (2M x 28 uint8 per level) dominated callback cost;
# the registered-matrix path passes a scalar int32 token instead. The
# token is a TRACED operand (not a jit constant): successive fits reuse
# one compiled step with different tokens, so the compile caches (and
# the sanitizer's recompile budget) see one program, not one per fit.
# ---------------------------------------------------------------------------

_HOST_BINNED_REG: Dict[int, np.ndarray] = {}
_HOST_BINNED_NEXT = [1]


def _register_host_binned(arr: np.ndarray) -> int:
    """Register a host binned matrix for callback-side lookup; returns
    the token to pass as the builder's ``hist_token``. The caller owns
    the lifetime: release after every dispatched step has completed
    (``train`` releases after the final ``block_until_ready``)."""
    tok = _HOST_BINNED_NEXT[0]
    _HOST_BINNED_NEXT[0] += 1
    _HOST_BINNED_REG[tok] = arr
    return tok


def _release_host_binned(tok: int) -> None:
    _HOST_BINNED_REG.pop(tok, None)


def _host_binned_lookup(tok: int) -> np.ndarray:
    try:
        return _HOST_BINNED_REG[tok]
    except KeyError:
        raise RuntimeError(
            f"host-binned token {tok} is not registered — a histogram "
            "callback ran after its train() call released the training "
            "matrix (or a compiled step was invoked outside train)"
        ) from None


_NATIVE_HIST_PRIM_V2 = None


def _native_hist_primitive_v2():
    """Second-generation raw-callback primitive (jax 0.4.x; see
    ``_native_hist_primitive`` for why pure_callback is unusable
    there). Extends v1 with two statics the flagship CPU path needs:

      - ``quant``: "off" | "q16" | "q8" — dispatch to the quantized
        int32-accumulation kernels (mmls_level_hist_q16/_q8), taking
        int grad/hess, a uint8 live gate and the two f32 dequant
        scales as extra scalar operands;
      - ``has_token``: the binned matrix is looked up host-side from
        ``_HOST_BINNED_REG`` by a scalar token operand instead of
        being marshalled through the callback per level.

    v1 stays as-is: it serves the operand-passing formulation the
    shard_map builders and direct ``make_build_tree`` callers use."""
    global _NATIVE_HIST_PRIM_V2
    if _NATIVE_HIST_PRIM_V2 is not None:
        return _NATIVE_HIST_PRIM_V2
    import jax.numpy as jnp
    from jax._src import core as jcore
    from jax._src.interpreters import mlir as jmlir

    prim = jcore.Primitive("mmlspark_native_level_hist_v2")

    def _run(first, g, h, lv, lo, *scales, width, n_bins, num_features,
             quant, has_token):
        try:
            fault_point("native.callback")
            from mmlspark_tpu.native import bindings
            with resilience.boundary("host_callback",
                                     "native.level_histogram"):
                bn = (_host_binned_lookup(int(np.asarray(first)))
                      if has_token else np.asarray(first))
                if quant == "off":
                    return bindings.level_histogram(bn, g, h, lv, lo,
                                                    width, n_bins)
                gsi, hsi = scales
                return bindings.level_histogram_quant(
                    bn, g, h, lv, lo, width, n_bins,
                    float(np.asarray(gsi)), float(np.asarray(hsi)))
        except BaseException as e:  # XLA would swallow it — latch it
            _latch_callback_failure(e)
            return np.zeros((width, num_features, n_bins, 3), np.float32)

    def _abstract(first, g, h, lv, lo, *scales, width, n_bins,
                  num_features, quant, has_token):
        return jcore.ShapedArray((width, num_features, n_bins, 3),
                                 np.float32)

    def _impl(*args, width, n_bins, num_features, quant, has_token):
        host = [np.asarray(a) for a in args]
        return jnp.asarray(_run(*host, width=width, n_bins=n_bins,
                                num_features=num_features, quant=quant,
                                has_token=has_token))

    def _lowering(ctx, *args, width, n_bins, num_features, quant,
                  has_token):
        def _cb(*host_args):
            return (_run(*host_args, width=width, n_bins=n_bins,
                         num_features=num_features, quant=quant,
                         has_token=has_token),)
        result, _, _ = jmlir.emit_python_callback(
            ctx, _cb, None, list(args), ctx.avals_in, ctx.avals_out,
            has_side_effect=False)
        return result

    prim.def_abstract_eval(_abstract)
    prim.def_impl(_impl)
    jmlir.register_lowering(prim, _lowering)
    _NATIVE_HIST_PRIM_V2 = prim
    return prim


def _native_level_histogram_v2(binned, grad, hess, live, local, width,
                               f, b, gscale_inv=None, hscale_inv=None,
                               token=None, quant="off"):
    """Native level histogram through the v2 callback: optional
    registered-matrix token (``binned`` is ignored when set) and
    optional quantized kernels. Output contract matches
    ``_native_level_histogram``: (width, f, b, 3) f32."""
    import jax
    import jax.numpy as jnp

    ops = [token if token is not None else binned,
           grad, hess, live, local.astype(jnp.int32)]
    if quant != "off":
        ops += [gscale_inv, hscale_inv]

    if _raw_callback_needed():
        return _native_hist_primitive_v2().bind(
            *ops, width=width, n_bins=b, num_features=f, quant=quant,
            has_token=token is not None)

    _warn_async_callback_hazard()

    def _cb(*args, _w=width, _b=b, _q=quant, _tok=token is not None):
        try:
            fault_point("native.callback")
            from mmlspark_tpu.native import bindings
            with resilience.boundary("host_callback",
                                     "native.level_histogram"):
                host = [np.asarray(a) for a in args]
                bn = (_host_binned_lookup(int(host[0])) if _tok
                      else host[0])
                if _q == "off":
                    return bindings.level_histogram(bn, *host[1:5],
                                                    _w, _b)
                return bindings.level_histogram_quant(
                    bn, *host[1:5], _w, _b, float(host[5]),
                    float(host[6]))
        except BaseException as e:
            _latch_callback_failure(e)
            raise

    from mmlspark_tpu.core.jax_compat import (operand_vma,
                                              shape_dtype_struct)
    out_type = shape_dtype_struct((width, f, b, 3), jnp.float32,
                                  vma=operand_vma(*ops))
    return jax.pure_callback(_cb, out_type, *ops)


def _pow2_scale(amax, qmax):
    """Power-of-two quantization scale pair (scale, scale_inv) mapping
    |x| <= amax into [-qmax, qmax]. Restricting to powers of two makes
    ``int_value * scale_inv`` an exponent shift — exact in f32 — so
    every backend dequantizing the same int32 totals produces identical
    floats, and the native kernel's int64-exact merge stays bit-stable
    across worker counts."""
    import jax.numpy as jnp
    amax = jnp.maximum(amax.astype(jnp.float32), jnp.float32(1e-30))
    e = jnp.clip(jnp.floor(jnp.log2(jnp.float32(qmax) / amax)),
                 -126.0, 126.0)
    return jnp.exp2(e).astype(jnp.float32), \
        jnp.exp2(-e).astype(jnp.float32)


def _level_histogram_quant(binned, grad_q, hess_q, live, local, width,
                           f, b, gscale_inv, hscale_inv,
                           formulation: str, token=None):
    """Quantized-gradient level histogram: (N,) int16/int8 grad/hess ->
    (width, F, B, 3) f32 dequantized sums. ``live`` keeps the f32 0/1
    row-mask contract of ``_level_histogram`` (converted to the uint8
    gate the native kernel takes). Three formulations mirror the f32
    dispatch:

      - native: mmls_level_hist_q16/_q8 (int32 SIMD tiles, periodic
        flush into per-worker int64 accumulators, single f32 rounding
        at merge — bit-identical to an int64 reference for any worker
        count);
      - pallas: exact dequantize (int * pow2 scale) feeding the
        existing Mosaic kernel — int histogramming inside VMEM is a
        measured-on-TPU follow-up, the mirror exists for parity;
      - XLA: lax.scan over flush-sized row chunks, int32 segment_sum
        per chunk folded into an f32 accumulator — the periodic-rescale
        idiom (graftlint GL007 enforces the int32 widening).
    """
    import jax
    import jax.numpy as jnp

    if formulation == "native":
        return _native_level_histogram_v2(
            binned, grad_q, hess_q, live.astype(jnp.uint8), local,
            width, f, b, gscale_inv=gscale_inv, hscale_inv=hscale_inv,
            token=token,
            quant="q8" if grad_q.dtype == jnp.int8 else "q16")

    if formulation == "pallas":
        from mmlspark_tpu.models.gbdt.hist_pallas import (
            pallas_level_histogram_quant,
        )
        return pallas_level_histogram_quant(
            binned, grad_q, hess_q, live, local, width, f, b,
            gscale_inv, hscale_inv)

    # XLA mirror, one implementation for the segment_sum formulations:
    # int32 products are safe within a chunk (q16: 2^16 rows * 32001 <
    # 2^31; q8: 2^24 rows * 121 < 2^31), and each chunk's exact int32
    # partial is rescaled into the f32 accumulator before the next
    # chunk can overflow.
    n = binned.shape[0]
    if n == 0:
        return jnp.zeros((width, f, b, 3), jnp.float32)
    flush = (1 << 24) if grad_q.dtype == jnp.int8 else (1 << 16)
    chunk = min(n, flush)
    pad = (-n) % chunk
    gate = (live > 0).astype(jnp.int32)
    g32 = grad_q.astype(jnp.int32) * gate
    h32 = hess_q.astype(jnp.int32) * gate
    bc = jnp.pad(binned, ((0, pad), (0, 0))) if pad else binned
    lc = jnp.pad(local, (0, pad)) if pad else local
    gc = jnp.pad(g32, (0, pad)) if pad else g32
    hc = jnp.pad(h32, (0, pad)) if pad else h32
    # padded rows carry a zero gate, so they add nothing to bin 0
    cc = jnp.pad(gate, (0, pad)) if pad else gate

    def chunk_body(acc, xs):
        cb, cl, cg, ch, cn = xs
        base = (cl[:, None] * f + jnp.arange(f, dtype=jnp.int32)[None, :]) * b
        idx = (base + cb.astype(jnp.int32)).reshape(-1)
        data = jnp.stack([
            jnp.broadcast_to(cg[:, None], (chunk, f)).reshape(-1),
            jnp.broadcast_to(ch[:, None], (chunk, f)).reshape(-1),
            jnp.broadcast_to(cn[:, None], (chunk, f)).reshape(-1),
        ], axis=-1)
        part = jax.ops.segment_sum(data, idx,
                                   num_segments=width * f * b)
        return acc + part.astype(jnp.float32), None

    xs = (bc.reshape(-1, chunk, f), lc.reshape(-1, chunk),
          gc.reshape(-1, chunk), hc.reshape(-1, chunk),
          cc.reshape(-1, chunk))
    acc, _ = jax.lax.scan(
        chunk_body, jnp.zeros((width * f * b, 3), jnp.float32), xs)
    scales = jnp.stack([gscale_inv, hscale_inv, jnp.float32(1.0)])
    return (acc * scales[None, :]).reshape(width, f, b, 3)


def _level_histogram(binned, grad, hess, live, local, width, f, b,
                     in_shard_map: bool = False,
                     allow_pallas: bool = True,
                     allow_native: bool = True,
                     formulation: Optional[str] = None):
    """Per-level histogram: (N, F) bins + per-row stats ->
    (width, F, B, 3) grad/hess/count sums.

    ``formulation`` pins a pre-resolved choice (the serial builder
    resolves once per build so its subtraction strategy and histogram
    backend agree); otherwise ``resolve_histogram_formulation`` picks
    the best available kernel for this backend/caller.

    XLA formulation notes (bench_hist.py measures them): a fori_loop of
    per-feature segment_sums avoids materializing the (N*F, 3)
    broadcast and wins ~4x on CPU over the fused scatter. On the first
    real TPU window (2026-07-31, v5e via axon) it won there too: 5.1
    Mrow/s per level vs 1.6 for three separate segment_sums, while the
    fused 3-channel stack failed remote compile (HTTP 500; possibly an
    artifact of the then-buggy bench harness jitting closure-captured
    inputs as constants — the next window's argument-passing benches
    decide) — so per_feature is the XLA default outside shard_map.
    Under shard_map the fori_loop carry would need manual varying-axes
    casts, so those callers use the separate formulation on TPU and
    keep the fused scatter on CPU (the long-tested path). onehot is the
    chunked MXU one-hot contraction, insurance for the Pallas kernel.
    """
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.gbdt.hist_pallas import (
        pallas_level_histogram,
    )

    choice = formulation or resolve_histogram_formulation(
        b, in_shard_map=in_shard_map, allow_pallas=allow_pallas,
        allow_native=allow_native)

    if choice == "pallas":
        # Pallas kernel (hist_pallas.py; bench_hist.py measures it
        # against the XLA formulations below on each backend). Safe
        # per-shard under shard_map too: the kernel only ever sees this
        # program's local rows, and the cross-device psum happens on the
        # returned histogram exactly as for the XLA formulations
        # (tests/gbdt/test_hist_pallas.py::test_pallas_under_shard_map_modes)
        return pallas_level_histogram(binned, grad, hess, live, local,
                                      width, f, b)

    if choice == "native":
        # same per-shard story as pallas: the callback sees only this
        # program's local rows and the psum happens on the result
        return _native_level_histogram(binned, grad, hess, live, local,
                                       width, f, b)

    if choice == "onehot":
        # MXU formulation in pure XLA (insurance for the Pallas kernel,
        # which restructures the same contraction without materializing
        # the one-hots): rows are chunked; per chunk the bin one-hot
        # (chunk, F, B) is contracted against the node-expanded stats
        # (chunk, width*3) in ONE f32 dot — bin accumulation becomes a
        # (F*B, chunk) @ (chunk, width*3) matmul instead of a scatter.
        # Sum order differs from segment_sum, so grad/hess match the
        # other formulations to float tolerance (counts exactly).
        # On-window tuning knobs (no code edits during a TPU window):
        # MMLSPARK_TPU_ONEHOT_CHUNK (rows per dot, default 4096) and
        # MMLSPARK_TPU_ONEHOT_BF16=1 (bf16 operands at 2x MXU rate and
        # half the one-hot bandwidth; f32 accumulation. Counts stay
        # exact — 0/1 and the stat values are bf16-representable only
        # for counts — while grad/hess pick up bf16 input rounding,
        # ~0.4% relative: an accuracy-vs-speed A/B, not a default).
        n = binned.shape[0]
        if n == 0:
            # ADVICE r5: a zero-row level must return a zero histogram,
            # not ZeroDivisionError from chunk == 0 in the padding math
            return jnp.zeros((width, f, b, 3), jnp.float32)
        # bad values warn once and fall back (core.env contract): they
        # must not abort — or silently mislabel — a measurement run
        chunk = env_int("MMLSPARK_TPU_ONEHOT_CHUNK", 4096, minimum=1)
        chunk = min(chunk, n)
        op_dtype = (jnp.bfloat16 if env_flag("MMLSPARK_TPU_ONEHOT_BF16")
                    else jnp.float32)
        pad = (-n) % chunk
        data = jnp.stack([grad * live, hess * live, live], axis=-1)
        bc = jnp.pad(binned, ((0, pad), (0, 0))) if pad else binned
        dc = jnp.pad(data, ((0, pad), (0, 0))) if pad else data
        # padded rows carry all-zero stats, so whichever node their
        # zero-filled local id points at receives nothing
        lc = jnp.pad(local, (0, pad)) if pad else local
        nb = jnp.arange(b, dtype=jnp.int32)
        nw = jnp.arange(width, dtype=jnp.int32)

        def chunk_body(acc, xs):
            cb, cd, cl = xs
            b1h = (cb.astype(jnp.int32)[:, :, None] == nb).astype(
                op_dtype)                               # (chunk, F, B)
            n1h = (cl[:, None] == nw).astype(jnp.float32)
            d2 = (n1h[:, :, None] * cd[:, None, :]).reshape(
                chunk, width * 3).astype(op_dtype)
            part = jnp.einsum("rfb,rk->fbk", b1h, d2,
                              preferred_element_type=jnp.float32)
            return acc + part, None

        xs = (bc.reshape(-1, chunk, f), dc.reshape(-1, chunk, 3),
              lc.reshape(-1, chunk))
        acc0 = jnp.zeros((f, b, width * 3), jnp.float32)
        if in_shard_map:
            # the scan carry must advertise the same varying axes as
            # the per-shard data or check_vma rejects the carry update;
            # folding in a zero-valued data element inherits them
            acc0 = acc0 + 0.0 * dc.reshape(-1)[0]
        acc, _ = jax.lax.scan(chunk_body, acc0, xs)
        return acc.reshape(f, b, width, 3).transpose(2, 0, 1, 3)

    if choice == "per_feature":
        data = jnp.stack([grad * live, hess * live, live], axis=-1)

        def body(fi, acc):
            idx = local * b + binned[:, fi].astype(jnp.int32)
            h = jax.ops.segment_sum(data, idx, num_segments=width * b)
            return acc.at[:, fi].set(h.reshape(width, b, 3))

        return jax.lax.fori_loop(
            0, f, body, jnp.zeros((width, f, b, 3), jnp.float32))

    n = binned.shape[0]
    # flat index = (local * F + f) * B + bin, shared by the two
    # remaining formulations
    base = (local[:, None] * f + jnp.arange(f, dtype=jnp.int32)[None, :]) * b
    idx = (base + binned).reshape(-1)

    # Three separate scalar segment_sums sharing the index vector: the
    # only formulation other than per_feature that compiled on the real
    # TPU stack (1.6 Mrow/s/level), and shard_map-safe (no loop carry).
    if choice == "separate":
        outs = []
        for chan in (grad * live, hess * live, live):
            flat = jnp.broadcast_to(chan[:, None],
                                    (n, f)).reshape(-1)
            outs.append(jax.ops.segment_sum(
                flat, idx, num_segments=width * f * b))
        return jnp.stack(outs, axis=-1).reshape(width, f, b, 3)

    data = jnp.stack([
        jnp.broadcast_to((grad * live)[:, None], (n, f)).reshape(-1),
        jnp.broadcast_to((hess * live)[:, None], (n, f)).reshape(-1),
        jnp.broadcast_to(live[:, None], (n, f)).reshape(-1),
    ], axis=-1)
    hist = jax.ops.segment_sum(data, idx, num_segments=width * f * b)
    return hist.reshape(width, f, b, 3)


def _leaf_objective_impl(g, h, lam1, lam2, extra_l2=0.0):
    """L1-regularized leaf value and its score contribution.

    Module-level so the out-of-core loop (models/gbdt/ooc.py) evaluates
    the exact same expression graph as the compiled builder — a shared
    subgraph is the cheapest bitwise-parity guarantee."""
    import jax.numpy as jnp

    g_adj = jnp.sign(g) * jnp.maximum(jnp.abs(g) - lam1, 0.0)
    denom = h + lam2 + extra_l2 + 1e-30
    value = -g_adj / denom
    score = g_adj * g_adj / denom
    return value, score


def _derive_sibling_hist(hist_small, prev_hist, prev_split, prev_ss):
    """Histogram-subtraction sibling derivation for one level.

    ``hist_small`` (width, F, B, 3) holds real histograms only on each
    split's smaller child; the larger sibling is parent - smaller, and
    slots under non-split parents are zeroed. Shared between the
    compiled builder and the out-of-core loop (bitwise-equal trees need
    identical derive arithmetic, not just identical inputs)."""
    import jax.numpy as jnp

    width = hist_small.shape[0]
    kids = jnp.arange(width, dtype=jnp.int32)
    par_idx = kids // 2
    is_small = (kids % 2) == prev_ss[par_idx]
    sib = hist_small[kids ^ 1]
    parent_h = prev_hist[par_idx]
    hist = jnp.where(
        is_small[:, None, None, None], hist_small,
        jnp.where(prev_split[par_idx][:, None, None, None],
                  parent_h - sib, 0.0))
    # float cancellation can leave tiny negative counts / hessians on
    # the derived side; clamp for the guards
    hist = hist.at[..., 1].max(0.0)
    hist = hist.at[..., 2].max(0.0)
    return hist


def _find_numeric_splits(hist, feat_mask, remaining, parent_value, *, b,
                         lam1, lam2, min_child, min_hess, min_gain,
                         path_smooth, max_delta_step):
    """Numeric-only split finding for one level: ordered cumulative scan,
    leaf-budget ranking, and child values, from the (width, F, B, 3)
    level histogram. ``parent_value`` is the per-slot current node value
    (path smoothing shrinks children toward it).

    Returns (do_split, best_feat, best_bin, left_mask, lval, rval,
    left_stats, right_stats, remaining, smaller_side). This is the
    whole split pipeline for fits with no categorical / monotone /
    extra-trees / per-node-sampling features — the depthwise builder's
    fast path and the out-of-core loop both call it, so the two paths
    build bitwise-identical trees from bitwise-identical histograms.
    """
    import jax.numpy as jnp

    width = hist.shape[0]
    cum = jnp.cumsum(hist, axis=2)              # left stats per bin
    tot = cum[:, :, -1:, :]
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gt, ht, ct = tot[..., 0], tot[..., 1], tot[..., 2]
    gr, hr, cr = gt - gl, ht - hl, ct - cl
    _, score_l = _leaf_objective_impl(gl, hl, lam1, lam2)
    _, score_r = _leaf_objective_impl(gr, hr, lam1, lam2)
    _, score_p = _leaf_objective_impl(gt, ht, lam1, lam2)
    gain = 0.5 * (score_l + score_r - score_p)
    ok = ((cl >= min_child) & (cr >= min_child)
          & (hl >= min_hess) & (hr >= min_hess)
          & (gain > min_gain))
    node_fmask = feat_mask[None, :] > 0
    ok &= node_fmask[:, :, None]
    # last bin can't split (right side empty by construction)
    ok &= jnp.arange(b, dtype=jnp.int32)[None, None, :] < b - 1
    gain = jnp.where(ok, gain, -jnp.inf)

    flat_gain = gain.reshape(width, -1)
    best_fb = jnp.argmax(flat_gain, axis=1)
    best_gain = jnp.take_along_axis(flat_gain, best_fb[:, None], 1)[:, 0]
    best_feat = (best_fb // b).astype(jnp.int32)
    best_bin = (best_fb % b).astype(jnp.int32)

    # leaf budget: within-level gain ranking
    can_split = jnp.isfinite(best_gain)
    order = jnp.argsort(-jnp.where(can_split, best_gain, -jnp.inf))
    rank = jnp.zeros(width, dtype=jnp.int32).at[order].set(
        jnp.arange(width, dtype=jnp.int32))
    do_split = can_split & (rank < remaining)
    remaining = remaining - jnp.sum(do_split.astype(jnp.int32))

    left_mask = jnp.arange(b, dtype=jnp.int32)[None, :] <= best_bin[:, None]
    hist_best = hist[jnp.arange(width, dtype=jnp.int32), best_feat]      # (width, B, 3)
    left_stats = jnp.sum(hist_best * left_mask[..., None], axis=1)
    tot_best = jnp.sum(hist_best, axis=1)
    right_stats = tot_best - left_stats
    lval, _ = _leaf_objective_impl(left_stats[:, 0], left_stats[:, 1],
                                   lam1, lam2)
    rval, _ = _leaf_objective_impl(right_stats[:, 0], right_stats[:, 1],
                                   lam1, lam2)
    if path_smooth > 0:
        # shrink child outputs toward the parent's by n/(n+ps)
        wl = left_stats[:, 2] / (left_stats[:, 2] + path_smooth)
        wr = right_stats[:, 2] / (right_stats[:, 2] + path_smooth)
        lval = lval * wl + parent_value * (1.0 - wl)
        rval = rval * wr + parent_value * (1.0 - wr)
    if max_delta_step > 0:
        lval = jnp.clip(lval, -max_delta_step, max_delta_step)
        rval = jnp.clip(rval, -max_delta_step, max_delta_step)
    smaller_side = jnp.where(
        left_stats[:, 2] <= right_stats[:, 2], 0, 1).astype(jnp.int32)
    return (do_split, best_feat, best_bin, left_mask, lval, rval,
            left_stats, right_stats, remaining, smaller_side)


def make_build_tree(num_features: int, total_bins: int, cfg: TrainConfig,
                    subtract: bool = False, allow_pallas: bool = True,
                    allow_native: bool = True, efb_plan=None):
    """Compile-once tree builder: (binned, grad, hess, valid, feat_mask,
    remaining_leaves) -> (split_feature, threshold_bin, node_value, count,
    decision_type, bin_go_left).

    All shapes static: N rows, F features, B bins, depth D. Returns the
    full-layout arrays described in booster.py; ``bin_go_left`` is a
    (num_slots, B) bool mask — for every internal slot, which bin ids
    route left. Numerical splits fill it with ``bin <= threshold``;
    categorical splits with the chosen category subset, so row routing
    and binned prediction are a single gather regardless of split type.

    ``subtract=True`` enables LightGBM's histogram-subtraction trick
    (feature_histogram.hpp Subtract): below the root, only the SMALLER
    child of each split is histogrammed and the sibling is derived as
    parent - smaller. Histogram row-work per tree drops from N*D to
    ~N*(1 + (D-1)/2). With the native CPU kernel the smaller child is
    selected by MASKING its sibling's rows out of ``live`` — the kernel
    skips masked rows before touching their bin row, so masking is the
    compaction; the XLA formulations instead compact rows to a static
    N/2 buffer via sized nonzero (a scatter over masked-to-zero rows
    would still cost full-N work there). Single-program only: the
    compaction gather is data-dependent, so sharded (GSPMD) builders
    keep the full pass.

    Categorical features (``cfg.categorical_features``) follow LightGBM's
    algorithm (core/schema/Categoricals.scala; LightGBM's
    FindBestThresholdCategorical): bins sorted by grad/(hess+cat_smooth),
    prefix scan with ``lambda_l2 + cat_l2`` regularization and the
    ``max_cat_threshold`` side cap; nodes with few used categories
    (<= max_cat_to_onehot) use one-vs-rest splits instead. The missing
    bin (0) is never placed in a categorical left set — missing routes
    right, matching LightGBM's unseen-category rule.
    """
    import jax
    import jax.numpy as jnp

    depth = cfg.effective_depth
    num_slots = 2 ** (depth + 1) - 1
    lam1, lam2 = cfg.lambda_l1, cfg.lambda_l2
    min_child = float(cfg.min_data_in_leaf)
    min_hess = cfg.min_sum_hessian_in_leaf
    min_gain = cfg.min_gain_to_split
    cat_feats = tuple(cfg.categorical_features or ())
    is_cat_np = np.zeros(num_features, dtype=bool)
    if cat_feats:
        is_cat_np[list(cat_feats)] = True
    has_cat = bool(is_cat_np.any())
    # one resolution per builder: the subtraction strategy (masking vs
    # compaction) and every level's histogram call must agree on the
    # kernel; the compiled-builder cache is keyed on the same env state
    hist_formulation = resolve_histogram_formulation(
        total_bins, in_shard_map=False, allow_pallas=allow_pallas,
        allow_native=allow_native, warn=False)
    masked_subtract = subtract and hist_formulation == "native"
    # quantization and EFB are serial single-program paths; the GSPMD /
    # shard_map builders keep f32 full-feature histograms (allow_native
    # is the single-program proxy the native default shares)
    hist_quant = resolve_hist_quant(warn=False) if allow_native else "off"
    use_efb = efb_plan is not None
    f_hist = efb_plan.n_cols if use_efb else num_features
    if use_efb:
        # static unbundling index maps (ops/efb.py): bundled-histogram
        # slots scatter back to (original feature, original bin), then
        # every bundled member's default bin is reconstructed as the
        # node total minus its present bins (each live row contributes
        # exactly once per bundled column)
        ub_sc_col, ub_sc_bin, ub_sc_feat, ub_sc_obin = \
            efb_plan.scatter_arrays()
        ub_md_feat, ub_md_bin = efb_plan.member_default_arrays()
        ub_pt_col, ub_pt_feat = efb_plan.passthrough_arrays()
    mono_np = np.zeros(num_features, dtype=np.float32)
    if cfg.monotone_constraints:
        if len(cfg.monotone_constraints) > num_features:
            raise ValueError(
                f"monotone_constraints has {len(cfg.monotone_constraints)} "
                f"entries but there are only {num_features} features")
        mono_np[:len(cfg.monotone_constraints)] = cfg.monotone_constraints
    has_mono = bool(mono_np.any())
    # numeric-only fast path: split math delegates to the module-level
    # _find_numeric_splits shared with the out-of-core loop, so both
    # build bitwise-identical trees from identical histograms
    simple_numeric = (not has_cat and not has_mono and not cfg.extra_trees
                      and cfg.feature_fraction_by_node >= 1.0)

    def leaf_objective(g, h, extra_l2=0.0):
        # L1-regularized leaf value and its score contribution
        return _leaf_objective_impl(g, h, lam1, lam2, extra_l2)

    def build_tree(binned, grad, hess, valid, feat_mask, remaining_leaves,
                   key=None, hist_token=None, binned_hist=None):
        """binned (N,F) int32; grad/hess (N,) f32; valid (N,) f32 row mask
        (bagging/GOSS already folded into grad/hess scaling + this mask);
        feat_mask (F,) f32; remaining_leaves traced int; key seeds the
        extra_trees random thresholds (required when extra_trees).

        ``hist_token``: scalar int32 token of a host-registered binned
        matrix (native formulation only) — histogram callbacks read the
        registered matrix instead of marshalling ``binned`` per level.
        ``binned_hist``: the EFB-bundled matrix for non-native
        formulations (when a plan is active and no token is given).
        Both default to None so direct callers keep the old signature;
        routing and split recording always use the original ``binned``."""
        if (cfg.extra_trees or cfg.feature_fraction_by_node < 1.0) \
                and key is None:
            raise ValueError("extra_trees / feature_fraction_by_node "
                             "need an rng key")
        if use_efb and binned_hist is None and hist_token is None:
            raise ValueError("an EFB-planned builder needs binned_hist "
                             "(XLA formulations) or hist_token (native)")
        n = binned.shape[0]
        f = num_features
        b = total_bins
        # matrix histogram calls index: the bundled one under EFB (the
        # token path never reads it — callbacks hold the bundled host
        # matrix — so the original stands in as a placeholder operand)
        hist_mat = binned_hist if (use_efb and binned_hist is not None) \
            else binned
        if hist_quant != "off":
            # per-round shared pow2 scale; invalid rows quantize to 0
            # (valid is folded in) so the kernels' live gate and the
            # quantized values agree
            qdt = jnp.int8 if hist_quant == "q8" else jnp.int16
            qmax = 120.0 if hist_quant == "q8" else 32000.0
            gscale, gscale_inv = _pow2_scale(
                jnp.max(jnp.abs(grad) * valid), qmax)
            hscale, hscale_inv = _pow2_scale(
                jnp.max(jnp.abs(hess) * valid), qmax)
            grad_h = jnp.rint(grad * valid * gscale).astype(qdt)
            hess_h = jnp.rint(hess * valid * hscale).astype(qdt)
        else:
            grad_h, hess_h = grad, hess
            gscale_inv = hscale_inv = None

        def _unbundle_hist(hb, width):
            # (width, f_hist, B, 3) bundled -> (width, F, B, 3) original
            hist = jnp.zeros((width, f, b, 3), hb.dtype)
            if len(ub_pt_col):
                hist = hist.at[:, ub_pt_feat].set(hb[:, ub_pt_col])
            if len(ub_sc_col):
                hist = hist.at[:, ub_sc_feat, ub_sc_obin].set(
                    hb[:, ub_sc_col, ub_sc_bin])
            if len(ub_md_feat):
                # node totals from any one bundled column (every live
                # row lands in exactly one of its bins); a member's
                # default-bin stats are total minus its present bins —
                # exact for counts, f32-rounding for grad/hess
                total = hb[:, 0].sum(axis=1)             # (width, 3)
                present = hist[:, ub_md_feat].sum(axis=2)
                hist = hist.at[:, ub_md_feat, ub_md_bin].set(
                    total[:, None, :] - present)
            return hist

        def _hist(bn_h, g_, h_, lv, lo, width):
            if hist_quant != "off":
                hist = _level_histogram_quant(
                    bn_h, g_, h_, lv, lo, width, f_hist, b,
                    gscale_inv, hscale_inv,
                    formulation=hist_formulation,
                    token=(hist_token
                           if hist_formulation == "native" else None))
            elif hist_token is not None and hist_formulation == "native":
                hist = _native_level_histogram_v2(
                    bn_h, g_, h_, lv, lo, width, f_hist, b,
                    token=hist_token)
            else:
                hist = _level_histogram(
                    bn_h, g_, h_, lv, lo, width, f_hist, b,
                    allow_pallas=allow_pallas,
                    allow_native=allow_native,
                    formulation=hist_formulation)
            return _unbundle_hist(hist, width) if use_efb else hist

        if subtract:
            prev_hist = prev_split = prev_ss = None
            if not masked_subtract:
                # +1 dummy slot: sized-nonzero fill target for the
                # smaller-child compaction gather (over the histogram
                # matrix and the possibly-quantized stats)
                n_half = n // 2 + 1
                binned_pad = jnp.concatenate(
                    [hist_mat, jnp.zeros((1, f_hist), hist_mat.dtype)])
                grad_pad = jnp.concatenate(
                    [grad_h, jnp.zeros(1, grad_h.dtype)])
                hess_pad = jnp.concatenate(
                    [hess_h, jnp.zeros(1, hess_h.dtype)])

        node = jnp.zeros(n, dtype=jnp.int32)       # slot in full layout
        done = jnp.zeros(n, dtype=jnp.bool_)        # settled in a leaf
        split_feature = jnp.full(num_slots, -1, dtype=jnp.int32)
        threshold_bin = jnp.zeros(num_slots, dtype=jnp.int32)
        node_value = jnp.zeros(num_slots, dtype=jnp.float32)
        node_count = jnp.zeros(num_slots, dtype=jnp.float32)
        decision_type = jnp.zeros(num_slots, dtype=jnp.int8)
        bin_go_left = jnp.zeros((num_slots, b), dtype=jnp.bool_)
        is_cat_f = jnp.asarray(is_cat_np)
        mono_f = jnp.asarray(mono_np)
        # per-slot output bounds (monotone "basic" method): children of
        # a constrained split may not cross the split midpoint
        node_lower = jnp.full(num_slots, -jnp.inf, dtype=jnp.float32)
        node_upper = jnp.full(num_slots, jnp.inf, dtype=jnp.float32)
        # root stats: exact-plane fits reduce grad/hess directly; the
        # quantized plane instead derives them from the level-0
        # histogram totals (below, inside the loop) — bin sums of the
        # exact integer accumulation — so a chunk-merged out-of-core
        # histogram reproduces the root bitwise too
        if hist_quant == "off":
            root_g, root_h, root_c = (jnp.sum(grad * valid),
                                      jnp.sum(hess * valid),
                                      jnp.sum(valid))
            rv, _ = leaf_objective(root_g, root_h)
            if cfg.max_delta_step > 0:
                rv = jnp.clip(rv, -cfg.max_delta_step, cfg.max_delta_step)
            node_value = node_value.at[0].set(rv)
            node_count = node_count.at[0].set(root_c)

        remaining = remaining_leaves - 1  # root is one leaf

        for d in range(depth):
            level_start = 2 ** d - 1
            width = 2 ** d
            local = jnp.clip(node - level_start, 0, width - 1)
            live = (~done).astype(grad.dtype) * valid

            # --- histogram --------------------------------------------
            if subtract and d > 0:
                # smaller child only; sibling by subtraction.
                # INVARIANT (ADVICE r4): ``live`` must stay BINARY.
                # prev_ss picks the smaller child by the cover stat
                # (left_stats[:,2] = sum of live), which bounds its ROW
                # count by n//2+1 only because every live row weighs
                # exactly 1 (GOSS folds amplification into grad/hess,
                # bagging masks are 0/1). A fractional row mask would
                # let the weighted-smaller side hold more than n_half
                # rows and the sized nonzero below would silently drop
                # rows, corrupting histograms.
                par_row = local // 2
                side = (local % 2).astype(jnp.int32)
                sel = (live > 0) & (side == prev_ss[par_row])
                if masked_subtract:
                    # native kernel: masked rows are skipped before
                    # their bin row is read, so zeroing ``live`` on the
                    # larger sibling IS the compaction — no gather
                    hist_small = _hist(
                        hist_mat, grad_h, hess_h,
                        live * sel.astype(live.dtype), local, width)
                else:
                    idx = jnp.nonzero(sel, size=n_half, fill_value=n)[0]
                    live_pad = jnp.concatenate(
                        [live, jnp.zeros(1, live.dtype)])
                    local_pad = jnp.concatenate(
                        [local, jnp.zeros(1, local.dtype)])
                    hist_small = _hist(
                        binned_pad[idx], grad_pad[idx], hess_pad[idx],
                        live_pad[idx], local_pad[idx], width)
                hist = _derive_sibling_hist(hist_small, prev_hist,
                                            prev_split, prev_ss)
            else:
                hist = _hist(hist_mat, grad_h, hess_h, live, local,
                             width)
            if subtract:
                prev_hist = hist
            if hist_quant != "off" and d == 0:
                # quantized-plane root stats from the level-0 histogram
                # (any one feature's bins partition the live rows);
                # recorded before split finding so path smoothing sees
                # the root value at this level
                tot0 = jnp.sum(hist[0, 0], axis=0)
                rv0, _ = leaf_objective(tot0[0], tot0[1])
                if cfg.max_delta_step > 0:
                    rv0 = jnp.clip(rv0, -cfg.max_delta_step,
                                   cfg.max_delta_step)
                node_value = node_value.at[0].set(rv0)
                node_count = node_count.at[0].set(tot0[2])

            slots = level_start + jnp.arange(width, dtype=jnp.int32)
            if simple_numeric:
                (do_split, best_feat, best_bin, left_mask, lval, rval,
                 left_stats, right_stats, remaining, small_side) = \
                    _find_numeric_splits(
                        hist, feat_mask, remaining, node_value[slots],
                        b=b, lam1=lam1, lam2=lam2, min_child=min_child,
                        min_hess=min_hess, min_gain=min_gain,
                        path_smooth=cfg.path_smooth,
                        max_delta_step=cfg.max_delta_step)
                split_feature = split_feature.at[slots].set(
                    jnp.where(do_split, best_feat, -1))
                threshold_bin = threshold_bin.at[slots].set(
                    jnp.where(do_split, best_bin, 0))
                num_bits = 6 if cfg.zero_as_missing else 10
                decision_type = decision_type.at[slots].set(
                    jnp.where(do_split, num_bits, 0).astype(jnp.int8))
                bin_go_left = bin_go_left.at[slots].set(
                    left_mask & do_split[:, None])
                lslots, rslots = 2 * slots + 1, 2 * slots + 2
                node_value = node_value.at[lslots].set(
                    jnp.where(do_split, lval, 0.0))
                node_value = node_value.at[rslots].set(
                    jnp.where(do_split, rval, 0.0))
                node_count = node_count.at[lslots].set(
                    jnp.where(do_split, left_stats[:, 2], 0.0))
                node_count = node_count.at[rslots].set(
                    jnp.where(do_split, right_stats[:, 2], 0.0))
                if subtract:
                    prev_split = do_split
                    prev_ss = small_side
                # --- route rows (shared with the general path below) --
                nfeat = best_feat[local]
                nbin = jnp.take_along_axis(binned, nfeat[:, None], 1)[:, 0]
                nsplit = do_split[local]
                go_left = left_mask[local, nbin]
                child = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
                newly_done = ~nsplit & ~done
                node = jnp.where(done | ~nsplit, node, child)
                done = done | newly_done
                continue

            # --- numerical split finding: ordered cumulative scan -------
            cum = jnp.cumsum(hist, axis=2)              # left stats per bin
            tot = cum[:, :, -1:, :]
            gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
            gt, ht, ct = tot[..., 0], tot[..., 1], tot[..., 2]
            gr, hr, cr = gt - gl, ht - hl, ct - cl
            val_l, score_l = leaf_objective(gl, hl)
            val_r, score_r = leaf_objective(gr, hr)
            _, score_p = leaf_objective(gt, ht)
            gain = 0.5 * (score_l + score_r - score_p)
            ok = ((cl >= min_child) & (cr >= min_child)
                  & (hl >= min_hess) & (hr >= min_hess)
                  & (gain > min_gain))
            # per-tree feature mask, optionally re-sampled per node
            # (LightGBM feature_fraction_bynode)
            node_fmask = feat_mask[None, :] > 0         # (1|width, F)
            if cfg.feature_fraction_by_node < 1.0:
                # sample per node from the TREE's feature subset (as
                # LightGBM feature_fraction_bynode composes with
                # feature_fraction), never leaving a node featureless
                avail = jnp.sum(feat_mask > 0)
                keep_n = jnp.maximum(1, jnp.round(
                    avail * cfg.feature_fraction_by_node)).astype(jnp.int32)
                kn = jax.random.fold_in(jax.random.fold_in(key, 101), d)
                draw = jax.random.uniform(kn, (width, num_features))
                draw = jnp.where(feat_mask[None, :] > 0, draw, -1.0)
                sortd = jnp.sort(draw, axis=1)[:, ::-1]  # descending
                kth = jnp.take_along_axis(
                    sortd, jnp.broadcast_to(keep_n - 1, (width,))[:, None],
                    axis=1)
                node_fmask = node_fmask & (draw >= kth)
            ok &= node_fmask[:, :, None]
            # last bin can't split (right side empty by construction)
            ok &= jnp.arange(b, dtype=jnp.int32)[None, None, :] < b - 1
            if has_mono:
                # reject splits whose child values violate the feature's
                # monotone direction (LightGBM "basic" rejection)
                ok &= mono_f[None, :, None] * (val_r - val_l) >= 0
            if cfg.extra_trees:
                # one random candidate threshold per (node, feature)
                kd = jax.random.fold_in(key, d)
                rand_bin = jax.random.randint(kd, (width, f), 0, b - 1)
                ok &= jnp.arange(b, dtype=jnp.int32)[None, None, :] == rand_bin[..., None]
            gain = jnp.where(ok, gain, -jnp.inf)

            if has_cat:
                # --- categorical split finding ----------------------
                g_b, h_b, c_b = hist[..., 0], hist[..., 1], hist[..., 2]
                not_missing = jnp.arange(b, dtype=jnp.int32)[None, None, :] > 0
                used = (c_b > 0) & not_missing
                # LightGBM min_data_per_group: the sorted scan only
                # considers categories with enough rows (filtered ones
                # route right); one-hot mode keeps the plain used set
                used_sorted = used & (
                    c_b >= float(max(cfg.min_data_per_group, 1)))
                ratio = jnp.where(used_sorted,
                                  g_b / (h_b + cfg.cat_smooth), jnp.inf)
                sort_idx = jnp.argsort(ratio, axis=2)   # unused sort last
                shist = jnp.take_along_axis(
                    hist, sort_idx[..., None], axis=2)
                scum = jnp.cumsum(shist, axis=2)
                num_used = jnp.sum(used, axis=2)        # (width, F)
                num_sorted = jnp.sum(used_sorted, axis=2)
                gl_c, hl_c, cl_c = scum[..., 0], scum[..., 1], scum[..., 2]
                gr_c, hr_c = gt - gl_c, ht - hl_c
                cr_c = ct - cl_c
                _, cscore_l = leaf_objective(gl_c, hl_c, cfg.cat_l2)
                _, cscore_r = leaf_objective(gr_c, hr_c, cfg.cat_l2)
                _, cscore_p = leaf_objective(gt, ht, cfg.cat_l2)
                cgain = 0.5 * (cscore_l + cscore_r - cscore_p)
                pos1 = jnp.arange(1, b + 1, dtype=jnp.int32)[None, None, :]  # left-set size
                side = jnp.minimum(pos1, num_sorted[..., None] - pos1)
                cok = ((pos1 < num_sorted[..., None])
                       & (side <= cfg.max_cat_threshold)
                       & (cl_c >= min_child) & (cr_c >= min_child)
                       & (hl_c >= min_hess) & (hr_c >= min_hess)
                       & (cgain > min_gain))
                cgain = jnp.where(cok, cgain, -jnp.inf)
                # one-vs-rest for low-cardinality nodes (indexed by the
                # actual bin id, not a sort position)
                gr_o, hr_o, cr_o = gt - g_b, ht - h_b, ct - c_b
                _, oscore_l = leaf_objective(g_b, h_b, cfg.cat_l2)
                _, oscore_r = leaf_objective(gr_o, hr_o, cfg.cat_l2)
                ogain = 0.5 * (oscore_l + oscore_r - cscore_p)
                ook = (used & (c_b >= min_child) & (cr_o >= min_child)
                       & (h_b >= min_hess) & (hr_o >= min_hess)
                       & (ogain > min_gain) & (num_used[..., None] > 1))
                ogain = jnp.where(ook, ogain, -jnp.inf)
                onehot = (num_used <= cfg.max_cat_to_onehot)[..., None]
                cat_gain = jnp.where(onehot, ogain, cgain)
                cat_gain = jnp.where(node_fmask[:, :, None],
                                     cat_gain, -jnp.inf)
                gain = jnp.where(is_cat_f[None, :, None], cat_gain, gain)

            flat_gain = gain.reshape(width, f * b)
            best_fb = jnp.argmax(flat_gain, axis=1)
            best_gain = jnp.take_along_axis(flat_gain, best_fb[:, None], 1)[:, 0]
            best_feat = (best_fb // b).astype(jnp.int32)
            best_bin = (best_fb % b).astype(jnp.int32)

            # --- leaf budget: within-level gain ranking ------------------
            can_split = jnp.isfinite(best_gain)
            order = jnp.argsort(-jnp.where(can_split, best_gain, -jnp.inf))
            rank = jnp.zeros(width, dtype=jnp.int32).at[order].set(
                jnp.arange(width, dtype=jnp.int32))
            do_split = can_split & (rank < remaining)
            remaining = remaining + 0 if width == 0 else (
                remaining - jnp.sum(do_split.astype(jnp.int32)))

            # --- per-node left-bin mask for the chosen split -------------
            sel = jnp.arange(width, dtype=jnp.int32)
            mask_num = jnp.arange(b, dtype=jnp.int32)[None, :] <= best_bin[:, None]
            if has_cat:
                chosen_cat = is_cat_f[best_feat] & do_split
                s_idx = sort_idx[sel, best_feat]        # (width, B)
                # rank of bin id in sorted order = inverse permutation
                bin_rank = jnp.argsort(s_idx, axis=1)
                used_sel = used_sorted[sel, best_feat]
                onehot_sel = num_used[sel, best_feat] <= cfg.max_cat_to_onehot
                mask_prefix = (bin_rank <= best_bin[:, None]) & used_sel
                mask_onehot = jnp.arange(b, dtype=jnp.int32)[None, :] == best_bin[:, None]
                mask_cat = jnp.where(onehot_sel[:, None], mask_onehot,
                                     mask_prefix)
                left_mask = jnp.where(chosen_cat[:, None], mask_cat, mask_num)
            else:
                chosen_cat = jnp.zeros(width, dtype=jnp.bool_)
                left_mask = mask_num

            # --- record splits & child stats -----------------------------
            split_feature = split_feature.at[slots].set(
                jnp.where(do_split, best_feat, -1))
            threshold_bin = threshold_bin.at[slots].set(
                jnp.where(do_split, best_bin, 0))
            # numerical splits carry default-left + NaN-missing bits
            # (2 | 8 = 10): training routes the missing bin left, and
            # loaded models reproduce that routing from the bits
            num_bits = 6 if cfg.zero_as_missing else 10
            decision_type = decision_type.at[slots].set(
                jnp.where(do_split,
                          jnp.where(chosen_cat, 1, num_bits),
                          0).astype(jnp.int8))
            bin_go_left = bin_go_left.at[slots].set(
                left_mask & do_split[:, None])

            hist_best = hist[sel, best_feat]            # (width, B, 3)
            left_stats = jnp.sum(hist_best * left_mask[..., None], axis=1)
            tot_best = jnp.sum(hist_best, axis=1)
            right_stats = tot_best - left_stats
            lx2 = jnp.where(chosen_cat, cfg.cat_l2, 0.0)
            lval, _ = leaf_objective(left_stats[:, 0], left_stats[:, 1], lx2)
            rval, _ = leaf_objective(right_stats[:, 0], right_stats[:, 1], lx2)
            lslots, rslots = 2 * slots + 1, 2 * slots + 2
            if cfg.path_smooth > 0:
                # shrink child outputs toward the parent's by n/(n+ps)
                pv = node_value[slots]
                wl = left_stats[:, 2] / (left_stats[:, 2] + cfg.path_smooth)
                wr = right_stats[:, 2] / (right_stats[:, 2] + cfg.path_smooth)
                lval = lval * wl + pv * (1.0 - wl)
                rval = rval * wr + pv * (1.0 - wr)
            if cfg.max_delta_step > 0:
                lval = jnp.clip(lval, -cfg.max_delta_step,
                                cfg.max_delta_step)
                rval = jnp.clip(rval, -cfg.max_delta_step,
                                cfg.max_delta_step)
            if has_mono:
                # clamp child outputs into the parent's bounds, then
                # tighten the children's bounds at the split midpoint
                # when this split's feature is constrained
                p_lo, p_hi = node_lower[slots], node_upper[slots]
                lval = jnp.clip(lval, p_lo, p_hi)
                rval = jnp.clip(rval, p_lo, p_hi)
                c_mono = mono_f[best_feat] * (~chosen_cat)
                mid = (lval + rval) / 2.0
                l_hi = jnp.where(c_mono > 0, jnp.minimum(p_hi, mid), p_hi)
                r_lo = jnp.where(c_mono > 0, jnp.maximum(p_lo, mid), p_lo)
                l_lo = jnp.where(c_mono < 0, jnp.maximum(p_lo, mid), p_lo)
                r_hi = jnp.where(c_mono < 0, jnp.minimum(p_hi, mid), p_hi)
                node_lower = node_lower.at[lslots].set(
                    jnp.where(do_split, l_lo, p_lo))
                node_upper = node_upper.at[lslots].set(
                    jnp.where(do_split, l_hi, p_hi))
                node_lower = node_lower.at[rslots].set(
                    jnp.where(do_split, r_lo, p_lo))
                node_upper = node_upper.at[rslots].set(
                    jnp.where(do_split, r_hi, p_hi))
            node_value = node_value.at[lslots].set(
                jnp.where(do_split, lval, 0.0))
            node_value = node_value.at[rslots].set(
                jnp.where(do_split, rval, 0.0))
            node_count = node_count.at[lslots].set(
                jnp.where(do_split, left_stats[:, 2], 0.0))
            node_count = node_count.at[rslots].set(
                jnp.where(do_split, right_stats[:, 2], 0.0))

            if subtract:
                prev_split = do_split
                prev_ss = jnp.where(
                    left_stats[:, 2] <= right_stats[:, 2], 0, 1
                ).astype(jnp.int32)

            # --- route rows ---------------------------------------------
            nfeat = best_feat[local]
            nbin = jnp.take_along_axis(binned, nfeat[:, None], 1)[:, 0]
            nsplit = do_split[local]
            go_left = left_mask[local, nbin]
            child = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
            newly_done = ~nsplit & ~done
            node = jnp.where(done | ~nsplit, node, child)
            done = done | newly_done

        return (split_feature, threshold_bin, node_value, node_count,
                decision_type, bin_go_left)

    return build_tree


# ---------------------------------------------------------------------------
# Compiled-function caches (cross-call reuse)
# ---------------------------------------------------------------------------
#
# ``train`` used to build fresh closures (and therefore fresh jit caches)
# on every call, so every ``fit`` recompiled the tree builder; and the
# boosting loop dispatched ~30 eager ops + a blocking ``float()`` metric
# sync per iteration. On a remote-attached TPU each sync is a full
# round trip, which dominated wall clock (the histogram math itself is
# sub-millisecond). The redesign below:
#
#   - caches compiled builders/fused-steps at module level, keyed by the
#     (hashable) TrainConfig + shapes-independent statics;
#   - fuses each boosting iteration into ONE jitted step dispatched
#     asynchronously (no host syncs inside the loop), with per-iteration
#     metrics computed on device and synced in blocks;
#   - keeps a Python-loop fallback only for DART, whose dropped-tree
#     bookkeeping is dynamic across iterations.

_CACHE_LIMIT = 64  # crude eviction bound: sweeps over many configs


def _cache_put(cache, key, factory):
    if key not in cache:
        if len(cache) >= _CACHE_LIMIT:
            cache.clear()  # drop all compiled fns; next calls recompile
        sanitizer.count_recompile(repr(key))
        cache[key] = factory()
    return cache[key]


_CHUNK_CACHE: Dict[Any, Callable] = {}
_BUILDER_CACHE: Dict[Any, Callable] = {}
_PREDICT_CACHE: Dict[int, Callable] = {}


def _make_predict_tree(depth: int) -> Callable:
    """(sf, bin_go_left, nv, binned) -> (N,) leaf values. Routing is one
    gather into the per-slot left-bin mask, uniform across numerical and
    categorical splits."""
    import jax
    import jax.numpy as jnp

    def predict_tree_binned(sf, bgl, nv, bd):
        nodev = jnp.zeros(bd.shape[0], dtype=jnp.int32)
        for _ in range(depth):
            feat = sf[nodev]
            is_leaf = feat < 0
            fb = jnp.take_along_axis(bd, jnp.maximum(feat, 0)[:, None], 1)[:, 0]
            child = jnp.where(bgl[nodev, fb], 2 * nodev + 1, 2 * nodev + 2)
            nodev = jnp.where(is_leaf, nodev, child)
        return nv[nodev]

    return predict_tree_binned


def _get_predict_tree(depth: int) -> Callable:
    import jax
    return _cache_put(_PREDICT_CACHE, depth,
                      lambda: jax.jit(_make_predict_tree(depth)))


def _loop_only_normalized(cfg: TrainConfig) -> TrainConfig:
    """Zero out fields the compiled step/builder never reads (they only
    steer the host loop, or are passed in as traced data), so sweeps
    over them reuse one compiled executable."""
    return replace(cfg, num_iterations=0, early_stopping_round=0, seed=0,
                   learning_rate=0.1)


def _resolve_mode(cfg: TrainConfig, mesh) -> str:
    """Distributed tree-learner mode: explicit shard_map builders exist
    for voting/feature (selected by ``tree_learner``) and for the
    data-parallel reduce-scatter path (``data_sharded``, selected by
    MMLSPARK_TPU_HIST_SHARD when the config supports it); everything
    else is the serial builder (which GSPMD data-parallelizes when
    inputs are row-sharded, with a full-histogram allreduce)."""
    if cfg.tree_learner in ("voting", "feature") and mesh is not None:
        return cfg.tree_learner
    if mesh is not None and resolve_hist_shard_mode(
            cfg, mesh, warn=False)[0] == "on":
        return "data_sharded"
    return "serial"


def _with_bin_mask(fn, total_bins):
    """Adapt a 4-tuple (numerical-only) builder to the 6-tuple contract:
    synthesize decision_type=0 and the ordered ``bin <= threshold`` left
    mask from the recorded thresholds."""
    import jax.numpy as jnp

    def wrapped(*args):
        sf, tb, nv, cnt = fn(*args)
        bins = jnp.arange(total_bins, dtype=jnp.int32)
        bgl = (bins[None, :] <= tb[:, None]) & (sf >= 0)[:, None]
        return sf, tb, nv, cnt, jnp.zeros(sf.shape[0], jnp.int8), bgl

    return wrapped


def _get_builder(num_f: int, total_bins: int, cfg: TrainConfig, mode: str,
                 mesh, efb_plan=None) -> Callable:
    import jax

    cfg = _loop_only_normalized(cfg)

    def build():
        if mode == "voting":
            from mmlspark_tpu.models.gbdt.parallel_modes import (
                make_build_tree_voting)
            fn = _with_bin_mask(
                make_build_tree_voting(num_f, total_bins, cfg, mesh),
                total_bins)
        elif mode == "feature":
            from mmlspark_tpu.models.gbdt.parallel_modes import (
                make_build_tree_feature_parallel)
            fn = _with_bin_mask(
                make_build_tree_feature_parallel(num_f, total_bins, cfg,
                                                 mesh),
                total_bins)
        elif mode == "data_sharded":
            from mmlspark_tpu.models.gbdt.parallel_modes import (
                make_build_tree_data_parallel)
            fn = _with_bin_mask(
                make_build_tree_data_parallel(num_f, total_bins, cfg,
                                              mesh),
                total_bins)
        else:
            # serial builder under a mesh = GSPMD auto-partitioning,
            # which can partition neither Mosaic kernels ("Please wrap
            # the call in a shard_map") nor host callbacks — the Pallas
            # and native histograms are only selectable single-program
            # here; the distributed modes above run them per-shard
            # inside their explicit shard_maps
            fn = make_build_tree(num_f, total_bins, cfg,
                                 subtract=subtract,
                                 allow_pallas=mesh is None,
                                 allow_native=mesh is None,
                                 efb_plan=efb_plan)
        return jax.jit(fn)

    if mode in ("voting", "feature") and cfg.categorical_features:
        raise NotImplementedError(
            "categorical splits are implemented for the serial/data "
            "tree learners; voting/feature parallel modes treat all "
            "features as numerical — drop categorical_features or use "
            "tree_learner='data'")
    if mode in ("voting", "feature") and any(cfg.monotone_constraints or ()):
        raise NotImplementedError(
            "monotone constraints are implemented for the serial/data "
            "tree learners; voting/feature parallel modes would silently "
            "violate them — use tree_learner='data'")
    if mode in ("voting", "feature") and cfg.extra_trees:
        raise NotImplementedError(
            "extra_trees is implemented for the serial/data tree "
            "learners — use tree_learner='data'")
    from mmlspark_tpu.models.gbdt.hist_pallas import (
        pallas_histogram_enabled,
    )
    subtract = resolve_subtract(mode, total_bins, mesh)
    # the histogram backend is chosen at trace time, so it must key the
    # compiled-builder cache or flipping env flags is silently ignored;
    # an EFB plan bakes static index maps into the trace, so its
    # fingerprint keys the cache the same way
    return _cache_put(
        _BUILDER_CACHE,
        (num_f, total_bins, cfg, mode, mesh, pallas_histogram_enabled(),
         subtract, _hist_env_key(),
         efb_plan.cache_key if efb_plan is not None else None),
        build)


def resolve_subtract(mode: str, total_bins: int, mesh=None) -> bool:
    """Histogram-subtraction default policy (LightGBM's sibling trick),
    shared by the builder cache and bench attribution.

    MMLSPARK_TPU_HIST_SUB=1/0 forces it on/off. Unset, subtraction is
    ON exactly when the serial single-program builder's histogram
    resolves to the native CPU kernel, whose masked smaller-child pass
    skips rows instead of compacting them (parity pinned by
    tests/gbdt/test_hist_native.py; 2.0x fit throughput at bench shape
    vs the full pass). It stays OFF elsewhere: the XLA compaction
    gather measured slower than the full pass on CPU (1.287 vs 1.548
    Mrow-trees/s, ROUND4_NOTES.md), and the pallas kernel's cost is
    row-proportional but unmeasured on real hardware — re-measure
    before defaulting there. Sharded modes never subtract (the
    compaction is data-dependent)."""
    if mode != "serial":
        return False
    raw = env_str("MMLSPARK_TPU_HIST_SUB", "").strip()
    if raw:
        return env_flag("MMLSPARK_TPU_HIST_SUB")
    return resolve_histogram_formulation(
        total_bins, in_shard_map=False, allow_pallas=mesh is None,
        allow_native=mesh is None, warn=False) == "native"


def _hist_env_key() -> tuple:
    """Trace-time histogram-formulation env state; every compiled-step/
    builder cache key must include it or flipping the env vars between
    fits in one process is silently ignored (review catch: the
    onehot-under-shard_map parity test compared a cached default step
    against itself)."""
    from mmlspark_tpu.core.jax_compat import ensure_sync_cpu_dispatch
    # the sync-dispatch guarantee only gates the pure_callback path
    # (jax >= 0.5); on 0.4.x the raw-callback primitive is used and
    # probing the guard here would needlessly flip the global flag
    sync_state = (True if _raw_callback_needed()
                  else ensure_sync_cpu_dispatch())
    return (env_str("MMLSPARK_TPU_HIST_FORMULATION", "").strip(),
            env_str("MMLSPARK_TPU_ONEHOT_CHUNK", "").strip(),
            env_flag("MMLSPARK_TPU_ONEHOT_BF16"),
            env_str("MMLSPARK_TPU_HIST_SUB", "").strip(),
            env_str("MMLSPARK_TPU_NATIVE_HIST", "").strip(),
            env_str("MMLSPARK_TPU_HIST_QUANT", "").strip(),
            env_str("MMLSPARK_TPU_HIST_SHARD", "").strip(),
            native_histogram_available(),
            sync_state)


def _resolve_metrics(cfg: TrainConfig):
    """(metric_name, [(label, fn)], higher_better, metric_kwargs)."""
    metric_name = cfg.metric or metrics_mod.default_metric(cfg.objective)
    if metric_name == "ndcg":
        positions = cfg.eval_at if isinstance(cfg.eval_at, (list, tuple)) \
            else [cfg.eval_at]
        lg = tuple(cfg.label_gain or ()) or None
        metric_list = [(f"ndcg@{p}",
                        metrics_mod.ndcg_at(int(p), label_gain=lg))
                       for p in positions]
        higher_better = True
    else:
        metric_fn, higher_better = metrics_mod.METRICS[metric_name]
        metric_list = [(metric_name, metric_fn)]
    # evaluate with the same objective params we train with
    # (TrainUtils.scala evals via the booster's own config): quantile's
    # pinball alpha must match cfg.alpha, not the metric default
    metric_kwargs = {"alpha": cfg.alpha} if metric_name == "quantile" else {}
    return metric_name, metric_list, higher_better, metric_kwargs


# ---------------------------------------------------------------------------
# Fused scan path (gbdt / goss / rf)
# ---------------------------------------------------------------------------

def _make_step_fn(num_f: int, total_bins: int, cfg: TrainConfig, k: int,
                  n_valid: int, mode: str, mesh, efb_plan=None):
    """One jitted function running ONE fused boosting iteration on device:
    gradients → tree build → raw/valid-raw updates → metric vector.

    ``step(data, carry, it)`` takes the global iteration number as a
    traced scalar (so bagging refresh schedules and RNG folding don't
    recompile per iteration). Carry: (raw, valid raws, bag mask). The
    host loop dispatches steps asynchronously and never syncs inside the
    loop except for (block-wise) early-stopping checks.

    A ``lax.scan`` over iterations would be the obvious alternative, but
    the TPU backend compiles scan-of-scatter bodies pathologically
    slowly (minutes for a 20-iteration scan at depth 6); a single-step
    jit compiles in seconds and async dispatch hides the per-step
    launch cost.
    """
    import jax
    import jax.numpy as jnp

    depth = cfg.effective_depth
    build_tree = _get_builder(num_f, total_bins, cfg, mode, mesh,
                              efb_plan=efb_plan)
    predict_tree = _make_predict_tree(depth)
    objective_fn = obj_mod.get_objective(cfg.objective)
    obj_kwargs = _objective_kwargs(cfg)
    metric_name, metric_list, _, metric_kwargs = _resolve_metrics(cfg)
    is_rf = cfg.boosting_type == "rf"
    is_goss = cfg.boosting_type == "goss"
    nl = cfg.num_leaves if cfg.num_leaves > 0 else 2 ** depth
    frac = cfg.bagging_fraction
    freq = cfg.bagging_freq
    pos_neg = (cfg.pos_bagging_fraction < 1.0
               or cfg.neg_bagging_fraction < 1.0)
    bag_active = (freq > 0 and (frac < 1.0 or pos_neg)) or is_rf
    rf_frac = frac if frac < 1.0 else 0.632

    def step(data, carry, it):
        binned, labels = data["binned"], data["labels"]
        weights, groups = data["weights"], data["groups"]
        base = data["base"]
        # seed key and learning rate ride in as traced data so sweeps
        # over them don't recompile the step
        base_key = data["key"]
        shrink = 1.0 if is_rf else data["lr"]
        n = labels.shape[0]
        rv = data["row_valid"]
        raw, vraws = carry
        # ----- sampling masks (device RNG, deterministic by seed) ----
        if bag_active:
            # key by the last refresh iteration rather than carrying the
            # mask: iterations within a bagging period draw the same
            # mask, and a resumed segment (iteration_offset) reproduces
            # it exactly
            if freq > 0:
                ref_it = it - (it % freq)
            else:
                ref_it = 0  # rf with no freq: one fixed bag
            kbag = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(base_key, 1), cfg.bagging_seed),
                ref_it)
            draw = jax.random.uniform(kbag, (n,))
            if pos_neg and not is_rf:
                # per-class rates (LightGBM pos/neg_bagging_fraction)
                thr_vec = jnp.where(labels > 0,
                                    cfg.pos_bagging_fraction,
                                    cfg.neg_bagging_fraction)
                sample_mask = (draw < thr_vec).astype(jnp.float32) * rv
            else:
                use_frac = rf_frac if is_rf else frac
                sample_mask = (draw < use_frac).astype(jnp.float32) * rv
        else:
            sample_mask = rv
        if cfg.feature_fraction < 1.0:
            keep = max(1, int(round(num_f * cfg.feature_fraction)))
            kf = jax.random.fold_in(jax.random.fold_in(
                jax.random.fold_in(base_key, 2),
                cfg.feature_fraction_seed), it)
            perm = jax.random.permutation(kf, num_f)
            feat_mask = jnp.zeros(num_f, jnp.float32).at[perm[:keep]].set(1.0)
        else:
            feat_mask = jnp.ones(num_f, jnp.float32)

        # ----- gradients --------------------------------------------
        score_in = raw if not is_rf else jnp.full_like(raw, base)
        okw = dict(obj_kwargs)
        if cfg.objective == "lambdarank":
            okw["group_ids"] = groups
            if data.get("group_layout") is not None:
                okw["group_layout"] = data["group_layout"]
        g, h = objective_fn(score_in, labels, weights, **okw)
        if mode == "data_sharded" and mesh is not None:
            # pin the per-round grad/hess recompute to the dp slice
            # owning the rows — the sharded histogram builder consumes
            # them shard-local, so nothing may force a gather here
            from mmlspark_tpu.parallel.mesh import row_sharded
            g = jax.lax.with_sharding_constraint(
                g, row_sharded(mesh, g.ndim))
            h = jax.lax.with_sharding_constraint(
                h, row_sharded(mesh, h.ndim))

        if is_goss:
            absg = jnp.abs(g) if k == 1 else jnp.sum(jnp.abs(g), axis=1)
            # padded rows are excluded from the gradient quantile
            thr = jnp.nanquantile(jnp.where(rv > 0, absg, jnp.nan),
                                  1.0 - cfg.top_rate)
            big = absg >= thr
            kg = jax.random.fold_in(jax.random.fold_in(base_key, 3), it)
            small_keep = jax.random.uniform(kg, absg.shape) < (
                cfg.other_rate / max(1.0 - cfg.top_rate, 1e-12))
            amplify = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-12)
            mult = jnp.where(big, 1.0, jnp.where(small_keep, amplify, 0.0))
            sample_mask = sample_mask * (mult > 0)
            gm = mult if k == 1 else mult[:, None]
            g, h = g * gm, h * gm

        # ----- one tree per class, raw updates ----------------------
        sfs, tbs, nvs, cnts, dts, bgls = [], [], [], [], [], []
        new_vraws = list(vraws)
        tkw = {}
        if data.get("hist_token") is not None:
            tkw["hist_token"] = data["hist_token"]
        if data.get("binned_hist") is not None:
            tkw["binned_hist"] = data["binned_hist"]
        for cls in range(k):
            gc = g if k == 1 else g[:, cls]
            hc = h if k == 1 else h[:, cls]
            if cfg.extra_trees or cfg.feature_fraction_by_node < 1.0:
                kt = jax.random.fold_in(jax.random.fold_in(
                    jax.random.fold_in(base_key, 4 + cls),
                    cfg.extra_seed), it)
                sf, tb, nv, cnt, dt, bgl = build_tree(
                    binned, gc.astype(jnp.float32), hc.astype(jnp.float32),
                    sample_mask.astype(jnp.float32), feat_mask,
                    jnp.int32(nl), key=kt, **tkw)
            else:
                sf, tb, nv, cnt, dt, bgl = build_tree(
                    binned, gc.astype(jnp.float32), hc.astype(jnp.float32),
                    sample_mask.astype(jnp.float32), feat_mask,
                    jnp.int32(nl), **tkw)
            nv = nv * shrink
            sfs.append(sf); tbs.append(tb); nvs.append(nv); cnts.append(cnt)
            dts.append(dt); bgls.append(bgl)
            pred = predict_tree(sf, bgl, nv, binned)
            raw = raw + pred if k == 1 else raw.at[:, cls].add(pred)
            for vi in range(n_valid):
                vpred = predict_tree(sf, bgl, nv,
                                     data["valids"][vi]["binned"])
                new_vraws[vi] = (new_vraws[vi] + vpred if k == 1
                                 else new_vraws[vi].at[:, cls].add(vpred))

        # ----- per-iteration metrics (on device) --------------------
        mvals = []
        for m_label, m_fn in metric_list:
            mkw = dict(metric_kwargs)
            if metric_name == "ndcg" and groups is not None:
                mkw["group_ids"] = groups
            mvals.append(m_fn(raw, labels, weights, **mkw))
            for vi in range(n_valid):
                vs = data["valids"][vi]
                vkw = dict(metric_kwargs)
                if metric_name == "ndcg":
                    vkw["group_ids"] = vs["groups"]
                mvals.append(m_fn(new_vraws[vi], vs["labels"],
                                  vs["weights"], **vkw))
        ys = (jnp.stack(sfs), jnp.stack(tbs), jnp.stack(nvs),
              jnp.stack(cnts), jnp.stack(mvals).astype(jnp.float32))
        if cfg.categorical_features:
            # only categorical trees need the per-slot masks on host;
            # numerical ones are fully derivable from threshold_bin, so
            # don't retain (num_slots, B) bools per iteration for them
            ys = ys + (jnp.stack(dts), jnp.stack(bgls))
        return (raw, tuple(new_vraws)), ys


    return jax.jit(step)


def _get_step_fn(num_f, total_bins, cfg, k, n_valid, mode, mesh,
                 efb_plan=None):
    from mmlspark_tpu.models.gbdt.hist_pallas import (
        pallas_histogram_enabled,
    )

    cfg = _loop_only_normalized(cfg)
    key = (num_f, total_bins, cfg, k, n_valid, mode, mesh,
           pallas_histogram_enabled(), env_flag("MMLSPARK_TPU_HIST_SUB"),
           _hist_env_key(),
           efb_plan.cache_key if efb_plan is not None else None)
    return _cache_put(_CHUNK_CACHE, key,
                      lambda: _make_step_fn(num_f, total_bins, cfg, k,
                                            n_valid, mode, mesh,
                                            efb_plan=efb_plan))


def aot_lower_step(cfg: TrainConfig, n: int, num_f: int,
                   platform: str = "tpu",
                   rows_per_group: int = 0) -> str:
    """AOT-lower ONE fused boosting step for ``platform`` and return
    its StableHLO text — the exact program ``train()`` dispatches per
    iteration (bench.py's hot loop), checkable on any host. Used by
    tests/parallel/test_mosaic_lowering.py to gate TPU-day risk, and
    handy on TPU day itself to inspect what XLA is given.

    ``rows_per_group``: > 0 builds lambdarank group structure (uniform
    query sizes) with the bucketed pairwise layout."""
    import jax
    import jax.numpy as jnp

    cfg = _loop_only_normalized(cfg)
    k = cfg.num_class if cfg.objective in ("multiclass", "softmax",
                                           "multiclassova") else 1
    # the artifact must represent the TPU-day program: the lowering
    # host's default backend is cpu, which would otherwise bake the
    # host-callback native histogram into a "tpu" lowering that the
    # real TPU run (backend == tpu) never selects
    with env_override("MMLSPARK_TPU_NATIVE_HIST", "0"):
        return _aot_lower_step_inner(cfg, n, num_f, k, platform,
                                     rows_per_group)


def _aot_lower_step_inner(cfg: TrainConfig, n: int, num_f: int, k: int,
                          platform: str, rows_per_group: int) -> str:
    import jax
    import jax.numpy as jnp

    step_fn = _get_step_fn(num_f, cfg.max_bin, cfg, k, 0, "serial", None)
    rng = np.random.default_rng(0)
    ones = jnp.ones(n, jnp.float32)
    if cfg.objective == "lambdarank":
        if rows_per_group <= 0:
            raise ValueError("lambdarank lowering needs rows_per_group")
        from mmlspark_tpu.models.gbdt.objectives import make_group_layout
        gids = np.repeat(np.arange(n // rows_per_group + 1),
                         rows_per_group)[:n]
        groups = jnp.asarray(gids)
        group_layout = tuple((jnp.asarray(r), jnp.asarray(m))
                             for r, m in make_group_layout(gids))
        labels = jnp.asarray(rng.integers(0, 5, size=n).astype(np.float32))
    else:
        groups, group_layout = None, None
        labels = jnp.asarray(
            rng.integers(0, max(k, 2), size=n).astype(np.float32))
    data = {
        "binned": jnp.asarray(
            rng.integers(0, cfg.max_bin, size=(n, num_f)).astype(
                np.uint8 if cfg.max_bin <= 256 else np.int32)),
        "labels": labels,
        "weights": ones,
        "groups": groups,
        "group_layout": group_layout,
        "row_valid": ones,
        "base": jnp.float32(0.0),
        "key": jax.random.key(0),
        "lr": jnp.float32(0.1),
        "valids": (),
    }
    raw_shape = (n,) if k == 1 else (n, k)
    carry = (jnp.zeros(raw_shape, jnp.float32), ())
    # step_fn is already jitted by _make_step_fn
    return step_fn.trace(data, carry, jnp.int32(0)).lower(
        lowering_platforms=(platform,)).as_text()


# ---------------------------------------------------------------------------
# Boosting driver
# ---------------------------------------------------------------------------

@dataclass
class TrainResult:
    booster: BoosterArrays
    evals: List[Dict[str, float]] = field(default_factory=list)
    best_iteration: int = -1
    # histogram-path provenance for this fit (bench.py copies it into
    # the artifact so a throughput swing is attributable without
    # rerunning): resolved grow policy, quant mode, EFB bundle counts
    hist_stats: Dict[str, object] = field(default_factory=dict)


def warm_start_scores(init_model: Optional[BoosterArrays],
                      x: np.ndarray,
                      offset: Optional[np.ndarray] = None
                      ) -> Optional[np.ndarray]:
    """Raw-space warm-start margins for continuing a fit on fresh data.

    A continued booster needs the previous ensemble's margin as
    ``train(init_raw=)``; computing it on the **raw** features (not bin
    ids) keeps the warm start valid even when the new data is binned
    differently — which is exactly the streaming-refresh case, where
    each refit re-fits its BinMapper on the fresh window. ``offset``
    is the optional per-row initScoreCol contribution. Returns ``None``
    when there is nothing to warm-start from (both args None)."""
    s = None if init_model is None else np.asarray(
        init_model.predict_jit()(x))
    if offset is not None:
        s = offset if s is None else s + offset
    return s


def train(binned: np.ndarray, labels: np.ndarray, cfg: TrainConfig,
          weights: Optional[np.ndarray] = None,
          group_ids: Optional[np.ndarray] = None,
          bin_upper: Optional[np.ndarray] = None,
          valid_sets: Optional[List[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]] = None,
          init_model: Optional[BoosterArrays] = None,
          init_raw: Optional[np.ndarray] = None,
          valid_init_raws: Optional[List[np.ndarray]] = None,
          custom_objective: Optional[Callable] = None,
          mesh=None,
          callbacks: Optional[List[Callable[[int, Dict[str, float]], None]]] = None,
          measures=None, iteration_offset: int = 0) -> TrainResult:
    """Boosting loop. ``binned``: (N,F) int32 bin ids; ``bin_upper``:
    (F,B) raw-value bin upper edges (threshold materialization).

    ``valid_sets``: list of (binned_valid, labels_valid, weights_valid);
    early stopping follows TrainUtils.scala:143-169 semantics — stop when
    the first metric hasn't improved for ``early_stopping_round`` rounds,
    return the best iteration.

    ``mesh``: if given, rows are device_put sharded over the ``dp`` axis
    and XLA inserts the histogram all-reduce (data_parallel mode).

    gbdt/goss/rf run as one fused jitted step per iteration, dispatched
    asynchronously with no host syncs in the loop (iterations
    chunked only for early stopping); DART falls back to a per-iteration
    host loop because its dropped-tree set is dynamic.
    """
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core.timer import InstrumentationMeasures
    from mmlspark_tpu.parallel.mesh import replicated, row_sharded

    measures = measures if measures is not None else InstrumentationMeasures()

    n, num_f = binned.shape
    total_bins = cfg.max_bin
    k = cfg.num_class if cfg.objective in ("multiclass", "softmax",
                                           "multiclassova") else 1
    depth = cfg.effective_depth
    num_slots = 2 ** (depth + 1) - 1

    if cfg.objective == "lambdarank" and group_ids is None:
        raise ValueError("lambdarank requires group_ids")
    if (cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0) \
            and cfg.objective != "binary":
        raise ValueError(
            "pos/neg_bagging_fraction applies to the binary objective "
            "only (LightGBM semantics); got objective="
            f"{cfg.objective!r}")

    # ---- out-of-core dispatch: supported big fits stream from a spill
    # directory instead of residing on device (models/gbdt/ooc.py) ------
    ooc_mode = resolve_ooc(warn=True)
    if ooc_mode == "off":
        ooc_reason: Optional[str] = "MMLSPARK_TPU_OOC=off"
    else:
        ooc_reason = _ooc_supported(
            cfg, mesh, k=k, has_valid=bool(valid_sets),
            has_custom=custom_objective is not None,
            has_groups=group_ids is not None, total_bins=total_bins)
        want_ooc = (ooc_mode == "on"
                    or n >= env_int("MMLSPARK_TPU_OOC_ROWS", 4_000_000,
                                    minimum=1))
        if want_ooc and ooc_reason is None:
            from mmlspark_tpu.core.serialize import DiskFull
            from mmlspark_tpu.models.gbdt import ooc as ooc_mod
            try:
                return ooc_mod.train_from_binned(
                    binned, labels, cfg, weights=weights,
                    bin_upper=bin_upper,
                    init_model=init_model, init_raw=init_raw,
                    callbacks=callbacks, measures=measures,
                    iteration_offset=iteration_offset)
            except DiskFull as e:
                # the spill disk filled up, but this entry point was
                # handed the full binned matrix — the rows fit in
                # memory, so degrade to the in-core path instead of
                # killing the fit (truly larger-than-memory fits enter
                # via train_ooc directly and keep the hard error)
                from mmlspark_tpu.core.logging_utils import warn_once
                warn_once(
                    "gbdt.ooc.disk_full",
                    "out-of-core spill hit a full disk (%s); the rows "
                    "already fit in memory, so this fit continues "
                    "IN-CORE — free spill space to restore chunked "
                    "training", e)
                ooc_reason = "io.disk_full: spill write failed"
        elif want_ooc and ooc_mode == "on":
            global _WARNED_OOC_DOWNGRADE
            if not _WARNED_OOC_DOWNGRADE:
                _WARNED_OOC_DOWNGRADE = True
                import warnings
                warnings.warn(
                    f"MMLSPARK_TPU_OOC=on cannot stream this fit "
                    f"({ooc_reason}); training in-core — label A/B "
                    "measurements accordingly", stacklevel=2)
        elif ooc_reason is None:
            ooc_reason = (f"auto: {n} rows below the "
                          "MMLSPARK_TPU_OOC_ROWS threshold")

    with measures.phase("dataPreparation"):
        if init_model is not None:
            # continued training (modelString warm start): keep the old
            # model's base, fit residuals on top of its predictions
            base_score = init_model.init_score
            if init_raw is None:
                raise ValueError("warm start needs init_raw (the init "
                                 "model's raw scores on the training rows)")
        elif init_raw is not None:
            # standalone per-row init scores (LightGBM init_score):
            # boost_from_average is auto-disabled and the offset is NOT
            # recorded in the model (predict excludes it, as LightGBM)
            base_score = 0.0
        else:
            base_score = (obj_mod.init_score(cfg.objective, labels, weights)
                          if cfg.boost_from_average and cfg.objective != "lambdarank"
                          else 0.0)
        feature_mode = cfg.tree_learner == "feature" and mesh is not None
        row_valid = None
        if mesh is not None and not feature_mode:
            # row sharding needs N divisible by the dp axis: pad with
            # zero-weight rows masked out of sampling/histograms via
            # ``row_valid`` (the device analog of the reference's
            # empty-partition tolerance, BasePartitionTask.scala:134-137)
            from mmlspark_tpu.parallel.mesh import axis_size
            dp_size = axis_size(mesh, "dp")
            rem = n % dp_size
            if rem:
                pad_n = dp_size - rem
                binned = np.concatenate(
                    [binned, np.repeat(binned[-1:], pad_n, axis=0)])
                labels = np.concatenate(
                    [np.asarray(labels, np.float64), np.zeros(pad_n)])
                weights = np.concatenate(
                    [np.asarray(weights, np.float64) if weights is not None
                     else np.ones(n), np.zeros(pad_n)])
                if group_ids is not None:
                    # padded rows get their OWN group: in lambdarank a
                    # pad row sharing a real group would form valid
                    # pairs (and rank positions) with real rows even at
                    # weight 0
                    group_ids = np.concatenate(
                        [group_ids,
                         np.full(pad_n, np.max(group_ids) + 1,
                                 dtype=np.asarray(group_ids).dtype)])
                if init_raw is not None:
                    init_raw = np.concatenate(
                        [np.asarray(init_raw, np.float32).reshape(
                            (n,) if k == 1 else (n, k)),
                         np.zeros((pad_n,) if k == 1 else (pad_n, k),
                                  np.float32)])
                row_valid = np.concatenate(
                    [np.ones(n, np.float32), np.zeros(pad_n, np.float32)])
                n = n + pad_n
        # binned rows stream to device in async chunks at the narrowest
        # bin dtype (the StreamingPartitionTask micro-batch push analog);
        # uint8 widens for free in downstream gathers/index math
        from mmlspark_tpu.ops.ingest import (binned_ingest_dtype,
                                             chunked_device_put)
        ing_dtype = binned_ingest_dtype(total_bins)
        if feature_mode:
            # feature_parallel: rows replicated, features sharded on fp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from mmlspark_tpu.parallel.mesh import FEATURE_AXIS
            dev_put = lambda a, nd=1: jax.device_put(a, replicated(mesh))  # noqa: E731
            binned_d = chunked_device_put(
                binned, NamedSharding(mesh, P(None, FEATURE_AXIS)),
                dtype=ing_dtype)
        else:
            dev_put = (lambda a, nd=1: jax.device_put(
                a, row_sharded(mesh, nd)) if mesh is not None
                else jnp.asarray(a))
            from mmlspark_tpu.parallel.mesh import axis_size as _axis_size
            binned_d = chunked_device_put(
                binned, row_sharded(mesh, 2) if mesh is not None else None,
                dtype=ing_dtype,
                row_multiple=_axis_size(mesh, "dp") if mesh is not None
                else 1)
        labels_d = dev_put(np.asarray(labels, dtype=np.float32))
        weights_d = None if weights is None else dev_put(
            np.asarray(weights, dtype=np.float32))
        row_valid_d = None if row_valid is None else dev_put(row_valid)

        # ---- histogram-construction acceleration (serial
        # single-program fits only) --------------------------------------
        # grow policy: leaf-wise routes through the eager host loop
        # (its frontier is dynamically shaped); unsupported configs
        # fall back to depthwise with one warning so results stay
        # honest rather than silently ignoring constraints
        grow_policy = resolve_grow_policy()
        if grow_policy == "leafwise":
            reason = _leafwise_supported(cfg, mesh)
            if reason is not None:
                global _WARNED_LEAFWISE_DOWNGRADE
                if not _WARNED_LEAFWISE_DOWNGRADE:
                    _WARNED_LEAFWISE_DOWNGRADE = True
                    import warnings
                    warnings.warn(
                        "MMLSPARK_TPU_GROW_POLICY=leafwise does not "
                        f"support {reason}; growing depthwise — label "
                        "A/B measurements accordingly", stacklevel=2)
                grow_policy = "depthwise"
        # EFB plan + host-binned token: the compiled builders take the
        # bundled matrix (or a host-registry token) as call-time data,
        # so everything here is per-fit state released in the finally
        # below. Leaf-wise histograms on the host loop's own matrix and
        # skips both.
        efb_plan = None
        hist_token_d = None
        binned_hist_d = None
        host_tokens: List[int] = []
        # resolved shard mode is recorded for EVERY fit (serial fits
        # trivially "off") so a multi-device A/B is attributable from
        # hist_stats alone; forced-on downgrades warn once inside
        # resolve_hist_shard_mode
        shard_mode, shard_reason = resolve_hist_shard_mode(cfg, mesh,
                                                           warn=True)
        hist_stats: Dict[str, object] = {
            "grow_policy": grow_policy, "hist_quant": "off",
            "hist_shard": shard_mode,
            # raw-score carry (and therefore the per-round grad/hess
            # recompute) placement: row-sharded over dp in data-parallel
            # fits, replicated/serial otherwise
            "grad_shard": ("dp" if (mesh is not None and not feature_mode)
                           else "off"),
            "efb_bundles": 0, "efb_bundled_features": 0,
            "ooc": False, "ooc_reason": ooc_reason}
        if mesh is not None and shard_reason is not None:
            hist_stats["hist_shard_reason"] = shard_reason
        if mesh is not None and resolve_hist_quant(warn=False) != "off":
            # the quantized accumulation is single-program only; sharded
            # fits (GSPMD full-psum AND the explicit builders) keep f32
            # histograms — warn once and record the honest resolution
            # instead of the old silent serial-only downgrade
            resolve_hist_quant(in_shard_map=True, warn=True)
        if (mesh is None and _resolve_mode(cfg, mesh) == "serial"
                and grow_policy == "depthwise"):
            serial_formulation = resolve_histogram_formulation(
                total_bins, in_shard_map=False, allow_pallas=True,
                allow_native=True, warn=False)
            if not cfg.categorical_features:
                # categorical splits index per-feature bin HISTOGRAM
                # positions during the sorted scan; bundling those
                # columns would change category identity — skip
                from mmlspark_tpu.ops import efb as efb_mod
                efb_plan = efb_mod.plan_bundles(
                    np.asarray(binned), total_bins,
                    mode=efb_mod.resolve_efb())
            hist_host = None
            if efb_plan is not None:
                from mmlspark_tpu.ops import efb as efb_mod
                hist_host = efb_mod.apply_plan(np.asarray(binned),
                                               efb_plan)
            if serial_formulation == "native":
                mat = (hist_host if hist_host is not None
                       else np.asarray(binned))
                tok = _register_host_binned(
                    np.ascontiguousarray(mat, dtype=ing_dtype))
                host_tokens.append(tok)
                hist_token_d = jnp.asarray(tok, jnp.int32)
            elif hist_host is not None:
                binned_hist_d = chunked_device_put(hist_host, None,
                                                   dtype=ing_dtype)
            hist_stats["hist_quant"] = resolve_hist_quant(warn=True)
            if efb_plan is not None:
                hist_stats["efb_bundles"] = len(efb_plan.bundles)
                hist_stats["efb_bundled_features"] = (
                    efb_plan.n_bundled_features)
    group_ids_dev = None if group_ids is None else jnp.asarray(group_ids)
    if cfg.objective == "lambdarank" and group_ids is not None:
        # host-computed padded (G, S) bucket layout, built ONCE from the
        # host array: the lambdarank pairwise work runs per group,
        # never as an (N, N) matrix
        from mmlspark_tpu.models.gbdt.objectives import make_group_layout
        group_layout = tuple(
            (jnp.asarray(r), jnp.asarray(m))
            for r, m in make_group_layout(np.asarray(group_ids)))
    else:
        group_layout = None

    # raw scores, (N,) or (N,K) — placed like the rows they score: in
    # data-parallel fits the carry is sharded over dp so each round's
    # grad/hess recompute stays on the replica owning the rows and
    # feeds the sharded histogram builder without a gather
    raw_shape = (n,) if k == 1 else (n, k)
    if init_raw is not None:
        # warm start (modelString continuation, LightGBMBase.scala:48-51,
        # where init_raw includes the old model's base score) or
        # standalone init scores (initScoreCol)
        raw = dev_put(np.asarray(init_raw, dtype=np.float32).reshape(
            raw_shape), len(raw_shape))
    else:
        raw = dev_put(np.full(raw_shape, base_score, dtype=np.float32),
                      len(raw_shape))

    valid_states = []
    for vi, vset in enumerate(valid_sets or []):
        vb, vy, vw = vset[:3]
        vgroup = vset[3] if len(vset) > 3 else None
        if valid_init_raws is not None:
            vraw = jnp.asarray(np.asarray(
                valid_init_raws[vi], dtype=np.float32).reshape(
                    (vb.shape[0],) if k == 1 else (vb.shape[0], k)))
        else:
            vraw = jnp.full((vb.shape[0],) if k == 1 else (vb.shape[0], k),
                            base_score, dtype=jnp.float32)
        valid_states.append({
            "binned": jnp.asarray(vb, dtype=jnp.int32),
            "labels": jnp.asarray(vy, dtype=jnp.float32),
            "weights": None if vw is None else jnp.asarray(vw, dtype=np.float32),
            "raw": vraw,
            "group_ids": None if vgroup is None else jnp.asarray(vgroup),
        })

    metric_name, metric_list, higher_better, metric_kwargs = \
        _resolve_metrics(cfg)
    if metric_name == "ndcg":
        for vi, vs in enumerate(valid_states):
            if vs["group_ids"] is None:
                raise ValueError(
                    f"valid set {vi}: ndcg eval requires its own "
                    f"group ids (pass 4-tuples in valid_sets)")

    try:
        with resilience.fit_watchdog("gbdt.train"):
            if (cfg.boosting_type == "dart" or custom_objective is not None
                    or grow_policy == "leafwise"):
                trees, tree_weights, evals, best_iter = _train_loop(
                    cfg, k, num_f, total_bins, depth, binned_d, labels_d,
                    weights_d, group_ids_dev, raw, valid_states,
                    custom_objective, mesh, metric_name, metric_list,
                    higher_better, metric_kwargs, base_score, callbacks,
                    measures, n, row_valid, iteration_offset,
                    group_layout=group_layout, hist_token=hist_token_d,
                    binned_hist=binned_hist_d, efb_plan=efb_plan,
                    leafwise=grow_policy == "leafwise")
            else:
                trees, tree_weights, evals, best_iter = _train_scan(
                    cfg, k, num_f, total_bins, binned_d, labels_d, weights_d,
                    group_ids_dev, raw, valid_states, mesh,
                    metric_list, higher_better, base_score, callbacks,
                    measures, row_valid_d, iteration_offset,
                    group_layout=group_layout, hist_token=hist_token_d,
                    binned_hist=binned_hist_d, efb_plan=efb_plan)
    finally:
        # the loops drain every dispatched step before returning
        # (block_until_ready / eager device_get) — except when a step
        # raised (fault injection, preemption): a histogram callback
        # still in flight then must not outlive its token, or it fails
        # with a spurious "token not registered" when the runtime
        # blocks on outstanding effects at interpreter exit
        if host_tokens:
            try:
                jax.effects_barrier()
            except Exception:
                pass  # a poisoned step must not mask the real error
        for tok in host_tokens:
            _release_host_binned(tok)
    booster = _assemble_booster(trees, tree_weights, cfg, k, num_f,
                                total_bins, depth, num_slots, bin_upper,
                                base_score, best_iter, init_model)
    return TrainResult(booster=booster, evals=evals,
                       best_iteration=best_iter, hist_stats=hist_stats)


def _assemble_booster(trees, tree_weights, cfg, k, num_f, total_bins, depth,
                      num_slots, bin_upper, base_score, best_iter,
                      init_model):
    """Pack per-tree host arrays into a BoosterArrays (shared by the
    in-core loops and the out-of-core trainer): rf weight normalization,
    early-stop truncation, raw-value thresholds from bin_upper,
    categorical bitsets, and warm-start concat."""
    trees_sf, trees_tb, trees_nv, trees_cnt, trees_dt, trees_bgl = trees

    num_trees = len(trees_sf)
    weights_arr = np.asarray(tree_weights, dtype=np.float32)
    if cfg.boosting_type == "rf" and num_trees:
        weights_arr = weights_arr / (num_trees / max(k, 1))
    if (cfg.early_stopping_round > 0 and best_iter >= 0
            and best_iter + 1 < (num_trees // max(k, 1))):
        keep = (best_iter + 1) * k
        trees_sf, trees_tb = trees_sf[:keep], trees_tb[:keep]
        trees_nv, trees_cnt = trees_nv[:keep], trees_cnt[:keep]
        trees_dt, trees_bgl = trees_dt[:keep], trees_bgl[:keep]
        weights_arr = weights_arr[:keep]

    if bin_upper is None:
        bin_upper = np.full((num_f, total_bins), np.inf)
    sf_all = np.stack(trees_sf) if trees_sf else np.full((0, num_slots), -1, np.int32)
    tb_all = np.stack(trees_tb) if trees_tb else np.zeros((0, num_slots), np.int32)
    dt_all = (np.stack(trees_dt).astype(np.int8) if trees_dt
              else np.zeros(sf_all.shape, np.int8))
    thr_val = np.where(
        sf_all >= 0,
        bin_upper[np.maximum(sf_all, 0), tb_all],
        np.inf)
    cat_bitset = None
    if cfg.categorical_features and trees_bgl:
        # bin-subset masks -> packed bitsets over raw category VALUES
        # (bin_upper holds the category id at each categorical bin), the
        # layout LightGBM model strings use (cat_threshold words)
        thr_val = np.where(dt_all == 1, np.nan, thr_val)
        bgl_all = np.stack(trees_bgl)
        node_vals = []  # (t, m, left-set category values)
        for t, m in np.argwhere(dt_all == 1):
            vals = bin_upper[sf_all[t, m], 1:][bgl_all[t, m, 1:]]
            vals = vals[np.isfinite(vals)]
            if vals.size and ((vals < 0).any()
                              or (vals != np.floor(vals)).any()):
                raise ValueError(
                    "categorical feature values must be non-negative "
                    "integers (index them first, e.g. ValueIndexer)")
            node_vals.append((t, m, vals.astype(np.int64)))
        max_val = max((int(v.max()) for _, _, v in node_vals if v.size),
                      default=0)
        if max_val >= 1 << 20:
            raise ValueError(
                f"categorical value {max_val} too large for bitset "
                f"representation; re-index categories to a dense range")
        words = max_val // 32 + 1
        cat_bitset = np.zeros((sf_all.shape[0], num_slots, words), np.uint32)
        for t, m, vals in node_vals:
            for v in vals:
                cat_bitset[t, m, v // 32] |= np.uint32(1) << np.uint32(v % 32)
    booster = BoosterArrays(
        split_feature=sf_all,
        threshold_bin=tb_all,
        threshold_value=thr_val,
        node_value=np.stack(trees_nv) if trees_nv else np.zeros((0, num_slots), np.float32),
        count=np.stack(trees_cnt) if trees_cnt else np.zeros((0, num_slots), np.float32),
        tree_weights=weights_arr,
        max_depth=depth,
        num_features=num_f,
        num_class=k,
        objective=cfg.objective,
        init_score=base_score,
        decision_type=(
            dt_all if cat_bitset is not None
            # numeric-only trees don't retain per-tree decision bits,
            # but zero-as-missing scoring needs the zero-missing stamp
            # (6 = default-left | missing_type zero) on internal nodes
            else np.where(sf_all >= 0, 6, 0).astype(np.int8)
            if cfg.zero_as_missing else None),
        cat_bitset=cat_bitset,
    )
    if init_model is not None:
        booster = BoosterArrays.concat(init_model, booster)
    return booster


def _train_scan(cfg, k, num_f, total_bins, binned_d, labels_d, weights_d,
                group_ids_dev, raw, valid_states, mesh,
                metric_list, higher_better, base_score, callbacks, measures,
                row_valid_d=None, iteration_offset=0, group_layout=None,
                hist_token=None, binned_hist=None, efb_plan=None):
    """Fused device loop: one async dispatch per iteration, zero host
    syncs inside the loop. Early stopping syncs the (tiny) metric matrix
    in blocks of ``early_stopping_round`` and truncates post hoc — trees
    don't depend on metrics, so this reproduces the per-iteration stop
    rule exactly, overshooting by at most one block of compute."""
    import jax
    import jax.numpy as jnp

    # graftsan: fresh collective/recompile log per run (keeps ranks'
    # cumulative sequence hashes comparable) BEFORE the compile caches
    # run, so their misses are counted against this run's budget
    sanitizer.reset()

    n_valid = len(valid_states)
    mode = _resolve_mode(cfg, mesh)
    step_fn = _get_step_fn(num_f, total_bins, cfg, k, n_valid, mode, mesh,
                           efb_plan=efb_plan)
    ones = jnp.ones(labels_d.shape[0], jnp.float32)
    data = {
        "binned": binned_d,
        "hist_token": hist_token,
        "binned_hist": binned_hist,
        "labels": labels_d,
        "weights": weights_d if weights_d is not None else ones,
        "groups": group_ids_dev,
        "group_layout": group_layout,
        "row_valid": row_valid_d if row_valid_d is not None else ones,
        "base": jnp.float32(base_score),
        "key": jax.random.key(cfg.seed),
        "lr": jnp.float32(cfg.learning_rate),
        "valids": tuple({
            "binned": vs["binned"],
            "labels": vs["labels"],
            "weights": (vs["weights"] if vs["weights"] is not None
                        else jnp.ones(vs["labels"].shape[0], jnp.float32)),
            "groups": vs["group_ids"],
        } for vs in valid_states),
    }
    carry = (raw, tuple(vs["raw"] for vs in valid_states))

    # entry guard: a NaN entering here would otherwise surface 100
    # iterations later as a mysteriously constant model; the dtype
    # contract pins the input widths so a config-flipped default
    # cannot silently retrain at a different precision
    sanitizer.check_finite("gbdt.train_scan.entry", data)
    sanitizer.check_dtype_contract("gbdt.train_scan.entry", data)

    # metric record layout must match the step body's stacking order
    labels_order = []
    for m_label, _ in metric_list:
        labels_order.append(f"train_{m_label}")
        for vi in range(n_valid):
            labels_order.append(f"valid{vi}_{m_label}")

    esr = cfg.early_stopping_round
    has_es = esr > 0 and n_valid > 0
    total = cfg.num_iterations
    block = max(esr, 8) if has_es else total

    outs: List[Any] = []          # device-resident per-iteration tuples
    met_host: List[np.ndarray] = []   # synced metric rows (host)
    stop_after = total            # iterations to keep (1-based)
    best_val = -np.inf if higher_better else np.inf
    best_iter, rounds_no_improve = -1, 0

    def sync_metrics_through(upto):
        """Pull metric rows [len(met_host), upto) to host in one get."""
        if upto > len(met_host):
            # host boundary of the cross-replica metric reduction: the
            # device_get below is where an allreduce failure would
            # surface, so the injection point lives here — and a hang
            # here is what the watchdog classifies as collective-stall
            fault_point("allreduce")
            prev_b = resilience.mark_boundary(
                "collective",
                lambda: f"gbdt metric sync through iter {upto}")
            try:
                fault_point("mesh.collective_hang")
                stacked = jnp.stack([outs[i][4] for i in
                                     range(len(met_host), upto)])
                rows = np.asarray(jax.device_get(stacked))
            finally:
                resilience.restore_boundary(prev_b)
            met_host.extend(rows)
            # first host sync after the reduced metrics land: guard
            # them and cross-check the collective-sequence hash here
            sanitizer.check_finite("gbdt.metrics_sync", rows)
            sanitizer.step_boundary("gbdt.metrics_sync")

    vidx = (labels_order.index(f"valid0_{metric_list[0][0]}")
            if has_es else -1)
    es_fed = 0  # iterations already fed to the stop rule

    def feed_stop_rule(upto):
        """Apply the per-iteration stop rule to synced rows [es_fed, upto);
        returns True once stopping triggers (stop_after set)."""
        nonlocal es_fed, best_val, best_iter, rounds_no_improve, stop_after
        while es_fed < upto:
            j = es_fed
            es_fed += 1
            cur = float(met_host[j][vidx])
            # TrainUtils.scala:143-169: improvement must clear the
            # tolerance (higher-better), or stay within it (lower-better)
            tol = cfg.improvement_tolerance
            improved = (cur - best_val > tol if higher_better
                        else cur - best_val < tol)
            if improved:
                best_val, best_iter, rounds_no_improve = cur, j, 0
            else:
                rounds_no_improve += 1
                if rounds_no_improve >= esr:
                    stop_after = j + 1
                    return True
        return False

    it = 0
    _clear_callback_failure()
    while it < total:
        # per-iteration injection point (host side, outside the jitted
        # step): arming a raise here is the deterministic stand-in for
        # a preempted worker mid-fit — the kill-and-resume parity test
        # interrupts exactly here and resumes from the last checkpoint
        resilience.step_start(it + iteration_offset)
        _check_callback_failure()
        fault_point("gbdt.train_step")
        fault_point("train.participant_loss")
        with measures.phase("training"):
            carry, ys = step_fn(data, carry, it + iteration_offset)
            outs.append(ys)
            it += 1
        if callbacks:
            # live per-iteration contract: callbacks force a sync each
            # iteration (opt-in cost; without callbacks the loop is
            # fully asynchronous)
            with measures.phase("training"):
                jax.block_until_ready(carry)  # attribute compute honestly
            with measures.phase("validation"):
                sync_metrics_through(it)
            record = {"iteration": it - 1}
            for mi, name in enumerate(labels_order):
                record[name] = float(met_host[it - 1][mi])
            for cb in callbacks:
                cb(it - 1, record)
        if has_es:
            # metrics already on host when callbacks ran: check every
            # iteration (no phantom work past the stop point); otherwise
            # sync in blocks and replay the rule over the new rows
            if callbacks:
                if feed_stop_rule(it):
                    break
            elif it % block == 0 or it == total:
                with measures.phase("training"):
                    jax.block_until_ready(carry)  # attribute compute honestly
                with measures.phase("validation"):
                    sync_metrics_through(it)
                if feed_stop_rule(it):
                    break
        resilience.step_end()
    _check_callback_failure()

    kept = outs[:stop_after]
    trees_sf: List[np.ndarray] = []
    trees_tb: List[np.ndarray] = []
    trees_nv: List[np.ndarray] = []
    trees_cnt: List[np.ndarray] = []
    trees_dt: List[np.ndarray] = []
    trees_bgl: List[np.ndarray] = []
    evals: List[Dict[str, float]] = []
    if not kept:  # num_iterations == 0: empty booster, no evals
        return ((trees_sf, trees_tb, trees_nv, trees_cnt, trees_dt,
                 trees_bgl), [], evals, best_iter)
    has_cat = len(kept[0]) > 5
    # the fused loop dispatches steps asynchronously, so nearly all
    # device compute lands in this drain — the watchdog times it as one
    # span (the MIN_S floor must cover it; see PARAMS.md)
    resilience.step_start("drain")
    with measures.phase("training"):
        jax.block_until_ready(carry)  # drain async dispatches
    # async dispatch: the last steps' callbacks only ran during the
    # drain, so a latched callback failure is first visible here
    _check_callback_failure()
    # jit-boundary exit guard: raw scores after the last fused step
    sanitizer.check_finite("gbdt.train_scan.exit", carry)
    sanitizer.check_dtype_contract("gbdt.train_scan.exit", carry)
    with measures.phase("validation"):
        sync_metrics_through(stop_after)
        # single batched transfer of all kept trees
        sf_h, tb_h, nv_h, cnt_h = jax.device_get((
            jnp.stack([o[0] for o in kept]),
            jnp.stack([o[1] for o in kept]),
            jnp.stack([o[2] for o in kept]),
            jnp.stack([o[3] for o in kept])))
        if has_cat:
            dt_h, bgl_h = jax.device_get((
                jnp.stack([o[5] for o in kept]),
                jnp.stack([o[6] for o in kept])))
    resilience.step_end()

    for j in range(stop_after):
        for cls in range(k):
            trees_sf.append(sf_h[j, cls])
            trees_tb.append(tb_h[j, cls])
            trees_nv.append(nv_h[j, cls])
            trees_cnt.append(cnt_h[j, cls])
            if has_cat:
                trees_dt.append(dt_h[j, cls])
                trees_bgl.append(bgl_h[j, cls])
        record: Dict[str, float] = {"iteration": j}
        for mi, name in enumerate(labels_order):
            record[name] = float(met_host[j][mi])
        evals.append(record)
    return ((trees_sf, trees_tb, trees_nv, trees_cnt, trees_dt, trees_bgl),
            [1.0] * len(trees_sf), evals, best_iter)


def _train_loop(cfg, k, num_f, total_bins, depth, binned_d, labels_d,
                weights_d, group_ids_dev, raw, valid_states,
                custom_objective, mesh, metric_name, metric_list,
                higher_better, metric_kwargs, base_score, callbacks,
                measures, n, row_valid=None, iteration_offset=0,
                group_layout=None, hist_token=None, binned_hist=None,
                efb_plan=None, leafwise=False):
    """Per-iteration eager host loop. Used for (a) DART, whose
    dropped-tree set is a dynamically sized subset of all prior trees
    that doesn't fit a fixed-shape compiled step, and (b) custom
    objectives, which the eager path calls with concrete arrays so
    host-side (numpy) objectives keep working. Compiled pieces are
    cached across calls."""
    import jax
    import jax.numpy as jnp

    sanitizer.reset()
    sanitizer.check_finite(
        "gbdt.train_loop.entry",
        (labels_d, weights_d, raw, row_valid))

    is_dart = cfg.boosting_type == "dart"
    is_rf = cfg.boosting_type == "rf"
    is_goss = cfg.boosting_type == "goss"

    mode = _resolve_mode(cfg, mesh)
    if leafwise:
        from mmlspark_tpu.models.gbdt.leafwise import make_build_tree_leafwise
        build_tree = make_build_tree_leafwise(num_f, total_bins, cfg)
    else:
        build_tree = _get_builder(num_f, total_bins, cfg, mode, mesh,
                                  efb_plan=efb_plan)
    predict_tree_binned = _get_predict_tree(depth)
    objective_fn = custom_objective or obj_mod.get_objective(cfg.objective)
    obj_kwargs = _objective_kwargs(cfg)
    if cfg.objective == "lambdarank":
        obj_kwargs = {
            "group_ids": group_ids_dev, "sigmoid": cfg.sigmoid,
            "truncation_level": cfg.lambdarank_truncation_level,
            "group_layout": group_layout}
        if cfg.label_gain:
            obj_kwargs["label_gain"] = tuple(cfg.label_gain)
    if custom_objective is not None:
        # the documented fobj contract is (preds, labels, weights) ->
        # (grad, hess): the named objective's kwargs must not leak in
        # (group-aware custom objectives close over their group ids)
        obj_kwargs = {}

    # offset keys the host/device RNG streams so a resumed segment
    # continues rather than replays (exact on the fused path; the eager
    # loop's host RNG re-seeds per segment)
    bag_rng = np.random.default_rng(
        cfg.seed * 1000003 + cfg.bagging_seed + iteration_offset)
    ff_rng = np.random.default_rng(
        cfg.seed * 1000003 + cfg.feature_fraction_seed + iteration_offset)
    # DART drop decisions ride a dedicated stream (LightGBM drop_seed)
    # so changing drop params never perturbs bagging/feature sampling
    drop_rng = np.random.default_rng(
        (cfg.seed + 4 if cfg.drop_seed is None else cfg.drop_seed)
        + iteration_offset)
    trees_sf, trees_tb, trees_nv, trees_cnt = [], [], [], []
    trees_dt, trees_bgl = [], []
    tree_weights: List[float] = []
    dart_tree_preds: List[Any] = []

    evals: List[Dict[str, float]] = []
    best_val = -np.inf if higher_better else np.inf
    best_iter = -1
    rounds_no_improve = 0

    rv_host = (np.ones(n, dtype=np.float32) if row_valid is None
               else np.asarray(row_valid, dtype=np.float32))
    pos_neg = (cfg.pos_bagging_fraction < 1.0
               or cfg.neg_bagging_fraction < 1.0)
    labels_host = np.asarray(labels_d) if pos_neg else None
    bag_mask = rv_host.copy()
    _clear_callback_failure()
    for it in range(cfg.num_iterations):
        # same per-iteration injection point as the fused path
        resilience.step_start(it + iteration_offset)
        _check_callback_failure()
        fault_point("gbdt.train_step")
        fault_point("train.participant_loss")
        # ----- sampling masks (host RNG, deterministic by seed) ----------
        if (cfg.bagging_freq > 0
                and (cfg.bagging_fraction < 1.0 or pos_neg)
                and it % cfg.bagging_freq == 0) or (is_rf and it == 0):
            if pos_neg and not is_rf:
                thr_vec = np.where(labels_host > 0,
                                   cfg.pos_bagging_fraction,
                                   cfg.neg_bagging_fraction)
                bag_mask = (bag_rng.random(n) < thr_vec).astype(np.float32) * rv_host
            else:
                frac = cfg.bagging_fraction if cfg.bagging_fraction < 1.0 else 0.632
                bag_mask = (bag_rng.random(n) < frac).astype(np.float32) * rv_host
        feat_mask = np.ones(num_f, dtype=np.float32)
        if cfg.feature_fraction < 1.0:
            keep = max(1, int(round(num_f * cfg.feature_fraction)))
            chosen = ff_rng.choice(num_f, size=keep, replace=False)
            feat_mask = np.zeros(num_f, dtype=np.float32)
            feat_mask[chosen] = 1.0

        # ----- dart: drop trees for this iteration's gradients -----------
        raw_for_grad = raw
        dropped: List[int] = []
        if is_dart and trees_sf and drop_rng.random() >= cfg.skip_drop:
            if cfg.uniform_drop:
                probs = np.full(len(trees_sf), cfg.drop_rate)
            else:
                # LightGBM dart.hpp: drop probability proportional to
                # tree weight, normalized to mean drop_rate
                wts = np.asarray(tree_weights, dtype=np.float64)
                mean_w = max(float(wts.mean()), 1e-12)
                probs = np.clip(cfg.drop_rate * wts / mean_w, 0.0, 1.0)
            drops = drop_rng.random(len(trees_sf)) < probs
            dropped = list(np.nonzero(drops)[0])
            if cfg.max_drop > 0 and len(dropped) > cfg.max_drop:
                dropped = sorted(drop_rng.choice(
                    dropped, size=cfg.max_drop, replace=False))
            for i in dropped:  # tree i belongs to class i % k
                contrib = dart_tree_preds[i] * tree_weights[i]
                if k == 1:
                    raw_for_grad = raw_for_grad - contrib
                else:
                    raw_for_grad = raw_for_grad.at[:, i % k].add(-contrib)

        # ----- gradients --------------------------------------------------
        with measures.phase("training"):
            score_in = raw_for_grad if not is_rf else jnp.full_like(
                raw, base_score)
            g, h = objective_fn(score_in, labels_d, weights_d,
                                **obj_kwargs)

        sample_mask = jnp.asarray(bag_mask)
        if is_goss:
            g = jnp.asarray(g)
            h = jnp.asarray(h)
            absg = jnp.abs(g) if k == 1 else jnp.sum(jnp.abs(g), axis=1)
            thr = jnp.nanquantile(
                jnp.where(jnp.asarray(rv_host) > 0, absg, jnp.nan),
                1.0 - cfg.top_rate)
            big = absg >= thr
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(cfg.seed), 3),
                it + iteration_offset)
            small_keep = jax.random.uniform(key, absg.shape) < (
                cfg.other_rate / max(1.0 - cfg.top_rate, 1e-12))
            amplify = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-12)
            mult = jnp.where(big, 1.0, jnp.where(small_keep, amplify, 0.0))
            sample_mask = sample_mask * (mult > 0)
            gm = mult if k == 1 else mult[:, None]
            g, h = g * gm, h * gm

        # ----- one tree per class ----------------------------------------
        it_trees = []
        for cls in range(k):
            gc = g if k == 1 else g[:, cls]
            hc = h if k == 1 else h[:, cls]
            with measures.phase("training"):
                kw = {}
                if cfg.extra_trees or cfg.feature_fraction_by_node < 1.0:
                    kw["key"] = jax.random.fold_in(jax.random.fold_in(
                        jax.random.fold_in(jax.random.key(cfg.seed),
                                           4 + cls), cfg.extra_seed),
                        it + iteration_offset)
                if not leafwise:
                    if hist_token is not None:
                        kw["hist_token"] = hist_token
                    if binned_hist is not None:
                        kw["binned_hist"] = binned_hist
                sf, tb, nv, cnt, dt, bgl = build_tree(
                    binned_d, jnp.asarray(gc, jnp.float32),
                    jnp.asarray(hc, jnp.float32),
                    sample_mask.astype(jnp.float32),
                    jnp.asarray(feat_mask),
                    jnp.int32(cfg.num_leaves if cfg.num_leaves > 0 else 2 ** depth),
                    **kw)
            nv = nv * (1.0 if is_rf else cfg.learning_rate)
            trees_sf.append(np.asarray(sf))
            trees_tb.append(np.asarray(tb))
            trees_nv.append(np.asarray(nv))
            trees_cnt.append(np.asarray(cnt))
            if cfg.categorical_features:
                # numerical-only masks are derivable from threshold_bin;
                # don't pull (num_slots, B) bools to host per tree
                trees_dt.append(np.asarray(dt))
                trees_bgl.append(np.asarray(bgl))
            it_trees.append((sf, bgl, nv))

        # ----- dart weight updates / raw score update ---------------------
        if dropped:
            norm = len(dropped) / (len(dropped) + 1.0)
            # scale dropped trees toward the new ensemble (per class)
            for i in dropped:
                old_w = tree_weights[i]
                tree_weights[i] = old_w * norm
                delta = dart_tree_preds[i] * (tree_weights[i] - old_w)
                if k == 1:
                    raw = raw + delta
                else:
                    raw = raw.at[:, i % k].add(delta)
            w_new = 1.0 / (len(dropped) + 1.0)
        else:
            w_new = 1.0

        for cls, (sf, bgl, nv) in enumerate(it_trees):
            with measures.phase("training"):
                pred = predict_tree_binned(sf, bgl, nv, binned_d)
            tree_weights.append(w_new)
            if is_dart:
                dart_tree_preds.append(pred)
            upd = pred * w_new
            if k == 1:
                raw = raw + upd
            else:
                raw = raw.at[:, cls].add(upd)
            for vs in valid_states:
                vpred = predict_tree_binned(sf, bgl, nv, vs["binned"]) * w_new
                vs["raw"] = (vs["raw"] + vpred if k == 1
                             else vs["raw"].at[:, cls].add(vpred))

        # ----- eval + early stopping -------------------------------------
        with measures.phase("validation"):
            # host boundary of the per-iteration metric sync (the
            # float() casts block on cross-replica reductions)
            prev_b = resilience.mark_boundary(
                "collective", lambda: f"gbdt eager metric eval iter {it}")
            fault_point("mesh.collective_hang")
            record: Dict[str, float] = {"iteration": it}
            for m_label, m_fn in metric_list:
                mkw = dict(metric_kwargs)
                if metric_name == "ndcg" and group_ids_dev is not None:
                    mkw["group_ids"] = group_ids_dev
                record[f"train_{m_label}"] = float(
                    m_fn(raw, labels_d, weights_d, **mkw))
                for vi, vs in enumerate(valid_states):
                    vkw = dict(metric_kwargs)
                    if metric_name == "ndcg":
                        vkw["group_ids"] = vs["group_ids"]
                    record[f"valid{vi}_{m_label}"] = float(
                        m_fn(vs["raw"], vs["labels"], vs["weights"], **vkw))
            evals.append(record)
            resilience.restore_boundary(prev_b)
        for cb in (callbacks or []):
            cb(it, record)

        if cfg.early_stopping_round > 0 and valid_states:
            cur = record[f"valid0_{metric_list[0][0]}"]
            # TrainUtils.scala:143-169: improvement must clear the
            # tolerance (higher-better), or stay within it (lower-better)
            tol = cfg.improvement_tolerance
            improved = (cur - best_val > tol if higher_better
                        else cur - best_val < tol)
            if improved:
                best_val, best_iter, rounds_no_improve = cur, it, 0
            else:
                rounds_no_improve += 1
                if rounds_no_improve >= cfg.early_stopping_round:
                    break
        resilience.step_end()
    # a failure on the final iteration must not be checkpointed away
    _check_callback_failure()

    return ((trees_sf, trees_tb, trees_nv, trees_cnt, trees_dt, trees_bgl),
            tree_weights, evals, best_iter)
