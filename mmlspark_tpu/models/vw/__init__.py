from mmlspark_tpu.models.vw.featurizer import (  # noqa: F401
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
)
from mmlspark_tpu.models.vw.learners import (  # noqa: F401
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitGeneric,
    VowpalWabbitGenericModel,
    VowpalWabbitGenericProgressive,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
)
from mmlspark_tpu.models.vw.bandit import (  # noqa: F401
    VowpalWabbitContextualBandit,
    VowpalWabbitContextualBanditModel,
)
from mmlspark_tpu.models.vw.policyeval import (  # noqa: F401
    BanditEstimator,
    cressie_read,
    cressie_read_interval,
    ips,
    snips,
)
from mmlspark_tpu.models.vw.cse import (  # noqa: F401
    VowpalWabbitCSETransformer,
    VowpalWabbitDSJsonTransformer,
)
