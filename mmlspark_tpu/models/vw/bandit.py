"""Contextual bandit learner (cb_explore parity).

Replaces the reference's VW ``--cb_explore_adf``-style path
(vw/.../VowpalWabbitContextualBandit.scala:105,311): IPS-weighted
cost regression per action over shared+action features, epsilon-greedy
action distribution at prediction time. Training uses the same hashed
(idx, val) feature blocks and SGD core as the other VW learners.

Input schema (ADF-style): per row, a chosen ``actionCol`` (1-based like
VW), ``labelCol`` = observed cost, ``probabilityCol`` = logged
probability of the chosen action, and per-action hashed feature blocks
``<sharedCol>`` + ``<featuresCol>`` (the action's features).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import Param, ge, to_float, to_int, to_str
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.models.vw.learners import (
    _VWBaseLearner,
    _VWBaseModel,
    _batchify,
    jitted_sgd_train,
    sanitize_values,
)
from mmlspark_tpu.models.vw.policyeval import BanditEstimator


class VowpalWabbitContextualBandit(_VWBaseLearner):
    numActions = Param("numActions", "number of discrete actions", to_int,
                       ge(2), default=2)
    actionCol = Param("actionCol", "chosen action column (1-based)", to_str,
                      default="chosenAction")
    probabilityCol = Param("probabilityCol", "logged action probability",
                           to_str, default="probability")
    epsilon = Param("epsilon", "exploration rate for the learned policy",
                    to_float, ge(0), default=0.05)
    labelCol = Param("labelCol", "observed cost of the chosen action", to_str,
                     default="label")

    def _fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        import jax
        import jax.numpy as jnp

        idx, val = self._get_features(df)
        num_actions = self.get("numActions")
        action = np.asarray(df.col(self.get("actionCol")), dtype=np.int64) - 1
        if action.min() < 0 or action.max() >= num_actions:
            raise ValueError("actions must be in [1, numActions]")
        cost = np.asarray(df.col(self.get("labelCol")), dtype=np.float32)
        prob = np.asarray(df.col(self.get("probabilityCol")), dtype=np.float32)
        # IPS weighting: cost regression importance 1/p(logged action)
        wt = 1.0 / np.maximum(prob, 1e-6)

        overrides = self._apply_pass_through()
        get = lambda k: overrides.get(k, self.get(k))
        num_weights = 1 << get("numBits")
        if int(idx.max(initial=0)) >= num_weights:
            raise ValueError("feature indices exceed numBits hash space; "
                             "featurizer and learner numBits must match")
        # one weight bank per action: shift hashed indices by action block
        run = jitted_sgd_train(num_weights * num_actions, "squared",
                               get("learningRate"), get("powerT"),
                               get("initialT"), get("adaptive"),
                               get("l1"), get("l2"),
                               normalized=get("normalized"),
                               invariant=get("invariant"))
        shifted = (idx.astype(np.int64)
                   + (action[:, None] * num_weights)).astype(np.int64)
        bidx, bval, by, bwt = _batchify(shifted, val, cost, wt, get("batchSize"))
        w = jnp.zeros(num_weights * num_actions, dtype=jnp.float32)
        g2 = jnp.zeros_like(w)
        s = jnp.zeros_like(w)
        n_acc = jnp.zeros(())
        bias = jnp.zeros(())
        t = jnp.zeros(())
        for _ in range(get("numPasses")):
            w, g2, s, n_acc, bias, t, _ = run(
                w, g2, s, n_acc, bias, t, jnp.asarray(bidx),
                jnp.asarray(bval), jnp.asarray(by), jnp.asarray(bwt))
        model = VowpalWabbitContextualBanditModel(
            **{k: v for k, v in self._paramMap.items()
               if VowpalWabbitContextualBanditModel.has_param(k)})
        model.weights = np.asarray(w)
        model.bias = float(bias)
        model.loss = "squared"
        model.num_actions = num_actions
        model.num_weights_per_action = num_weights
        return model


class VowpalWabbitContextualBanditModel(_VWBaseModel):
    numActions = Param("numActions", "number of discrete actions", to_int,
                       ge(2), default=2)
    epsilon = Param("epsilon", "exploration rate", to_float, ge(0),
                    default=0.05)
    num_actions: int = 2
    num_weights_per_action: int = 0

    def _get_state(self):
        s = super()._get_state()
        s["num_actions"] = self.num_actions
        s["num_weights_per_action"] = self.num_weights_per_action
        return s

    def _set_state(self, state):
        super()._set_state(state)
        self.num_actions = state["num_actions"]
        self.num_weights_per_action = state["num_weights_per_action"]

    def _transform(self, df: DataFrame) -> DataFrame:
        base = self.get("featuresCol")
        if f"{base}_idx" in df:
            idx = df.col(f"{base}_idx").astype(np.int64)
            val = df.col(f"{base}_val").astype(np.float64)
        else:  # dense vector fallback: identity indexing
            val = df.col(base).astype(np.float64)
            idx = np.broadcast_to(
                np.arange(val.shape[1], dtype=np.int64), val.shape).copy()
        val = sanitize_values(val)
        nw = self.num_weights_per_action
        costs = np.stack([
            (self.weights[idx + a * nw] * val).sum(axis=1) + self.bias
            for a in range(self.num_actions)], axis=1)
        best = np.argmin(costs, axis=1)
        eps = self.get("epsilon")
        probs = np.full(costs.shape, eps / self.num_actions)
        probs[np.arange(len(best)), best] += 1.0 - eps
        return (df.with_column("predictedCosts", costs)
                  .with_column(self.get("predictionCol"),
                               (best + 1).astype(np.float64))
                  .with_column("actionProbabilities", probs))

    def evaluate_policy(self, df: DataFrame,
                        action_col: str = "chosenAction",
                        prob_col: str = "probability",
                        reward_col: str = "reward") -> Dict[str, float]:
        """Off-policy estimates of this model's policy on logged data."""
        scored = self.transform(df)
        act = np.asarray(df.col(action_col), dtype=np.int64) - 1
        plog = np.asarray(df.col(prob_col), dtype=np.float64)
        reward = np.asarray(df.col(reward_col), dtype=np.float64)
        ppred = np.asarray(scored["actionProbabilities"])[
            np.arange(len(act)), act]
        est = BanditEstimator()
        for a, b, c in zip(plog, reward, ppred):
            est.add(a, b, c)
        return est.get()
