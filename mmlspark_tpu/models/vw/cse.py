"""DSJson decoding + counterfactual success experimentation (CSE).

Parity: vw/.../VowpalWabbitDSJsonTransformer.scala:17 (decision-service
json lines -> columns: EventId, probabilityLogged, chosenActionIndex,
rewards struct, probabilities/actions arrays) and
VowpalWabbitCSETransformer.scala:18 (per-stratum counterfactual metrics:
importance-weight stats + IPS/SNIPS/CressieRead(+interval) per reward
column, importance weight clipped to [minImportanceWeight,
maxImportanceWeight]).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasInputCol, Param, Params, ge, to_float, to_list, to_str,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.models.vw.policyeval import (
    cressie_read,
    cressie_read_interval,
    ips,
    snips,
)


class VowpalWabbitDSJsonTransformer(Transformer):
    dsJsonColumn = Param("dsJsonColumn", "column of dsjson strings", to_str,
                         default="value")
    rewards = Param("rewards", "alias -> json field map for rewards",
                    is_complex=True, default={"reward": "_label_cost"})

    def _transform(self, dataset: DataFrame) -> DataFrame:
        lines = dataset.col(self.get("dsJsonColumn"))
        rewards_map = dict(self.get("rewards"))
        n = len(lines)
        event_ids = np.empty(n, dtype=object)
        prob_logged = np.zeros(n)
        chosen_idx = np.zeros(n, np.int64)
        probabilities = np.empty(n, dtype=object)
        actions = np.empty(n, dtype=object)
        reward_cols: Dict[str, np.ndarray] = {
            alias: np.zeros(n) for alias in rewards_map}
        for i, line in enumerate(lines):
            doc = json.loads(line)
            event_ids[i] = doc.get("EventId", "")
            prob_logged[i] = float(doc.get("_label_probability", 0.0))
            # dsjson actions are 1-based with the chosen action first
            acts = doc.get("_labelIndex", None)
            chosen_idx[i] = int(acts) if acts is not None \
                else int(doc.get("_label_Action", 1)) - 1
            probabilities[i] = list(doc.get("p", []))
            actions[i] = list(doc.get("a", []))
            for alias, field in rewards_map.items():
                v = doc.get(field, 0.0)
                # _label_cost is a cost: reward = -cost, as the reference's
                # downstream consumers negate it
                reward_cols[alias][i] = float(v)
        out = dataset.with_columns({
            "EventId": event_ids,
            "probabilityLogged": prob_logged,
            "chosenActionIndex": chosen_idx,
            "probabilities": probabilities,
            "actions": actions,
        })
        reward_struct = np.empty(n, dtype=object)
        for i in range(n):
            reward_struct[i] = {alias: float(reward_cols[alias][i])
                                for alias in rewards_map}
        return out.with_column("rewards", reward_struct)


class VowpalWabbitCSETransformer(Transformer):
    minImportanceWeight = Param("minImportanceWeight",
                                "importance-weight lower clip", to_float,
                                ge(0), default=0.0)
    maxImportanceWeight = Param("maxImportanceWeight",
                                "importance-weight upper clip", to_float,
                                ge(0), default=100.0)
    metricsStratificationCols = Param("metricsStratificationCols",
                                      "stratify metrics by these columns",
                                      to_list(to_str), default=[])

    def _metrics(self, sub: DataFrame) -> Dict[str, Any]:
        p_log = np.asarray(sub.col("probabilityLogged"), np.float64)
        p_pred = np.asarray(sub.col("probabilityPredicted"), np.float64)
        # diagnostics are computed on RAW importance weights — the clip
        # bounds apply inside the estimators only, as in the reference
        # (raw w stats, clipped w in CressieRead/Interval)
        w = p_pred / np.maximum(p_log, 1e-12)
        out: Dict[str, Any] = {
            "exampleCount": float(len(w)),
            "probabilityPredictedNonZeroCount": float((p_pred > 0).sum()),
            "minimumImportanceWeight": float(w.min()) if len(w) else 0.0,
            "maximumImportanceWeight": float(w.max()) if len(w) else 0.0,
            "averageImportanceWeight": float(w.mean()) if len(w) else 0.0,
            "averageSquaredImportanceWeight": float((w ** 2).mean())
            if len(w) else 0.0,
            "proportionOfMaximumImportanceWeight":
                float(w.max() / max(len(w), 1)) if len(w) else 0.0,
            "quantilesOfImportanceWeight":
                np.quantile(w, [0.25, 0.5, 0.75, 0.95]).tolist()
                if len(w) else [],
        }
        rewards = sub.col("rewards")
        aliases = list(rewards[0].keys()) if len(rewards) else []
        w_min = self.get("minImportanceWeight")
        w_max = self.get("maxImportanceWeight")
        for alias in aliases:
            r = np.asarray([d[alias] for d in rewards], np.float64)
            # per-column reward range bounds the interval search, as the
            # reference's min_reward/max_reward aggregates do
            r_lo = float(r.min()) if len(r) else 0.0
            r_hi = float(r.max()) if len(r) else 1.0
            if r_hi <= r_lo:
                r_hi = r_lo + 1.0
            lo, hi = cressie_read_interval(p_log, r, p_pred,
                                           reward_min=r_lo, reward_max=r_hi,
                                           w_min=w_min, w_max=w_max)
            out[f"{alias}_ips"] = ips(p_log, r, p_pred,
                                      w_min=w_min, w_max=w_max)
            out[f"{alias}_snips"] = snips(p_log, r, p_pred,
                                          w_min=w_min, w_max=w_max)
            out[f"{alias}_cressieRead"] = cressie_read(
                p_log, r, p_pred, w_min=w_min, w_max=w_max)
            out[f"{alias}_cressieReadIntervalLow"] = lo
            out[f"{alias}_cressieReadIntervalHigh"] = hi
        return out

    def _transform(self, dataset: DataFrame) -> DataFrame:
        strat = self.get("metricsStratificationCols")
        if not strat:
            return DataFrame.from_rows([self._metrics(dataset)])
        # composite stratification key
        keys = [" | ".join(str(dataset.col(c)[i]) for c in strat)
                for i in range(dataset.num_rows)]
        tmp = dataset.with_column("__stratum__", np.asarray(keys,
                                                            dtype=object))
        rows = []
        for key, idx in tmp.group_indices("__stratum__").items():
            m = self._metrics(dataset.take_rows(idx))
            m["stratum"] = key
            rows.append(m)
        return DataFrame.from_rows(rows)
