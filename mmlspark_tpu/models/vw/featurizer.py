"""VW-style hashed featurization.

Parity with vw/.../VowpalWabbitFeaturizer.scala:1 (230 LoC) and its
per-type featurizers (featurizer/*.scala): Spark Rows become hashed
(index, value) pairs without going through VW's string format. Here the
output is the TPU-friendly fixed-width sparse format: two vector columns
``<out>_idx`` (int32 hashed indices) and ``<out>_val`` (float32 values),
padded to a static per-row width — dense gathers on device, no CSR.

Hashing matches VW conventions: numeric col -> value at hash(colName);
string col -> 1.0 at hash(colName + value); vector col -> value at
(hash(colName) + slot) & mask.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasInputCols,
    HasOutputCol,
    Param,
    ge,
    to_bool,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.ops.hashing import hash_feature, interact_hash, mask_bits


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    numBits = Param("numBits", "hash-space bits", to_int, ge(1), default=18)
    seed = Param("seed", "murmur seed", to_int, default=0)
    stringSplit = Param("stringSplit", "split string cols on whitespace into "
                        "multiple hashed tokens", to_bool, default=False)
    sumCollisions = Param("sumCollisions", "sum values on hash collision "
                          "(else last wins; summing matches VW)", to_bool,
                          default=True)
    prefixStringsWithColumnName = Param(
        "prefixStringsWithColumnName",
        "prefix hashed string tokens with the column name", to_bool,
        default=True)

    def _transform(self, df: DataFrame) -> DataFrame:
        bits = self.get("numBits")
        seed = self.get("seed")
        cols = self.get("inputCols")
        if not cols:
            raise ValueError("inputCols must be set")
        n = df.num_rows
        idx_parts: List[np.ndarray] = []
        val_parts: List[np.ndarray] = []
        for name in cols:
            arr = df.col(name)
            if arr.ndim == 2:  # vector column: base hash + slot index
                base = hash_feature(name, seed)
                idx = mask_bits(base + np.arange(arr.shape[1]), bits)
                idx_parts.append(np.broadcast_to(idx, arr.shape).copy())
                val_parts.append(arr.astype(np.float32))
            elif arr.dtype == object:  # string column
                prefix = name if self.get("prefixStringsWithColumnName") else ""
                if self.get("stringSplit"):
                    rows_idx, rows_val, width = [], [], 0
                    toks_per_row = [str(v).split() for v in arr]
                    width = max((len(t) for t in toks_per_row), default=1) or 1
                    iout = np.zeros((n, width), dtype=np.int32)
                    vout = np.zeros((n, width), dtype=np.float32)
                    for i, toks in enumerate(toks_per_row):
                        for j, t in enumerate(toks):
                            iout[i, j] = mask_bits(
                                hash_feature(prefix + t, seed), bits)
                            vout[i, j] = 1.0
                    idx_parts.append(iout)
                    val_parts.append(vout)
                else:
                    iout = np.array(
                        [mask_bits(hash_feature(prefix + str(v), seed), bits)
                         for v in arr], dtype=np.int32)[:, None]
                    idx_parts.append(iout)
                    val_parts.append(np.ones((n, 1), dtype=np.float32))
            else:  # numeric column: value at hash(name)
                h = mask_bits(hash_feature(name, seed), bits)
                idx_parts.append(np.full((n, 1), h, dtype=np.int32))
                val_parts.append(arr.astype(np.float32)[:, None])
        idx = np.concatenate(idx_parts, axis=1)
        val = np.concatenate(val_parts, axis=1)
        out = self.get("outputCol")
        return (df.with_column(f"{out}_idx", idx)
                  .with_column(f"{out}_val", val)
                  .with_metadata(f"{out}_idx", {"numBits": bits}))


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Quadratic namespace interactions (VowpalWabbitInteractions.scala:1):
    cross two hashed feature blocks into a new (idx, val) block."""

    numBits = Param("numBits", "hash-space bits", to_int, ge(1), default=18)

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("inputCols")
        if not cols or len(cols) != 2:
            raise ValueError("VowpalWabbitInteractions needs exactly 2 "
                             "inputCols (hashed blocks)")
        bits = self.get("numBits")
        a_idx, a_val = df.col(f"{cols[0]}_idx"), df.col(f"{cols[0]}_val")
        b_idx, b_val = df.col(f"{cols[1]}_idx"), df.col(f"{cols[1]}_val")
        n, wa = a_idx.shape
        wb = b_idx.shape[1]
        # all pairs (wa x wb) per row
        ii = interact_hash(
            np.repeat(a_idx, wb, axis=1), np.tile(b_idx, (1, wa)), bits)
        vv = (np.repeat(a_val, wb, axis=1) * np.tile(b_val, (1, wa)))
        out = self.get("outputCol")
        return (df.with_column(f"{out}_idx", ii.astype(np.int32))
                  .with_column(f"{out}_val", vv.astype(np.float32)))


def concat_feature_blocks(df: DataFrame, blocks: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Stack several hashed blocks into one (idx, val) pair."""
    idx = np.concatenate([df.col(f"{b}_idx") for b in blocks], axis=1)
    val = np.concatenate([df.col(f"{b}_val") for b in blocks], axis=1)
    return idx.astype(np.int32), val.astype(np.float32)
