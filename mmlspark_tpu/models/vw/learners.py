"""VW-parity online linear learners on TPU.

Replaces the reference's JNI path into VW C++ (vw/.../
VowpalWabbitBaseLearner.scala:135-188, VowpalWabbitNative) with a
jit-compiled minibatched online-SGD scan over hashed features:

  - AdaGrad per-weight adaptivity (VW ``--adaptive``), invariant-style
    power_t learning-rate decay, L1/L2;
  - multiple passes with weight averaging across the ``dp`` mesh axis at
    pass boundaries — `jax.lax.pmean` replacing VW's spanning-tree
    allreduce (VowpalWabbitClusterUtil.scala:15-43,
    VowpalWabbitSyncSchedule.scala:15-72);
  - progressive (one-step-ahead) predictions
    (VowpalWabbitBaseProgressive.scala:1);
  - ``batchSize=1`` reproduces exact example-by-example online updates;
    larger batches trade fidelity for TPU throughput.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasLabelCol,
    HasPredictionCol,
    HasWeightCol,
    Param,
    ge,
    gt,
    one_of,
    to_bool,
    to_float,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model


# ---------------------------------------------------------------------------
# Device-side SGD core
# ---------------------------------------------------------------------------

def _loss_grad(loss: str, pred, y, quantile_tau: float = 0.5):
    import jax
    import jax.numpy as jnp

    if loss == "squared":
        return pred - y
    if loss == "logistic":
        # y in {0,1}; VW uses {-1,1} internally — same gradient
        return jax.nn.sigmoid(pred) - y
    if loss == "hinge":
        s = 2.0 * y - 1.0
        return jnp.where(s * pred < 1.0, -s, 0.0)
    if loss == "quantile":
        d = pred - y
        return jnp.where(d >= 0, 1.0 - quantile_tau, -quantile_tau)
    raise ValueError(f"unknown loss {loss!r}")



def sanitize_values(val: np.ndarray) -> np.ndarray:
    """Non-finite feature values drop to 0 (VW semantics: an absent
    feature contributes nothing); one inf/NaN would otherwise poison
    every weight through the SGD update or every margin at scoring."""
    finite = np.isfinite(val)
    if finite.all():
        return val
    return np.where(finite, val, 0.0).astype(val.dtype)

_SGD_JIT_CACHE: OrderedDict = OrderedDict()
_SGD_JIT_CACHE_MAX = 32  # LRU bound: sweeps must not leak executables


def jitted_sgd_train(*args, **kwargs):
    """``jax.jit(make_sgd_train(...))`` memoized by config (bounded
    LRU): repeated fits with the same hyperparameters reuse one
    traced+compiled update function instead of re-tracing per fit."""
    import jax
    key = (args, tuple(sorted(kwargs.items())))
    if key in _SGD_JIT_CACHE:
        _SGD_JIT_CACHE.move_to_end(key)
        return _SGD_JIT_CACHE[key]
    fn = jax.jit(make_sgd_train(*args, **kwargs))
    _SGD_JIT_CACHE[key] = fn
    while len(_SGD_JIT_CACHE) > _SGD_JIT_CACHE_MAX:
        _SGD_JIT_CACHE.popitem(last=False)
    return fn


def _invariant_delta_p(loss: str, pred, y, t_budget, quantile_tau):
    """Closed-form importance-aware prediction shift (Karampatziakis &
    Langford 2011, VW loss_functions.cc getUpdate): the limit of
    infinitely many infinitesimal gradient steps whose total learning
    "time" is ``t_budget`` = lr * importance * x'Rx. Never overshoots
    the label, no matter how large the rate or importance weight."""
    import jax
    import jax.numpy as jnp

    if loss == "squared":
        # dp/dt = -(p - y)  =>  p(T) = y + (p0 - y) e^-T
        return (y - pred) * (1.0 - jnp.exp(-t_budget))
    if loss == "logistic":
        # in margin space s = y_pm * p: ds/dt = sigmoid(-s), whose
        # flow satisfies s + e^s = s0 + e^s0 + T; solve by Newton
        # (monotone convex), exp clamped (for s>30 the update is ~0)
        y_pm = 2.0 * y - 1.0
        s0 = y_pm * pred
        # clamp c finite (t_budget=inf would NaN the solver) — the
        # root only grows logarithmically in c anyway
        c = jnp.minimum(s0 + jnp.exp(jnp.minimum(s0, 30.0)) + t_budget,
                        1e30)
        init = jnp.where(c > 1.0, jnp.log(jnp.maximum(c, 1e-6)), s0)

        def newton(s, _):
            es = jnp.exp(jnp.minimum(s, 30.0))
            return s - (s + es - c) / (1.0 + es), None

        s1, _ = jax.lax.scan(newton, init, None, length=8)
        # bracket the root: the flow is monotone non-decreasing (>= s0)
        # and s* < log(c) for large c, so log(c)+1 is a safe upper
        # bound — without it the exp clamp above lets Newton walk
        # arbitrarily past the root once the margin exceeds 30
        upper = jnp.log(jnp.maximum(c, 1e-6)) + 1.0
        s1 = jnp.clip(s1, s0, jnp.maximum(upper, s0))
        return (s1 - s0) * y_pm
    if loss == "hinge":
        # constant unit slope toward margin 1, then stops
        y_pm = 2.0 * y - 1.0
        s0 = y_pm * pred
        return y_pm * jnp.minimum(t_budget, jnp.maximum(1.0 - s0, 0.0))
    if loss == "quantile":
        # constant slope tau / (1-tau) toward the label, never past it
        d = pred - y
        slope = jnp.where(d >= 0, 1.0 - quantile_tau, quantile_tau)
        return -jnp.sign(d) * jnp.minimum(slope * t_budget, jnp.abs(d))
    raise ValueError(f"unknown loss {loss!r}")


def make_sgd_train(num_weights: int, loss: str, learning_rate: float,
                   power_t: float, initial_t: float, adaptive: bool,
                   l1: float, l2: float, normalized: bool = False,
                   invariant: bool = False,
                   quantile_tau: float = 0.5, progressive: bool = False):
    """Build jittable (w, g2, scale, n_acc, bias, t0, idx, val, y, wt)
    -> updated state scanning over leading batch dim. Shapes: idx/val
    (B, W), y/wt (B,).

    ``normalized`` adds VW's ``--normalized`` per-feature scale
    accumulators (VowpalWabbitBaseLearner.scala driving vw gd.cc; the
    NAG algorithm of Ross/Mineiro/Langford 2013): ``scale_i`` tracks
    max |x_i| seen, weights are squashed when a feature's scale grows,
    per-feature learning rates divide by the scale, and a global
    ``(t/N)^power_t`` factor (N = accumulated normalized squared
    norms) restores the effective rate. Net effect: predictions are
    invariant to per-feature rescaling of the input — pinned by
    tests/vw/test_vw.py::test_normalized_scale_invariance.

    ``invariant`` adds VW's ``--invariant`` importance-aware updates
    (the remaining member of native VW's default
    adaptive+normalized+invariant trio): per example, the closed-form
    prediction shift of :func:`_invariant_delta_p` is distributed over
    the features proportionally to ``x_i * r_i`` (r = the per-feature
    rate metric from adaptive/normalized state), so huge importance
    weights or learning rates saturate at the label instead of
    overshooting — pinned by
    tests/vw/test_vw.py::test_invariant_importance_aware. Exact online
    semantics at batchSize=1; larger batches apply the per-row closed
    form against the batch-start weights (minibatch approximation,
    same contract as the gradient path).
    """
    import jax
    import jax.numpy as jnp

    def step(carry, batch):
        w, g2, s, n_acc, bias, t = carry
        idx, val, y, wt = batch
        batch_n = jnp.maximum(jnp.sum((wt > 0)), 1)
        if normalized:
            # observe new per-feature scales (pad rows excluded); when
            # a scale grows, squash the weight trained at the old scale
            # (one power of the ratio with adaptive — its sqrt(G) term
            # carries the other — else two, per the NAG paper)
            av = (jnp.abs(val) * (wt[:, None] > 0)).reshape(-1)
            # one scatter-max straight onto s (av >= 0 and s >= 0, so
            # this equals max(s, per-feature batch max) without a
            # num_weights-sized temporary in the scanned hot loop)
            s_new = s.at[idx.reshape(-1)].max(av)
            ratio = jnp.where(s_new > 0,
                              jnp.where(s > 0,
                                        s / jnp.maximum(s_new, 1e-30),
                                        1.0),
                              1.0)
            w = w * (ratio if adaptive else ratio * ratio)
            s = s_new
            sj = s[idx]
            xn2 = jnp.where(sj > 0,
                            (val / jnp.maximum(sj, 1e-30)) ** 2, 0.0)
            n_acc = n_acc + jnp.sum(
                jnp.sum(xn2, axis=-1) * (wt > 0)) / batch_n
        pred = jnp.sum(w[idx] * val, axis=-1) + bias
        dldp = _loss_grad(loss, pred, y, quantile_tau) * wt
        gw = jnp.zeros_like(w).at[idx.reshape(-1)].add(
            (dldp[:, None] * val).reshape(-1) / batch_n)
        gb = jnp.sum(dldp) / batch_n
        if l2:
            gw = gw + l2 * w
        lr_t = learning_rate * (initial_t / (initial_t + t)) ** power_t
        if normalized:
            # bias behaves as a constant feature with scale 1, so the
            # global factor applies to it too
            nf = (jnp.maximum(t + 1.0, 1.0)
                  / jnp.maximum(n_acc, 1e-8)) ** power_t
            lr_t = lr_t * nf
        # per-feature rate metric r: the update direction is always
        # gradient * r (gradient path) or x * r (invariant path)
        if adaptive and normalized:
            # accumulate AdaGrad state in NORMALIZED gradient units
            # (g/s is invariant to per-feature rescaling), so the
            # 1e-8 epsilon compares against a scale-free quantity —
            # accumulating raw g^2 ~ c^2 would let the epsilon
            # distort small-scale features and break invariance
            sg = jnp.where(s > 0, s, 1.0)
            gn = gw / sg
            g2 = g2 + gn * gn
            r = 1.0 / (sg * sg * jnp.sqrt(g2 + 1e-8))
        elif adaptive:
            g2 = g2 + gw * gw
            r = 1.0 / jnp.sqrt(g2 + 1e-8)
        elif normalized:
            r = 1.0 / jnp.where(s > 0, s * s, 1.0)
        else:
            r = None  # unit rates; avoid a num_weights-sized constant
        if invariant:
            # closed-form importance-aware step: shift the prediction
            # by delta_p (never past the label) and distribute it over
            # the example's features as Delta w_i = delta_p x_i r_i /
            # (x'Rx), so sum_i Delta w_i x_i = delta_p exactly. The
            # bias rides as a constant feature at unit rate (the +1).
            rj = jnp.ones_like(val) if r is None else r[idx]
            xrx = jnp.sum(val * val * rj, axis=-1) + 1.0
            t_budget = lr_t * wt * xrx
            delta_p = _invariant_delta_p(loss, pred, y, t_budget,
                                         quantile_tau)
            coeff = delta_p / xrx
            w = w + jnp.zeros_like(w).at[idx.reshape(-1)].add(
                (coeff[:, None] * val * rj).reshape(-1) / batch_n)
            if l2:
                w = w - lr_t * l2 * (w if r is None else w * r)
            bias = bias + jnp.sum(coeff) / batch_n
        else:
            w = w - lr_t * (gw if r is None else gw * r)
            bias = bias - lr_t * gb
        if l1:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr_t * l1, 0.0)
        out = pred if progressive else jnp.zeros(())
        return (w, g2, s, n_acc, bias, t + 1.0), out

    def run(w, g2, s, n_acc, bias, t0, idx, val, y, wt):
        (w, g2, s, n_acc, bias, t), preds = jax.lax.scan(
            step, (w, g2, s, n_acc, bias, t0), (idx, val, y, wt))
        return w, g2, s, n_acc, bias, t, preds

    return run


def _batchify(idx: np.ndarray, val: np.ndarray, y: np.ndarray,
              wt: np.ndarray, batch_size: int):
    """Pad rows to a batch multiple (padding weight 0) and reshape to
    (num_batches, batch, ...)."""
    n, wdt = idx.shape
    nb = (n + batch_size - 1) // batch_size
    pad = nb * batch_size - n
    if pad:
        idx = np.concatenate([idx, np.zeros((pad, wdt), idx.dtype)])
        val = np.concatenate([val, np.zeros((pad, wdt), val.dtype)])
        y = np.concatenate([y, np.zeros(pad, y.dtype)])
        wt = np.concatenate([wt, np.zeros(pad, wt.dtype)])
    return (idx.reshape(nb, batch_size, wdt), val.reshape(nb, batch_size, wdt),
            y.reshape(nb, batch_size), wt.reshape(nb, batch_size))


# ---------------------------------------------------------------------------
# Params / base classes
# ---------------------------------------------------------------------------

class _VWParams(HasLabelCol, HasWeightCol, HasPredictionCol):
    featuresCol = Param("featuresCol", "hashed feature block prefix (expects "
                        "<name>_idx / <name>_val columns from "
                        "VowpalWabbitFeaturizer)", to_str, default="features")
    numBits = Param("numBits", "hash-space bits", to_int, ge(1), default=18)
    numPasses = Param("numPasses", "passes over the data", to_int, ge(1),
                      default=1)
    learningRate = Param("learningRate", "base learning rate", to_float, gt(0),
                         default=0.5)
    powerT = Param("powerT", "lr decay exponent", to_float, ge(0), default=0.5)
    initialT = Param("initialT", "lr schedule offset", to_float, gt(0),
                     default=1.0)
    adaptive = Param("adaptive", "AdaGrad per-weight rates (--adaptive)",
                     to_bool, default=False)
    normalized = Param(
        "normalized", "per-feature scale-invariant updates "
        "(--normalized)", to_bool, default=False)
    invariant = Param(
        "invariant", "importance-aware closed-form updates that never "
        "overshoot the label (--invariant); adaptive+normalized+"
        "invariant together reproduce native VW's default update "
        "family", to_bool, default=False)
    l1 = Param("l1", "L1 regularization", to_float, ge(0), default=0.0)
    l2 = Param("l2", "L2 regularization", to_float, ge(0), default=0.0)
    batchSize = Param("batchSize", "rows per online update (1 = exact "
                      "example-wise VW semantics)", to_int, ge(1), default=16)
    interPassSync = Param("interPassSync", "average weights across the dp "
                          "mesh axis at pass boundaries", to_bool, default=True)
    syncScheduleRows = Param(
        "syncScheduleRows", "also sync within a pass after every N rows "
        "processed globally (0 = pass boundaries only) — the row-count "
        "sync schedule, VowpalWabbitSyncSchedule.scala:15-72", to_int,
        ge(0), default=0)
    shufflePerPass = Param("shufflePerPass", "reshuffle batch order between "
                           "passes (seeded; VW replays its cache in order, "
                           "so default off for parity)", to_bool,
                           default=False)
    seed = Param("seed", "seed", to_int, default=0)
    checkpointDir = Param(
        "checkpointDir", "directory for pass-boundary optimizer-state "
        "checkpoints (weights + AdaGrad/normalization accumulators + "
        "schedule counters, the --save_resume state); a restarted fit "
        "resumes from the latest one", to_str)
    checkpointInterval = Param(
        "checkpointInterval", "save a checkpoint every n passes (0 = "
        "off; requires checkpointDir)", to_int, ge(0), default=0)
    passThroughArgs = Param("passThroughArgs", "VW-style argument string; "
                            "recognized flags are mapped onto params "
                            "(ParamsStringBuilder analog)", to_str, default="")

    def _apply_pass_through(self) -> Dict[str, Any]:
        """Parse a VW arg string into param overrides (the reverse of the
        reference's ParamsStringBuilder rendering)."""
        args = (self.get("passThroughArgs") or "").split()
        out: Dict[str, Any] = {}
        i = 0
        while i < len(args):
            a = args[i]
            def take():
                nonlocal i
                i += 1
                return args[i]
            if a in ("--adaptive",):
                out["adaptive"] = True
            elif a == "--normalized":
                out["normalized"] = True
            elif a == "--invariant":
                out["invariant"] = True
            elif a in ("-l", "--learning_rate"):
                out["learningRate"] = float(take())
            elif a == "--power_t":
                out["powerT"] = float(take())
            elif a == "--initial_t":
                out["initialT"] = float(take())
            elif a == "--l1":
                out["l1"] = float(take())
            elif a == "--l2":
                out["l2"] = float(take())
            elif a in ("-b", "--bit_precision"):
                out["numBits"] = int(take())
            elif a == "--passes":
                out["numPasses"] = int(take())
            i += 1
        return out


class _VWBaseLearner(Estimator, _VWParams):
    _loss = "squared"
    _mesh = None

    def set_mesh(self, mesh):
        self._mesh = mesh
        return self

    def _get_features(self, df: DataFrame) -> Tuple[np.ndarray, np.ndarray]:
        base = self.get("featuresCol")
        if f"{base}_idx" in df:
            idx = df.col(f"{base}_idx").astype(np.int32)
            val = df.col(f"{base}_val").astype(np.float32)
        else:
            # dense vector column fallback: identity indexing
            x = df.col(base)
            if x.ndim != 2:
                raise ValueError(
                    f"featuresCol {base!r}: need <{base}_idx/_val> "
                    f"hashed columns or a dense vector column")
            idx = np.broadcast_to(
                np.arange(x.shape[1], dtype=np.int32), x.shape).copy()
            val = x.astype(np.float32)
        return idx, sanitize_values(val)

    def _train_weights(self, df: DataFrame, progressive: bool = False,
                       labels_override=None, features_override=None):
        import jax
        import jax.numpy as jnp

        overrides = self._apply_pass_through()
        get = lambda k: overrides.get(k, self.get(k))
        # overrides let one-vs-all reuse one feature extraction across
        # its K sub-fits (only the label vector differs)
        idx, val = (features_override if features_override is not None
                    else self._get_features(df))
        y = (np.asarray(labels_override, dtype=np.float32)
             if labels_override is not None
             else np.asarray(df.col(self.get("labelCol")),
                             dtype=np.float32))
        wt = (np.asarray(df.col(self.get("weightCol")), dtype=np.float32)
              if self.is_set("weightCol") else np.ones(len(y), np.float32))
        num_weights = 1 << get("numBits")
        if int(idx.max(initial=0)) >= num_weights:
            raise ValueError("feature indices exceed numBits hash space; "
                             "featurizer and learner numBits must match")
        sgd_args = (num_weights, self._loss, get("learningRate"),
                    get("powerT"), get("initialT"), get("adaptive"),
                    get("l1"), get("l2"))
        sgd_kwargs = dict(normalized=get("normalized"),
                          invariant=get("invariant"), quantile_tau=0.5,
                          progressive=progressive)
        bidx, bval, by, bwt = _batchify(idx, val, y, wt, get("batchSize"))
        mesh = self._mesh
        if mesh is not None and self.get("interPassSync"):
            # sharded online training: each dp shard scans its own batch
            # stream, weights are pmean-averaged at the pass boundary —
            # the VW spanning-tree allreduce analog
            # (VowpalWabbitSyncSchedule.scala:15-72)
            from mmlspark_tpu.core.jax_compat import (pcast_varying,
                                                       shard_map)
            from jax.sharding import PartitionSpec as P

            from mmlspark_tpu.parallel.mesh import DATA_AXIS, axis_size

            run = make_sgd_train(*sgd_args, **sgd_kwargs)
            ndev = axis_size(mesh, DATA_AXIS)
            nb = bidx.shape[0]
            nb_pad = ((nb + ndev - 1) // ndev) * ndev
            if nb_pad != nb:
                def padb(a):
                    return np.concatenate(
                        [a, np.zeros((nb_pad - nb,) + a.shape[1:], a.dtype)])
                bidx, bval, by, bwt = map(padb, (bidx, bval, by, bwt))

            def sharded_pass(w, g2, s, n_acc, bias, t, bi, bv, byy, bw):
                # mark the replicated carry as device-varying so the scan
                # carry type stays consistent once batch data flows in
                w, g2, s, n_acc, bias, t = pcast_varying(
                    (w, g2, s, n_acc, bias, t), (DATA_AXIS,))
                w, g2, s, n_acc, bias, t, preds = run(
                    w, g2, s, n_acc, bias, t, bi, bv, byy, bw)
                w = jax.lax.pmean(w, DATA_AXIS)
                g2 = jax.lax.pmean(g2, DATA_AXIS)
                # scales are maxima, not means: pmax keeps the squash
                # bound valid on every shard after the sync
                s = jax.lax.pmax(s, DATA_AXIS)
                n_acc = jax.lax.pmean(n_acc, DATA_AXIS)
                bias = jax.lax.pmean(bias, DATA_AXIS)
                t = jax.lax.pmean(t, DATA_AXIS)
                return w, g2, s, n_acc, bias, t, preds

            batch_spec = P(DATA_AXIS)
            run_pass = jax.jit(shard_map(
                sharded_pass, mesh=mesh,
                in_specs=(P(), P(), P(), P(), P(), P(), batch_spec,
                          batch_spec, batch_spec, batch_spec),
                out_specs=(P(), P(), P(), P(), P(), P(), batch_spec)))
        else:
            run_pass = jitted_sgd_train(*sgd_args, **sgd_kwargs)
        init = getattr(self, "_initial_model", None)
        if init is not None and init.weights is not None:
            iw = np.asarray(init.weights)
            if iw.ndim != 1:
                raise ValueError(
                    "initial model carries multi-bank (one-vs-all) "
                    "weights; only single-bank models can warm start "
                    "a single-bank learner")
            if len(iw) != num_weights:
                raise ValueError(
                    f"initial model has {len(iw)} weights; this "
                    f"learner's numBits gives {num_weights} — they must "
                    "match (same hash space)")
            w = jnp.asarray(iw, dtype=jnp.float32)
            bias = jnp.asarray(np.float32(init.bias))
            ig2 = getattr(init, "g2", None)
            isc = getattr(init, "scale", None)
            g2 = (jnp.asarray(ig2, jnp.float32) if ig2 is not None
                  else jnp.zeros(num_weights, dtype=jnp.float32))
            s = (jnp.asarray(isc, jnp.float32) if isc is not None
                 else jnp.zeros(num_weights, dtype=jnp.float32))
            # resume the schedule counters too (VW --save_resume
            # persists example counters so lr decay and the normalized
            # global factor continue instead of restarting hot)
            n_acc = jnp.asarray(np.float32(getattr(init, "n_acc", 0.0)
                                           or 0.0))
            t = jnp.asarray(np.float32(getattr(init, "t_count", 0.0)
                                       or 0.0))
        else:
            w = jnp.zeros(num_weights, dtype=jnp.float32)
            g2 = jnp.zeros(num_weights, dtype=jnp.float32)
            s = jnp.zeros(num_weights, dtype=jnp.float32)
            bias = jnp.zeros(())
            n_acc = jnp.zeros(())
            t = jnp.ones(()) * 0.0
        all_preds = []
        nb_total = bidx.shape[0]
        ndev = 1
        if mesh is not None and self.get("interPassSync"):
            from mmlspark_tpu.parallel.mesh import DATA_AXIS, axis_size
            ndev = axis_size(mesh, DATA_AXIS)
        # within-pass sync schedule: each run_pass call ends in a weight
        # average, so slicing the batch stream into segments of
        # ~syncScheduleRows rows reproduces the row-count schedule
        sync_rows = get("syncScheduleRows")
        if sync_rows and ndev > 1:
            seg = max(round(sync_rows / get("batchSize") / ndev), 1) * ndev
        else:
            seg = nb_total
        rng_order = np.random.default_rng(get("seed"))
        from mmlspark_tpu.core.timer import StopWatch
        from mmlspark_tpu.parallel.prefetch import (BatchPrefetcher,
                                                    resolve_prefetch_depth)
        prefetch_async = resolve_prefetch_depth() > 0
        watch = StopWatch()
        pass_losses: List[float] = []
        # -- pass-boundary checkpoints + elastic restart ----------------
        # The VW analog of the GBDT elastic-restart path: the full
        # resumable state (weights, AdaGrad g2, normalization scales,
        # bias, schedule counters t/n_acc — exactly what VW
        # --save_resume persists) snapshots through the shared
        # serialize.save_checkpoint protocol (atomic write-rename,
        # monotonic pass tag, config-hash manifest). A resumed fit
        # continues bit-exactly: the state is the entire carry of the
        # pass loop. Progressive mode never checkpoints (its product is
        # the pass-0 prediction stream, not the final weights).
        ckpt_every = 0 if progressive else get("checkpointInterval")
        start_pass = 0
        ckpt_dir = fhash = None
        if ckpt_every:
            if not self.is_set("checkpointDir"):
                raise ValueError(
                    "checkpointInterval requires checkpointDir")
            from mmlspark_tpu.core.serialize import (
                load_latest_checkpoint, save_checkpoint)
            ckpt_dir = self.get("checkpointDir")
            fhash = self._checkpoint_fingerprint(
                sgd_args, sgd_kwargs, get, idx, val, y, wt, init)
            latest = load_latest_checkpoint(ckpt_dir, fhash)
            if latest is not None:
                start_pass, st = latest
                if start_pass > get("numPasses"):
                    raise ValueError(
                        f"checkpoint at pass {start_pass} in {ckpt_dir} "
                        f"exceeds numPasses={get('numPasses')}; clear "
                        "the directory or raise numPasses")
                w = jnp.asarray(st["weights"], jnp.float32)
                g2 = jnp.asarray(st["g2"], jnp.float32)
                s = jnp.asarray(st["scale"], jnp.float32)
                bias = jnp.asarray(np.float32(st["bias"]))
                n_acc = jnp.asarray(np.float32(st["n_acc"]))
                t = jnp.asarray(np.float32(st["t_count"]))
                pass_losses = [float(x) for x in st.get("passLosses", [])]
        with watch.measure():
            for p in range(get("numPasses")):
                if p > 0 and self.get("shufflePerPass"):
                    # replayed even for checkpointed-and-skipped passes
                    # so the shuffle RNG stream (and therefore the data
                    # order of every later pass) matches the
                    # uninterrupted run exactly
                    order = rng_order.permutation(nb_total)
                    bidx, bval = bidx[order], bval[order]
                    by, bwt = by[order], bwt[order]
                if p < start_pass:
                    continue  # completed before the restart
                preds_parts = []

                def pass_segments(bi=bidx, bv=bval, yy=by, ww=bwt):
                    # bound defaults: the shuffle reassigns the outer
                    # names each pass, and the producer thread must
                    # keep reading THIS pass's arrays
                    for b0 in range(0, nb_total, seg):
                        yield (bi[b0:b0 + seg], bv[b0:b0 + seg],
                               yy[b0:b0 + seg], ww[b0:b0 + seg])

                def place_segment(segt):
                    return tuple(jnp.asarray(a) for a in segt)

                # one prefetcher per pass: host slicing + the
                # device transfer overlap the previous segment's
                # run_pass dispatch
                with BatchPrefetcher(pass_segments(), place_segment,
                                     label="vw.pass") as pf:
                    prefetch_async = prefetch_async and pf.async_mode
                    for si, sv, sy, sw in pf:
                        if mesh is not None and self.get("interPassSync"):
                            # host boundary of the cross-shard weight
                            # average (the VW spanning-tree allreduce)
                            from mmlspark_tpu.core.faults import \
                                fault_point
                            fault_point("allreduce")
                        w, g2, s, n_acc, bias, t, preds = run_pass(
                            w, g2, s, n_acc, bias, t, si, sv, sy, sw)
                        if progressive and p == 0:
                            preds_parts.append(
                                np.asarray(preds).reshape(-1))
                if progressive and p == 0:
                    all_preds = np.concatenate(preds_parts)[:len(y)]
                pass_losses.append(self._train_loss(
                    np.asarray(w), float(bias), idx, val, y, wt))
                if ckpt_every and ((p + 1) % ckpt_every == 0
                                   or p + 1 == get("numPasses")):
                    try:
                        save_checkpoint(
                            ckpt_dir, p + 1,
                            {"weights": np.asarray(w),
                             "g2": np.asarray(g2),
                             "scale": np.asarray(s),
                             "bias": float(bias),
                             "n_acc": float(n_acc),
                             "t_count": float(t),
                             "passLosses": [float(x)
                                            for x in pass_losses]},
                            fhash)
                    except OSError as e:
                        from mmlspark_tpu.core.logging_utils import \
                            warn_once
                        warn_once(
                            "vw.checkpoint_skip",
                            "VW checkpoint write at pass %s failed "
                            "(%s: %s); continuing WITHOUT this "
                            "checkpoint", p + 1, type(e).__name__, e)
        state = {
            "weights": np.asarray(w),
            "g2": np.asarray(g2),
            "scale": np.asarray(s),
            "t_count": float(t),
            "n_acc": float(n_acc),
            "bias": float(bias),
            "loss": self._loss,
            "stats": {
                "numExamples": int(len(y)),
                "numPasses": int(get("numPasses")),
                "avgTrainLossPerPass": pass_losses,
                "trainSeconds": watch.elapsed,
                "syncsPerPass": int((nb_total + seg - 1) // seg),
                "prefetch": "on" if prefetch_async else "off",
            },
        }
        return state, (np.asarray(all_preds) if progressive else None)

    def _train_loss(self, w, bias, idx, val, y, wt) -> float:
        """Weighted mean training loss under the current weights (the
        per-partition loss in TrainingStats,
        VowpalWabbitBaseLearner.scala:20-59)."""
        margin = (w[idx.astype(np.int64)] * val).sum(axis=1) + bias
        if self._loss == "logistic":
            yy = np.where(y > 0, 1.0, -1.0)
            # logaddexp(0, x) = log(1+e^x) without overflow at large x
            per = np.logaddexp(0.0, -yy * margin)
        elif self._loss == "quantile":
            d = y - margin
            per = np.maximum(0.5 * d, -0.5 * d)
        else:
            per = (margin - y) ** 2
        return float((per * wt).sum() / max(wt.sum(), 1e-12))

    @staticmethod
    def _checkpoint_fingerprint(sgd_args, sgd_kwargs, get, idx, val, y,
                                wt, init=None) -> str:
        """Digest of everything a resumed pass must agree on: the SGD
        config (numPasses deliberately excluded — raising the pass
        budget is the supported elastic-restart path), the batch/shuffle
        schedule, and a cheap data digest (shapes + corner slices +
        moments, mirroring the GBDT fingerprint)."""
        import hashlib

        cfg = {k: v for k, v in sorted(sgd_kwargs.items())
               if k != "progressive"}
        h = hashlib.sha256(repr((sgd_args, cfg, get("batchSize"),
                                 get("seed"), get("syncScheduleRows"),
                                 get("shufflePerPass")),).encode())
        h.update(repr((idx.shape, bool(init is not None))).encode())
        for a in (idx, val, y, wt):
            h.update(np.ascontiguousarray(a[:64]).tobytes())
            h.update(np.ascontiguousarray(a[-64:]).tobytes())
        h.update(np.asarray([float(np.sum(val)), float(np.sum(y)),
                             float(np.sum(wt))]).tobytes())
        if init is not None and init.weights is not None:
            h.update(np.asarray(
                [float(np.sum(init.weights)),
                 float(init.bias)]).tobytes())
        return h.hexdigest()[:16]

    def set_initial_model(self, model: "_VWBaseModel") -> "_VWBaseLearner":
        """Warm start from a fitted model (VW ``initialModel`` / the
        ``-i`` model file, VowpalWabbitBase.scala:89): the fit begins
        from its weights/bias — and its optimizer state (AdaGrad
        accumulators, normalization scales) when the model carries it,
        matching VW model files which persist the adaptive state."""
        self._initial_model = model
        return self

    def fit_incremental(self, df: DataFrame, base_model=None,
                        num_passes: Optional[int] = None,
                        checkpoint_dir: Optional[str] = None,
                        checkpoint_interval: Optional[int] = None):
        """Online warm-start refit: continue ``base_model``'s weights
        and optimizer state with more passes over ``df`` (the streaming
        -refresh entry point — the GBDT twin adds trees, the online
        learner keeps updating the same weight vector).

        ``num_passes`` overrides ``numPasses`` for this refit;
        ``checkpoint_dir`` + ``checkpoint_interval`` thread through the
        pass-boundary checkpointing, so a refit killed mid-flight and
        re-run resumes from the latest checkpointed pass. The learner
        itself is not mutated — overrides ride a :meth:`copy`."""
        overrides: Dict[str, Any] = {}
        if num_passes is not None:
            overrides["numPasses"] = num_passes
        if checkpoint_dir is not None:
            overrides["checkpointDir"] = checkpoint_dir
            overrides["checkpointInterval"] = (checkpoint_interval
                                               or 1)
        est = self.copy(**overrides)
        if base_model is not None:
            est.set_initial_model(base_model)
        return est.fit(df)

    def _make_model(self, model_cls, state):
        model = model_cls(**{k: v for k, v in self._paramMap.items()
                             if model_cls.has_param(k)})
        model.weights = state["weights"]
        model.bias = state["bias"]
        model.loss = state["loss"]
        model.g2 = state.get("g2")
        model.scale = state.get("scale")
        model.t_count = float(state.get("t_count") or 0.0)
        model.n_acc = float(state.get("n_acc") or 0.0)
        model.train_stats = state.get("stats")
        model._mesh = self._mesh
        return model


class _VWBaseModel(Model, _VWParams):
    weights: Optional[np.ndarray] = None
    bias: float = 0.0
    loss: str = "squared"
    train_stats: Optional[Dict[str, Any]] = None
    # optimizer state, persisted like VW --save_resume persists the
    # adaptive state and example counters — a reloaded model
    # warm-starts identically
    g2: Optional[np.ndarray] = None
    scale: Optional[np.ndarray] = None
    t_count: float = 0.0
    n_acc: float = 0.0

    rawPredictionCol = Param("rawPredictionCol", "margin column", to_str,
                             default="rawPrediction")

    _mesh = None
    _scorers = None

    def set_mesh(self, mesh) -> "_VWBaseModel":
        """Score with rows sharded over the mesh 'dp' axis through the
        shared engine (inherited from the learner's mesh at fit time)."""
        self._mesh = mesh
        self._scorers = None
        return self

    def _ensure_scorer(self, kind: str):
        """Engine per margin form (dense matvec / sparse gather-dot):
        the weight vector + bias live resident on-device under the vw
        rule table instead of re-entering jax per call."""
        if self._scorers is None:
            self._scorers = {}
        scorer = self._scorers.get(kind)
        if scorer is None:
            from mmlspark_tpu.parallel.shard_rules import ShardedScorer
            if kind == "sparse":
                def apply(p, d):
                    return ((p["w"][d["idx"]] * d["val"]).sum(axis=1)
                            + p["b"])
            else:
                def apply(p, x):
                    return x @ p["w"][:x.shape[1]] + p["b"]
            params = {"w": np.asarray(self.weights, np.float32),
                      "b": np.float32(self.bias)}
            scorer = ShardedScorer(apply, params, family="vw",
                                   mesh=self._mesh, max_batch=8192,
                                   label=f"vw_{kind}")
            self._scorers[kind] = scorer
        return scorer

    def shard_metadata(self) -> Dict[str, Any]:
        """Resolved sharding mode + reason (the warn-once downgrade
        contract's queryable side)."""
        return self._ensure_scorer("dense").metadata()

    def _get_state(self):
        state = {"weights": self.weights, "bias": self.bias,
                 "loss": self.loss, "t_count": self.t_count,
                 "n_acc": self.n_acc}
        if self.g2 is not None:
            state["g2"] = self.g2
        if self.scale is not None:
            state["scale"] = self.scale
        return state

    def _set_state(self, state):
        self.weights = np.asarray(state["weights"])
        self.bias = float(state["bias"])
        self._scorers = None
        self.loss = state["loss"]
        self.g2 = (np.asarray(state["g2"]) if state.get("g2") is not None
                   else None)
        self.scale = (np.asarray(state["scale"])
                      if state.get("scale") is not None else None)
        self.t_count = float(state.get("t_count", 0.0) or 0.0)
        self.n_acc = float(state.get("n_acc", 0.0) or 0.0)

    def _margin(self, df: DataFrame) -> np.ndarray:
        base = self.get("featuresCol")
        if f"{base}_idx" in df:
            idx = df.col(f"{base}_idx").astype(np.int64)
            val = sanitize_values(df.col(f"{base}_val").astype(np.float64))
            if self._mesh is not None:
                # padded rows gather weight[0] * 0.0 -> bias only, and
                # are sliced away by the engine
                out = self._ensure_scorer("sparse")(
                    {"idx": idx, "val": val.astype(np.float32)})
                return np.asarray(out, np.float64)
            return (self.weights[idx] * val).sum(axis=1) + self.bias
        x = sanitize_values(df.col(base).astype(np.float64))
        if self._mesh is not None:
            out = self._ensure_scorer("dense")(x.astype(np.float32))
            return np.asarray(out, np.float64)
        # mesh-less dense path stays a BLAS matvec in f64 (no
        # O(rows*features) gather, no f32 round trip)
        return x @ self.weights[:x.shape[1]] + self.bias

    def get_performance_statistics(self) -> Dict[str, Any]:
        """TrainingStats analog (VowpalWabbitBaseLearner.scala:20-59):
        loss name + weights + per-pass training loss, example counts,
        sync cadence and wall clock from the fit."""
        out = {"numWeights": int((np.abs(self.weights) > 0).sum()),
               "bias": self.bias, "loss": self.loss}
        if self.train_stats:
            out.update(self.train_stats)
        return out


# ---------------------------------------------------------------------------
# Public learners
# ---------------------------------------------------------------------------

class VowpalWabbitRegressor(_VWBaseLearner):
    """Linear regression via online SGD (VowpalWabbitRegressor.scala:1)."""

    lossFunction = Param("lossFunction", "squared | quantile", to_str,
                         one_of("squared", "quantile"), default="squared")

    def _fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        self._loss = self.get("lossFunction")
        state, _ = self._train_weights(df)
        return self._make_model(VowpalWabbitRegressionModel, state)


class VowpalWabbitRegressionModel(_VWBaseModel):
    def _transform(self, df: DataFrame) -> DataFrame:
        return df.with_column(self.get("predictionCol"), self._margin(df))


class VowpalWabbitClassifier(_VWBaseLearner):
    """Binary logistic classifier; ``numClasses > 2`` trains
    one-vs-all — the engine-side form of the ``--oaa`` argument the
    reference forwards for its ``numClasses`` param
    (VowpalWabbitClassifier.scala:43)."""

    _loss = "logistic"
    lossFunction = Param("lossFunction", "logistic | hinge", to_str,
                         one_of("logistic", "hinge"), default="logistic")
    numClasses = Param("numClasses", "class count; > 2 trains "
                       "one-vs-all (--oaa)", to_int, ge(2), default=2)

    def _fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        self._loss = self.get("lossFunction")
        k = self.get("numClasses")
        if k == 2:
            # labelConversion analog (VowpalWabbitClassifier.scala:37):
            # any two distinct label values train as {0,1} and predict
            # back as the originals; more than two is a config error
            y = np.asarray(df.col(self.get("labelCol")))
            classes = np.unique(y)
            if len(classes) > 2:
                raise ValueError(
                    f"numClasses=2 but the label column holds "
                    f"{len(classes)} distinct values")
            decode = None
            if len(classes) == 2 \
                    and not np.array_equal(classes, [0.0, 1.0]):
                df = df.with_column(
                    self.get("labelCol"),
                    (y == classes[1]).astype(np.float64))
                decode = classes.astype(np.float64)
            state, _ = self._train_weights(df)
            model = self._make_model(VowpalWabbitClassificationModel,
                                     state)
            model.binary_classes_ = decode
            return model
        if getattr(self, "_initial_model", None) is not None:
            raise NotImplementedError(
                "initialModel warm start is binary-only; fit the "
                "one-vs-all classes separately to warm start them")
        y = np.asarray(df.col(self.get("labelCol")))
        classes = np.unique(y)
        if len(classes) > k:
            raise ValueError(
                f"numClasses={k} but the label column holds "
                f"{len(classes)} distinct values")
        feats = self._get_features(df)  # hash once, share across banks
        per_class = []
        for c in classes:
            state_c, _ = self._train_weights(
                df, labels_override=(y == c).astype(np.float32),
                features_override=feats)
            per_class.append(state_c)
        all_stats = [s.get("stats") or {} for s in per_class]
        stats = {
            "numExamples": all_stats[0].get("numExamples"),
            "numPasses": all_stats[0].get("numPasses"),
            "syncsPerPass": all_stats[0].get("syncsPerPass"),
            # wall clock sums over the K one-vs-all fits; losses are
            # reported per class (per-pass lists), not averaged away
            "trainSeconds": float(sum(st.get("trainSeconds") or 0.0
                                      for st in all_stats)),
            "avgTrainLossPerPassPerClass": [
                st.get("avgTrainLossPerPass") for st in all_stats],
        }
        state = {
            "weights": np.stack([s["weights"] for s in per_class]),
            "bias": 0.0,
            "loss": self._loss,
            "stats": stats,
        }
        model = self._make_model(VowpalWabbitClassificationModel, state)
        model.biases = np.asarray([s["bias"] for s in per_class])
        model.classes_ = classes.astype(np.float64)
        return model


class VowpalWabbitClassificationModel(_VWBaseModel):
    probabilityCol = Param("probabilityCol", "probability column", to_str,
                           default="probability")
    # one-vs-all state: weights becomes (K, num_weights), with
    # per-class biases and the original label values
    biases: Optional[np.ndarray] = None
    classes_: Optional[np.ndarray] = None
    # binary labelConversion decode: (2,) original label values
    binary_classes_: Optional[np.ndarray] = None

    def _get_state(self):
        state = super()._get_state()
        if self.classes_ is not None:
            state["biases"] = self.biases
            state["classes_"] = self.classes_
        if self.binary_classes_ is not None:
            state["binary_classes_"] = self.binary_classes_
        return state

    def _set_state(self, state):
        super()._set_state(state)
        c = state.get("classes_")
        self.classes_ = None if c is None else np.asarray(c)
        b = state.get("biases")
        self.biases = None if b is None else np.asarray(b)
        bc = state.get("binary_classes_")
        self.binary_classes_ = None if bc is None else np.asarray(bc)

    def _oaa_margins(self, df: DataFrame) -> np.ndarray:
        base = self.get("featuresCol")
        if f"{base}_idx" in df:
            idx = df.col(f"{base}_idx").astype(np.int64)
            val = sanitize_values(df.col(f"{base}_val").astype(np.float64))
            return np.stack([(w[idx] * val).sum(axis=1) + b
                             for w, b in zip(self.weights, self.biases)],
                            axis=1)
        x = sanitize_values(df.col(base).astype(np.float64))
        return x @ self.weights[:, :x.shape[1]].T + self.biases[None, :]

    def _transform(self, df: DataFrame) -> DataFrame:
        if self.classes_ is not None:  # one-vs-all
            margins = self._oaa_margins(df)
            e = np.exp(margins - margins.max(axis=1, keepdims=True))
            probs = e / e.sum(axis=1, keepdims=True)
            pred = self.classes_[np.argmax(margins, axis=1)]
            return (df.with_column(self.get("rawPredictionCol"), margins)
                      .with_column(self.get("probabilityCol"), probs)
                      .with_column(self.get("predictionCol"),
                                   pred.astype(np.float64)))
        margin = self._margin(df)
        prob = 1.0 / (1.0 + np.exp(-margin))
        pred01 = (margin > 0).astype(np.int64)
        pred = (self.binary_classes_[pred01]
                if self.binary_classes_ is not None
                else pred01.astype(np.float64))
        return (df.with_column(self.get("rawPredictionCol"),
                               np.stack([-margin, margin], axis=1))
                  .with_column(self.get("probabilityCol"),
                               np.stack([1 - prob, prob], axis=1))
                  .with_column(self.get("predictionCol"),
                               pred.astype(np.float64)))


class VowpalWabbitGeneric(_VWBaseLearner):
    """Raw-args learner (VowpalWabbitGeneric.scala:19): configure entirely
    through a VW-style ``passThroughArgs`` string; loss via --loss_function."""

    def _fit(self, df: DataFrame) -> "VowpalWabbitGenericModel":
        args = (self.get("passThroughArgs") or "").split()
        self._loss = "squared"
        if "--loss_function" in args:
            self._loss = args[args.index("--loss_function") + 1]
        state, _ = self._train_weights(df)
        return self._make_model(VowpalWabbitGenericModel, state)


class VowpalWabbitGenericModel(_VWBaseModel):
    def _transform(self, df: DataFrame) -> DataFrame:
        margin = self._margin(df)
        pred = (1.0 / (1.0 + np.exp(-margin)) if self.loss == "logistic"
                else margin)
        return df.with_column(self.get("predictionCol"), pred)


class VowpalWabbitGenericProgressive(_VWBaseLearner, ):
    """One-step-ahead training predictions as a transform
    (VowpalWabbitGenericProgressive.scala:1): the output column holds the
    prediction each row received *before* the model learned from it."""

    def _fit(self, df: DataFrame):
        raise TypeError("progressive mode is transform-only; call transform")

    def transform(self, df: DataFrame) -> DataFrame:
        args = (self.get("passThroughArgs") or "").split()
        self._loss = "squared"
        if "--loss_function" in args:
            self._loss = args[args.index("--loss_function") + 1]
        _, preds = self._train_weights(df, progressive=True)
        if self._loss == "logistic":
            preds = 1.0 / (1.0 + np.exp(-preds))
        return df.with_column(self.get("predictionCol"), preds)
