"""Off-policy evaluation estimators.

Parity with vw/.../policyeval: IPS (Ips.scala:1), SNIPS (Snips.scala:1),
CressieRead point estimate and confidence interval
(CressieRead.scala:1, CressieReadInterval.scala:1, 216 LoC), plus the
bandit-metrics accumulator (ContextualBanditMetrics,
VowpalWabbitContextualBandit.scala:54) and Kahan summation
(KahanSum.scala:1). The reference runs these as Spark UDAFs; here they
are pure vectorized reductions over (probability-logged, reward,
probability-predicted) triples.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

import numpy as np


class KahanSum:
    """Compensated summation (KahanSum.scala:1)."""

    def __init__(self):
        self.sum = 0.0
        self._c = 0.0

    def add(self, v: float) -> "KahanSum":
        t = self.sum + v
        if abs(self.sum) >= abs(v):
            self._c += (self.sum - t) + v
        else:
            self._c += (v - t) + self.sum
        self.sum = t
        return self

    @property
    def value(self) -> float:
        return self.sum + self._c


def _ratios(prob_logged, reward, prob_pred, count=None,
            w_min=None, w_max=None):
    prob_logged = np.asarray(prob_logged, dtype=np.float64)
    reward = np.asarray(reward, dtype=np.float64)
    prob_pred = np.asarray(prob_pred, dtype=np.float64)
    count = (np.ones_like(reward) if count is None
             else np.asarray(count, dtype=np.float64))
    w = prob_pred / np.maximum(prob_logged, 1e-12)
    if w_min is not None or w_max is not None:
        # importance-weight clip INSIDE the estimator, as the reference
        # passes its min/max bounds into CressieRead/Interval
        w = np.clip(w, w_min if w_min is not None else -np.inf,
                    w_max if w_max is not None else np.inf)
    return w, reward, count


def ips(prob_logged, reward, prob_pred, count=None,
        w_min=None, w_max=None) -> float:
    """Inverse propensity score estimate (Ips.scala:1)."""
    w, r, c = _ratios(prob_logged, reward, prob_pred, count, w_min, w_max)
    return float(np.sum(w * r * c) / np.maximum(np.sum(c), 1e-12))


def snips(prob_logged, reward, prob_pred, count=None,
          w_min=None, w_max=None) -> float:
    """Self-normalized IPS (Snips.scala:1)."""
    w, r, c = _ratios(prob_logged, reward, prob_pred, count, w_min, w_max)
    denom = np.sum(w * c)
    return float(np.sum(w * r * c) / np.maximum(denom, 1e-12))


def cressie_read(prob_logged, reward, prob_pred, count=None,
                 w_min=None, w_max=None) -> float:
    """Cressie-Read power-divergence estimator (CressieRead.scala:1):
    solves for the dual weights that minimize chi-square divergence
    subject to the importance-weight moment constraint."""
    w, r, c = _ratios(prob_logged, reward, prob_pred, count, w_min, w_max)
    n = np.sum(c)
    wsum = np.sum(w * c)
    w2sum = np.sum(w * w * c)
    wrsum = np.sum(w * r * c)
    w2rsum = np.sum(w * w * r * c)
    denom = n * w2sum - wsum * wsum
    if abs(denom) < 1e-12:
        return snips(prob_logged, reward, prob_pred, count)
    beta = (wsum * wrsum - n * w2rsum) / denom  # lagrange-dual slope
    gamma = (wsum * w2rsum - w2sum * wrsum) / denom
    return float(-gamma - beta)  # estimate at the constrained optimum


def cressie_read_interval(prob_logged, reward, prob_pred, count=None,
                          alpha: float = 0.05,
                          reward_min: float = 0.0,
                          reward_max: float = 1.0,
                          w_min=None, w_max=None) -> Tuple[float, float]:
    """Empirical-likelihood confidence interval for the CR estimate
    (CressieReadInterval.scala:1): bisection on the reward bound whose
    chi-square statistic crosses the (1-alpha) quantile."""
    from scipy.stats import chi2

    w, r, c = _ratios(prob_logged, reward, prob_pred, count, w_min, w_max)
    n = max(np.sum(c), 1.0)
    crit = chi2.ppf(1 - alpha, df=1) / (2 * n)

    def stat(mu: float) -> float:
        # profile chi-square divergence at hypothesized value mu
        z = w * (r - mu)
        zbar = np.sum(z * c) / n
        zvar = np.sum(z * z * c) / n - zbar * zbar
        if zvar < 1e-12:
            return 0.0 if abs(zbar) < 1e-9 else np.inf
        return zbar * zbar / (2 * zvar)

    center = cressie_read(prob_logged, reward, prob_pred, count,
                          w_min=w_min, w_max=w_max)
    center = min(max(center, reward_min), reward_max)

    def bisect(lo, hi, target_low: bool):
        for _ in range(60):
            mid = (lo + hi) / 2
            if (stat(mid) > crit) == target_low:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2

    lower = bisect(reward_min, center, True)
    upper = bisect(center, reward_max, False)
    # note bisection direction: swap ends for the upper bound
    upper = reward_max - (upper - center) if upper < center else upper
    return float(lower), float(upper)


@dataclass
class BanditEstimator:
    """Streaming accumulator of all policy-eval estimates
    (ContextualBanditMetrics analog)."""

    _plog: list = field(default_factory=list)
    _r: list = field(default_factory=list)
    _ppred: list = field(default_factory=list)
    _c: list = field(default_factory=list)

    def add(self, prob_logged: float, reward: float, prob_pred: float,
            count: float = 1.0) -> "BanditEstimator":
        self._plog.append(prob_logged)
        self._r.append(reward)
        self._ppred.append(prob_pred)
        self._c.append(count)
        return self

    def get(self) -> Dict[str, float]:
        if not self._plog:
            return {}
        args = (self._plog, self._r, self._ppred, self._c)
        out = {"ips": ips(*args), "snips": snips(*args),
               "cressieRead": cressie_read(*args)}
        lo, hi = cressie_read_interval(*args)
        out["cressieReadLower"] = lo
        out["cressieReadUpper"] = hi
        return out
