"""ctypes bindings for the C++ data plane, with numpy fallbacks.

Parity: the reference's native loader layer (core/env NativeLoader,
LightGBMUtils.initializeNativeLibrary, lightgbm/.../LightGBMUtils.scala:29-35)
— a lazily-loaded shared library with a pure-JVM/Python fallback path.
The library is built from ``native/data_plane.cpp`` by ``make`` (g++);
:func:`ensure_built` compiles on first use and caches the .so.
"""

from mmlspark_tpu.native.bindings import (
    NativeDataPlane,
    bin_matrix,
    ensure_built,
    is_available,
    level_histogram,
    level_histogram_quant,
    load_csv,
    load_libsvm,
    murmur3_batch,
    quant_histogram_available,
)

__all__ = ["NativeDataPlane", "ensure_built", "is_available",
           "load_csv", "load_libsvm", "murmur3_batch", "bin_matrix",
           "level_histogram", "level_histogram_quant",
           "quant_histogram_available"]
