"""ctypes surface of libmmlspark_native.so + numpy fallbacks."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from mmlspark_tpu.core import sanitizer
from mmlspark_tpu.core.faults import fault_point
from mmlspark_tpu.core.logging_utils import logger

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmmlspark_native.so")

_lock = sanitizer.san_lock("native.build")
_lib: Optional[ctypes.CDLL] = None
_build_failed = False
_building = False
_build_done = threading.Event()
_quant_symbols = False


def ensure_built() -> bool:
    """Compile the shared library if missing; returns availability.

    The compile (make, up to 120s) runs OUTSIDE ``_lock``: one caller
    is elected builder under the lock, concurrent callers park on
    ``_build_done`` — holding a lock across a subprocess would stall
    every thread that merely wants the cached availability answer
    (GL012, blocking-under-lock)."""
    global _lib, _build_failed, _building
    with _lock:
        if _lib is not None:
            return True
        if _build_failed:
            return False
        if _building:
            elected = False
        else:
            _building = True
            _build_done.clear()
            elected = True
    if not elected:
        # another thread is compiling: wait for its verdict (bounded
        # well past the make timeout so a crashed builder can't park
        # us forever), then read the published result
        _build_done.wait(timeout=300)
        with _lock:
            return _lib is not None
    lib: Optional[ctypes.CDLL] = None
    failed = False
    try:
        # always run make: it is a no-op when the .so is fresh and
        # rebuilds when data_plane.cpp is newer (a stale library would
        # silently miss symbols added since it was built)
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception as e:
            if not os.path.exists(_SO_PATH):
                logger.warning("native build failed (%s); using numpy "
                               "fallbacks", e)
                failed = True
            else:
                logger.warning("native rebuild failed (%s); loading "
                               "the existing library", e)
        if not failed:
            try:
                loaded = ctypes.CDLL(_SO_PATH)
            except OSError as e:
                logger.warning("native load failed (%s); using numpy "
                               "fallbacks", e)
                failed = True
            else:
                _configure(loaded)
                lib = loaded    # published only once fully configured
    finally:
        with _lock:
            _lib = lib
            _build_failed = failed or lib is None
            _building = False
        _build_done.set()
    return lib is not None


def _configure(lib: ctypes.CDLL) -> None:
    i64 = ctypes.c_int64
    lib.mmls_murmur3_32.restype = ctypes.c_uint32
    lib.mmls_murmur3_32.argtypes = [ctypes.c_char_p, i64, ctypes.c_uint32]
    lib.mmls_murmur3_batch.restype = None
    lib.mmls_murmur3_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(i64), i64, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32)]
    lib.mmls_bin_matrix.restype = None
    lib.mmls_bin_matrix.argtypes = [
        ctypes.POINTER(ctypes.c_double), i64, i64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32)]
    lib.mmls_csv_dims.restype = ctypes.c_int
    lib.mmls_csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.POINTER(i64), ctypes.POINTER(i64)]
    lib.mmls_csv_parse.restype = ctypes.c_int
    lib.mmls_csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_double), i64, i64]
    lib.mmls_libsvm_dims.restype = i64
    lib.mmls_libsvm_dims.argtypes = [ctypes.c_char_p, ctypes.POINTER(i64),
                                     ctypes.POINTER(i64)]
    lib.mmls_libsvm_parse.restype = ctypes.c_int
    lib.mmls_libsvm_parse.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), i64, i64]
    f32p = ctypes.POINTER(ctypes.c_float)
    i32 = ctypes.c_int32
    for name, binp in (("mmls_level_hist_u8",
                        ctypes.POINTER(ctypes.c_uint8)),
                       ("mmls_level_hist_i32", ctypes.POINTER(i32))):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [binp, i64, i64, f32p, f32p, f32p,
                       ctypes.POINTER(i32), i32, i32, f32p]
    global _quant_symbols
    u8p = ctypes.POINTER(ctypes.c_uint8)
    _quant_symbols = True
    for name, binp, qp in (
            ("mmls_level_hist_q16_u8", u8p,
             ctypes.POINTER(ctypes.c_int16)),
            ("mmls_level_hist_q16_i32", ctypes.POINTER(i32),
             ctypes.POINTER(ctypes.c_int16)),
            ("mmls_level_hist_q8_u8", u8p,
             ctypes.POINTER(ctypes.c_int8)),
            ("mmls_level_hist_q8_i32", ctypes.POINTER(i32),
             ctypes.POINTER(ctypes.c_int8))):
        try:
            fn = getattr(lib, name)
        except AttributeError:
            # stale pre-built .so from before the quantized kernels
            # landed (rebuild failed): keep the f32 surface usable
            _quant_symbols = False
            break
        fn.restype = None
        fn.argtypes = [binp, i64, i64, qp, qp, u8p,
                       ctypes.POINTER(i32), i32, i32,
                       ctypes.c_float, ctypes.c_float, f32p]


def is_available() -> bool:
    return ensure_built()


# ---------------------------------------------------------------------------
# public ops (native when available, numpy otherwise)
# ---------------------------------------------------------------------------

def murmur3_batch(strings, seed: int = 0) -> np.ndarray:
    """uint32 murmur3 of each string."""
    if ensure_built():
        blob = b"".join(s.encode() if isinstance(s, str) else bytes(s)
                        for s in strings)
        offsets = np.zeros(len(strings) + 1, np.int64)
        pos = 0
        for i, s in enumerate(strings):
            pos += len(s.encode() if isinstance(s, str) else s)
            offsets[i + 1] = pos
        out = np.zeros(len(strings), np.uint32)
        _lib.mmls_murmur3_batch(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(strings), seed,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return out
    from mmlspark_tpu.ops.hashing import murmur3_32
    return np.asarray([murmur3_32(s, seed) for s in strings], np.uint32)


def bin_matrix(vals: np.ndarray, uppers: np.ndarray) -> np.ndarray:
    """(n, f) doubles -> int32 bin ids via (f, B) upper edges."""
    vals = np.ascontiguousarray(vals, np.float64)
    uppers = np.ascontiguousarray(uppers, np.float64)
    n, f = vals.shape
    n_bins = uppers.shape[1]
    if ensure_built():
        out = np.zeros((n, f), np.int32)
        _lib.mmls_bin_matrix(
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, f,
            uppers.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n_bins,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out
    out = np.empty((n, f), np.int32)
    for j in range(f):
        out[:, j] = np.minimum(
            np.searchsorted(uppers[j], vals[:, j], side="left"), n_bins - 1)
    return out


def level_histogram(binned: np.ndarray, grad: np.ndarray,
                    hess: np.ndarray, live: np.ndarray,
                    local: np.ndarray, width: int,
                    n_bins: int) -> np.ndarray:
    """GBDT per-level histogram: (n, f) bin ids + per-row stats ->
    (width, f, n_bins, 3) float32 grad/hess/count sums, accumulated as
    ``(grad*live, hess*live, live)`` into the row's ``local`` node.

    The cache-blocked C++ kernel when the library is available (row
    order within a worker chunk, worker chunks merged in order — the
    float sum order is deterministic for a given thread count); a
    bincount fallback otherwise. Bin ids must be < ``n_bins`` and
    ``local`` in [0, width) — the trainer's binning/clipping guarantees
    both.
    """
    n, f = binned.shape
    grad = np.ascontiguousarray(grad, np.float32)
    hess = np.ascontiguousarray(hess, np.float32)
    live = np.ascontiguousarray(live, np.float32)
    local = np.ascontiguousarray(local, np.int32)
    if ensure_built():
        if binned.dtype == np.uint8:
            binned = np.ascontiguousarray(binned)
            fn, binp = _lib.mmls_level_hist_u8, ctypes.c_uint8
        else:
            binned = np.ascontiguousarray(binned, np.int32)
            fn, binp = _lib.mmls_level_hist_i32, ctypes.c_int32
        out = np.empty((width, f, n_bins, 3), np.float32)
        fn(binned.ctypes.data_as(ctypes.POINTER(binp)), n, f,
           grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           live.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
           local.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
           width, n_bins,
           out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        # injection point on the histogram RESULT: arming corrupt here
        # proves a bad data-plane answer changes the model (so parity
        # tests really exercise this kernel); delay simulates a slow one
        return sanitizer.check_dtype_contract(
            "gbdt.level_hist", sanitizer.check_finite(
                "gbdt.level_hist",
                fault_point("gbdt.level_hist", out)))
    out = np.zeros((width, f, n_bins, 3), np.float32)
    if n == 0:
        return sanitizer.check_dtype_contract(
            "gbdt.level_hist", sanitizer.check_finite(
                "gbdt.level_hist",
                fault_point("gbdt.level_hist", out)))
    idx_base = local.astype(np.int64) * n_bins
    chans = (grad * live, hess * live, live)
    for j in range(f):
        idx = idx_base + binned[:, j]
        for c, w in enumerate(chans):
            out[:, j, :, c] = np.bincount(
                idx, weights=w, minlength=width * n_bins
            ).reshape(width, n_bins).astype(np.float32)
    return sanitizer.check_dtype_contract(
        "gbdt.level_hist", sanitizer.check_finite(
            "gbdt.level_hist",
            fault_point("gbdt.level_hist", out)))


def quant_histogram_available() -> bool:
    """True when the loaded library exports the quantized kernels."""
    return ensure_built() and _quant_symbols


def level_histogram_quant(binned: np.ndarray, grad_q: np.ndarray,
                          hess_q: np.ndarray, live: np.ndarray,
                          local: np.ndarray, width: int, n_bins: int,
                          gscale_inv: float, hscale_inv: float
                          ) -> np.ndarray:
    """Quantized GBDT per-level histogram: int16 (or int8) grad/hess
    accumulated into int32 SIMD tiles with periodic folds into exact
    int64 sums, dequantized once at the merge. ``live`` is a 0/1 uint8
    gate. Bit-identical to the int64 bincount fallback below for any
    worker count because the inverse scales are powers of two (the
    single f32 rounding step happens after the exact integer sum).
    """
    n, f = binned.shape
    qdt = np.int8 if grad_q.dtype == np.int8 else np.int16
    grad_q = np.ascontiguousarray(grad_q, qdt)
    hess_q = np.ascontiguousarray(hess_q, qdt)
    live = np.ascontiguousarray(live, np.uint8)
    local = np.ascontiguousarray(local, np.int32)
    if quant_histogram_available():
        if binned.dtype == np.uint8:
            binned = np.ascontiguousarray(binned)
            binp = ctypes.c_uint8
            fn = (_lib.mmls_level_hist_q8_u8 if qdt == np.int8
                  else _lib.mmls_level_hist_q16_u8)
        else:
            binned = np.ascontiguousarray(binned, np.int32)
            binp = ctypes.c_int32
            fn = (_lib.mmls_level_hist_q8_i32 if qdt == np.int8
                  else _lib.mmls_level_hist_q16_i32)
        qp = ctypes.c_int8 if qdt == np.int8 else ctypes.c_int16
        out = np.empty((width, f, n_bins, 3), np.float32)
        fn(binned.ctypes.data_as(ctypes.POINTER(binp)), n, f,
           grad_q.ctypes.data_as(ctypes.POINTER(qp)),
           hess_q.ctypes.data_as(ctypes.POINTER(qp)),
           live.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
           local.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
           width, n_bins, gscale_inv, hscale_inv,
           out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return sanitizer.check_dtype_contract(
            "gbdt.level_hist", sanitizer.check_finite(
                "gbdt.level_hist",
                fault_point("gbdt.level_hist", out)))
    out = np.zeros((width, f, n_bins, 3), np.float32)
    if n == 0:
        return sanitizer.check_dtype_contract(
            "gbdt.level_hist", sanitizer.check_finite(
                "gbdt.level_hist",
                fault_point("gbdt.level_hist", out)))
    gate = live != 0
    idx_base = local.astype(np.int64) * n_bins
    # float64 bincount of integer-valued weights is exact below 2^53,
    # matching the native kernel's int64 accumulators bit-for-bit
    chans = (np.where(gate, grad_q, 0).astype(np.float64),
             np.where(gate, hess_q, 0).astype(np.float64),
             gate.astype(np.float64))
    scales = (np.float64(gscale_inv), np.float64(hscale_inv),
              np.float64(1.0))
    for j in range(f):
        idx = idx_base + binned[:, j]
        for c, (w, s) in enumerate(zip(chans, scales)):
            sums = np.bincount(idx, weights=w,
                               minlength=width * n_bins)
            out[:, j, :, c] = (sums.reshape(width, n_bins)
                               * s).astype(np.float32)
    return sanitizer.check_dtype_contract(
        "gbdt.level_hist", sanitizer.check_finite(
            "gbdt.level_hist",
            fault_point("gbdt.level_hist", out)))


def load_csv(path: str, skip_header: bool = True
             ) -> np.ndarray:
    """Parse a numeric CSV into an (n, f) float64 matrix."""
    if ensure_built():
        i64 = ctypes.c_int64
        rows, cols = i64(), i64()
        rc = _lib.mmls_csv_dims(path.encode(), int(skip_header),
                                ctypes.byref(rows), ctypes.byref(cols))
        if rc != 0:
            raise IOError(f"csv dims failed ({rc}) for {path}")
        out = np.zeros((rows.value, cols.value), np.float64)
        rc = _lib.mmls_csv_parse(
            path.encode(), int(skip_header),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            rows.value, cols.value)
        if rc != 0:
            raise IOError(f"csv parse failed ({rc}) for {path}")
        return out
    return np.loadtxt(path, delimiter=",",
                      skiprows=1 if skip_header else 0, ndmin=2)


def load_libsvm(path: str, num_features: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse libsvm lines into dense (x, y)."""
    if ensure_built():
        i64 = ctypes.c_int64
        rows, maxi = i64(), i64()
        rc = _lib.mmls_libsvm_dims(path.encode(), ctypes.byref(rows),
                                   ctypes.byref(maxi))
        if rc != 0:
            raise IOError(f"libsvm dims failed ({rc}) for {path}")
        f = num_features or maxi.value
        x = np.zeros((rows.value, f), np.float64)
        y = np.zeros(rows.value, np.float64)
        rc = _lib.mmls_libsvm_parse(
            path.encode(),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            rows.value, f)
        if rc != 0:
            raise IOError(f"libsvm parse failed ({rc}) for {path}")
        return x, y
    xs, ys, maxf = [], [], 0
    with open(path) as fh:
        for line in fh:
            parts = line.split()
            if not parts:
                continue
            ys.append(float(parts[0]))
            row = {}
            for kv in parts[1:]:
                k, v = kv.split(":")
                row[int(k)] = float(v)
                maxf = max(maxf, int(k))
            xs.append(row)
    f = num_features or maxf
    x = np.zeros((len(xs), f), np.float64)
    for i, row in enumerate(xs):
        for k, v in row.items():
            if 1 <= k <= f:
                x[i, k - 1] = v
    return x, np.asarray(ys)


class NativeDataPlane:
    """Facade used by DataFrame readers and BinMapper."""

    is_available = staticmethod(is_available)
    load_csv = staticmethod(load_csv)
    load_libsvm = staticmethod(load_libsvm)
    murmur3_batch = staticmethod(murmur3_batch)
    bin_matrix = staticmethod(bin_matrix)
    level_histogram = staticmethod(level_histogram)
