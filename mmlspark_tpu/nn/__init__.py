"""Exact (conditional) nearest neighbors.

Parity surface: reference ``nn`` package (nn/BallTree.scala:109,
nn/KNN.scala:49, nn/ConditionalKNN.scala:32). Matching is by **maximum
inner product** as in the reference's ``findMaximumInnerProducts``.
"""

from mmlspark_tpu.nn.balltree import BallTree, BestMatch, ConditionalBallTree
from mmlspark_tpu.nn.knn import KNN, ConditionalKNN, ConditionalKNNModel, KNNModel

__all__ = ["BallTree", "ConditionalBallTree", "BestMatch",
           "KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]
