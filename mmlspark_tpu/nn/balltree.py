"""Serializable ball tree for maximum-inner-product search.

Parity: nn/BallTree.scala:109 (BallTree), :203 (ConditionalBallTree) —
a binary space partition over the *keys* with per-node bounding balls;
queries return the top-k **inner products** (BestMatch(index, distance)).

TPU-first note: the tree exists for host-side parity and small
single-query use; the batch path used by the KNN transformer
(:mod:`mmlspark_tpu.nn.knn`) is a dense ``Q @ K.T`` + ``lax.top_k`` on
device — MXU-shaped, no tree traversal. The ball-bound pruning math
(mu + r*|q| upper bound) matches the reference's traversal order.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Set

import numpy as np


@dataclass(order=True)
class BestMatch:
    distance: float  # inner product (higher = better), name kept for parity
    index: int = field(compare=False)


class _Node:
    __slots__ = ("center", "radius", "lo", "hi", "left", "right")

    def __init__(self, center, radius, lo, hi, left=None, right=None):
        self.center = center
        self.radius = radius
        self.lo = lo          # [lo, hi) range into the permuted index array
        self.hi = hi
        self.left = left
        self.right = right

    @property
    def is_leaf(self):
        return self.left is None


class BallTree:
    """Ball tree over ``keys`` (n, d); ``values[i]`` is returned payload."""

    def __init__(self, keys: np.ndarray, values: Sequence[Any],
                 leaf_size: int = 50):
        keys = np.asarray(keys, dtype=np.float64)
        if keys.ndim != 2:
            raise ValueError("keys must be (n, d)")
        self.keys = keys
        self.values = list(values)
        self.leaf_size = int(leaf_size)
        self._perm = np.arange(len(keys))
        self._root = self._build(0, len(keys))

    # -- construction (farthest-point split, as the reference's
    # BallTreeBase.upperSplit/lowerSplit pivoting) ---------------------------
    def _build(self, lo: int, hi: int) -> _Node:
        idx = self._perm[lo:hi]
        pts = self.keys[idx]
        center = pts.mean(axis=0)
        radius = float(np.sqrt(((pts - center) ** 2).sum(axis=1).max())) \
            if len(pts) else 0.0
        node = _Node(center, radius, lo, hi)
        if hi - lo <= self.leaf_size:
            return node
        # pick the dimension-spanning pivot pair: farthest point from the
        # first point, then farthest from that
        a = pts[0]
        d_a = ((pts - a) ** 2).sum(axis=1)
        p1 = pts[int(np.argmax(d_a))]
        d_p1 = ((pts - p1) ** 2).sum(axis=1)
        p2 = pts[int(np.argmax(d_p1))]
        d_p2 = ((pts - p2) ** 2).sum(axis=1)
        closer_p1 = d_p1 < d_p2
        if closer_p1.all() or (~closer_p1).all():  # degenerate: split evenly
            closer_p1 = np.arange(len(pts)) < len(pts) // 2
        order = np.argsort(~closer_p1, kind="stable")  # p1-side first
        self._perm[lo:hi] = idx[order]
        mid = lo + int(closer_p1.sum())
        node.left = self._build(lo, mid)
        node.right = self._build(mid, hi)
        return node

    # -- query ---------------------------------------------------------------
    def _upper_bound(self, node: _Node, q: np.ndarray, qnorm: float) -> float:
        # max_{x in ball} <q, x> = <q, c> + r * |q|
        return float(q @ node.center) + node.radius * qnorm

    def find_maximum_inner_products(self, query: np.ndarray, k: int = 1,
                                    conditioner: Optional[Set[Any]] = None,
                                    labels: Optional[Sequence[Any]] = None
                                    ) -> List[BestMatch]:
        q = np.asarray(query, dtype=np.float64)
        qnorm = float(np.linalg.norm(q))
        heap: List[BestMatch] = []  # min-heap on inner product

        def admit(i: int) -> bool:
            return conditioner is None or labels[i] in conditioner

        def visit(node: _Node):
            if len(heap) == k and self._upper_bound(node, q, qnorm) <= heap[0].distance:
                return  # prune: ball can't beat current worst
            if node.is_leaf:
                for i in self._perm[node.lo:node.hi]:
                    if not admit(i):
                        continue
                    ip = float(q @ self.keys[i])
                    if len(heap) < k:
                        heapq.heappush(heap, BestMatch(ip, int(i)))
                    elif ip > heap[0].distance:
                        heapq.heapreplace(heap, BestMatch(ip, int(i)))
                return
            ub_l = self._upper_bound(node.left, q, qnorm)
            ub_r = self._upper_bound(node.right, q, qnorm)
            first, second = (node.left, node.right) if ub_l >= ub_r \
                else (node.right, node.left)
            visit(first)
            visit(second)

        visit(self._root)
        return sorted(heap, key=lambda m: -m.distance)


class ConditionalBallTree(BallTree):
    """BallTree whose points carry labels; queries restrict matches to a
    conditioner label set (nn/BallTree.scala:203)."""

    def __init__(self, keys: np.ndarray, values: Sequence[Any],
                 labels: Sequence[Any], leaf_size: int = 50):
        super().__init__(keys, values, leaf_size)
        self.labels = list(labels)

    def find_maximum_inner_products(self, query: np.ndarray,
                                    conditioner: Set[Any], k: int = 1
                                    ) -> List[BestMatch]:
        return super().find_maximum_inner_products(
            query, k, conditioner=conditioner, labels=self.labels)
