"""KNN / ConditionalKNN estimators.

Parity: nn/KNN.scala:49 (fit indexes the dataset's features+values;
transform adds an array-of-(value, distance) column of the top-k
maximum-inner-product matches) and nn/ConditionalKNN.scala:32 (adds a
per-query conditioner set restricting matches by label; output structs
gain a ``label`` field).

TPU-first: instead of broadcasting a ball tree to executors and running
a per-row UDF (KNN.scala:100-113), the index matrix is resident on
device and queries run as one jitted ``scores = Q @ K.T`` +
``lax.top_k`` — batched MXU work. The conditional variant masks scores
with a label-membership matrix before top-k. The host
:class:`~mmlspark_tpu.nn.balltree.BallTree` remains available for
single-query use.
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasFeaturesCol, HasLabelCol, HasOutputCol, Param, gt, to_int, to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.nn.balltree import BallTree, ConditionalBallTree

_BATCH = 4096  # query rows per device call; keeps the score tile in VMEM


def _topk_inner_products(keys: np.ndarray, queries: np.ndarray, k: int):
    """Batched max-inner-product top-k on device. Returns (scores, idx)."""
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(2,))
    def run(kmat, q, kk):
        scores = q @ kmat.T  # (b, n) — the MXU does the heavy lifting
        return jax.lax.top_k(scores, kk)

    kmat = jnp.asarray(keys, jnp.float32)
    out_s, out_i = [], []
    for start in range(0, len(queries), _BATCH):
        q = jnp.asarray(queries[start:start + _BATCH], jnp.float32)
        s, i = run(kmat, q, k)
        out_s.append(np.asarray(s))
        out_i.append(np.asarray(i))
    return np.concatenate(out_s), np.concatenate(out_i)


def _masked_topk_inner_products(keys: np.ndarray, queries: np.ndarray,
                                member: np.ndarray, k: int):
    """Same, but scores where ``member[b, n]`` is False become -inf."""
    import jax
    import jax.numpy as jnp

    @partial(jax.jit, static_argnums=(3,))
    def run(kmat, q, m, kk):
        scores = q @ kmat.T
        scores = jnp.where(m, scores, -jnp.inf)
        return jax.lax.top_k(scores, kk)

    kmat = jnp.asarray(keys, jnp.float32)
    out_s, out_i = [], []
    for start in range(0, len(queries), _BATCH):
        q = jnp.asarray(queries[start:start + _BATCH], jnp.float32)
        m = jnp.asarray(member[start:start + _BATCH])
        s, i = run(kmat, q, m, k)
        out_s.append(np.asarray(s))
        out_i.append(np.asarray(i))
    return np.concatenate(out_s), np.concatenate(out_i)


class _KNNParams(HasFeaturesCol, HasOutputCol):
    valuesCol = Param("valuesCol", "column of values returned for matches",
                      to_str, default="values")
    leafSize = Param("leafSize", "max leaf size of the host ball tree", to_int,
                     gt(0), default=50)
    k = Param("k", "number of matches to return", to_int, gt(0), default=5)


class KNN(Estimator, _KNNParams):
    def _fit(self, dataset: DataFrame) -> "KNNModel":
        keys = np.asarray(dataset.col(self.get("featuresCol")), np.float64)
        values = list(dataset.col(self.get("valuesCol")))
        model = KNNModel(**{p.name: v for p, v in self.iter_set_params()})
        model._init_state(keys, values)
        return model


class KNNModel(Model, _KNNParams):
    _keys: np.ndarray
    _values: List[Any]

    def _init_state(self, keys, values):
        self._keys = keys
        self._values = values
        return self

    def _get_state(self):
        return {"keys": self._keys, "values": self._values}

    def _set_state(self, state):
        self._keys = np.asarray(state["keys"])
        self._values = list(state["values"])

    @property
    def ball_tree(self) -> BallTree:
        """Host-side tree view of the same index (single-query use)."""
        return BallTree(self._keys, self._values, self.get("leafSize"))

    def _transform(self, dataset: DataFrame) -> DataFrame:
        q = np.asarray(dataset.col(self.get("featuresCol")), np.float64)
        k = min(self.get("k"), len(self._keys))
        scores, idx = _topk_inner_products(self._keys, q, k)
        out = np.empty(len(q), dtype=object)
        for r in range(len(q)):
            out[r] = [{"value": self._values[int(i)], "distance": float(s)}
                      for s, i in zip(scores[r], idx[r])]
        return dataset.with_column(self.get("outputCol"), out)


class ConditionalKNN(Estimator, _KNNParams, HasLabelCol):
    conditionerCol = Param("conditionerCol", "column of per-query allowed "
                           "label sets", to_str, default="conditioner")

    def _fit(self, dataset: DataFrame) -> "ConditionalKNNModel":
        keys = np.asarray(dataset.col(self.get("featuresCol")), np.float64)
        values = list(dataset.col(self.get("valuesCol")))
        labels = list(dataset.col(self.get("labelCol")))
        model = ConditionalKNNModel(
            **{p.name: v for p, v in self.iter_set_params()})
        model._init_state(keys, values, labels)
        return model


class ConditionalKNNModel(Model, _KNNParams, HasLabelCol):
    conditionerCol = Param("conditionerCol", "column of per-query allowed "
                           "label sets", to_str, default="conditioner")

    _keys: np.ndarray
    _values: List[Any]
    _labels: List[Any]

    def _init_state(self, keys, values, labels):
        self._keys = keys
        self._values = values
        self._labels = labels
        return self

    def _get_state(self):
        return {"keys": self._keys, "values": self._values,
                "labels": self._labels}

    def _set_state(self, state):
        self._keys = np.asarray(state["keys"])
        self._values = list(state["values"])
        self._labels = list(state["labels"])

    @property
    def ball_tree(self) -> ConditionalBallTree:
        return ConditionalBallTree(self._keys, self._values, self._labels,
                                   self.get("leafSize"))

    def _transform(self, dataset: DataFrame) -> DataFrame:
        q = np.asarray(dataset.col(self.get("featuresCol")), np.float64)
        conditioners = dataset.col(self.get("conditionerCol"))
        k = min(self.get("k"), len(self._keys))
        # label-membership mask built host-side over the distinct label ids
        uniq = {v: j for j, v in enumerate(dict.fromkeys(self._labels))}
        label_ids = np.asarray([uniq[v] for v in self._labels])
        member = np.zeros((len(q), len(self._keys)), dtype=bool)
        for r, cond in enumerate(conditioners):
            allowed = {uniq[c] for c in cond if c in uniq}
            if allowed:
                member[r] = np.isin(label_ids, list(allowed))
        scores, idx = _masked_topk_inner_products(self._keys, q, member, k)
        out = np.empty(len(q), dtype=object)
        for r in range(len(q)):
            matches = []
            for s, i in zip(scores[r], idx[r]):
                if not np.isfinite(s):
                    continue  # fewer than k admissible points
                matches.append({"value": self._values[int(i)],
                                "distance": float(s),
                                "label": self._labels[int(i)]})
            out[r] = matches
        return dataset.with_column(self.get("outputCol"), out)
