"""ONNX-graph inference compiled through XLA.

Parity surface: reference deep-learning module's ONNX stack
(onnx/ONNXModel.scala:211, ONNXRuntime.scala:25-108, ONNXUtils.scala:1,
ONNXHub.scala:72-99, ImageFeaturizer.scala:34). The onnxruntime-CUDA
session is replaced by importing the ONNX graph into jax and letting
XLA compile it for TPU (SURVEY.md §2.7 ONNX row); per-task GPU
selection becomes per-core batch sharding.
"""

from mmlspark_tpu.onnx.convert import OnnxGraph, convert_model, load_model
from mmlspark_tpu.onnx.model import ImageFeaturizer, ONNXHub, ONNXModel

__all__ = ["ONNXModel", "ImageFeaturizer", "ONNXHub",
           "load_model", "convert_model", "OnnxGraph"]
