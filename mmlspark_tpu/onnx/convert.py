"""ONNX graph -> jax function.

The importer reads ModelProto through the vendored protobuf subset
(onnx_subset.proto — field numbers match the public ONNX schema, so
real .onnx files parse) and emits a pure jax function evaluating the
graph node-by-node; under ``jax.jit`` XLA fuses it exactly like any
hand-written model. Covers the op surface the reference exercises
through onnxruntime for CNN/MLP/transformer inference
(ONNXUtils.scala:1 tensor marshaling + ONNXModel fetch/feed contract).

Model slicing at intermediate outputs (ONNXModel.sliceAtOutputs,
onnx/ONNXModel.scala:207) falls out of the design: request any internal
tensor name as an output and dead nodes are skipped.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

_PB_DIR = os.path.dirname(__file__)
if _PB_DIR not in sys.path:
    sys.path.insert(0, _PB_DIR)
import onnx_subset_pb2 as pb  # noqa: E402

# TensorProto.DataType values (public ONNX enum)
_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
           7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def load_model(source) -> "pb.ModelProto":
    """Parse a ModelProto from bytes or a file path."""
    if isinstance(source, (str, os.PathLike)):
        with open(source, "rb") as f:
            data = f.read()
    else:
        data = bytes(source)
    model = pb.ModelProto()
    model.ParseFromString(data)
    return model


def tensor_to_array(t: "pb.TensorProto") -> np.ndarray:
    dtype = _DTYPES.get(t.data_type)
    if dtype is None:
        raise ValueError(f"unsupported tensor dtype {t.data_type}")
    shape = tuple(t.dims)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = np.asarray(t.float_data, np.float32).astype(dtype)
    elif t.int64_data:
        arr = np.asarray(t.int64_data, np.int64).astype(dtype)
    elif t.int32_data:
        arr = np.asarray(t.int32_data, np.int32).astype(dtype)
    elif t.double_data:
        arr = np.asarray(t.double_data, np.float64).astype(dtype)
    else:
        arr = np.zeros(int(np.prod(shape)) if shape else 1, dtype)
    return arr.reshape(shape)


def _attrs(node: "pb.NodeProto") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for a in node.attribute:
        if a.type == 1:       # FLOAT
            out[a.name] = a.f
        elif a.type == 2:     # INT
            out[a.name] = int(a.i)
        elif a.type == 3:     # STRING
            out[a.name] = a.s.decode()
        elif a.type == 4:     # TENSOR
            out[a.name] = tensor_to_array(a.t)
        elif a.type == 6:     # FLOATS
            out[a.name] = list(a.floats)
        elif a.type == 7:     # INTS
            out[a.name] = [int(v) for v in a.ints]
        elif a.type == 8:     # STRINGS
            out[a.name] = [s.decode() for s in a.strings]
        else:
            out[a.name] = None
    return out


def _reduce_axes(vals, attrs):
    if len(vals) > 1:
        return tuple(int(x) for x in np.asarray(vals[1]).tolist()) or None
    return tuple(attrs.get("axes", [])) or None


def _conv_padding(attrs, spatial_rank):
    pads = attrs.get("pads")
    if pads:
        half = len(pads) // 2
        return [(int(pads[i]), int(pads[i + half])) for i in range(half)]
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME"
    return [(0, 0)] * spatial_rank


def _pool(x, attrs, reducer, init, is_avg):
    import jax
    import jax.numpy as jnp

    k = attrs["kernel_shape"]
    strides = attrs.get("strides", [1] * len(k))
    pads = _conv_padding(attrs, len(k))
    window = (1, 1, *k)
    stride = (1, 1, *strides)
    if pads == "SAME":
        padding = "SAME"
    else:
        padding = ((0, 0), (0, 0), *pads)
    out = jax.lax.reduce_window(x, init, reducer, window, stride, padding)
    if is_avg:
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       stride, padding)
        out = out / counts
    return out


def _build_op_table():
    import jax
    import jax.numpy as jnp

    def conv(vals, node, attrs):
        x, w = vals[0], vals[1]
        b = vals[2] if len(vals) > 2 else None
        group = attrs.get("group", 1)
        spatial = w.ndim - 2
        strides = attrs.get("strides", [1] * spatial)
        dilations = attrs.get("dilations", [1] * spatial)
        padding = _conv_padding(attrs, spatial)
        dn = jax.lax.conv_dimension_numbers(
            x.shape, w.shape,
            ("NCHW", "OIHW", "NCHW") if spatial == 2 else
            ("NCW", "OIW", "NCW"))
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=group)
        if b is not None:
            out = out + b.reshape((1, -1) + (1,) * spatial)
        return out

    def gemm(vals, node, attrs):
        a, bmat = vals[0], vals[1]
        alpha = attrs.get("alpha", 1.0)
        beta = attrs.get("beta", 1.0)
        if attrs.get("transA"):
            a = a.T
        if attrs.get("transB"):
            bmat = bmat.T
        out = alpha * (a @ bmat)
        if len(vals) > 2:
            out = out + beta * vals[2]
        return out

    def batchnorm(vals, node, attrs):
        x, scale, bias, mean, var = vals[:5]
        eps = attrs.get("epsilon", 1e-5)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return (x - mean.reshape(shape)) / jnp.sqrt(
            var.reshape(shape) + eps) * scale.reshape(shape) \
            + bias.reshape(shape)

    def layernorm(vals, node, attrs):
        x, scale = vals[0], vals[1]
        bias = vals[2] if len(vals) > 2 else None
        axis = attrs.get("axis", -1)
        eps = attrs.get("epsilon", 1e-5)
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps) * scale
        return out + bias if bias is not None else out

    def reshape(vals, node, attrs):
        x, shape = vals[0], np.asarray(vals[1]).astype(np.int64)
        target = []
        for i, s in enumerate(shape):
            if s == 0:
                target.append(x.shape[i])
            else:
                target.append(int(s))
        return jnp.reshape(x, target)

    def slice_op(vals, node, attrs):
        x = vals[0]
        if len(vals) > 1:
            starts = np.asarray(vals[1]).tolist()
            ends = np.asarray(vals[2]).tolist()
            axes = np.asarray(vals[3]).tolist() if len(vals) > 3 \
                else list(range(len(starts)))
            steps = np.asarray(vals[4]).tolist() if len(vals) > 4 \
                else [1] * len(starts)
        else:
            starts, ends = attrs["starts"], attrs["ends"]
            axes = attrs.get("axes", list(range(len(starts))))
            steps = [1] * len(starts)
        slices = [slice(None)] * x.ndim
        for st, en, ax, sp in zip(starts, ends, axes, steps):
            n = x.shape[ax]
            en = min(en, n) if en >= 0 else en
            slices[ax] = slice(int(st), int(en), int(sp))
        return x[tuple(slices)]

    def resize(vals, node, attrs):
        x = vals[0]
        sizes = np.asarray(vals[3]).astype(int) if len(vals) > 3 else None
        if sizes is None:
            scales = np.asarray(vals[2], np.float64)
            sizes = (np.asarray(x.shape) * scales).astype(int)
        mode = attrs.get("mode", "nearest")
        method = {"nearest": "nearest", "linear": "linear",
                  "cubic": "cubic"}[mode]
        return jax.image.resize(x, tuple(int(s) for s in sizes), method)

    def pad_op(vals, node, attrs):
        x = vals[0]
        pads = np.asarray(vals[1]).tolist() if len(vals) > 1 \
            else attrs["pads"]
        value = float(np.asarray(vals[2])) if len(vals) > 2 else \
            attrs.get("value", 0.0)
        half = len(pads) // 2
        width = [(int(pads[i]), int(pads[i + half])) for i in range(half)]
        return jnp.pad(x, width, constant_values=value)

    table: Dict[str, Callable] = {
        "Conv": conv,
        "Gemm": gemm,
        "MatMul": lambda v, n, a: v[0] @ v[1],
        "Add": lambda v, n, a: v[0] + v[1],
        "Sub": lambda v, n, a: v[0] - v[1],
        "Mul": lambda v, n, a: v[0] * v[1],
        "Div": lambda v, n, a: v[0] / v[1],
        "Pow": lambda v, n, a: v[0] ** v[1],
        "Neg": lambda v, n, a: -v[0],
        "Sqrt": lambda v, n, a: jnp.sqrt(v[0]),
        "Exp": lambda v, n, a: jnp.exp(v[0]),
        "Log": lambda v, n, a: jnp.log(v[0]),
        "Abs": lambda v, n, a: jnp.abs(v[0]),
        "Erf": lambda v, n, a: jax.scipy.special.erf(v[0]),
        "Relu": lambda v, n, a: jax.nn.relu(v[0]),
        "LeakyRelu": lambda v, n, a: jax.nn.leaky_relu(
            v[0], a.get("alpha", 0.01)),
        "Sigmoid": lambda v, n, a: jax.nn.sigmoid(v[0]),
        "Tanh": lambda v, n, a: jnp.tanh(v[0]),
        "Gelu": lambda v, n, a: jax.nn.gelu(
            v[0], approximate=a.get("approximate", "none") == "tanh"),
        "Softmax": lambda v, n, a: jax.nn.softmax(v[0], a.get("axis", -1)),
        "LogSoftmax": lambda v, n, a: jax.nn.log_softmax(
            v[0], a.get("axis", -1)),
        "Clip": lambda v, n, a: jnp.clip(
            v[0],
            v[1] if len(v) > 1 else a.get("min"),
            v[2] if len(v) > 2 else a.get("max")),
        "MaxPool": lambda v, n, a: _pool(v[0], a, jax.lax.max, -jnp.inf,
                                         False),
        "AveragePool": lambda v, n, a: _pool(v[0], a, jax.lax.add, 0.0, True),
        "GlobalAveragePool": lambda v, n, a: jnp.mean(
            v[0], axis=tuple(range(2, v[0].ndim)), keepdims=True),
        "GlobalMaxPool": lambda v, n, a: jnp.max(
            v[0], axis=tuple(range(2, v[0].ndim)), keepdims=True),
        "BatchNormalization": batchnorm,
        "LayerNormalization": layernorm,
        "Flatten": lambda v, n, a: jnp.reshape(
            v[0], (int(np.prod(v[0].shape[:a.get("axis", 1)])), -1)),
        "Reshape": reshape,
        "Transpose": lambda v, n, a: jnp.transpose(v[0], a.get("perm")),
        "Concat": lambda v, n, a: jnp.concatenate(v, axis=a["axis"]),
        "Squeeze": lambda v, n, a: jnp.squeeze(
            v[0], tuple(int(x) for x in (
                np.asarray(v[1]).tolist() if len(v) > 1
                else a.get("axes", []))) or None),
        "Unsqueeze": lambda v, n, a: jnp.expand_dims(
            v[0], tuple(int(x) for x in (
                np.asarray(v[1]).tolist() if len(v) > 1 else a["axes"]))),
        "Identity": lambda v, n, a: v[0],
        "Dropout": lambda v, n, a: v[0],  # inference mode
        "Constant": lambda v, n, a: jnp.asarray(
            a.get("value") if a.get("value") is not None
            else a.get("value_float", a.get("value_int"))),
        "ConstantOfShape": lambda v, n, a: jnp.full(
            tuple(int(x) for x in np.asarray(v[0]).tolist()),
            a["value"].item() if a.get("value") is not None else 0.0),
        "Shape": lambda v, n, a: jnp.asarray(v[0].shape, jnp.int64),
        "Gather": lambda v, n, a: jnp.take(
            v[0], jnp.asarray(v[1]).astype(jnp.int32),
            axis=a.get("axis", 0)),
        "Cast": lambda v, n, a: v[0].astype(_DTYPES[a["to"]]),
        # axes come as an attribute (opset <= 17) or a second input
        # (opset >= 18); both forms are accepted for every reduction
        "ReduceMean": lambda v, n, a: jnp.mean(
            v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1))),
        "ReduceSum": lambda v, n, a: jnp.sum(
            v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1))),
        "ReduceMax": lambda v, n, a: jnp.max(
            v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1))),
        "ArgMax": lambda v, n, a: jnp.argmax(
            v[0], axis=a.get("axis", 0)) if not a.get("keepdims", 1)
            else jnp.expand_dims(jnp.argmax(v[0], axis=a.get("axis", 0)),
                                 a.get("axis", 0)),
        "Where": lambda v, n, a: jnp.where(v[0], v[1], v[2]),
        "Equal": lambda v, n, a: v[0] == v[1],
        "Greater": lambda v, n, a: v[0] > v[1],
        "Less": lambda v, n, a: v[0] < v[1],
        "Expand": lambda v, n, a: jnp.broadcast_to(
            v[0], np.broadcast_shapes(
                v[0].shape, tuple(int(x) for x in np.asarray(v[1])))),
        "Split": None,  # multi-output, handled inline
        "Slice": slice_op,
        "Pad": pad_op,
        "Resize": resize,
        "Softplus": lambda v, n, a: jax.nn.softplus(v[0]),
        "HardSigmoid": lambda v, n, a: jnp.clip(
            a.get("alpha", 0.2) * v[0] + a.get("beta", 0.5), 0, 1),
        "Min": lambda v, n, a: jnp.minimum(v[0], v[1]),
        "Max": lambda v, n, a: jnp.maximum(v[0], v[1]),
        "Sum": lambda v, n, a: sum(v[1:], v[0]),
        # -- long tail of simple ops (round-5 robustness batch) ----------
        "Floor": lambda v, n, a: jnp.floor(v[0]),
        "Ceil": lambda v, n, a: jnp.ceil(v[0]),
        "Round": lambda v, n, a: jnp.round(v[0]),  # banker's, as ONNX
        "Reciprocal": lambda v, n, a: 1.0 / v[0],
        "Sign": lambda v, n, a: jnp.sign(v[0]),
        "Not": lambda v, n, a: jnp.logical_not(v[0]),
        "And": lambda v, n, a: jnp.logical_and(v[0], v[1]),
        "Or": lambda v, n, a: jnp.logical_or(v[0], v[1]),
        "Xor": lambda v, n, a: jnp.logical_xor(v[0], v[1]),
        "GreaterOrEqual": lambda v, n, a: v[0] >= v[1],
        "LessOrEqual": lambda v, n, a: v[0] <= v[1],
        "Mod": lambda v, n, a: (jnp.fmod(v[0], v[1]) if a.get("fmod", 0)
                                else jnp.mod(v[0], v[1])),
        "ReduceMin": lambda v, n, a: jnp.min(
            v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1))),
        "ReduceProd": lambda v, n, a: jnp.prod(
            v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1))),
        "ReduceL2": lambda v, n, a: jnp.sqrt(jnp.sum(
            v[0] * v[0], axis=_reduce_axes(v, a),
            keepdims=bool(a.get("keepdims", 1)))),
        "ArgMin": lambda v, n, a: jnp.argmin(
            v[0], axis=a.get("axis", 0)) if not a.get("keepdims", 1)
            else jnp.expand_dims(jnp.argmin(v[0], axis=a.get("axis", 0)),
                                 a.get("axis", 0)),
        "Tile": lambda v, n, a: jnp.tile(
            v[0], tuple(int(x) for x in np.asarray(v[1]))),
        "CumSum": lambda v, n, a: _cumsum_op(v, a),
        "Range": lambda v, n, a: jnp.arange(
            np.asarray(v[0]).item(), np.asarray(v[1]).item(),
            np.asarray(v[2]).item()),
        "OneHot": lambda v, n, a: _onehot_op(v, a),
        "Trilu": lambda v, n, a: (
            jnp.triu(v[0], int(np.asarray(v[1]).item()) if len(v) > 1
                     else 0) if a.get("upper", 1)
            else jnp.tril(v[0], int(np.asarray(v[1]).item())
                          if len(v) > 1 else 0)),
        "IsNaN": lambda v, n, a: jnp.isnan(v[0]),
        "IsInf": lambda v, n, a: jnp.isinf(v[0]),
    }
    return table


def _cumsum_op(v, a):
    import jax.numpy as jnp

    axis = int(np.asarray(v[1]).item())
    x = v[0]
    if a.get("reverse", 0):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if a.get("exclusive", 0):
        out = out - (jnp.flip(v[0], axis) if a.get("reverse", 0) else v[0])
    if a.get("reverse", 0):
        out = jnp.flip(out, axis)
    return out


def _onehot_op(v, a):
    """indices, depth, values=[off, on]; negative indices wrap as ONNX."""
    import jax.nn
    import jax.numpy as jnp

    depth = int(np.asarray(v[1]).item())
    idx = jnp.where(v[0] < 0, v[0] + depth, v[0]).astype(jnp.int32)
    oh = jax.nn.one_hot(idx, depth, axis=a.get("axis", -1))
    off, on = v[2][0], v[2][1]
    return oh * (on - off) + off


class OnnxGraph:
    """Parsed + converted graph: callable as fn(feeds) -> fetches."""

    def __init__(self, model: "pb.ModelProto",
                 outputs: Optional[Sequence[str]] = None):
        self.model = model
        g = model.graph
        self.initializers = {t.name: tensor_to_array(t)
                             for t in g.initializer}
        self.input_names = [vi.name for vi in g.input
                            if vi.name not in self.initializers]
        self.output_names = list(outputs) if outputs else \
            [vi.name for vi in g.output]
        self.all_output_names = [vi.name for vi in g.output]
        self.input_shapes: Dict[str, Tuple] = {}
        self.input_dtypes: Dict[str, Any] = {}
        for vi in g.input:
            if vi.name in self.initializers:
                continue
            dims = []
            for d in vi.type.tensor_type.shape.dim:
                dims.append(int(d.dim_value) if d.dim_value else None)
            self.input_shapes[vi.name] = tuple(dims)
            elem = vi.type.tensor_type.elem_type
            self.input_dtypes[vi.name] = _DTYPES.get(elem)
        self._nodes = self._live_nodes()

    def _live_nodes(self) -> List["pb.NodeProto"]:
        """Topological node list pruned to the requested outputs — this IS
        the reference's model slicing (ONNXModel.scala:207)."""
        needed = set(self.output_names)
        live = []
        for node in reversed(list(self.model.graph.node)):
            if any(o in needed for o in node.output):
                live.append(node)
                needed.update(node.input)
        return list(reversed(live))

    def _make_executor(self):
        """Shared node-execution loop for convert/convert_trainable:
        (env) -> fetches, where env already holds initializers + feeds."""
        import jax.numpy as jnp

        table = _build_op_table()
        nodes = self._nodes
        out_names = self.output_names

        for node in nodes:
            if node.op_type not in table:
                raise NotImplementedError(
                    f"ONNX op {node.op_type!r} not supported by the "
                    f"XLA importer")

        def execute(env: Dict[str, Any]) -> Dict[str, Any]:
            for node in nodes:
                vals = [env[i] for i in node.input if i]
                attrs = _attrs(node)
                if node.op_type == "Split":
                    axis = attrs.get("axis", 0)
                    k = len(node.output)
                    parts = jnp.split(vals[0], k, axis=axis)
                    for name, p in zip(node.output, parts):
                        env[name] = p
                    continue
                env[node.output[0]] = table[node.op_type](vals, node, attrs)
            missing = [o for o in out_names if o not in env]
            if missing:
                raise KeyError(f"graph has no tensors {missing}")
            return {o: env[o] for o in out_names}

        return execute

    def convert(self) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
        import jax.numpy as jnp

        execute = self._make_executor()
        inits = self.initializers

        def run(feeds: Dict[str, Any]) -> Dict[str, Any]:
            # initializers stay numpy: shape-consuming ops (Reshape) need
            # concrete values, and int64 -> int32 jnp conversion under a
            # trace would turn them into tracers
            env: Dict[str, Any] = dict(inits)
            for k, v in feeds.items():
                env[k] = jnp.asarray(v)
            return execute(env)

        return run

    def convert_trainable(self):
        """(fn, weights): the graph as ``fn(weights, feeds) -> fetches``
        with the FLOATING-POINT initializers lifted into the ``weights``
        dict — differentiable, so an imported ONNX checkpoint becomes a
        fine-tunable parameter pytree (the pretrained-weight bridge the
        reference gets from torchvision/HF checkpoints,
        dl/DeepVisionClassifier.py:7-31). Integer initializers (shapes,
        axes, gather indices) stay baked as static constants.
        """
        import jax.numpy as jnp

        execute = self._make_executor()
        weights = {k: np.asarray(v) for k, v in self.initializers.items()
                   if np.issubdtype(np.asarray(v).dtype, np.floating)}
        static = {k: v for k, v in self.initializers.items()
                  if k not in weights}

        def run(params: Dict[str, Any], feeds: Dict[str, Any]
                ) -> Dict[str, Any]:
            # static (non-float) initializers stay numpy — see convert()
            env: Dict[str, Any] = dict(static)
            env.update(params)
            for k, v in feeds.items():
                env[k] = jnp.asarray(v)
            return execute(env)

        return run, weights


def convert_model(source, outputs: Optional[Sequence[str]] = None
                  ) -> OnnxGraph:
    return OnnxGraph(load_model(source), outputs)
