"""ONNXModel transformer + ImageFeaturizer + hub stub.

Parity: onnx/ONNXModel.scala:211-256 — feedDict (model input name ->
DataFrame column), fetchDict (output column -> graph tensor name, which
may be an INTERMEDIATE tensor: the graph is sliced there exactly like
sliceAtOutputs, :207), miniBatchSize batching, softMaxDict/argMaxDict
post-ops (:255-301). ImageFeaturizer (onnx/ImageFeaturizer.scala:34)
chains ImageTransformer preprocessing into a headless network.

TPU-first: one jitted graph evaluation per batch; the reference's
per-task GPU selection (ONNXRuntime.scala:47-57) is unnecessary — XLA
owns the chip, and batch rows shard over cores via the mesh.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasInputCol, HasOutputCol, Param, gt, to_int, to_str,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.onnx.convert import OnnxGraph, load_model


class ONNXModel(Transformer):
    modelPayload = Param("modelPayload", "ONNX model bytes", is_complex=True)
    feedDict = Param("feedDict", "model input name -> input column",
                     is_complex=True)
    fetchDict = Param("fetchDict", "output column -> graph tensor name",
                      is_complex=True)
    miniBatchSize = Param("miniBatchSize", "rows per device batch", to_int,
                          gt(0), default=256)
    softMaxDict = Param("softMaxDict", "input col -> output col softmax "
                        "post-op", is_complex=True)
    argMaxDict = Param("argMaxDict", "input col -> output col argmax "
                       "post-op", is_complex=True)

    _graph: Optional[OnnxGraph] = None
    _run = None
    _mesh = None

    def set_model_location(self, path: str) -> "ONNXModel":
        with open(path, "rb") as f:
            self._set(modelPayload=f.read())
        return self

    def set_mesh(self, mesh) -> "ONNXModel":
        """Shard each minibatch's rows over the mesh 'dp' axis — the
        embarrassing-parallel scoring mode (model broadcast + partition
        scoring, onnx/ONNXModel.scala:242-251)."""
        self._mesh = mesh
        return self

    def _ensure_graph(self):
        if self._graph is None:
            fetch = self.get("fetchDict") or {}
            outputs = list(fetch.values()) or None
            self._graph = OnnxGraph(load_model(self.get("modelPayload")),
                                    outputs)
            import jax
            self._run = jax.jit(self._graph.convert())
        return self._graph

    @property
    def model_inputs(self) -> Dict[str, tuple]:
        return dict(self._ensure_graph().input_shapes)

    @property
    def model_outputs(self) -> List[str]:
        return list(self._ensure_graph().all_output_names)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        graph = self._ensure_graph()
        feed = self.get("feedDict") or {
            graph.input_names[0]: "features"}
        fetch = self.get("fetchDict") or {
            "output": graph.output_names[0]}
        bs = self.get("miniBatchSize")
        n = dataset.num_rows

        cols: Dict[str, List[np.ndarray]] = {c: [] for c in fetch}
        for start in range(0, n, bs):
            feeds = {}
            for input_name, col_name in feed.items():
                col = dataset.col(col_name)
                if col.dtype == object:
                    batch = np.stack([np.asarray(v)
                                      for v in col[start:start + bs]])
                else:
                    batch = col[start:start + bs]
                # honor the graph's declared input dtype; otherwise keep
                # int/bool columns intact and only downcast f64 -> f32
                declared = graph.input_dtypes.get(input_name)
                if declared is not None:
                    batch = np.asarray(batch, declared)
                elif batch.dtype == np.float64:
                    batch = batch.astype(np.float32)
                feeds[input_name] = np.asarray(batch)
            if self._mesh is not None:
                from mmlspark_tpu.parallel.inference import sharded_apply
                fetched = sharded_apply(self._run, feeds, self._mesh)
            else:
                fetched = self._run(feeds)
            for out_col, tensor_name in fetch.items():
                cols[out_col].append(np.asarray(fetched[tensor_name]))

        out = dataset
        for out_col in fetch:
            stacked = np.concatenate(cols[out_col])
            if stacked.ndim > 2:  # ragged-safe object column
                obj = np.empty(len(stacked), dtype=object)
                for i in range(len(stacked)):
                    obj[i] = stacked[i]
                stacked = obj
            out = out.with_column(out_col, stacked)

        import jax
        for src, dst in (self.get("softMaxDict") or {}).items():
            vals = np.asarray(list(out.col(src)), np.float64)
            out = out.with_column(dst, np.asarray(
                jax.nn.softmax(vals, axis=-1)))
        for src, dst in (self.get("argMaxDict") or {}).items():
            vals = np.asarray(list(out.col(src)), np.float64)
            out = out.with_column(dst, vals.argmax(axis=-1)
                                  .astype(np.float64))
        return out

    def slice_at_output(self, tensor_name: str,
                        output_col: str = "output") -> "ONNXModel":
        """New ONNXModel fetching an intermediate tensor
        (ONNXModel.sliceAtOutputs parity)."""
        clone = self.copy(fetchDict={output_col: tensor_name})
        clone._graph = None
        return clone


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """image column -> preprocessing -> headless ONNX net -> feature
    vector (onnx/ImageFeaturizer.scala:34)."""

    onnxModel = Param("onnxModel", "the ONNXModel to run", is_complex=True)
    headless = Param("headless", "fetch the penultimate (feature) tensor "
                     "instead of the classifier output", is_complex=False,
                     converter=lambda v: bool(v), default=True)
    featureTensorName = Param("featureTensorName", "tensor to fetch in "
                              "headless mode (default: input of the last "
                              "node)", to_str)
    imageHeight = Param("imageHeight", "resize height", to_int, gt(0))
    imageWidth = Param("imageWidth", "resize width", to_int, gt(0))
    channelOrderNCHW = Param("channelOrderNCHW", "emit NCHW float tensors",
                             is_complex=False, converter=lambda v: bool(v),
                             default=True)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from mmlspark_tpu.image import ImageTransformer

        onnx_model: ONNXModel = self.get("onnxModel")
        graph = onnx_model._ensure_graph()

        df = dataset
        it = ImageTransformer(inputCol=self.get("inputCol"),
                              outputCol="__img__",
                              toTensor=self.get("channelOrderNCHW"))
        if self.is_set("imageHeight") != self.is_set("imageWidth"):
            raise ValueError("imageHeight and imageWidth must be set "
                             "together")
        if self.is_set("imageHeight"):
            it = it.resize(self.get("imageHeight"), self.get("imageWidth"))
        df = it.transform(df)

        if self.get("headless"):
            tensor = self.get("featureTensorName")
            if not tensor:
                last = graph.model.graph.node[-1]
                tensor = last.input[0]
            scorer = onnx_model.copy(
                feedDict={graph.input_names[0]: "__img__"},
                fetchDict={self.get("outputCol"): tensor})
        else:
            scorer = onnx_model.copy(
                feedDict={graph.input_names[0]: "__img__"},
                fetchDict={self.get("outputCol"): graph.all_output_names[0]})
        scorer._graph = None
        out = scorer.transform(df)
        feats = out.col(self.get("outputCol"))
        if feats.dtype == object:  # flatten feature maps to vectors
            flat = np.stack([np.asarray(v).reshape(-1) for v in feats])
            out = out.with_column(self.get("outputCol"), flat)
        return out.drop("__img__")


class ONNXHub:
    """Model-zoo stub (onnx/ONNXHub.scala:72-99). The environment has no
    egress; models must be local files."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir

    def list_models(self):
        raise RuntimeError(
            "ONNXHub requires network access, which this deployment "
            "disables; load models from local files via "
            "ONNXModel().set_model_location(path)")

    load_model = list_models
