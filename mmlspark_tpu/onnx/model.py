"""ONNXModel transformer + ImageFeaturizer + hub stub.

Parity: onnx/ONNXModel.scala:211-256 — feedDict (model input name ->
DataFrame column), fetchDict (output column -> graph tensor name, which
may be an INTERMEDIATE tensor: the graph is sliced there exactly like
sliceAtOutputs, :207), miniBatchSize batching, softMaxDict/argMaxDict
post-ops (:255-301). ImageFeaturizer (onnx/ImageFeaturizer.scala:34)
chains ImageTransformer preprocessing into a headless network.

TPU-first: one jitted graph evaluation per batch; the reference's
per-task GPU selection (ONNXRuntime.scala:47-57) is unnecessary — XLA
owns the chip, and batch rows shard over cores via the mesh.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasInputCol, HasOutputCol, Param, gt, to_int, to_str,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.onnx.convert import OnnxGraph, load_model


class ONNXModel(Transformer):
    modelPayload = Param("modelPayload", "ONNX model bytes", is_complex=True)
    feedDict = Param("feedDict", "model input name -> input column",
                     is_complex=True)
    fetchDict = Param("fetchDict", "output column -> graph tensor name",
                      is_complex=True)
    miniBatchSize = Param("miniBatchSize", "rows per device batch", to_int,
                          gt(0), default=256)
    softMaxDict = Param("softMaxDict", "input col -> output col softmax "
                        "post-op", is_complex=True)
    argMaxDict = Param("argMaxDict", "input col -> output col argmax "
                       "post-op", is_complex=True)

    _graph: Optional[OnnxGraph] = None
    _scorer = None
    _mesh = None

    def set_model_location(self, path: str) -> "ONNXModel":
        with open(path, "rb") as f:
            self._set(modelPayload=f.read())
        return self

    def set_mesh(self, mesh) -> "ONNXModel":
        """Shard each minibatch's rows over the mesh 'dp' axis — the
        embarrassing-parallel scoring mode (model broadcast + partition
        scoring, onnx/ONNXModel.scala:242-251)."""
        self._mesh = mesh
        self._scorer = None
        return self

    def _ensure_graph(self):
        if self._graph is None:
            fetch = self.get("fetchDict") or {}
            outputs = list(fetch.values()) or None
            self._graph = OnnxGraph(load_model(self.get("modelPayload")),
                                    outputs)
            self._scorer = None
        return self._graph

    def _ensure_scorer(self):
        """The shared scoring engine: float initializers lifted into a
        params pytree resident on-device under the onnx rule table,
        batches bucket-padded and row-sharded over dp."""
        self._ensure_graph()
        if self._scorer is None:
            from mmlspark_tpu.parallel.shard_rules import ShardedScorer
            run, weights = self._graph.convert_trainable()
            self._scorer = ShardedScorer(
                run, weights, family="onnx", mesh=self._mesh,
                max_batch=self.get("miniBatchSize"), label="onnx")
        return self._scorer

    def shard_metadata(self) -> Dict[str, Any]:
        """Resolved sharding mode + reason (the warn-once downgrade
        contract's queryable side)."""
        return self._ensure_scorer().metadata()

    @property
    def model_inputs(self) -> Dict[str, tuple]:
        return dict(self._ensure_graph().input_shapes)

    @property
    def model_outputs(self) -> List[str]:
        return list(self._ensure_graph().all_output_names)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        graph = self._ensure_graph()
        scorer = self._ensure_scorer()
        feed = self.get("feedDict") or {
            graph.input_names[0]: "features"}
        fetch = self.get("fetchDict") or {
            "output": graph.output_names[0]}

        feeds = {}
        for input_name, col_name in feed.items():
            col = dataset.col(col_name)
            if col.dtype == object:
                batch = np.stack([np.asarray(v) for v in col])
            else:
                batch = col
            # honor the graph's declared input dtype; otherwise keep
            # int/bool columns intact and only downcast f64 -> f32
            declared = graph.input_dtypes.get(input_name)
            if declared is not None:
                batch = np.asarray(batch, declared)
            elif batch.dtype == np.float64:
                batch = batch.astype(np.float32)
            feeds[input_name] = np.asarray(batch)
        # one engine call: the scorer chunks to miniBatchSize-capped
        # bucket rungs internally and keeps weights resident on-device
        fetched = scorer(feeds)

        out = dataset
        for out_col, tensor_name in fetch.items():
            stacked = np.asarray(fetched[tensor_name])
            if stacked.ndim > 2:  # ragged-safe object column
                obj = np.empty(len(stacked), dtype=object)
                for i in range(len(stacked)):
                    obj[i] = stacked[i]
                stacked = obj
            out = out.with_column(out_col, stacked)

        import jax
        for src, dst in (self.get("softMaxDict") or {}).items():
            vals = np.asarray(list(out.col(src)), np.float64)
            out = out.with_column(dst, np.asarray(
                jax.nn.softmax(vals, axis=-1)))
        for src, dst in (self.get("argMaxDict") or {}).items():
            vals = np.asarray(list(out.col(src)), np.float64)
            out = out.with_column(dst, vals.argmax(axis=-1)
                                  .astype(np.float64))
        return out

    def slice_at_output(self, tensor_name: str,
                        output_col: str = "output") -> "ONNXModel":
        """New ONNXModel fetching an intermediate tensor
        (ONNXModel.sliceAtOutputs parity)."""
        clone = self.copy(fetchDict={output_col: tensor_name})
        clone._graph = None
        clone._scorer = None
        return clone


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """image column -> preprocessing -> headless ONNX net -> feature
    vector (onnx/ImageFeaturizer.scala:34)."""

    onnxModel = Param("onnxModel", "the ONNXModel to run", is_complex=True)
    headless = Param("headless", "fetch the penultimate (feature) tensor "
                     "instead of the classifier output", is_complex=False,
                     converter=lambda v: bool(v), default=True)
    featureTensorName = Param("featureTensorName", "tensor to fetch in "
                              "headless mode (default: input of the last "
                              "node)", to_str)
    imageHeight = Param("imageHeight", "resize height", to_int, gt(0))
    imageWidth = Param("imageWidth", "resize width", to_int, gt(0))
    channelOrderNCHW = Param("channelOrderNCHW", "emit NCHW float tensors",
                             is_complex=False, converter=lambda v: bool(v),
                             default=True)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        from mmlspark_tpu.image import ImageTransformer

        onnx_model: ONNXModel = self.get("onnxModel")
        graph = onnx_model._ensure_graph()

        df = dataset
        it = ImageTransformer(inputCol=self.get("inputCol"),
                              outputCol="__img__",
                              toTensor=self.get("channelOrderNCHW"))
        if self.is_set("imageHeight") != self.is_set("imageWidth"):
            raise ValueError("imageHeight and imageWidth must be set "
                             "together")
        if self.is_set("imageHeight"):
            it = it.resize(self.get("imageHeight"), self.get("imageWidth"))
        df = it.transform(df)

        if self.get("headless"):
            tensor = self.get("featureTensorName")
            if not tensor:
                last = graph.model.graph.node[-1]
                tensor = last.input[0]
            scorer = onnx_model.copy(
                feedDict={graph.input_names[0]: "__img__"},
                fetchDict={self.get("outputCol"): tensor})
        else:
            scorer = onnx_model.copy(
                feedDict={graph.input_names[0]: "__img__"},
                fetchDict={self.get("outputCol"): graph.all_output_names[0]})
        scorer._graph = None
        scorer._scorer = None
        out = scorer.transform(df)
        feats = out.col(self.get("outputCol"))
        if feats.dtype == object:  # flatten feature maps to vectors
            flat = np.stack([np.asarray(v).reshape(-1) for v in feats])
            out = out.with_column(self.get("outputCol"), flat)
        return out.drop("__img__")


class ONNXHub:
    """Local model zoo with a JSON manifest + checksum verification.

    The reference hub (onnx/ONNXHub.scala:72-99) fetches a manifest of
    models and caches verified downloads. Zero-egress redesign: the hub
    root is a local directory holding ``manifest.json`` — entries of
    ``{"model": name, "model_path": relpath, "model_sha256": hex,
    "tags": [...]}`` — and the model files; ``get_model`` verifies the
    checksum and memoizes bytes, ``register_model`` builds the manifest.
    """

    MANIFEST = "manifest.json"

    def __init__(self, hub_dir: str):
        import os
        self.hub_dir = hub_dir
        os.makedirs(hub_dir, exist_ok=True)
        self._cache: Dict[str, bytes] = {}

    def _manifest_path(self) -> str:
        import os
        return os.path.join(self.hub_dir, self.MANIFEST)

    def _read_manifest(self) -> List[Dict[str, Any]]:
        import json
        import os
        if not os.path.exists(self._manifest_path()):
            return []
        with open(self._manifest_path()) as f:
            return json.load(f)

    def list_models(self, tags: Optional[List[str]] = None
                    ) -> List[Dict[str, Any]]:
        """Manifest entries, optionally filtered to those carrying ALL
        the given tags (ONNXHub.listModels parity)."""
        entries = self._read_manifest()
        if tags:
            want = set(tags)
            entries = [e for e in entries
                       if want.issubset(set(e.get("tags", [])))]
        return entries

    def get_model_info(self, name: str) -> Dict[str, Any]:
        for e in self._read_manifest():
            if e["model"] == name:
                return e
        known = [e["model"] for e in self._read_manifest()]
        raise KeyError(f"model {name!r} not in hub manifest; have {known}")

    def get_model(self, name: str) -> bytes:
        """Model bytes, checksum-verified and cached in memory."""
        import hashlib
        import os
        if name in self._cache:
            return self._cache[name]
        info = self.get_model_info(name)
        path = os.path.join(self.hub_dir, info["model_path"])
        with open(path, "rb") as f:
            data = f.read()
        digest = hashlib.sha256(data).hexdigest()
        if info.get("model_sha256") and digest != info["model_sha256"]:
            raise ValueError(
                f"checksum mismatch for {name!r}: manifest "
                f"{info['model_sha256'][:12]}..., file {digest[:12]}...")
        self._cache[name] = data
        return data

    def register_model(self, name: str, payload: bytes,
                       tags: Optional[List[str]] = None) -> Dict[str, Any]:
        """Add a model file + manifest entry (builds local zoos)."""
        import hashlib
        import json
        import os
        import re
        if not re.fullmatch(r"[A-Za-z0-9._-]+", name) or ".." in name:
            raise ValueError(
                f"model name {name!r} must be a plain identifier "
                f"(letters, digits, . _ -); path separators would escape "
                f"the hub directory")
        rel = f"{name}.onnx"
        with open(os.path.join(self.hub_dir, rel), "wb") as f:
            f.write(payload)
        entry = {"model": name, "model_path": rel,
                 "model_sha256": hashlib.sha256(payload).hexdigest(),
                 "tags": list(tags or [])}
        entries = [e for e in self._read_manifest() if e["model"] != name]
        entries.append(entry)
        with open(self._manifest_path(), "w") as f:
            json.dump(entries, f, indent=1)
        self._cache.pop(name, None)
        return entry

    def load_model(self, name: str) -> "ONNXModel":
        """ONNXModel ready to transform (getModel -> scorer parity)."""
        return ONNXModel(modelPayload=self.get_model(name))
