"""Quantile feature binning — the "reference dataset" of GBDT training.

TPU-native analog of LightGBM's sampled bin-boundary construction that the
reference drives through ``LGBM_DatasetCreateFromSampledColumn`` and then
broadcasts as a serialized reference dataset
(lightgbm/.../ReferenceDatasetUtils.scala:14-127). Bin boundaries are
computed once on host from a row sample, are tiny, and are replicated to
every device; the binned (row, feature) -> uint8/int16 matrix is what
ships to the TPU, replacing the reference's CSR/dense native-buffer push
path (StreamingPartitionTask.scala:203-277) — TPUs want dense blocked
integer data, not CSR.

Conventions (matching LightGBM semantics where visible to users):
  - bin 0 is reserved for missing values (NaN);
  - boundaries are upper edges: value v lands in the smallest bin with
    v <= edge; the last bin catches +inf;
  - categorical features bin by integer category id (offset by 1 to keep
    bin 0 = missing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.ops.sketch import DEFAULT_SKETCH_K, QuantileSketch

# Row-block size for BinMapper.transform: bounds the float64 staging copy
# (and the int result staging) to block_rows x F instead of N x F.
_TRANSFORM_BLOCK_ROWS = 65536


def _feat_max_bin(fi: int, max_bin: int,
                  max_bin_by_feature: Optional[Sequence[int]]) -> int:
    if max_bin_by_feature is None or fi >= len(max_bin_by_feature):
        return max_bin
    o = int(max_bin_by_feature[fi])
    # floor of 4 mirrors the maxBin validator: below that the
    # missing + catch-all reservation leaves no usable bins
    return min(max_bin, max(o, 4)) if o > 0 else max_bin


def _numeric_edges(uniq: np.ndarray, counts: np.ndarray, usable_bins: int,
                   min_data_in_bin: int) -> np.ndarray:
    """Bin edges for one numeric feature from (distinct values, counts).

    Shared by the exact path (``fit`` / small-cardinality streaming) and
    the sketch path (``fit_streaming`` fallback, where ``counts`` are
    sketch item weights).  For integer-valued counts this is bitwise
    identical to the historical row-level computation: a weighted
    bincount over distinct values equals the bincount over rows, and the
    float accumulator comparisons are exact below 2**53.
    """
    if len(uniq) == 0:
        return np.empty(0, dtype=np.float64)
    if len(uniq) <= usable_bins:
        # boundary = midpoint between adjacent distinct values
        e = (uniq[:-1] + uniq[1:]) / 2.0
    else:
        # weighted quantiles over distinct values
        cum = np.cumsum(counts)
        total = cum[-1]
        qs = (np.arange(1, usable_bins) / usable_bins) * total
        idx = np.searchsorted(cum, qs)
        idx = np.unique(np.minimum(idx, len(uniq) - 2))
        e = (uniq[idx] + uniq[idx + 1]) / 2.0
    if min_data_in_bin > 1 and len(e):
        # drop edges that separate fewer than min_data_in_bin rows
        bins = np.searchsorted(e, uniq, side="left")
        counts_per = np.bincount(bins, weights=counts, minlength=len(e) + 1)
        keep = []
        acc = 0.0
        for i in range(len(e)):
            acc += counts_per[i]
            if acc >= min_data_in_bin:
                keep.append(i)
                acc = 0.0
        e = e[keep]
    return np.asarray(e, dtype=np.float64)


@dataclass
class BinMapper:
    """Per-dataset binning state: replicated, serializable."""

    # upper_edges[f] has shape (num_bins_f - 1,); +inf edge implicit
    upper_edges: List[np.ndarray]
    is_categorical: np.ndarray          # (F,) bool
    categories: List[Optional[np.ndarray]]  # per-feature sorted category ids
    max_bin: int

    @property
    def num_features(self) -> int:
        return len(self.upper_edges)

    def num_bins(self, f: int) -> int:
        if self.is_categorical[f]:
            return len(self.categories[f]) + 1
        return len(self.upper_edges[f]) + 2  # + catch-all last bin + missing bin

    @property
    def max_num_bins(self) -> int:
        return max((self.num_bins(f) for f in range(self.num_features)), default=2)

    # -- construction -------------------------------------------------------
    @staticmethod
    def fit(sample: np.ndarray, max_bin: int = 255,
            categorical_features: Sequence[int] = (),
            min_data_in_bin: int = 3,
            max_bin_by_feature: Optional[Sequence[int]] = None
            ) -> "BinMapper":
        """Compute bin boundaries from a host-side row sample.

        Quantile binning over distinct values, merging bins that would
        hold fewer than ``min_data_in_bin`` sampled rows (LightGBM's
        ``min_data_in_bin`` semantics). ``max_bin_by_feature`` caps
        individual features below ``max_bin`` (LightGBM
        max_bin_by_feature; entries <= 0 mean no override).
        """
        sample = np.asarray(sample, dtype=np.float64)
        n, num_f = sample.shape
        cat = np.zeros(num_f, dtype=bool)
        cat[list(categorical_features)] = True
        edges: List[np.ndarray] = []
        cats: List[Optional[np.ndarray]] = []

        def feat_max_bin(fi):
            return _feat_max_bin(fi, max_bin, max_bin_by_feature)

        for f in range(num_f):
            col = sample[:, f]
            col = col[~np.isnan(col)]
            if cat[f]:
                edges.append(np.empty(0))
                vals, counts = np.unique(col.astype(np.int64), return_counts=True)
                cap = feat_max_bin(f) - 2  # rare categories overflow to the
                if len(vals) > cap:  # missing/other bin (LightGBM-style cap)
                    keep = np.sort(vals[np.argsort(-counts)[:cap]])
                    vals = keep
                cats.append(vals)
                continue
            cats.append(None)
            if len(col) == 0:
                edges.append(np.empty(0))
                continue
            uniq, counts = np.unique(col, return_counts=True)
            usable_bins = feat_max_bin(f) - 2  # reserve missing + catch-all
            edges.append(_numeric_edges(uniq, counts, usable_bins,
                                        min_data_in_bin))
        return BinMapper(edges, cat, cats, max_bin)

    @staticmethod
    def fit_streaming(chunks: Iterable[np.ndarray], max_bin: int = 255,
                      categorical_features: Sequence[int] = (),
                      min_data_in_bin: int = 3,
                      max_bin_by_feature: Optional[Sequence[int]] = None,
                      sketch_k: int = DEFAULT_SKETCH_K) -> "BinMapper":
        """One-pass streaming construction over row chunks.

        Per feature, an exact distinct-value tally runs alongside a
        mergeable :class:`QuantileSketch`; if a feature's cardinality
        stays under the tally cap the edges come out **identical** to
        ``fit`` over the concatenated chunks, otherwise the sketch's
        (value, weight) items feed the same edge computation so the
        result is parity-comparable within the sketch's rank-error
        bound.  Peak memory is one chunk plus the per-feature sketches —
        never the concatenated dataset.

        Categorical features need exact global category counts and are
        not supported here; bin them via ``fit`` on a row sample.
        """
        if len(list(categorical_features)) > 0:
            raise ValueError(
                "fit_streaming supports numeric features only; bin "
                "categorical features via BinMapper.fit on a row sample")
        sketches: Optional[List[QuantileSketch]] = None
        tallies: List[Optional[Dict[float, int]]] = []
        num_f = 0
        for chunk in chunks:
            c = np.asarray(chunk, dtype=np.float64)
            if c.ndim != 2:
                raise ValueError(f"chunks must be 2-d, got shape {c.shape}")
            if sketches is None:
                num_f = c.shape[1]
                sketches = [QuantileSketch(sketch_k) for _ in range(num_f)]
                tallies = [dict() for _ in range(num_f)]
            elif c.shape[1] != num_f:
                raise ValueError(
                    f"chunk has {c.shape[1]} features, expected {num_f}")
            for f in range(num_f):
                col = c[:, f]
                col = col[~np.isnan(col)]
                sketches[f].update(col)
                tally = tallies[f]
                if tally is not None:
                    uniq, counts = np.unique(col, return_counts=True)
                    for v, cnt in zip(uniq.tolist(), counts.tolist()):
                        tally[v] = tally.get(v, 0) + cnt
                    usable = _feat_max_bin(f, max_bin, max_bin_by_feature) - 2
                    if len(tally) > max(4096, 4 * usable):
                        tallies[f] = None  # high cardinality: sketch only
        if sketches is None:
            raise ValueError("fit_streaming requires at least one chunk")
        edges: List[np.ndarray] = []
        cats: List[Optional[np.ndarray]] = [None] * num_f
        for f in range(num_f):
            usable = _feat_max_bin(f, max_bin, max_bin_by_feature) - 2
            tally = tallies[f]
            if tally is not None:
                items = sorted(tally.items())
                uniq = np.asarray([it[0] for it in items], dtype=np.float64)
                counts = np.asarray([it[1] for it in items], dtype=np.int64)
            else:
                uniq, counts = sketches[f].items()
            edges.append(_numeric_edges(uniq, counts, usable,
                                        min_data_in_bin))
        return BinMapper(edges, np.zeros(num_f, dtype=bool), cats, max_bin)

    # -- application --------------------------------------------------------
    def transform(self, x: np.ndarray) -> np.ndarray:
        """Map raw features (N, F) to bin ids (N, F) int32; NaN -> bin 0.

        Rows are binned in bounded blocks so a non-float64 input never
        materializes a full float64 copy — peak staging overhead is one
        block (``_TRANSFORM_BLOCK_ROWS`` rows), which also caps the
        in-core fit path's binning RSS.  Output is bitwise identical to
        whole-array binning (rows are independent).
        """
        x = np.asarray(x)
        out = np.zeros(x.shape, dtype=np.int32)
        try_native = not any(self.is_categorical)
        for s in range(0, x.shape[0], _TRANSFORM_BLOCK_ROWS):
            block = np.asarray(x[s:s + _TRANSFORM_BLOCK_ROWS],
                               dtype=np.float64)
            binned = self._transform_native(block) if try_native else None
            if binned is None:
                try_native = False
                binned = self._transform_python(block)
            out[s:s + _TRANSFORM_BLOCK_ROWS] = binned
        return out

    def _transform_python(self, x: np.ndarray) -> np.ndarray:
        out = np.zeros(x.shape, dtype=np.int32)
        for f in range(self.num_features):
            col = x[:, f]
            nan = np.isnan(col)
            if self.is_categorical[f]:
                idx = np.searchsorted(self.categories[f], col)
                idx = np.clip(idx, 0, len(self.categories[f]) - 1)
                hit = self.categories[f][idx] == col
                b = np.where(hit, idx + 1, 0)
            else:
                b = np.searchsorted(self.upper_edges[f], col, side="left") + 1
            out[:, f] = np.where(nan, 0, b)
        return out

    def _transform_native(self, x: np.ndarray) -> "np.ndarray | None":
        """Multithreaded C++ binning (native/data_plane.cpp
        mmls_bin_matrix); returns None when the library is unavailable."""
        from mmlspark_tpu.native.bindings import bin_matrix, is_available

        if not is_available():
            return None
        # pad per-feature edges to one (F, maxlen+1) inf-padded matrix so
        # lower_bound never hits the clamp for in-range values
        maxlen = max((len(e) for e in self.upper_edges), default=0) + 1
        padded = np.full((self.num_features, maxlen), np.inf)
        for f in range(self.num_features):
            padded[f, :len(self.upper_edges[f])] = self.upper_edges[f]
        nan_mask = np.isnan(x)
        safe = np.where(nan_mask, -np.inf, x)
        bins = bin_matrix(safe, padded) + 1  # bin 0 is the missing bin
        bins[nan_mask] = 0
        return bins.astype(np.int32)

    def bin_upper_values(self, total_bins: int) -> np.ndarray:
        """(F, total_bins) raw-value upper bound per bin — lets a trained
        model carry real-valued thresholds so prediction never needs the
        BinMapper (the analog of LightGBM model strings carrying
        thresholds, booster/LightGBMBooster.scala:458)."""
        out = np.full((self.num_features, total_bins), np.inf, dtype=np.float64)
        for f in range(self.num_features):
            if self.is_categorical[f]:
                ncat = len(self.categories[f])
                out[f, 1:ncat + 1] = self.categories[f]
            else:
                e = self.upper_edges[f]
                out[f, 1:len(e) + 1] = e
            out[f, 0] = np.nan  # missing bin has no upper value
        return out

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "max_bin": self.max_bin,
            "is_categorical": self.is_categorical.tolist(),
            "upper_edges": [e.tolist() for e in self.upper_edges],
            "categories": [None if c is None else c.tolist() for c in self.categories],
        }

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        return BinMapper(
            upper_edges=[np.asarray(e, dtype=np.float64) for e in d["upper_edges"]],
            is_categorical=np.asarray(d["is_categorical"], dtype=bool),
            categories=[None if c is None else np.asarray(c, dtype=np.int64)
                        for c in d["categories"]],
            max_bin=d["max_bin"],
        )
