"""Exclusive feature bundling (EFB) for histogram construction.

LightGBM's EFB (arXiv:1706.08359 §4; io/dataset.cc FeatureGroup
construction): sparse features that are rarely non-default at the same
time are packed into one physical column, so every histogram pass
scans F_bundled << F columns. This implementation is the strict
zero-conflict variant — two features share a bundle only if NO row has
both non-default — so bundled histograms are exactly recoverable:

  - each bundle member gets a contiguous slot range in the bundled
    column (offset + dense code over its observed non-default bins);
    slot 0 means "every member at its default bin";
  - unbundling scatters slots back to (original feature, original bin)
    with static index maps baked into the compiled tree builder, and
    reconstructs each member's default-bin stats as the node total
    minus its present bins (every live row contributes exactly once
    per bundled column, so the total is shared across columns);
  - bundled values stay < n_bins, so the bundled matrix keeps the
    original ingest dtype and the histogram shape keeps the same B.

The plan is built once per fit on the host matrix (``plan_bundles``)
and applied by ``apply_plan``; the trainer bakes the plan's index maps
into the compiled builder (cache-keyed by ``plan.cache_key``) and trees
always record ORIGINAL feature ids — bundling is invisible outside
histogram construction.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.env import env_str

_WARNED_BAD_EFB = False

_VALID_EFB = ("auto", "off", "on")


def resolve_efb(warn: bool = True) -> str:
    """EFB policy (MMLSPARK_TPU_EFB, default auto): ``auto`` gates the
    planner on a sampled sparsity estimate (dense data skips planning
    in ~ms), ``on`` runs the full conflict scan regardless, ``off``
    disables bundling. Bad values warn once and run ``auto``
    (core.env contract)."""
    global _WARNED_BAD_EFB
    raw = (env_str("MMLSPARK_TPU_EFB", "") or "").strip().lower()
    if not raw:
        return "auto"
    if raw not in _VALID_EFB:
        if warn and not _WARNED_BAD_EFB:
            _WARNED_BAD_EFB = True
            import warnings
            warnings.warn(
                f"MMLSPARK_TPU_EFB={raw!r} is not one of auto|off|on; "
                "using auto", stacklevel=2)
        return "auto"
    return raw


@dataclass(frozen=True)
class BundleMember:
    feature: int          # original feature id
    default_bin: int      # bin reconstructed as total - present
    offset: int           # slot range start within the bundled column
    vals: Tuple[int, ...]  # observed non-default bins, slot o+1+j -> vals[j]


@dataclass(frozen=True)
class EFBPlan:
    n_features: int
    n_bins: int
    passthrough: Tuple[int, ...]            # original ids, col = position
    bundles: Tuple[Tuple[BundleMember, ...], ...]  # cols P..P+K-1

    @property
    def n_cols(self) -> int:
        return len(self.passthrough) + len(self.bundles)

    @property
    def n_bundled_features(self) -> int:
        return sum(len(bd) for bd in self.bundles)

    @property
    def cache_key(self) -> str:
        """Stable fingerprint for compiled-builder cache keys: the plan
        bakes static index maps into the trace, so two different plans
        must never share an executable."""
        h = hashlib.sha1()
        h.update(repr((self.n_features, self.n_bins, self.passthrough,
                       self.bundles)).encode())
        return h.hexdigest()

    def scatter_arrays(self):
        """(col, bundled_bin, feature, original_bin) int arrays, one
        entry per non-default slot across all bundles."""
        cols, bins, feats, obins = [], [], [], []
        p = len(self.passthrough)
        for bi, bundle in enumerate(self.bundles):
            for m in bundle:
                for j, v in enumerate(m.vals):
                    cols.append(p + bi)
                    bins.append(m.offset + 1 + j)
                    feats.append(m.feature)
                    obins.append(v)
        return (np.asarray(cols, np.int32), np.asarray(bins, np.int32),
                np.asarray(feats, np.int32), np.asarray(obins, np.int32))

    def member_default_arrays(self):
        """(feature, default_bin) for every bundled member."""
        feats = [m.feature for bd in self.bundles for m in bd]
        bins = [m.default_bin for bd in self.bundles for m in bd]
        return np.asarray(feats, np.int32), np.asarray(bins, np.int32)

    def passthrough_arrays(self):
        """(bundled col, original feature) for unbundled columns."""
        return (np.arange(len(self.passthrough), dtype=np.int32),
                np.asarray(self.passthrough, np.int32))


def _column_defaults(binned: np.ndarray, n_bins: int,
                     sample: np.ndarray) -> np.ndarray:
    """Per-column mode over a row sample — the reconstruction-by-
    subtraction bin. The mode need not be exact over all rows (any bin
    is a valid default); the sample keeps the dense-data gate cheap."""
    defaults = np.empty(binned.shape[1], np.int64)
    for j in range(binned.shape[1]):
        defaults[j] = np.bincount(sample[:, j], minlength=n_bins).argmax()
    return defaults


def plan_bundles(binned: np.ndarray, n_bins: int, mode: str = "auto",
                 sample_rows: int = 100_000,
                 seed: int = 0) -> Optional[EFBPlan]:
    """One-shot bundling plan for a host binned matrix, or ``None``
    when bundling won't help (dense data, no conflict-free pairs, or
    ``mode == "off"``).

    ``auto`` only considers columns whose sampled non-default fraction
    is <= 0.5 and gives up immediately when fewer than two qualify —
    uniformly-dense benchmark data exits in milliseconds. ``on`` treats
    every column with at least one default-bin row as a candidate.
    Conflict detection is EXACT over all rows (packbits masks, greedy
    first-fit over descending density): a sampled conflict graph could
    pack two features that collide on an unseen row, which would
    corrupt histograms rather than merely lose a little speed."""
    if mode == "off":
        return None
    n, f = binned.shape
    if n == 0 or f < 2:
        return None
    rng = np.random.default_rng(seed)
    if n > sample_rows:
        sample = binned[rng.choice(n, size=sample_rows, replace=False)]
    else:
        sample = binned
    defaults = _column_defaults(binned, n_bins, sample)
    nondefault_frac = (sample != defaults[None, :]).mean(axis=0)
    thresh = 1.0 if mode == "on" else 0.5
    candidates = [j for j in range(f) if nondefault_frac[j] < thresh]
    if len(candidates) < 2:
        return None

    # exact per-candidate non-default masks, packed to bits
    masks = {}
    counts = {}
    vals = {}
    for j in candidates:
        col = binned[:, j]
        nz = col != defaults[j]
        masks[j] = np.packbits(nz)
        counts[j] = int(nz.sum())
        vals[j] = tuple(int(v) for v in np.unique(col[nz]))

    # greedy first-fit decreasing: densest features first claim slots;
    # a feature joins a bundle iff it conflicts with NO member (packed
    # AND is zero) and the bundle's slot budget keeps values < n_bins
    order = sorted(candidates, key=lambda j: (-counts[j], j))
    slot_budget = n_bins - 1   # slot 0 = all-default
    bundle_feats: List[List[int]] = []
    bundle_masks: List[np.ndarray] = []
    bundle_used: List[int] = []
    for j in order:
        need = len(vals[j])
        if need > slot_budget:
            continue
        placed = False
        for bi in range(len(bundle_feats)):
            if bundle_used[bi] + need > slot_budget:
                continue
            if np.bitwise_and(bundle_masks[bi], masks[j]).any():
                continue
            bundle_feats[bi].append(j)
            bundle_masks[bi] |= masks[j]
            bundle_used[bi] += need
            placed = True
            break
        if not placed:
            bundle_feats.append([j])
            bundle_masks.append(masks[j].copy())
            bundle_used.append(need)

    real = [sorted(bf) for bf in bundle_feats if len(bf) >= 2]
    if not real:
        return None
    bundled_set = {j for bf in real for j in bf}
    passthrough = tuple(j for j in range(f) if j not in bundled_set)
    bundles = []
    for bf in real:
        members, off = [], 0
        for j in bf:
            members.append(BundleMember(feature=j,
                                        default_bin=int(defaults[j]),
                                        offset=off, vals=vals[j]))
            off += len(vals[j])
        bundles.append(tuple(members))
    return EFBPlan(n_features=f, n_bins=n_bins,
                   passthrough=passthrough, bundles=tuple(bundles))


def apply_plan(binned: np.ndarray, plan: EFBPlan) -> np.ndarray:
    """Host-side transform: (N, F) original bins -> (N, n_cols) bundled
    matrix in the same dtype (bundled codes stay < n_bins). Zero
    conflicts make member writes disjoint, so write order is
    irrelevant."""
    n = binned.shape[0]
    out = np.zeros((n, plan.n_cols), dtype=binned.dtype)
    for c, j in enumerate(plan.passthrough):
        out[:, c] = binned[:, j]
    p = len(plan.passthrough)
    for bi, bundle in enumerate(plan.bundles):
        col = np.zeros(n, dtype=np.int64)
        for m in bundle:
            code = np.zeros(plan.n_bins, dtype=np.int64)
            for j, v in enumerate(m.vals):
                code[v] = m.offset + 1 + j
            src = binned[:, m.feature]
            nz = src != m.default_bin
            col[nz] = code[src[nz].astype(np.int64)]
        out[:, p + bi] = col.astype(binned.dtype)
    return out
