"""MurmurHash3 (x86 32-bit) — VW-compatible feature hashing.

The reference hashes features through VW's murmur variant with a cached
namespace prefix (vw/.../VowpalWabbitMurmurWithPrefix.scala:1,
VowpalWabbitFeaturizer.scala:1). Implemented here from the public
MurmurHash3 spec; scalar path for strings (host, cached per vocab) and a
vectorized path for integer index streams.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Union

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.uint32, r: int) -> np.uint32:
    x = np.uint32(x)
    return np.uint32((np.uint64(x) << np.uint64(r) | (np.uint64(x) >> np.uint64(32 - r))) & np.uint64(0xFFFFFFFF))


def murmur3_32(data: Union[bytes, str], seed: int = 0) -> int:
    """Scalar MurmurHash3_x86_32."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    with np.errstate(over="ignore"):
        h = np.uint32(seed)
        n = len(data)
        nblocks = n // 4
        for i in range(nblocks):
            k = np.uint32(int.from_bytes(data[4 * i:4 * i + 4], "little"))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h = np.uint32(h ^ k)
            h = _rotl32(h, 13)
            h = np.uint32(h * np.uint32(5) + np.uint32(0xE6546B64))
        tail = data[nblocks * 4:]
        k = np.uint32(0)
        if len(tail) >= 3:
            k = np.uint32(k ^ np.uint32(tail[2] << 16))
        if len(tail) >= 2:
            k = np.uint32(k ^ np.uint32(tail[1] << 8))
        if len(tail) >= 1:
            k = np.uint32(k ^ np.uint32(tail[0]))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h = np.uint32(h ^ k)
        h = np.uint32(h ^ np.uint32(n))
        h = np.uint32(h ^ (h >> np.uint32(16)))
        h = np.uint32(h * np.uint32(0x85EBCA6B))
        h = np.uint32(h ^ (h >> np.uint32(13)))
        h = np.uint32(h * np.uint32(0xC2B2AE35))
        h = np.uint32(h ^ (h >> np.uint32(16)))
        return int(h)


@lru_cache(maxsize=65536)
def hash_feature(name: str, seed: int = 0) -> int:
    """Cached string-feature hash (the MurmurWithPrefix cache analog)."""
    return murmur3_32(name, seed)


def interact_hash(a: np.ndarray, b: np.ndarray, num_bits: int) -> np.ndarray:
    """Combine two hashed index arrays for quadratic interactions
    (VW's FNV-style pair combination), masked to num_bits."""
    mask = (1 << num_bits) - 1
    with np.errstate(over="ignore"):
        combined = a.astype(np.uint64) * np.uint64(0x100000001B3) + b.astype(np.uint64)
    return (combined & np.uint64(mask)).astype(np.int32)


def mask_bits(h: Union[int, np.ndarray], num_bits: int):
    mask = (1 << num_bits) - 1
    if isinstance(h, np.ndarray):
        return (h & mask).astype(np.int32)
    return int(h) & mask
