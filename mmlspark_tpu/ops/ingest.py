"""Host -> device ingest pipeline.

The reference streams rows into the native dataset in micro-batches
(StreamingPartitionTask.scala:203-277, pushDenseMicroBatches) so JVM
marshaling overlaps native ingestion. The TPU analog: ``device_put`` is
asynchronous, so chunking a large host array overlaps the host-side
prep of chunk i+1 (dtype narrowing, contiguity copy) with the wire
transfer of chunk i — double buffering without threads. Binned GBDT
matrices additionally narrow to uint8 (max_bin <= 256), cutting bytes
on the wire 4x vs int32; XLA's implicit integer promotion makes the
narrow dtype free on device (gathers/adds fuse the widening).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np


def chunked_device_put(arr: np.ndarray, sharding=None,
                       dtype: Optional[Any] = None,
                       chunk_bytes: int = 64 << 20,
                       row_multiple: int = 1):
    """Transfer ``arr`` to device in async chunks; returns the device
    array (concatenated under one jit so the result carries
    ``sharding``).

    ``row_multiple``: chunk row counts stay multiples of this (the mesh
    dp axis size when sharded). Small arrays fall through to one put.
    """
    import jax
    import jax.numpy as jnp

    if dtype is not None and arr.dtype != dtype:
        row_nbytes = int(np.dtype(dtype).itemsize * np.prod(arr.shape[1:],
                                                            dtype=np.int64))
    else:
        row_nbytes = int(arr.dtype.itemsize * np.prod(arr.shape[1:],
                                                      dtype=np.int64))
    n = arr.shape[0]
    chunk_rows = max(chunk_bytes // max(row_nbytes, 1), 1)
    chunk_rows = max(chunk_rows // row_multiple, 1) * row_multiple

    def prep(part):
        part = np.ascontiguousarray(part)
        if dtype is not None:
            part = part.astype(dtype, copy=False)
        return part

    if chunk_rows >= n:
        full = prep(arr)
        return (jax.device_put(full, sharding) if sharding is not None
                else jnp.asarray(full))

    parts = []
    for s in range(0, n, chunk_rows):
        # device_put returns immediately: the next chunk's host prep
        # overlaps this chunk's transfer. Each chunk carries the final
        # sharding (chunk rows are row_multiple-aligned), so shards go
        # straight to their devices — no single-device staging
        part = prep(arr[s:s + chunk_rows])
        parts.append(jax.device_put(part, sharding)
                     if sharding is not None and len(part) % row_multiple == 0
                     else jax.device_put(part))
    concat = jax.jit(lambda *p: jnp.concatenate(p, axis=0),
                     out_shardings=sharding)
    return concat(*parts)


def binned_ingest_dtype(total_bins: int):
    """Narrowest integer dtype holding bin ids in [0, total_bins).

    The single source of truth for bin-id dtype selection (binned
    scoring gathers run in the input dtype, so narrower moves fewer
    bytes): uint8 for the common <=256-bin configs, uint16 up to 65536
    (derived binnings from deep imported models can exceed 256
    thresholds per feature), int32 beyond."""
    if total_bins <= 256:
        return np.uint8
    if total_bins <= 65536:
        return np.uint16
    return np.int32


# -- spill-directory chunk store (out-of-core training plane) ---------------
#
# The out-of-core GBDT fit streams pre-binned row chunks from disk instead
# of holding the (N, F) binned matrix resident. The format is deliberately
# dumb: one .npy per chunk plus a JSON manifest, written append-only and
# sealed by an atomic manifest rename, so a partially written spill is
# never mistaken for a complete one.

_SPILL_MANIFEST = "spill_meta.json"


class SpillWriter:
    """Append-only writer for a binned row-chunk spill directory.

    ``append`` writes each chunk as ``chunk_{i:06d}.npy`` (narrowed to
    ``dtype``); ``finalize`` atomically publishes the manifest and
    returns a :class:`SpillReader`. Chunks may have uneven row counts;
    the feature count and dtype must stay fixed.
    """

    def __init__(self, path: str, dtype: Any = np.uint8) -> None:
        self.path = path
        self.dtype = np.dtype(dtype)
        self.chunk_rows: List[int] = []
        self.n_features: Optional[int] = None
        self._sealed = False
        os.makedirs(path, exist_ok=True)

    def append(self, chunk: np.ndarray) -> None:
        if self._sealed:
            raise RuntimeError("SpillWriter already finalized")
        c = np.ascontiguousarray(chunk)
        if c.ndim != 2:
            raise ValueError(f"spill chunks must be 2-d, got {c.shape}")
        if self.n_features is None:
            self.n_features = int(c.shape[1])
        elif c.shape[1] != self.n_features:
            raise ValueError(
                f"chunk has {c.shape[1]} features, expected {self.n_features}")
        i = len(self.chunk_rows)
        np.save(os.path.join(self.path, f"chunk_{i:06d}.npy"),
                c.astype(self.dtype, copy=False))
        self.chunk_rows.append(int(c.shape[0]))

    def finalize(self) -> "SpillReader":
        from mmlspark_tpu.core.serialize import atomic_write

        if self.n_features is None:
            raise ValueError("spill has no chunks")
        meta = {
            "version": 1,
            "dtype": self.dtype.name,
            "n_features": self.n_features,
            "chunk_rows": self.chunk_rows,
            "total_rows": int(sum(self.chunk_rows)),
        }
        atomic_write(os.path.join(self.path, _SPILL_MANIFEST),
                     json.dumps(meta, indent=1))
        self._sealed = True
        return SpillReader(self.path)


class SpillReader:
    """Reader over a sealed spill directory (see :class:`SpillWriter`)."""

    def __init__(self, path: str) -> None:
        self.path = path
        with open(os.path.join(path, _SPILL_MANIFEST)) as fh:
            meta = json.load(fh)
        self.dtype = np.dtype(meta["dtype"])
        self.n_features = int(meta["n_features"])
        self.chunk_rows: List[int] = [int(r) for r in meta["chunk_rows"]]
        self.total_rows = int(meta["total_rows"])
        self.offsets: List[int] = []
        off = 0
        for r in self.chunk_rows:
            self.offsets.append(off)
            off += r

    @property
    def num_chunks(self) -> int:
        return len(self.chunk_rows)

    def read(self, i: int) -> np.ndarray:
        return np.load(os.path.join(self.path, f"chunk_{i:06d}.npy"))

    def __iter__(self) -> Iterator[np.ndarray]:
        for i in range(self.num_chunks):
            yield self.read(i)


class ChunkStore:
    """Per-chunk float array store for out-of-core per-row state (raw
    score carry, quantized grad/hess). Same chunking as the companion
    spill; overwritten in place each iteration via tmp + ``os.replace``
    so a torn write never corrupts a chunk (resume rebuilds this state
    from checkpoints anyway — the atomicity just keeps same-process
    retries honest)."""

    def __init__(self, path: str, name: str) -> None:
        self.path = path
        self.name = name
        os.makedirs(path, exist_ok=True)

    def _file(self, i: int) -> str:
        return os.path.join(self.path, f"{self.name}_{i:06d}.npy")

    def put(self, i: int, arr: np.ndarray) -> None:
        tmp = self._file(i) + ".tmp.npy"
        np.save(tmp, np.ascontiguousarray(arr))
        os.replace(tmp, self._file(i))

    def get(self, i: int) -> np.ndarray:
        return np.load(self._file(i))
