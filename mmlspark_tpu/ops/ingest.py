"""Host -> device ingest pipeline.

The reference streams rows into the native dataset in micro-batches
(StreamingPartitionTask.scala:203-277, pushDenseMicroBatches) so JVM
marshaling overlaps native ingestion. The TPU analog: ``device_put`` is
asynchronous, so chunking a large host array overlaps the host-side
prep of chunk i+1 (dtype narrowing, contiguity copy) with the wire
transfer of chunk i — double buffering without threads. Binned GBDT
matrices additionally narrow to uint8 (max_bin <= 256), cutting bytes
on the wire 4x vs int32; XLA's implicit integer promotion makes the
narrow dtype free on device (gathers/adds fuse the widening).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def chunked_device_put(arr: np.ndarray, sharding=None,
                       dtype: Optional[Any] = None,
                       chunk_bytes: int = 64 << 20,
                       row_multiple: int = 1):
    """Transfer ``arr`` to device in async chunks; returns the device
    array (concatenated under one jit so the result carries
    ``sharding``).

    ``row_multiple``: chunk row counts stay multiples of this (the mesh
    dp axis size when sharded). Small arrays fall through to one put.
    """
    import jax
    import jax.numpy as jnp

    if dtype is not None and arr.dtype != dtype:
        row_nbytes = int(np.dtype(dtype).itemsize * np.prod(arr.shape[1:],
                                                            dtype=np.int64))
    else:
        row_nbytes = int(arr.dtype.itemsize * np.prod(arr.shape[1:],
                                                      dtype=np.int64))
    n = arr.shape[0]
    chunk_rows = max(chunk_bytes // max(row_nbytes, 1), 1)
    chunk_rows = max(chunk_rows // row_multiple, 1) * row_multiple

    def prep(part):
        part = np.ascontiguousarray(part)
        if dtype is not None:
            part = part.astype(dtype, copy=False)
        return part

    if chunk_rows >= n:
        full = prep(arr)
        return (jax.device_put(full, sharding) if sharding is not None
                else jnp.asarray(full))

    parts = []
    for s in range(0, n, chunk_rows):
        # device_put returns immediately: the next chunk's host prep
        # overlaps this chunk's transfer. Each chunk carries the final
        # sharding (chunk rows are row_multiple-aligned), so shards go
        # straight to their devices — no single-device staging
        part = prep(arr[s:s + chunk_rows])
        parts.append(jax.device_put(part, sharding)
                     if sharding is not None and len(part) % row_multiple == 0
                     else jax.device_put(part))
    concat = jax.jit(lambda *p: jnp.concatenate(p, axis=0),
                     out_shardings=sharding)
    return concat(*parts)


def binned_ingest_dtype(total_bins: int):
    """Narrowest integer dtype holding bin ids in [0, total_bins).

    The single source of truth for bin-id dtype selection (binned
    scoring gathers run in the input dtype, so narrower moves fewer
    bytes): uint8 for the common <=256-bin configs, uint16 up to 65536
    (derived binnings from deep imported models can exceed 256
    thresholds per feature), int32 beyond."""
    if total_bins <= 256:
        return np.uint8
    if total_bins <= 65536:
        return np.uint16
    return np.int32
