"""Host -> device ingest pipeline.

The reference streams rows into the native dataset in micro-batches
(StreamingPartitionTask.scala:203-277, pushDenseMicroBatches) so JVM
marshaling overlaps native ingestion. The TPU analog: ``device_put`` is
asynchronous, so chunking a large host array overlaps the host-side
prep of chunk i+1 (dtype narrowing, contiguity copy) with the wire
transfer of chunk i — double buffering without threads. Binned GBDT
matrices additionally narrow to uint8 (max_bin <= 256), cutting bytes
on the wire 4x vs int32; XLA's implicit integer promotion makes the
narrow dtype free on device (gathers/adds fuse the widening).
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, Iterator, List, Optional, Sequence, Set

import numpy as np

from mmlspark_tpu.core.faults import FaultInjected, fault_point
from mmlspark_tpu.core.serialize import DiskFull


def chunked_device_put(arr: np.ndarray, sharding=None,
                       dtype: Optional[Any] = None,
                       chunk_bytes: int = 64 << 20,
                       row_multiple: int = 1):
    """Transfer ``arr`` to device in async chunks; returns the device
    array (concatenated under one jit so the result carries
    ``sharding``).

    ``row_multiple``: chunk row counts stay multiples of this (the mesh
    dp axis size when sharded). Small arrays fall through to one put.
    """
    import jax
    import jax.numpy as jnp

    if dtype is not None and arr.dtype != dtype:
        row_nbytes = int(np.dtype(dtype).itemsize * np.prod(arr.shape[1:],
                                                            dtype=np.int64))
    else:
        row_nbytes = int(arr.dtype.itemsize * np.prod(arr.shape[1:],
                                                      dtype=np.int64))
    n = arr.shape[0]
    chunk_rows = max(chunk_bytes // max(row_nbytes, 1), 1)
    chunk_rows = max(chunk_rows // row_multiple, 1) * row_multiple

    def prep(part):
        part = np.ascontiguousarray(part)
        if dtype is not None:
            part = part.astype(dtype, copy=False)
        return part

    if chunk_rows >= n:
        full = prep(arr)
        return (jax.device_put(full, sharding) if sharding is not None
                else jnp.asarray(full))

    parts = []
    for s in range(0, n, chunk_rows):
        # device_put returns immediately: the next chunk's host prep
        # overlaps this chunk's transfer. Each chunk carries the final
        # sharding (chunk rows are row_multiple-aligned), so shards go
        # straight to their devices — no single-device staging
        part = prep(arr[s:s + chunk_rows])
        parts.append(jax.device_put(part, sharding)
                     if sharding is not None and len(part) % row_multiple == 0
                     else jax.device_put(part))
    concat = jax.jit(lambda *p: jnp.concatenate(p, axis=0),
                     out_shardings=sharding)
    return concat(*parts)


def binned_ingest_dtype(total_bins: int):
    """Narrowest integer dtype holding bin ids in [0, total_bins).

    The single source of truth for bin-id dtype selection (binned
    scoring gathers run in the input dtype, so narrower moves fewer
    bytes): uint8 for the common <=256-bin configs, uint16 up to 65536
    (derived binnings from deep imported models can exceed 256
    thresholds per feature), int32 beyond."""
    if total_bins <= 256:
        return np.uint8
    if total_bins <= 65536:
        return np.uint16
    return np.int32


# -- spill-directory chunk store (out-of-core training plane) ---------------
#
# The out-of-core GBDT fit streams pre-binned row chunks from disk instead
# of holding the (N, F) binned matrix resident. The format is deliberately
# dumb: one framed file per chunk plus a JSON manifest, written append-only
# and sealed by an atomic manifest rename, so a partially written spill is
# never mistaken for a complete one.
#
# Chunk frame (since v2): MAGIC | header-len (uint32 LE) | JSON header
# {version, dtype, shape, nbytes, crc32} | raw C-order payload bytes.
# The crc32 (stdlib zlib) turns silent disk bit-rot into an attributed
# SpillCorrupt instead of wrong trees: the filesystem is NOT trusted
# (arXiv:1605.08695 treats checksummed persistence I/O as table stakes).
# Verification policy comes from MMLSPARK_TPU_SPILL_VERIFY
# (see resolve_spill_verify); the cost is accounted per reader/store so
# hist_stats can stamp it.

_SPILL_MANIFEST = "spill_meta.json"
_FRAME_MAGIC = b"MMSC"        # "mmlspark spill chunk"
_FRAME_VERSION = 1
_VERIFY_MODES = ("auto", "off", "on")


class SpillCorrupt(RuntimeError):
    """An on-disk chunk failed structural or checksum validation
    (truncation, bad magic, crc32 mismatch, missing file). Carries
    ``chunk`` (index, when known) and ``path`` so OOC failures are
    attributable to one artifact."""

    def __init__(self, message: str, *, chunk: Optional[int] = None,
                 path: Optional[str] = None) -> None:
        super().__init__(message)
        self.chunk = chunk
        self.path = path


def resolve_spill_verify() -> str:
    """MMLSPARK_TPU_SPILL_VERIFY policy: ``auto`` (default — always
    verify checkpoint payload digests, verify each spill chunk's crc32
    on its first read), ``on`` (verify every read), ``off`` (trust the
    disk). A bad value warns once and falls back to auto."""
    from mmlspark_tpu.core.env import env_str
    from mmlspark_tpu.core.logging_utils import warn_once
    v = (env_str("MMLSPARK_TPU_SPILL_VERIFY", "auto") or "auto")
    v = v.strip().lower() or "auto"
    if v not in _VERIFY_MODES:
        warn_once("spill.verify.mode",
                  "MMLSPARK_TPU_SPILL_VERIFY=%r is not one of %s; "
                  "using 'auto'", v, "|".join(_VERIFY_MODES))
        v = "auto"
    return v


def pack_frame(arr: np.ndarray) -> bytes:
    """Serialize one array to the framed chunk format (header + crc32
    over the payload bytes)."""
    c = np.ascontiguousarray(arr)
    payload = c.tobytes()
    header = json.dumps({
        "version": _FRAME_VERSION, "dtype": c.dtype.name,
        "shape": list(c.shape), "nbytes": len(payload),
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }, separators=(",", ":")).encode()
    return (_FRAME_MAGIC + struct.pack("<I", len(header))
            + header + payload)


def write_chunk(path: str, arr: np.ndarray) -> None:
    """Atomically persist one framed chunk (tmp + ``os.replace``).

    Every spill-plane write funnels through the ``io.disk_full`` fault
    boundary: a real ENOSPC/quota OSError — or an armed fault — comes
    back as the attributed :class:`~mmlspark_tpu.core.serialize.
    DiskFull` so callers can degrade (OOC falls back in-core) instead
    of surfacing a bare write error."""
    frame = pack_frame(arr)
    tmp = path + ".tmp"
    try:
        fault_point("io.disk_full")
        with open(tmp, "wb") as fh:
            fh.write(frame)
        os.replace(tmp, path)
    except (OSError, FaultInjected) as e:
        raise DiskFull(
            f"[io.disk_full] spill chunk write failed for {path} "
            f"({type(e).__name__}: {e})") from e


def read_chunk(path: str, *, verify: bool = True,
               chunk: Optional[int] = None,
               label: str = "spill") -> tuple:
    """Load one framed chunk; returns ``(array, verify_seconds)``.

    Structural damage (missing file, truncation, bad magic/header) and
    — when ``verify`` — a crc32 mismatch raise :class:`SpillCorrupt`
    with expected/actual byte counts. The payload passes through the
    ``spill.read`` fault point before the checksum, so an armed
    ``corrupt`` action is caught exactly like real bit-rot."""
    where = f"{label} chunk {chunk}" if chunk is not None else label
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
    except OSError as e:
        raise SpillCorrupt(
            f"{where}: chunk file missing or unreadable at {path} "
            f"({type(e).__name__}: {e})", chunk=chunk, path=path) from e
    if len(blob) < 8 or blob[:4] != _FRAME_MAGIC:
        raise SpillCorrupt(
            f"{where}: {path} is not a framed spill chunk (expected "
            f"magic {_FRAME_MAGIC!r} + header, found {len(blob)} "
            f"bytes)", chunk=chunk, path=path)
    (hlen,) = struct.unpack("<I", blob[4:8])
    try:
        header = json.loads(blob[8:8 + hlen])
        expected = int(header["nbytes"])
        stored_crc = int(header["crc32"])
        dtype = np.dtype(header["dtype"])
        shape = tuple(int(s) for s in header["shape"])
    except Exception as e:
        raise SpillCorrupt(
            f"{where}: torn frame header in {path} "
            f"({type(e).__name__}: {e})", chunk=chunk, path=path) from e
    payload = blob[8 + hlen:]
    if len(payload) != expected:
        raise SpillCorrupt(
            f"{where}: truncated payload in {path} — expected "
            f"{expected} bytes, found {len(payload)}",
            chunk=chunk, path=path)
    payload = fault_point("spill.read", payload)
    verify_s = 0.0
    if verify:
        t0 = time.perf_counter()
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        verify_s = time.perf_counter() - t0
        if crc != stored_crc:
            raise SpillCorrupt(
                f"{where}: crc32 mismatch in {path} (stored "
                f"{stored_crc:#010x}, found {crc:#010x}) — disk "
                f"bit-rot or tampering", chunk=chunk, path=path)
    try:
        arr = np.frombuffer(payload, dtype=dtype).reshape(shape)
    except ValueError as e:
        raise SpillCorrupt(
            f"{where}: payload in {path} does not reshape to "
            f"{shape} {dtype} ({e})", chunk=chunk, path=path) from e
    return arr, verify_s


class SpillWriter:
    """Append-only writer for a binned row-chunk spill directory.

    ``append`` writes each chunk as a framed ``chunk_{i:06d}.bin``
    (narrowed to ``dtype``, crc32-stamped); ``finalize`` atomically
    publishes the manifest and returns a :class:`SpillReader`. Chunks
    may have uneven row counts; the feature count and dtype must stay
    fixed.
    """

    def __init__(self, path: str, dtype: Any = np.uint8) -> None:
        self.path = path
        self.dtype = np.dtype(dtype)
        self.chunk_rows: List[int] = []
        self.n_features: Optional[int] = None
        self._sealed = False
        os.makedirs(path, exist_ok=True)

    def append(self, chunk: np.ndarray) -> None:
        if self._sealed:
            raise RuntimeError("SpillWriter already finalized")
        c = np.ascontiguousarray(chunk)
        if c.ndim != 2:
            raise ValueError(f"spill chunks must be 2-d, got {c.shape}")
        if self.n_features is None:
            self.n_features = int(c.shape[1])
        elif c.shape[1] != self.n_features:
            raise ValueError(
                f"chunk has {c.shape[1]} features, expected {self.n_features}")
        i = len(self.chunk_rows)
        write_chunk(os.path.join(self.path, f"chunk_{i:06d}.bin"),
                    c.astype(self.dtype, copy=False))
        self.chunk_rows.append(int(c.shape[0]))

    def finalize(self) -> "SpillReader":
        from mmlspark_tpu.core.serialize import atomic_write

        if self.n_features is None:
            raise ValueError("spill has no chunks")
        meta = {
            "version": 2,
            "dtype": self.dtype.name,
            "n_features": self.n_features,
            "chunk_rows": self.chunk_rows,
            "total_rows": int(sum(self.chunk_rows)),
        }
        atomic_write(os.path.join(self.path, _SPILL_MANIFEST),
                     json.dumps(meta, indent=1))
        self._sealed = True
        return SpillReader(self.path)


class SpillReader:
    """Reader over a sealed spill directory (see :class:`SpillWriter`).

    ``read`` verifies chunk checksums per :func:`resolve_spill_verify`
    (auto = first read of each chunk); the cumulative cost lands in
    ``verify_s`` / ``verify_chunks`` for hist_stats accounting.
    ``repair`` rewrites one chunk from trusted source bytes after a
    detected corruption."""

    def __init__(self, path: str) -> None:
        self.path = path
        meta_path = os.path.join(path, _SPILL_MANIFEST)
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            raise SpillCorrupt(
                f"spill manifest missing or unreadable at {meta_path} "
                f"({type(e).__name__}: {e}) — the spill was never "
                "sealed or the directory is damaged",
                path=meta_path) from e
        self.dtype = np.dtype(meta["dtype"])
        self.n_features = int(meta["n_features"])
        self.chunk_rows: List[int] = [int(r) for r in meta["chunk_rows"]]
        self.total_rows = int(meta["total_rows"])
        self.offsets: List[int] = []
        off = 0
        for r in self.chunk_rows:
            self.offsets.append(off)
            off += r
        self.verify_mode = resolve_spill_verify()
        self.verify_s = 0.0
        self.verify_chunks = 0
        self.repairs = 0
        self._verified: Set[int] = set()

    @property
    def num_chunks(self) -> int:
        return len(self.chunk_rows)

    def _chunk_path(self, i: int) -> str:
        return os.path.join(self.path, f"chunk_{i:06d}.bin")

    def read(self, i: int) -> np.ndarray:
        check = (self.verify_mode == "on"
                 or (self.verify_mode == "auto"
                     and i not in self._verified))
        arr, vs = read_chunk(self._chunk_path(i), verify=check, chunk=i)
        if check:
            self.verify_s += vs
            self.verify_chunks += 1
            self._verified.add(i)
        if (arr.dtype != self.dtype
                or arr.shape != (self.chunk_rows[i], self.n_features)):
            raise SpillCorrupt(
                f"spill chunk {i}: {self._chunk_path(i)} holds "
                f"{arr.shape} {arr.dtype}, manifest says "
                f"({self.chunk_rows[i]}, {self.n_features}) "
                f"{self.dtype}", chunk=i, path=self._chunk_path(i))
        return arr

    def repair(self, i: int, chunk: np.ndarray) -> None:
        """Rewrite chunk ``i`` from re-derived source data (binning is
        deterministic on fixed sketch edges, so the bytes are the
        originals)."""
        c = np.ascontiguousarray(chunk).astype(self.dtype, copy=False)
        if c.shape != (self.chunk_rows[i], self.n_features):
            raise ValueError(
                f"repair chunk {i}: source produced {c.shape}, spill "
                f"expects ({self.chunk_rows[i]}, {self.n_features})")
        write_chunk(self._chunk_path(i), c)
        self.repairs += 1
        # the frame was just built from trusted bytes: first-read
        # verification is already discharged
        self._verified.add(i)

    def __iter__(self) -> Iterator[np.ndarray]:
        for i in range(self.num_chunks):
            yield self.read(i)


class ChunkStore:
    """Per-chunk array store for out-of-core per-row state (raw score
    carry, quantized grad/hess, node ids). Same chunking as the
    companion spill; overwritten in place each iteration via tmp +
    ``os.replace`` so a torn write never corrupts a chunk (resume
    rebuilds this state from checkpoints anyway — the atomicity just
    keeps same-process retries honest). Entries carry the same framed
    crc32 as spill chunks; under SPILL_VERIFY=auto each entry is
    re-verified on its first read after every ``put``."""

    def __init__(self, path: str, name: str) -> None:
        self.path = path
        self.name = name
        self.verify_mode = resolve_spill_verify()
        self.verify_s = 0.0
        self.verify_chunks = 0
        self._verified: Set[int] = set()
        os.makedirs(path, exist_ok=True)

    def _file(self, i: int) -> str:
        return os.path.join(self.path, f"{self.name}_{i:06d}.bin")

    def put(self, i: int, arr: np.ndarray) -> None:
        write_chunk(self._file(i), np.ascontiguousarray(arr))
        self._verified.discard(i)

    def get(self, i: int) -> np.ndarray:
        path = self._file(i)
        check = (self.verify_mode == "on"
                 or (self.verify_mode == "auto"
                     and i not in self._verified))
        arr, vs = read_chunk(path, verify=check, chunk=i,
                             label=f"chunk store {self.name!r}")
        if check:
            self.verify_s += vs
            self.verify_chunks += 1
            self._verified.add(i)
        return arr
