"""Mergeable streaming quantile sketch for one-pass bin-edge estimation.

KLL/GK-style compactor hierarchy with a deterministic parity schedule:
level ``i`` holds items of weight ``2**i``; when a level overflows its
capacity it is sorted and every other element is promoted to level
``i + 1``, alternating which half survives on successive compactions.
Classic KLL flips a random coin per compaction; we replace the coin
with a per-level parity bit that flips on every compaction, which keeps
the same worst-case rank-error telescope while staying bit-reproducible
across runs (ops/ modules must not consume RNG or wall-clock state —
graftlint GL005).

Each compaction of level ``i`` perturbs the rank of any query point by
at most ``2**i`` (the weight of the items whose survival the parity
decides), so the sketch tracks an exact additive rank-error bound in
``rank_error()`` as it goes: ``sum(2**level over compactions)``.  Tests
assert against this analytic bound rather than a distributional one.

Sketches over disjoint chunks merge associatively: ``merge`` concatenates
per-level buffers and recompacts, and the error bounds add.  ``n``,
``min``/``max`` and NaN filtering are tracked exactly, so degenerate
features (constant, all-NaN, tiny-n) take exact paths downstream in
``BinMapper.fit_streaming``.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["QuantileSketch", "DEFAULT_SKETCH_K"]

# Per-level capacity.  Rank error after N items is roughly
# N / k * log2(N / k) in the worst case; k = 2048 keeps the relative
# rank error below ~1e-3 out to billions of rows while holding at most
# a few hundred KiB per feature.
DEFAULT_SKETCH_K = 2048


class QuantileSketch:
    """Deterministic mergeable quantile sketch over a stream of floats.

    NaNs are filtered on ingest (callers bin NaN/missing separately);
    +-inf are kept — they sort to the ends and cannot split a bin edge
    anyway.  All floats are handled as float64.
    """

    __slots__ = ("k", "n", "vmin", "vmax", "_levels", "_parity", "_err")

    def __init__(self, k: int = DEFAULT_SKETCH_K) -> None:
        if k < 8:
            raise ValueError(f"sketch capacity k must be >= 8, got {k}")
        self.k = int(k)
        self.n = 0              # exact count of non-NaN items ingested
        self.vmin = np.inf      # exact running min / max
        self.vmax = -np.inf
        self._levels: List[np.ndarray] = [np.empty(0, dtype=np.float64)]
        self._parity: List[int] = [0]
        self._err = 0           # additive rank-error bound (in rank units)

    # -- ingest ---------------------------------------------------------

    def update(self, values: np.ndarray) -> None:
        """Ingest a chunk of values (any shape; flattened, NaN-dropped)."""
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return
        v = v[~np.isnan(v)]
        if v.size == 0:
            return
        self.n += int(v.size)
        self.vmin = min(self.vmin, float(v.min()))
        self.vmax = max(self.vmax, float(v.max()))
        # Feed level 0 in capacity-sized slices so a huge chunk cannot
        # transiently hold chunk_rows extra floats in the buffer.
        buf = self._levels[0]
        for s in range(0, v.size, self.k):
            buf = np.concatenate([buf, v[s:s + self.k]])
            if buf.size >= self.k:
                self._levels[0] = buf
                self._compact_from(0)
                buf = self._levels[0]
        self._levels[0] = buf

    def _ensure_level(self, i: int) -> None:
        while len(self._levels) <= i:
            self._levels.append(np.empty(0, dtype=np.float64))
            self._parity.append(0)

    def _compact_from(self, start: int) -> None:
        i = start
        while i < len(self._levels) and self._levels[i].size >= self.k:
            arr = np.sort(self._levels[i], kind="stable")
            if arr.size % 2 == 1:
                # Odd length: the last element stays behind so the
                # promoted pairs cover an even prefix exactly.
                keep_back, arr = arr[-1:], arr[:-1]
            else:
                keep_back = arr[:0]
            p = self._parity[i]
            self._parity[i] = 1 - p
            promoted = arr[p::2]
            self._levels[i] = keep_back
            self._err += 1 << i
            self._ensure_level(i + 1)
            self._levels[i + 1] = np.concatenate(
                [self._levels[i + 1], promoted])
            i += 1

    # -- merge ----------------------------------------------------------

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into this sketch in place (and return self)."""
        if other.k != self.k:
            raise ValueError(
                f"cannot merge sketches with k={self.k} and k={other.k}")
        if other.n == 0:
            return self
        self.n += other.n
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        self._err += other._err
        self._ensure_level(len(other._levels) - 1)
        for i, arr in enumerate(other._levels):
            if arr.size:
                self._levels[i] = np.concatenate([self._levels[i], arr])
        self._compact_from(0)
        return self

    # -- queries --------------------------------------------------------

    def rank_error(self) -> int:
        """Additive bound on |estimated rank - true rank| for any value."""
        return self._err

    def items(self) -> Tuple[np.ndarray, np.ndarray]:
        """All retained items as (sorted unique values, summed weights).

        Weights are the level weights (2**i); summing them per unique
        value gives the sketch's estimate of each value's multiplicity.
        ``weights.sum() == n`` is NOT guaranteed exactly (odd-length
        compactions shed one item's weight per promotion), but stays
        within ``rank_error()`` of it.
        """
        vals: List[np.ndarray] = []
        wts: List[np.ndarray] = []
        for i, arr in enumerate(self._levels):
            if arr.size:
                vals.append(arr)
                wts.append(np.full(arr.size, float(1 << i)))
        if not vals:
            return (np.empty(0, dtype=np.float64),
                    np.empty(0, dtype=np.float64))
        v = np.concatenate(vals)
        w = np.concatenate(wts)
        uniq, inv = np.unique(v, return_inverse=True)
        agg = np.bincount(inv, weights=w, minlength=uniq.size)
        return uniq, agg

    def rank(self, value: float) -> float:
        """Estimated number of ingested items <= value."""
        total = 0.0
        for i, arr in enumerate(self._levels):
            if arr.size:
                total += float(np.sum(arr <= value)) * (1 << i)
        return total

    def quantiles(self, qs: Sequence[float]) -> np.ndarray:
        """Estimated quantile values for each q in [0, 1]."""
        uniq, w = self.items()
        out = np.empty(len(qs), dtype=np.float64)
        if uniq.size == 0:
            out.fill(np.nan)
            return out
        cum = np.cumsum(w)
        total = cum[-1]
        targets = np.clip(np.asarray(qs, dtype=np.float64), 0.0, 1.0) * total
        idx = np.searchsorted(cum, targets, side="left")
        idx = np.minimum(idx, uniq.size - 1)
        return uniq[idx]

    def quantile(self, q: float) -> float:
        return float(self.quantiles([q])[0])

    def __len__(self) -> int:
        return self.n

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        held = sum(a.size for a in self._levels)
        return (f"QuantileSketch(k={self.k}, n={self.n}, held={held}, "
                f"levels={len(self._levels)}, rank_err<={self._err})")
