from mmlspark_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    create_mesh,
    data_axis,
    default_mesh,
    feature_axis,
    model_axis,
)
