"""Long-context attention: blockwise, ring, and Ulysses (all-to-all).

The reference has NO sequence parallelism (SURVEY.md §5 — grep-verified
absent); its long-input story is chunking transformers only. This module
is the TPU-native long-context design mandated by the build brief:

- :func:`blockwise_attention` — single-device memory-efficient attention
  (online-softmax over KV blocks, flash-attention recurrence) as a
  ``lax.scan``; O(block) memory instead of O(n²).
- :func:`ring_attention` — sequence sharded over the ``sp`` mesh axis;
  KV blocks rotate around the ring via ``lax.ppermute`` (ICI
  neighbor exchange) while each device accumulates its queries' online
  softmax. Communication overlaps compute; no device ever holds the
  full sequence.
- :func:`ulysses_attention` — DeepSpeed-Ulysses style: ``all_to_all``
  swaps the sequence shard for a head shard, full attention runs per
  head group, then a second ``all_to_all`` restores sequence sharding.
  Cheaper collectives for models with many heads; requires
  heads % sp == 0.

All three produce results identical (up to float tolerance) to dense
softmax attention; tests check this on an 8-device CPU mesh.

Shapes follow (batch, seq, heads, head_dim). Causal masking uses global
positions, so sharded and dense results agree.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

from mmlspark_tpu.core.sanitizer import record_collective
from mmlspark_tpu.parallel.mesh import SEQUENCE_AXIS

_NEG_INF = -1e30


def _block_attend(q, k, v, out, row_max, row_sum, q_offset, k_offset,
                  causal: bool, scale: float):
    """One online-softmax accumulation step.

    q: (b, nq, h, d); k/v: (b, nk, h, d); out/row_max/row_sum are the
    running accumulators. Returns updated (out, row_max, row_sum).
    """
    import jax.numpy as jnp

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        nq, nk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(nq)
        k_pos = k_offset + jnp.arange(nk)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)

    blk_max = jnp.max(scores, axis=-1)                      # (b, h, q)
    new_max = jnp.maximum(row_max, blk_max)
    # rescale previous accumulators to the new max
    correction = jnp.exp(row_max - new_max)
    p = jnp.exp(scores - new_max[..., None])                # (b, h, q, k)
    new_sum = row_sum * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    new_out = out * correction.transpose(0, 2, 1)[..., None] + pv
    return new_out, new_max, new_sum


def _streamed_attend(q, k, v, out, row_max, row_sum, q_offset, k_offset,
                     causal: bool, scale: float, block_size: int = 512):
    """Online-softmax accumulation over ``k``/``v`` in sub-blocks, so
    the materialized score tile is (nq, block_size) instead of
    (nq, nk) — ring attention's per-rotation attend stays linear in
    the rotated chunk length at any sequence scale."""
    import jax
    import jax.numpy as jnp

    nk = k.shape[1]
    # divisor-fit block, exactly as blockwise_attention: awkward chunk
    # lengths stream at the largest fitting divisor; prime-ish lengths
    # take one dense tile rather than a column-at-a-time scan
    block = min(block_size, nk)
    while nk % block:
        block -= 1
    if block < min(block_size, nk) // 4:
        block = nk
    n_blocks = nk // block
    if n_blocks == 1:
        return _block_attend(q, k, v, out, row_max, row_sum,
                             q_offset, k_offset, causal, scale)
    b = k.shape[0]
    kb = k.reshape(b, n_blocks, block, *k.shape[2:]).transpose(
        1, 0, 2, 3, 4)
    vb = v.reshape(b, n_blocks, block, *v.shape[2:]).transpose(
        1, 0, 2, 3, 4)

    def step(carry, blk):
        out, row_max, row_sum, i = carry
        kk, vv = blk
        out, row_max, row_sum = _block_attend(
            q, kk, vv, out, row_max, row_sum, q_offset,
            k_offset + i * block, causal, scale)
        return (out, row_max, row_sum, i + 1), None

    from mmlspark_tpu.core.jax_compat import operand_vma, pcast_varying
    i0 = jnp.asarray(0)
    i0 = pcast_varying(
        i0, tuple(sorted(operand_vma(q, k, v, out, row_max, row_sum))))
    (out, row_max, row_sum, _), _ = jax.lax.scan(
        step, (out, row_max, row_sum, i0), (kb, vb))
    return out, row_max, row_sum


def blockwise_attention(q, k, v, block_size: int = 512,
                        causal: bool = False):
    """Memory-efficient attention via lax.scan over KV blocks."""
    import jax
    import jax.numpy as jnp

    b, n, h, d = q.shape
    nk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    # largest divisor of nk that fits the requested block: any kv
    # length streams (the scan needs equal blocks; a 704-long sequence
    # gets 352-wide blocks rather than a ValueError). Awkward lengths
    # whose divisors are all tiny (primes) take one dense tile instead
    # of degenerating into a column-at-a-time scan.
    block = min(block_size, nk)
    while nk % block:
        block -= 1
    if block < min(block_size, nk) // 4:
        block = nk
    n_blocks = nk // block
    k_blocks = k.reshape(b, n_blocks, block, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_blocks, block, h, d).transpose(1, 0, 2, 3, 4)

    def step(carry, blk):
        out, row_max, row_sum, blk_i = carry
        kb, vb = blk
        out, row_max, row_sum = _block_attend(
            q, kb, vb, out, row_max, row_sum,
            q_offset=0, k_offset=blk_i * block, causal=causal, scale=scale)
        return (out, row_max, row_sum, blk_i + 1), None

    stats0 = (jnp.full((b, h, n), _NEG_INF, q.dtype),
              jnp.zeros((b, h, n), q.dtype))
    # inside a shard_map (e.g. the Ulysses inner attention) the inputs
    # vary over the sp axis, so the freshly-created accumulators must be
    # promoted to the same varying type or the scan carry mismatches
    from mmlspark_tpu.core.jax_compat import operand_vma, pcast_varying
    stats0 = pcast_varying(stats0, tuple(sorted(operand_vma(q, k, v))))
    init = (jnp.zeros_like(q), *stats0, jnp.asarray(0))
    (out, row_max, row_sum, _), _ = jax.lax.scan(
        step, init, (k_blocks, v_blocks))
    return out / jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]


def fused_attention(q, k, v, causal: bool = False, block_size: int = 512):
    """Single-device attention through the fastest available path: the
    Pallas flash kernel on TPU (mmlspark_tpu.parallel.flash), else the
    XLA blockwise scan."""
    from mmlspark_tpu.parallel.flash import flash_attention, flash_available

    n, nk = q.shape[1], k.shape[1]
    if flash_available() and n % 128 == 0 and nk % 128 == 0:
        return flash_attention(q, k, v, causal=causal)
    return blockwise_attention(q, k, v, block_size=block_size,
                               causal=causal)


def ring_attention(q, k, v, mesh, causal: bool = False,
                   axis_name: str = SEQUENCE_AXIS):
    """Sequence-parallel attention: KV rotates around the ``sp`` ring.

    Inputs are GLOBAL arrays (b, n, h, d); the shard_map shards them on
    the sequence axis. Each of the P devices holds n/P queries and
    rotates its KV shard P times via ``ppermute``, accumulating online
    softmax. Equivalent to dense attention on the full sequence.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mmlspark_tpu.core.jax_compat import pcast_varying, shard_map

    n = q.shape[1]
    sp = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if n % sp:
        raise ValueError(f"sequence {n} not divisible by sp={sp}")
    chunk = n // sp
    scale = 1.0 / (q.shape[-1] ** 0.5)

    spec = P(None, axis_name, None, None)

    def local(qc, kc, vc):
        # qc/kc/vc: (b, n/P, h, d) — this device's shard
        idx = jax.lax.axis_index(axis_name)
        b, nq, h, d = qc.shape
        q_off = idx * chunk

        def step(i, carry):
            out, row_max, row_sum, kb, vb = carry
            # the KV block currently held started at device (idx - i)
            src = (idx - i) % sp
            out, row_max, row_sum = _streamed_attend(
                qc, kb, vb, out, row_max, row_sum,
                q_offset=q_off, k_offset=src * chunk,
                causal=causal, scale=scale)
            # rotate KV to the next device (neighbor exchange on ICI)
            perm = [(j, (j + 1) % sp) for j in range(sp)]
            record_collective("ppermute", axis_name, kb.shape, kb.dtype)
            record_collective("ppermute", axis_name, vb.shape, vb.dtype)
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
            return out, row_max, row_sum, kb, vb

        # accumulators must be marked sp-varying for the fori_loop carry
        # (they start shard-invariant but the updates differ per shard)
        stats0 = pcast_varying(
            (jnp.full((b, h, nq), _NEG_INF, qc.dtype),
             jnp.zeros((b, h, nq), qc.dtype)), (axis_name,))
        init = (jnp.zeros_like(qc), *stats0, kc, vc)
        out, row_max, row_sum, _, _ = jax.lax.fori_loop(0, sp, step, init)
        return out / jnp.maximum(row_sum, 1e-30).transpose(0, 2, 1)[..., None]

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def ulysses_attention(q, k, v, mesh, causal: bool = False,
                      axis_name: str = SEQUENCE_AXIS):
    """All-to-all sequence parallelism (Ulysses): trade the sequence
    shard for a head shard, run full attention per head group, swap back.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mmlspark_tpu.core.jax_compat import pcast_varying, shard_map

    b, n, h, d = q.shape
    sp = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if h % sp:
        raise ValueError(f"heads {h} not divisible by sp={sp}")
    if n % sp:
        raise ValueError(f"sequence {n} not divisible by sp={sp}")
    scale = 1.0 / (d ** 0.5)
    spec = P(None, axis_name, None, None)

    def local(qc, kc, vc):
        # (b, n/P, h, d) --all_to_all--> (b, n, h/P, d)
        def seq_to_heads(x):
            record_collective("all_to_all", axis_name, x.shape, x.dtype)
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        def heads_to_seq(x):
            record_collective("all_to_all", axis_name, x.shape, x.dtype)
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(qc), seq_to_heads(kc), seq_to_heads(vc)
        # memory-efficient inner attention: the head-group sees the FULL
        # sequence here, so a dense (n, n) score matrix would defeat the
        # point of sequence parallelism at long context — fused_attention
        # streams KV blocks (XLA blockwise; the Pallas flash kernel when
        # enabled on TPU, which is legal per-shard inside this shard_map)
        out = fused_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(out)

    return shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def dense_attention(q, k, v, causal: bool = False):
    """Reference dense softmax attention (for tests/verification)."""
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (d ** 0.5)
    if causal:
        nq, nk = q.shape[1], k.shape[1]
        mask = jnp.arange(nq)[:, None] >= jnp.arange(nk)[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
