"""Pallas TPU flash-attention kernel.

The hand-scheduled hot-op layer SURVEY.md §2.7 mandates for the
long-context path: one fused kernel per (batch, head, q-block) keeps
the online-softmax accumulators in VMEM and streams KV blocks through
the MXU — no (n, n) score materialization, no HBM round trips between
the matmul, softmax and weighted-sum stages (the XLA fallback in
:mod:`mmlspark_tpu.parallel.attention` pays one HBM pass per scan
step's carry).

Numerics match :func:`~mmlspark_tpu.parallel.attention.dense_attention`
to float tolerance; CPU tests run the same kernel in interpret mode.
"""

from __future__ import annotations

import functools

import numpy as np

_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_block: int):
    """One (batch*head, q-block) program: stream KV blocks, online
    softmax in f32 VMEM registers."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, d)
    nk = k_ref.shape[1]
    iq = pl.program_id(1)
    q_pos = iq * q_block + jax.lax.broadcasted_iota(jnp.int32,
                                                    (q.shape[0], 1), 0)

    def body(i, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = q @ kb.T                                   # (block_q, block_k)
        if causal:
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        blk_max = jnp.max(s, axis=1)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m[:, None])
        corr = jnp.exp(m - new_m)
        new_l = l * corr + jnp.sum(p, axis=1)
        new_acc = acc * corr[:, None] + p @ vb
        return new_acc, new_m, new_l

    d = q.shape[1]
    acc0 = jnp.zeros((q.shape[0], d), jnp.float32)
    m0 = jnp.full((q.shape[0],), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk // block_k, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


_JIT_CACHE = {}


def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128,
                    causal: bool = False, interpret: bool = False):
    """Fused attention: q/k/v (batch, seq, heads, head_dim) -> same
    shape. Sequence lengths must divide the block sizes; the whole
    per-(batch, head) K/V stream lives in VMEM, so ``seq * head_dim``
    is bounded by VMEM (~1M f32 elements per operand)."""
    import jax

    key = (block_q, block_k, causal, interpret)
    if key not in _JIT_CACHE:
        _JIT_CACHE[key] = jax.jit(functools.partial(
            _flash_call, block_q=block_q, block_k=block_k, causal=causal,
            interpret=interpret))
    return _JIT_CACHE[key](q, k, v)


def _flash_call(q, k, v, *, block_q: int, block_k: int, causal: bool,
                interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    b, n, h, d = q.shape
    nk = k.shape[1]
    block_q = min(block_q, n)
    block_k = min(block_k, nk)
    if n % block_q or nk % block_k:
        raise ValueError(f"seq lengths ({n}, {nk}) must be divisible by "
                         f"blocks ({block_q}, {block_k})")
    scale = 1.0 / (d ** 0.5)
    # (b, n, h, d) -> (b*h, n, d): one grid row per (batch, head)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, n, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, nk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, nk, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale, q_block=block_q)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
        grid=(b * h, n // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda ib, iq: (ib, iq, 0)),
            pl.BlockSpec((1, nk, d), lambda ib, iq: (ib, 0, 0)),
            pl.BlockSpec((1, nk, d), lambda ib, iq: (ib, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda ib, iq: (ib, iq, 0)),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, h, n, d).transpose(0, 2, 1, 3)


def flash_available() -> bool:
    """True when the compiled kernel should be used: a real TPU backend
    AND the MMLSPARK_TPU_FLASH=1 opt-in. The kernel has only ever been
    exercised in interpret mode (the tunnel has been down every round),
    so until a real-TPU compile + A/B against blockwise_attention is
    recorded (ROUND4_NOTES.md), production paths default to the known-
    good XLA fallback rather than first-contact a Mosaic compile."""
    import jax

    from mmlspark_tpu.core.env import env_flag
    return jax.default_backend() == "tpu" and env_flag("MMLSPARK_TPU_FLASH")
