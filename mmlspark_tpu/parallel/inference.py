"""Mesh-sharded batch inference (embarrassingly parallel scoring).

The reference broadcasts the model to executors and scores each Spark
partition independently (onnx/ONNXModel.scala:242-251; the per-row
booster UDF, LightGBMClassifier.scala:133). The TPU analog: model
arrays replicate (they are closed-over jit constants), rows shard over
the mesh ``dp`` axis, and XLA runs each device's shard locally — no
collectives in the scoring graph at all.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu.parallel.mesh import DATA_AXIS, axis_size, row_sharded


def pad_rows(x: np.ndarray, multiple: int) -> tuple:
    """Pad the leading dim to a multiple (repeating the last row so
    padded rows stay shape-valid); returns (padded, n_valid)."""
    n = x.shape[0]
    padded = ((n + multiple - 1) // multiple) * multiple
    if padded == n or n == 0:
        return x, n
    reps = np.repeat(x[-1:], padded - n, axis=0)
    return np.concatenate([x, reps]), n


def sharded_apply(fn: Callable, x: Any, mesh, axis: str = DATA_AXIS):
    """Run a jitted row-wise function with inputs sharded over ``axis``.

    ``x`` is an array or a dict of arrays sharing the leading (row) dim.
    Rows are padded to the axis size, device_put row-sharded, and the
    outputs sliced back to the true row count on host. The function's
    closed-over model arrays replicate automatically.
    """
    import jax

    size = axis_size(mesh, axis)
    if isinstance(x, dict):
        n = next(iter(x.values())).shape[0]
        fed = {}
        for k, v in x.items():
            pv, _ = pad_rows(np.asarray(v), size)
            fed[k] = jax.device_put(pv, row_sharded(mesh, pv.ndim, axis))
        out = fn(fed)
    else:
        x = np.asarray(x)
        n = x.shape[0]
        pv, _ = pad_rows(x, size)
        xd = jax.device_put(pv, row_sharded(mesh, pv.ndim, axis))
        out = fn(xd)
    padded = ((n + size - 1) // size) * size

    def unpad(a):
        a = np.asarray(a)
        # only strip rows from outputs that actually carry the batch dim
        # (reductions/scalars pass through untouched)
        return a[:n] if a.ndim >= 1 and a.shape[0] == padded else a

    return jax.tree_util.tree_map(unpad, out)
