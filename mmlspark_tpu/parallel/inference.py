"""Mesh-sharded batch inference (embarrassingly parallel scoring).

The reference broadcasts the model to executors and scores each Spark
partition independently (onnx/ONNXModel.scala:242-251; the per-row
booster UDF, LightGBMClassifier.scala:133). The TPU analog: model
arrays replicate (they are closed-over jit constants), rows shard over
the mesh ``dp`` axis, and XLA runs each device's shard locally — no
collectives in the scoring graph at all.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from mmlspark_tpu.parallel.mesh import DATA_AXIS, axis_size, row_sharded


def pad_rows(x: np.ndarray, multiple: int) -> tuple:
    """Pad the leading dim up to a multiple with zero rows; returns
    (padded, n_valid). Scorers are row-independent, so zero rows are
    output-safe (their outputs are sliced away) and cheaper than
    repeating real data. An empty batch pads up to one full multiple
    so downstream sharding constraints (leading dim divisible by the
    mesh axis) always hold."""
    n = x.shape[0]
    if multiple <= 1:
        return x, n
    padded = max(((n + multiple - 1) // multiple) * multiple, multiple)
    if padded == n:
        return x, n
    fill = np.zeros((padded - n,) + x.shape[1:], dtype=x.dtype)
    return np.concatenate([x, fill]), n


def bucket_ladder(max_batch: int, buckets: Optional[List[int]] = None
                  ) -> List[int]:
    """Pow2 padding ladder ending at ``max_batch`` (ascending).

    Shared by the serving data plane and the shard-rules scoring
    engine so both pad to the same rungs and the jitted scorer
    compiles once per rung. ``buckets`` overrides the ladder (values
    are clamped into [1, max_batch]; max_batch is always included so
    every batch has a rung)."""
    max_batch = max(int(max_batch), 1)
    if buckets:
        ladder = sorted({min(max(int(b), 1), max_batch) for b in buckets}
                        | {max_batch})
        return ladder
    ladder, b = [], 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return ladder


def bucket_for(n: int, ladder: List[int]) -> int:
    """Smallest rung >= n (top rung when n exceeds the ladder)."""
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


def sharded_apply(fn: Callable, x: Any, mesh, axis: str = DATA_AXIS):
    """Run a jitted row-wise function with inputs sharded over ``axis``.

    ``x`` is an array or a dict of arrays sharing the leading (row) dim.
    Rows are padded to the axis size, device_put row-sharded, and the
    outputs sliced back to the true row count on host. The function's
    closed-over model arrays replicate automatically.
    """
    import jax

    size = axis_size(mesh, axis)
    if isinstance(x, dict):
        n = next(iter(x.values())).shape[0]
        fed = {}
        padded = n
        for k, v in x.items():
            pv, _ = pad_rows(np.asarray(v), size)
            padded = pv.shape[0]
            fed[k] = jax.device_put(pv, row_sharded(mesh, pv.ndim, axis))
        out = fn(fed)
    else:
        x = np.asarray(x)
        n = x.shape[0]
        pv, _ = pad_rows(x, size)
        padded = pv.shape[0]
        xd = jax.device_put(pv, row_sharded(mesh, pv.ndim, axis))
        out = fn(xd)

    def unpad(a):
        a = np.asarray(a)
        # only strip rows from outputs that actually carry the batch dim
        # (reductions/scalars pass through untouched)
        return a[:n] if a.ndim >= 1 and a.shape[0] == padded else a

    return jax.tree_util.tree_map(unpad, out)
