"""Device-mesh conventions — the communication backbone.

Replaces all three coordination planes of the reference (SURVEY.md §2.9):
the LightGBM driver TCP rendezvous + native ring (NetworkManager.scala),
the VW spanning-tree allreduce (VowpalWabbitClusterUtil.scala:15-43), and
Spark broadcast/collect/barrier — with a single `jax.sharding.Mesh` whose
axes carry XLA collectives over ICI (intra-slice) and DCN (inter-slice).

Axis conventions (used framework-wide):
  - ``dp``  — data parallel: rows sharded; histogram/gradient `psum`
              (LightGBM ``data_parallel``, VW allreduce, Horovod DP).
  - ``fp``  — feature parallel: feature dimension of histogram build
              sharded (LightGBM ``feature_parallel``).
  - ``mp``  — model parallel: reserved for tensor-parallel DNN paths.

The deterministic ring ordering the reference computes by sorting hosts on
min partition id (NetworkManager.scala:322-328) is inherent here: mesh
device order is deterministic, so no rendezvous is needed. The per-executor
"main worker election" (SharedState.scala:55-63) maps to
``process_index == 0`` / leader-by-mesh-coordinate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

DATA_AXIS = "dp"
FEATURE_AXIS = "fp"
MODEL_AXIS = "mp"
SEQUENCE_AXIS = "sp"


def data_axis() -> str:
    return DATA_AXIS


def feature_axis() -> str:
    return FEATURE_AXIS


def model_axis() -> str:
    return MODEL_AXIS


def sequence_axis() -> str:
    return SEQUENCE_AXIS


@dataclass
class MeshConfig:
    """Declarative mesh shape; -1 means "all remaining devices".

    ``sp`` is the sequence/context-parallel axis used by the
    long-context attention ops (:mod:`mmlspark_tpu.parallel.attention`);
    like the others it defaults to 1 so existing data-parallel programs
    are unchanged.
    """

    dp: int = -1
    fp: int = 1
    mp: int = 1
    sp: int = 1

    def resolve(self, num_devices: int) -> Tuple[int, int, int, int]:
        dp, fp, mp, sp = self.dp, self.fp, self.mp, self.sp
        fixed = max(fp, 1) * max(mp, 1) * max(sp, 1)
        if dp == -1:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by "
                    f"fp*mp*sp={fixed}")
            dp = num_devices // fixed
        if dp * fp * mp * sp != num_devices:
            raise ValueError(
                f"mesh {dp}x{fp}x{mp}x{sp} != {num_devices} devices")
        return dp, fp, mp, sp


def create_mesh(config: Optional[MeshConfig] = None,
                devices: Optional[Sequence] = None,
                axis_names: Optional[Sequence[str]] = None):
    """Build a Mesh over all (or given) devices.

    Axes of size 1 are kept — collectives over singleton axes are no-ops,
    which lets the same shard_mapped program run from 1 chip to a pod.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    config = config or MeshConfig()
    dp, fp, mp, sp = config.resolve(len(devices))
    names = tuple(axis_names) if axis_names else (
        DATA_AXIS, FEATURE_AXIS, MODEL_AXIS, SEQUENCE_AXIS)
    shape = (dp, fp, mp, sp)
    if len(names) == 3:
        if sp != 1:
            raise ValueError("3 axis names require sp == 1")
        shape = (dp, fp, mp)
    elif len(names) != 4:
        raise ValueError(f"need 3 or 4 axis names, got {names}")
    dev_array = np.array(devices).reshape(shape)
    return jax.sharding.Mesh(dev_array, names)


def shrink_mesh(mesh, keep_dp: Optional[int] = None,
                lost_ranks: Sequence[int] = ()):
    """Re-form a mesh on a surviving slice of its ``dp`` axis.

    The elastic-recovery half of the resilience story: after a
    participant loss or an attributed stall, ``fit_resilient`` shrinks
    the data-parallel axis to the survivors and resumes from the last
    segment checkpoint. Either pass ``keep_dp`` (keep the first N dp
    coordinates) or ``lost_ranks`` (dp coordinates to drop). Returns
    the input mesh unchanged when nothing shrinks. The checkpoint
    fingerprint excludes the mesh, so segments fit before the shrink
    load cleanly on the re-formed mesh and the resumed fit is
    bitwise-identical to a deliberate elastic continuation with the
    same mesh schedule.
    """
    import jax

    if DATA_AXIS not in mesh.axis_names:
        raise ValueError(f"mesh has no '{DATA_AXIS}' axis: "
                         f"{mesh.axis_names}")
    di = list(mesh.axis_names).index(DATA_AXIS)
    dp = mesh.devices.shape[di]
    if lost_ranks:
        surviving = [r for r in range(dp) if r not in set(lost_ranks)]
    else:
        surviving = list(range(dp if keep_dp is None else keep_dp))
    if not surviving:
        raise ValueError("no surviving dp ranks to re-form the mesh on")
    if len(surviving) == dp:
        return mesh
    dev_array = np.take(mesh.devices, surviving, axis=di)
    return jax.sharding.Mesh(dev_array, mesh.axis_names)


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids: Optional[Sequence[int]] = None,
                     cpu_devices_per_process: Optional[int] = None,
                     **kwargs) -> None:
    """Join (or bootstrap) a multi-process JAX cluster.

    This is the rendezvous the reference implements by hand twice —
    the LightGBM driver opens a ServerSocket, collects every executor's
    ``ip:port``, sorts them into a deterministic ring and mails the
    roster back (NetworkManager.scala:59-84,322-328); VW builds a
    spanning tree the same way (VowpalWabbitClusterUtil.scala:15-43).
    On TPU both planes collapse into ``jax.distributed.initialize``:
    process 0 runs the coordinator service, every process registers,
    and afterwards ``jax.devices()`` is the *global* device list in a
    deterministic order, so ``create_mesh()`` spans hosts with no
    further ceremony and XLA lays collectives over ICI/DCN.

    All arguments default from the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/
    ``JAX_PROCESS_ID``) exactly as ``jax.distributed.initialize`` does,
    so launchers may pass either env or explicit values.

    ``cpu_devices_per_process``: when set, forces that many virtual CPU
    devices *before* the backend initializes — the offline multi-host
    test rig (N processes x M virtual CPU devices; collectives ride
    Gloo). Production TPU processes leave it ``None``.

    Extra keyword arguments pass through to
    ``jax.distributed.initialize`` (e.g. ``heartbeat_timeout_seconds``,
    which bounds how long survivors wait before a dead peer is
    detected and the process fail-fast terminates — the barrier
    failure-detection analog of the reference's socket-error
    propagation, pinned by
    tests/parallel/test_multihost.py::test_dead_rank_fails_fast).
    """
    import jax

    from mmlspark_tpu.core.faults import fault_point

    if cpu_devices_per_process is not None:
        from mmlspark_tpu.core.virtual_devices import force_cpu_devices
        force_cpu_devices(cpu_devices_per_process)
        try:
            # newer jax defaults CPU cross-process collectives to gloo;
            # 0.4.x needs the opt-in or device_put onto a
            # process-spanning mesh raises "Multiprocess computations
            # aren't implemented on the CPU backend"
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except (AttributeError, ValueError):
            pass
    import inspect
    accepted = inspect.signature(jax.distributed.initialize).parameters
    hb = kwargs.get("heartbeat_timeout_seconds")
    if hb is not None and "heartbeat_timeout_seconds" not in accepted:
        # jax 0.4.x: the public wrapper predates the knob, but the
        # underlying client takes heartbeat interval x max-missing —
        # map the requested window onto those so failure detection
        # stays bounded by ~hb seconds instead of the ~100 s default
        kwargs = {k: v for k, v in kwargs.items()
                  if k != "heartbeat_timeout_seconds"}
        try:
            from jax._src import distributed as _distributed
            from jax._src import xla_bridge as _xla_bridge
            inner = inspect.signature(
                _distributed.global_state.initialize).parameters
            if not {"client_heartbeat_interval_seconds",
                    "client_max_missing_heartbeats"} <= inner.keys():
                raise ImportError("heartbeat knobs not exposed")
            if _xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "jax.distributed.initialize() must be called before "
                    "any JAX computations are executed.")
            interval = max(1, int(hb) // 5)
            missing = max(2, -(-int(hb) // interval))
            _init_with_retries(
                lambda: _distributed.global_state.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    local_device_ids=local_device_ids,
                    service_heartbeat_interval_seconds=interval,
                    service_max_missing_heartbeats=missing,
                    client_heartbeat_interval_seconds=interval,
                    client_max_missing_heartbeats=missing,
                    **{k: v for k, v in kwargs.items() if k in inner}),
                fault_point)
            return
        except ImportError:
            import warnings
            warnings.warn(
                "this jax exposes no heartbeat configuration; dropping "
                "heartbeat_timeout_seconds — failure detection uses "
                "the runtime's default window", stacklevel=2)
    dropped = sorted(k for k in kwargs if k not in accepted)
    if dropped:
        import warnings
        warnings.warn(
            f"jax.distributed.initialize on jax {jax.__version__} does "
            f"not accept {dropped}; dropping", stacklevel=2)
        kwargs = {k: v for k, v in kwargs.items() if k in accepted}
    _init_with_retries(
        lambda: jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            **kwargs),
        fault_point)


def _init_with_retries(init_fn, fault_point) -> None:
    """Rendezvous with bounded retries: a coordinator that is still
    coming up (a restarted process 0, a slow container) must not kill
    every joiner permanently — the reference's executors likewise retry
    into the driver's ServerSocket. Attempts come from
    ``MMLSPARK_TPU_DIST_INIT_RETRIES`` (total tries, default 3);
    mis-use errors (double init, bad arguments) never retry."""
    from mmlspark_tpu.core.env import env_int
    from mmlspark_tpu.core.retries import RetryPolicy, with_retries
    from mmlspark_tpu.parallel.resilience import stall_guard

    def attempt():
        # MMLSPARK_TPU_WATCHDOG_INIT_S > 0 bounds each rendezvous
        # attempt — the BENCH_r05 failure shape is an init that never
        # returns, which no retry policy can see without this; a
        # TrainStalled attempt retries like any transient failure and
        # the exhaustion annotation says why the init gave up
        with stall_guard("distributed.init"):
            fault_point("distributed.init")
            init_fn()

    def should_retry(e: BaseException) -> bool:
        if isinstance(e, (ValueError, TypeError)):
            return False
        msg = str(e).lower()
        # "should only be called once" / "must be called before any
        # JAX computations": programming errors, not transient
        return "once" not in msg and "before any" not in msg

    tries = env_int("MMLSPARK_TPU_DIST_INIT_RETRIES", 3, minimum=1)
    with_retries(attempt,
                 policy=RetryPolicy(max_attempts=max(tries, 1),
                                    base_delay=1.0, max_delay=10.0),
                 should_retry=should_retry, describe="distributed.init")


def process_index() -> int:
    """This process's rank (the reference's main-worker election key,
    SharedState.scala:55-63: leader == process 0)."""
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_multiprocess() -> bool:
    import jax
    return jax.process_count() > 1


_DEFAULT_MESH = None


def default_mesh():
    """Process-wide data-parallel mesh over all devices (cached)."""
    global _DEFAULT_MESH
    import jax
    if _DEFAULT_MESH is None or _DEFAULT_MESH.devices.size != len(jax.devices()):
        _DEFAULT_MESH = create_mesh()
    return _DEFAULT_MESH


def axis_size(mesh, axis: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape))[axis]


def replicated(mesh):
    import jax
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def row_sharded(mesh, ndim: int = 1, axis: str = DATA_AXIS):
    import jax
    spec = [None] * ndim
    spec[0] = axis
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))


def named_sharding(mesh, *spec):
    """NamedSharding from positional PartitionSpec entries — the
    train/prefetch loops build ad-hoc placements often enough that the
    two-class ceremony deserves one helper."""
    import jax
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(*spec))
