"""Async double-buffered batch prefetch for training loops.

The reference feeds Spark partitions to workers through a streaming
micro-batch push; our fit loops were purely synchronous — each step
waited on a host slice + ``device_put`` before dispatching.
:class:`BatchPrefetcher` overlaps that input work with device compute
(the TensorFlow input-pipeline argument, arXiv:1605.08695): a
background thread pulls host batches from an iterator, places them
on-device (``device_put`` onto ``P("dp")`` for sharded loops), and
stages up to ``MMLSPARK_TPU_PREFETCH_DEPTH`` ready batches in a
bounded queue while the consumer runs the current step.

Honest fallback: depth 0 (or a failed thread start) degrades to the
synchronous path — same batches, same order, no thread. The consumer's
batch stream is bit-identical either way; only the overlap changes.

Teardown contract: ``close()`` (or leaving the ``with`` block, even on
an exception) stops the producer thread and joins it — no leaked
threads, pinned by tests/parallel/test_train_shard.py.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from mmlspark_tpu.core.logging_utils import warn_once
from mmlspark_tpu.parallel import resilience

_SENTINEL_DONE = object()


def resolve_prefetch_depth(depth: Optional[int] = None) -> int:
    """Staged-batch budget: explicit ``depth`` wins, else the
    MMLSPARK_TPU_PREFETCH_DEPTH knob (default 2 — double buffering).
    0 means synchronous feeding."""
    if depth is not None:
        return max(int(depth), 0)
    from mmlspark_tpu.core.env import env_int

    return env_int("MMLSPARK_TPU_PREFETCH_DEPTH", 2, minimum=0)


class BatchPrefetcher:
    """Iterate ``source`` with ``place_fn`` applied one-or-more batches
    ahead on a background thread.

    ``source``: iterable of host batches (any value).
    ``place_fn``: host batch -> device batch (e.g. a sharded
    ``device_put``); identity when None.
    ``depth``: staged-batch cap; None reads the env knob; 0 = sync.

    A producer-side exception is re-raised in the consumer at the point
    the failing batch would have been delivered, after which the
    prefetcher is closed.
    """

    _join_timeout = 10.0  # seconds; tests shrink it to force the leak path

    def __init__(self, source: Iterable, place_fn: Optional[Callable] = None,
                 depth: Optional[int] = None, label: str = "prefetch"):
        self.label = label
        self.depth = resolve_prefetch_depth(depth)
        self._place = place_fn if place_fn is not None else (lambda b: b)
        self._source = iter(source)
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        self._leaked_thread: Optional[str] = None
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._produce, name=f"mmlspark-{label}",
                daemon=True)
            self._thread.start()

    @property
    def async_mode(self) -> bool:
        """True when a producer thread is staging batches ahead."""
        return self._thread is not None

    # -- producer ------------------------------------------------------

    def _produce(self) -> None:
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                staged = self._place(batch)
                while not self._stop.is_set():
                    try:
                        self._queue.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                else:
                    return
            self._put_final(_SENTINEL_DONE)
        except BaseException as e:  # delivered to the consumer
            self._put_final(e)

    def _put_final(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- consumer ------------------------------------------------------

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        if self._queue is None:  # synchronous fallback
            try:
                return self._place(next(self._source))
            except StopIteration:
                self.close()
                raise
        prev = resilience.mark_boundary(
            "input_wait",
            lambda: f"{self.label}: queue {self._queue.qsize()}/"
                    f"{self.depth} staged, producer "
                    f"{'alive' if self._thread is not None and self._thread.is_alive() else 'dead'}")
        try:
            while True:
                try:
                    item = self._queue.get(timeout=0.1)
                    break
                except queue.Empty:
                    if self._thread is not None and not self._thread.is_alive():
                        # producer died without delivering its sentinel
                        # (should not happen; never hang the fit on it)
                        self.close()
                        raise StopIteration
        finally:
            resilience.restore_boundary(prev)
        if item is _SENTINEL_DONE:
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        return item

    def close(self) -> None:
        """Stop and join the producer; idempotent, exception-safe."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._queue is not None:
            # unblock a producer waiting on a full queue
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=self._join_timeout)
            if self._thread.is_alive():
                # the join timed out: the producer is wedged (most
                # likely inside place_fn) and its daemon thread leaks —
                # say so instead of silently dropping the handle
                self._leaked_thread = self._thread.name
                warn_once(
                    f"prefetch.leaked_thread.{self._thread.name}",
                    "prefetcher %s: producer thread %r did not stop "
                    "within %.1fs of close(); leaking it as a daemon",
                    self.label, self._thread.name, self._join_timeout)
            self._thread = None

    def stats(self) -> dict:
        """Observability snapshot: queue depth/occupancy and whether
        close() leaked the producer thread (None = clean)."""
        return {
            "label": self.label,
            "depth": self.depth,
            "queued": self._queue.qsize() if self._queue is not None else 0,
            "leaked_thread": self._leaked_thread,
        }

    def __enter__(self) -> "BatchPrefetcher":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def __del__(self):  # best-effort backstop; close() is the contract
        try:
            self.close()
        except Exception:
            pass
