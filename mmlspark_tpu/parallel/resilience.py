"""Train-step watchdog, stall attribution, and elastic dp-shrink recovery.

Mid-fit hangs are the one failure mode ``bench.py --preflight`` cannot
attribute: a collective that never completes, a native host callback that
wedges, or an input pipeline that starves all look identical from the
outside — a process that stops making progress but never dies (the
real-TPU flavor of this is the BENCH_r05 init hang, see BASELINE.md).
This module turns that silence into an attributed, recoverable error:

- :class:`TrainWatchdog` observes every train-step boundary (trainers call
  :func:`step_start` / :func:`step_end`, which are free when no watchdog
  is armed — a single ``is None`` check, same pattern as
  ``faults.fault_point``).  It keeps a rolling window of completed
  host-span wall times and computes an adaptive stall budget
  ``max(p99(window) * MMLSPARK_TPU_WATCHDOG_MULT,
  MMLSPARK_TPU_WATCHDOG_MIN_S)``.  When an in-flight span exceeds the
  budget, a monitor thread classifies the stall from the currently-marked
  blocking boundary (collective / host callback / input wait — trainers
  mark these with :func:`mark_boundary`), dumps a per-rank progress
  report, and aborts the fit with :class:`TrainStalled` instead of
  hanging forever.

- :func:`stall_guard` is the fixed-budget variant for single blocking
  calls (``distributed_init`` attempts — the BENCH_r05 shape).

- :func:`fit_resilient` is the elastic recovery loop: on
  :class:`TrainStalled` / :class:`ParticipantLost` it re-forms the mesh
  on the surviving ``dp`` slice (:func:`parallel.mesh.shrink_mesh`) and
  re-runs the fit, which resumes from the last segment checkpoint via
  the crash-safe checkpoint protocol.  The pinned contract: the
  recovered fit is bitwise-identical to an *uninterrupted elastic* run
  with the same mesh schedule (pre-loss segments at the original dp,
  later segments at the shrunken dp through a deliberate checkpoint
  continue) — the recovery machinery itself adds zero divergence.
  Fits are NOT bitwise-invariant across different dp values (float
  histogram reduction order changes with the row partition), so the
  reference for parity is the same mesh schedule, not a fixed-dp run.

Abort delivery: the monitor thread interrupts the fit thread with
``signal.pthread_kill(SIGUSR1)`` when the fit runs on the main thread
(promptly interrupts ``time.sleep`` and most blocking waits; the handler
raises :class:`_WatchdogInterrupt`), falling back to
``PyThreadState_SetAsyncExc`` for non-main threads (delivered at the
next bytecode boundary).  ``_WatchdogInterrupt`` derives from
``BaseException`` so library-level ``except Exception`` cannot swallow
it; the watchdog's ``__exit__`` translates it into the prepared
:class:`TrainStalled` carrying the classification and progress report.
"""

from __future__ import annotations

import ctypes
import signal
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterator, List, Optional, Tuple,
                    Union)

from mmlspark_tpu.core.env import (RECOVERY_MAX, RECOVERY_MIN_DP,
                                   WATCHDOG_INIT_S, WATCHDOG_MIN_S,
                                   WATCHDOG_MULT, env_float, env_int)
from mmlspark_tpu.core.logging_utils import logger
from mmlspark_tpu.core.sanitizer import san_lock

__all__ = [
    "TrainStalled", "ParticipantLost", "TrainWatchdog", "FitRecovery",
    "ResilientFitResult", "fit_watchdog", "stall_guard", "fit_resilient",
    "step_start", "step_end", "install_step_throttle", "mark_boundary",
    "restore_boundary", "boundary", "stall_count", "recovery_count",
    "reset",
]


class TrainStalled(RuntimeError):
    """A train step exceeded the watchdog's stall budget.

    Carries the classification (``backend-hang`` / ``collective-stall`` /
    ``host-callback-stall`` / ``input-starvation``), the elapsed and
    budget seconds, and the per-rank progress report dict.
    """

    def __init__(self, message: str, *, classification: str, label: str,
                 elapsed_s: float, budget_s: float,
                 report: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(message)
        self.classification = classification
        self.label = label
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s
        self.report = report or {}


class ParticipantLost(RuntimeError):
    """A mesh participant died or became unreachable mid-fit."""


class _WatchdogInterrupt(BaseException):
    """Async delivery sentinel; translated to TrainStalled on exit.

    BaseException so library ``except Exception`` blocks can't eat it.
    """


# ---------------------------------------------------------------------------
# module-level hooks — the disabled fast path is one global None check
# ---------------------------------------------------------------------------

_active: Optional["TrainWatchdog"] = None
_step_throttle: Optional[Callable[[Any], None]] = None
_lock = san_lock("resilience.state")
_stall_count = 0
_recovery_count = 0


def install_step_throttle(fn: Optional[Callable[[Any], None]]
                          ) -> Optional[Callable[[Any], None]]:
    """Install (``None`` clears) a callable invoked at every train-step
    boundary, before any watchdog span opens — the refit
    admission-control hook (io/refresh.py): a low-priority refit
    co-located with live serving yields here while the serving queue
    sits past its high-water mark.  Running before ``_span_start``
    means the yield never counts against the stall budget.  Returns the
    previous throttle so callers can restore it; the disabled fast path
    stays a single extra ``is None`` check.
    """
    global _step_throttle
    prev = _step_throttle
    _step_throttle = fn
    return prev


def step_start(tag: Any = None) -> None:
    """Open a host span at a train-step boundary. Free when disabled."""
    if _step_throttle is not None:
        _step_throttle(tag)
    if _active is None:
        return
    _active._span_start(tag)


def step_end() -> None:
    """Close the current host span. Free when disabled; idempotent."""
    if _active is None:
        return
    _active._span_end()


def mark_boundary(kind: Optional[str],
                  detail: Union[str, Callable[[], str], None] = None
                  ) -> Optional[Tuple[Any, Any]]:
    """Mark the kind of blocking call the fit thread is about to enter.

    ``kind`` is one of ``"collective"``, ``"host_callback"``,
    ``"input_wait"`` (or None to clear).  ``detail`` may be a string or a
    zero-arg callable evaluated lazily only if a stall fires.  Returns
    the previous marker for :func:`restore_boundary`.  Free when no
    watchdog is armed.
    """
    if _active is None:
        return None
    return _active._set_boundary(kind, detail)


def restore_boundary(prev: Optional[Tuple[Any, Any]]) -> None:
    """Restore a boundary marker saved by :func:`mark_boundary`."""
    if _active is None or prev is None:
        return
    _active._boundary = prev


class boundary:
    """Context-manager form of mark/restore for non-hot paths."""

    def __init__(self, kind: str,
                 detail: Union[str, Callable[[], str], None] = None) -> None:
        self._kind = kind
        self._detail = detail
        self._prev: Optional[Tuple[Any, Any]] = None

    def __enter__(self) -> "boundary":
        self._prev = mark_boundary(self._kind, self._detail)
        return self

    def __exit__(self, *exc: Any) -> None:
        restore_boundary(self._prev)


def stall_count() -> int:
    """Process-wide count of watchdog-fired stalls (bench telemetry)."""
    return _stall_count


def recovery_count() -> int:
    """Process-wide count of dp-shrink recoveries (bench telemetry)."""
    return _recovery_count


def reset() -> None:
    """Test hook: clear counters, any leaked active watchdog, and any
    leaked step throttle."""
    global _active, _step_throttle, _stall_count, _recovery_count
    _active = None
    _step_throttle = None
    _stall_count = 0
    _recovery_count = 0


_CLASSIFY = {
    "collective": "collective-stall",
    "host_callback": "host-callback-stall",
    "input_wait": "input-starvation",
}


def _p99(window: "deque[float]") -> float:
    ordered = sorted(window)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]


class TrainWatchdog:
    """Adaptive stall watchdog over train-step host spans.

    Use as a context manager around a fit; trainers feed it through the
    module-level :func:`step_start` / :func:`step_end` hooks.  Disabled
    (``MULT <= 0`` and no fixed budget) it is a complete no-op: enter
    and exit do nothing, no thread is started, ``_active`` stays None so
    the hooks stay one-check cheap and fits are bit-identical to a
    build without this module.
    """

    _WINDOW = 64
    _MIN_SAMPLES = 8

    def __init__(self, label: str, *, mult: Optional[float] = None,
                 min_s: Optional[float] = None,
                 fixed_budget_s: Optional[float] = None,
                 classification: Optional[str] = None) -> None:
        self.label = label
        self.mult = env_float(WATCHDOG_MULT, 0.0) if mult is None else mult
        self.min_s = (env_float(WATCHDOG_MIN_S, 60.0, minimum=0.001)
                      if min_s is None else min_s)
        self.fixed_budget_s = fixed_budget_s
        self._fixed_classification = classification
        self.enabled = (fixed_budget_s is not None and fixed_budget_s > 0) \
            or self.mult > 0
        self._window: "deque[float]" = deque(maxlen=self._WINDOW)
        self._steps = 0
        self._span_t0: Optional[float] = None
        self._span_tag: Any = None
        self._boundary: Tuple[Optional[str],
                              Union[str, Callable[[], str], None]] = (None,
                                                                      None)
        self._stall: Optional[TrainStalled] = None
        self._fired = False
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._observed: Optional[threading.Thread] = None
        self._prev_active: Optional["TrainWatchdog"] = None
        self._prev_handler: Any = None

    # -- span accounting (called from the fit thread via module hooks) --

    def _span_start(self, tag: Any) -> None:
        self._span_tag = tag
        self._span_t0 = time.monotonic()

    def _span_end(self) -> None:
        t0 = self._span_t0
        if t0 is None:
            return
        self._span_t0 = None
        self._window.append(time.monotonic() - t0)
        self._steps += 1

    def _set_boundary(self, kind: Optional[str],
                      detail: Union[str, Callable[[], str], None]
                      ) -> Tuple[Any, Any]:
        prev = self._boundary
        self._boundary = (kind, detail)
        return prev

    # -- budget ---------------------------------------------------------

    def budget_s(self) -> float:
        if self.fixed_budget_s is not None:
            return self.fixed_budget_s
        if len(self._window) >= self._MIN_SAMPLES:
            return max(_p99(self._window) * self.mult, self.min_s)
        return self.min_s

    # -- context manager ------------------------------------------------

    def __enter__(self) -> "TrainWatchdog":
        if not self.enabled:
            return self
        global _active
        with _lock:
            self._prev_active = _active
            _active = self
        self._observed = threading.current_thread()
        if self._observed is threading.main_thread():
            try:
                self._prev_handler = signal.signal(signal.SIGUSR1,
                                                   self._on_signal)
            except ValueError:  # not actually on the main thread
                self._prev_handler = None
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name=f"mmlspark-watchdog-{self.label}", daemon=True)
        self._monitor.start()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        if not self.enabled:
            return False
        global _active
        self._closed = True
        self._wake.set()
        with _lock:
            _active = self._prev_active
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGUSR1, self._prev_handler)
            except ValueError:
                pass
        observed = self._observed
        if (observed is not None
                and observed is not threading.main_thread()
                and observed.ident is not None):
            # cancel any still-pending async exception
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(observed.ident), None)
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        if exc_type is not None and issubclass(exc_type, _WatchdogInterrupt):
            assert self._stall is not None
            raise self._stall from None
        if exc_type is None and self._stall is not None:
            # the fit completed despite a fired stall (race between the
            # monitor firing and the blocking call returning) — prefer
            # the successful result and only log
            logger.warning(
                "watchdog %s fired (%s) but the fit completed; "
                "keeping the result", self.label,
                self._stall.classification)
        return False

    # -- monitor thread -------------------------------------------------

    def _poll_interval(self) -> float:
        return max(0.02, min(self.budget_s() / 4.0, 0.25))

    def _monitor_loop(self) -> None:
        while not self._closed:
            self._wake.wait(self._poll_interval())
            if self._closed or self._fired:
                return
            t0 = self._span_t0
            if t0 is None:
                continue
            elapsed = time.monotonic() - t0
            budget = self.budget_s()
            if elapsed > budget:
                self._fire(elapsed, budget)
                return

    def _fire(self, elapsed: float, budget: float) -> None:
        global _stall_count
        self._fired = True
        kind, detail = self._boundary
        if callable(detail):
            try:
                detail = detail()
            except Exception:
                detail = "<detail unavailable>"
        classification = _CLASSIFY.get(
            kind, self._fixed_classification or "backend-hang")
        report = self._progress_report(elapsed, budget, kind, detail)
        logger.error("train stall detected: %s", report)
        with _lock:
            _stall_count += 1
        self._stall = TrainStalled(
            f"{self.label}: train step stalled for {elapsed:.2f}s "
            f"(budget {budget:.2f}s, classification {classification}"
            f"{', at ' + str(detail) if detail else ''})",
            classification=classification, label=self.label,
            elapsed_s=elapsed, budget_s=budget, report=report)
        self._deliver()

    def _progress_report(self, elapsed: float, budget: float,
                         kind: Optional[str],
                         detail: Any) -> Dict[str, Any]:
        rank = 0
        try:
            import jax
            rank = jax.process_index()
        except Exception:
            pass
        window = sorted(self._window)
        last_coll = None
        try:
            from mmlspark_tpu.core import sanitizer
            last_coll = sanitizer.last_collective()
        except Exception:
            pass
        return {
            "label": self.label,
            "rank": rank,
            "span_tag": self._span_tag,
            "elapsed_s": round(elapsed, 3),
            "budget_s": round(budget, 3),
            "steps_observed": self._steps,
            "step_p50_s": round(window[len(window) // 2], 4) if window
            else None,
            "step_p99_s": round(_p99(self._window), 4) if window else None,
            "boundary": kind,
            "boundary_detail": detail,
            "last_collective": last_coll,
        }

    def _deliver(self) -> None:
        observed = self._observed
        if observed is None or observed.ident is None:
            return
        if observed is threading.main_thread() \
                and self._prev_handler is not None:
            signal.pthread_kill(observed.ident, signal.SIGUSR1)
        else:
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(observed.ident),
                ctypes.py_object(_WatchdogInterrupt))

    def _on_signal(self, signum: int, frame: Any) -> None:
        # only raise for our own, still-armed stall; a stray SIGUSR1
        # returns and the interrupted sleep resumes (PEP 475)
        if _active is self and self._stall is not None and not self._closed:
            raise _WatchdogInterrupt()


def fit_watchdog(label: str) -> TrainWatchdog:
    """Env-configured watchdog for a trainer fit (off unless MULT > 0)."""
    return TrainWatchdog(label)


@contextmanager
def stall_guard(label: str, budget_s: Optional[float] = None,
                classification: str = "backend-hang"
                ) -> Iterator[TrainWatchdog]:
    """Fixed-budget watchdog for one blocking call (e.g. backend init).

    With ``budget_s`` None the budget comes from
    ``MMLSPARK_TPU_WATCHDOG_INIT_S`` (0 = disabled).  The whole guarded
    block is timed as a single span.
    """
    if budget_s is None:
        budget_s = env_float(WATCHDOG_INIT_S, 0.0)
    wd = TrainWatchdog(label, mult=0.0, min_s=budget_s,
                       fixed_budget_s=budget_s if budget_s > 0 else None,
                       classification=classification)
    with wd:
        if wd.enabled:
            wd._span_start(label)
        yield wd


# ---------------------------------------------------------------------------
# elastic recovery
# ---------------------------------------------------------------------------


@dataclass
class FitRecovery:
    """One dp-shrink recovery hop taken by :func:`fit_resilient`."""
    cause: str
    classification: str
    dp_before: int
    dp_after: int
    error: str


@dataclass
class ResilientFitResult:
    """Outcome of :func:`fit_resilient`."""
    model: Any
    recoveries: List[FitRecovery] = field(default_factory=list)
    mesh: Any = None


def fit_resilient(estimator: Any, df: Any, *, checkpoint_dir: str,
                  checkpoint_interval: int = 1, mesh: Any = None,
                  max_recoveries: Optional[int] = None,
                  min_dp: Optional[int] = None) -> ResilientFitResult:
    """Fit with segment checkpoints and elastic dp-shrink recovery.

    Runs ``estimator.fit`` with the crash-safe checkpoint protocol
    armed (``checkpointDir`` / ``checkpointInterval``).  If the fit
    dies with :class:`TrainStalled`, :class:`ParticipantLost`, or an
    injected fault, the mesh is re-formed on half the surviving ``dp``
    slice and the fit re-runs — resuming from the last segment
    checkpoint (the fingerprint excludes the mesh, so the shrunken
    resume loads cleanly).  The recovered model is bitwise-identical
    to an uninterrupted elastic run with the same mesh schedule
    (tests/parallel/test_resilience.py pins this).

    Recovery stops (re-raising the original error) when ``mesh`` is
    None, dp cannot shrink below ``min_dp``
    (``MMLSPARK_TPU_RECOVERY_MIN_DP``), or ``max_recoveries``
    (``MMLSPARK_TPU_RECOVERY_MAX``) is exhausted.
    """
    from mmlspark_tpu.core.faults import FaultInjected
    from mmlspark_tpu.parallel import mesh as mesh_mod

    if max_recoveries is None:
        max_recoveries = env_int(RECOVERY_MAX, 2)
    if min_dp is None:
        min_dp = env_int(RECOVERY_MIN_DP, 1)

    global _recovery_count
    est = estimator.copy(checkpointDir=checkpoint_dir,
                         checkpointInterval=checkpoint_interval)
    recoveries: List[FitRecovery] = []
    while True:
        try:
            fitted = est.set_mesh(mesh) if mesh is not None else est
            model = fitted.fit(df)
            return ResilientFitResult(model=model, recoveries=recoveries,
                                      mesh=mesh)
        except (TrainStalled, ParticipantLost, FaultInjected) as err:
            dp_before = (mesh_mod.axis_size(mesh, mesh_mod.DATA_AXIS)
                         if mesh is not None else 1)
            dp_after = dp_before // 2
            if (mesh is None or dp_after < min_dp
                    or len(recoveries) >= max_recoveries):
                raise
            classification = getattr(err, "classification",
                                     type(err).__name__)
            logger.warning(
                "fit_resilient: %s (%s); re-forming mesh dp=%d -> dp=%d "
                "and resuming from the last segment checkpoint",
                type(err).__name__, classification, dp_before, dp_after)
            mesh = mesh_mod.shrink_mesh(mesh, keep_dp=dp_after)
            recoveries.append(FitRecovery(
                cause=type(err).__name__, classification=str(classification),
                dp_before=dp_before, dp_after=dp_after, error=str(err)))
            with _lock:
                _recovery_count += 1
