"""Unified sharding-rules layer: regex -> PartitionSpec per model family.

The reference ships a bespoke distribution story per estimator
(LightGBM's native ring, VW's spanning tree, ONNX/DNN broadcast);
our mesh plumbing had grown the same way — GBDT threads its own
specs, VW pmaps, dl/onnx re-``device_put`` per batch. This module
makes placement a declarative, system-level decision instead
(arXiv:2004.13336 makes the case for data-parallel weight updates;
arXiv:1605.08695 for a single placement layer under many workloads):

- ``*_RULES`` — an ordered ``(regex, spec)`` table per model family.
  A spec is a tuple of mesh-axis names (or ``None``) applied
  left-aligned to the leaf's dims, ``()`` meaning fully replicated.
  First match whose rank fits wins; anything unmatched replicates
  with a ``warn_once`` naming the leaf (no silent fallback).
- ``make_shard_and_gather_fns`` — per-leaf shard/gather callables
  with optional dtype casting (``MMLSPARK_TPU_INFER_AUTOCAST=bf16``
  casts resident float weights; off by default, parity-pinned).
- ``ShardedScorer`` — the shared pjit scoring engine every
  ``transform`` routes through: model pytrees stay resident
  on-device under their rule-derived shardings, batches pad to a
  pow2 bucket ladder (one compile per rung, counted under
  graftsan), and rows shard over ``dp``.

Bitwise contract: the engine's unit of compilation is a fixed
per-device micro-batch rung chosen from the ladder by row count
only — never by mesh size — and each dispatch feeds ``dp x rung``
rows sharded over ``dp``. XLA:CPU (and TPU) matmul numerics vary
with the batch dimension, so keeping the per-device shape constant
across dp is what makes dp=1/2/8 outputs bitwise-identical to each
other and to the serial chunked path (pinned by
tests/parallel/test_shard_rules.py).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.logging_utils import warn_once
from mmlspark_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    axis_size,
)

# Leaves at or below this element count replicate regardless of rules:
# sharding a bias vector buys nothing and costs a reshard. Matches the
# "scalar/small leaves replicated" convention of the exemplar tables.
SMALL_LEAF_NUMEL = 65536

# Training-state tables use a lower threshold: an optimizer moment is
# touched once per step (not once per row), so sharding pays off at
# much smaller sizes — and the ZeRO-1 memory win must materialize on
# test-scale models too.
TRAIN_SMALL_LEAF_NUMEL = 4096

# Per-family rule tables. Specs are tuples over the leaf's dims,
# left-aligned like PartitionSpec; axis names must be mesh.py *_AXIS
# constants (GL001 checks these statically). Scoring is row-parallel —
# the batch shards over dp at dispatch — so parameter leaves default
# to replication; the mp entries shard the large dense kernels of
# deep/onnx models across the model axis when the mesh has one
# (mp=1 meshes make them no-ops, keeping numerics bitwise).
GBDT_RULES: List[Tuple[str, Tuple]] = [
    # tree arrays (split_feature, thresholds, node values) are small
    # and traversed by every row: replicate everything
    (r".*", ()),
]

VW_RULES: List[Tuple[str, Tuple]] = [
    # the linear weight vector is read by every row's dot product
    (r".*", ()),
]

ONNX_RULES: List[Tuple[str, Tuple]] = [
    # large 2-d initializers (dense kernels) shard over mp; everything
    # else — biases, norms, scalars — replicates
    (r".*", (None, MODEL_AXIS)),
    (r".*", ()),
]

DL_RULES: List[Tuple[str, Tuple]] = [
    (r".*embedding.*", (MODEL_AXIS, None)),
    (r".*kernel$", (None, MODEL_AXIS)),
    (r".*", ()),
]

FAMILY_RULES: Dict[str, List[Tuple[str, Tuple]]] = {
    "gbdt": GBDT_RULES,
    "vw": VW_RULES,
    "onnx": ONNX_RULES,
    "dl": DL_RULES,
}

# Training-state placement (ZeRO-1, arXiv:2004.13336): optimizer
# moments and large param leaves partition over dp on the first dim
# the axis divides; small leaves (<= TRAIN_SMALL_LEAF_NUMEL) replicate
# via the match_partition_rules threshold. Each replica owns one shard
# of the weight update — grads reduce-scatter into it, updated params
# all-gather out of it (dl/estimator.py wires the constraints).
DL_TRAIN_RULES: List[Tuple[str, Tuple]] = [
    (r".*", (DATA_AXIS, None)),
    (r".*", (None, DATA_AXIS)),
    (r".*", (DATA_AXIS, None, None)),
    (r".*", (DATA_AXIS,)),
    (r".*", ()),
]

TRAIN_FAMILY_RULES: Dict[str, List[Tuple[str, Tuple]]] = {
    "dl": DL_TRAIN_RULES,
}


def _leaf_paths(tree) -> List[Tuple[str, Any]]:
    """(name, leaf) pairs with '/'-joined key paths."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for p in path:
            key = getattr(p, "key", getattr(p, "idx", getattr(p, "name",
                                                              None)))
            parts.append(str(key))
        out.append(("/".join(parts) if parts else "", leaf))
    return out


def _spec_fits(spec: Tuple, leaf, mesh) -> bool:
    """A rule applies only when its rank matches the leaf and every
    named axis exists in the mesh and divides the dim it shards."""
    ndim = getattr(leaf, "ndim", 0)
    shape = getattr(leaf, "shape", ())
    if spec == ():
        return True
    if len(spec) != ndim:
        return False
    for dim, entry in zip(shape, spec):
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            if ax is None:
                continue
            if mesh is None or ax not in mesh.axis_names:
                return False
            if dim % axis_size(mesh, ax):
                return False
    return True


def match_partition_rules(rules: List[Tuple[str, Tuple]], params,
                          mesh=None, label: str = "model",
                          small_numel: int = SMALL_LEAF_NUMEL):
    """Map a param pytree to a pytree of spec tuples via the rule table.

    Scalars and leaves at or below ``small_numel`` elements replicate
    before rules apply (training-state tables pass the lower
    TRAIN_SMALL_LEAF_NUMEL threshold). The first rule whose regex
    matches the '/'-joined leaf name AND whose spec fits the leaf's
    rank/shape on this mesh wins. A leaf no rule matches falls back to
    replication with a ``warn_once`` naming the leaf — the downgrade
    contract: no silent placement decisions.
    """
    import jax

    specs = _match_rules_flat(rules, params, mesh, label, small_numel)
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def _match_rules_flat(rules: List[Tuple[str, Tuple]], params, mesh,
                      label: str, small_numel: int) -> List[Tuple]:
    """Flat spec list in ``tree_leaves(params)`` order (the tree-free
    core of :func:`match_partition_rules`; training-state helpers use
    it directly because optax states are namedtuples, which a
    tuple-leaved spec pytree cannot round-trip through)."""
    named = _leaf_paths(params)
    specs = []
    for name, leaf in named:
        ndim = getattr(leaf, "ndim", 0)
        numel = int(np.prod(getattr(leaf, "shape", ()) or (1,)))
        if ndim == 0 or numel <= small_numel:
            specs.append(())
            continue
        for pattern, spec in rules:
            if re.search(pattern, name) and _spec_fits(spec, leaf, mesh):
                specs.append(spec)
                break
        else:
            warn_once(f"shard_rules.unmatched.{label}.{name}",
                      "shard_rules: no rule in the %s table fits leaf "
                      "%r (shape %s) on this mesh; replicating",
                      label, name, tuple(getattr(leaf, "shape", ())))
            specs.append(())
    return specs


def spec_to_pspec(spec: Tuple):
    import jax

    return jax.sharding.PartitionSpec(*spec)


def resolve_infer_autocast() -> str:
    """MMLSPARK_TPU_INFER_AUTOCAST: off (default, parity-pinned) or
    bf16. Unknown values warn once and fall back to off."""
    from mmlspark_tpu.core.env import env_str

    mode = (env_str("MMLSPARK_TPU_INFER_AUTOCAST", "off") or "off")
    mode = mode.strip().lower() or "off"
    if mode not in ("off", "bf16"):
        warn_once("shard_rules.autocast.unknown",
                  "MMLSPARK_TPU_INFER_AUTOCAST=%r not in off|bf16; "
                  "using off", mode)
        mode = "off"
    return mode


def placement_cast(x, dtype):
    """THE sanctioned low-precision placement seam: cast float ``x``
    to ``dtype`` (None or a non-float ``x`` passes through unchanged).

    Every low-precision cast in the tree must route through here —
    graftlint GL015 flags any other ``astype(bfloat16)`` in the repo —
    so bf16 placement stays behind :func:`resolve_infer_autocast`'s
    warn-once policy and the graftsan dtype contract sees one seam."""
    import jax.numpy as jnp

    v = jnp.asarray(x)
    if dtype is not None and jnp.issubdtype(v.dtype, jnp.floating):
        return v.astype(dtype)
    return v


def make_shard_and_gather_fns(partition_specs, mesh=None,
                              dtype_specs=None):
    """Per-leaf (shard_fns, gather_fns) pytrees.

    ``shard_fns`` place a host leaf on-device under its rule-derived
    NamedSharding (or as a plain committed array when ``mesh`` is
    None), optionally casting float leaves to ``dtype_specs`` via
    :func:`placement_cast` (a single dtype — the bf16 autocast path;
    None leaves dtypes alone). ``gather_fns`` fetch back to host
    numpy.
    """
    import jax

    def make_shard(spec):
        def shard(x):
            v = placement_cast(x, dtype_specs)
            if mesh is not None:
                sharding = jax.sharding.NamedSharding(
                    mesh, spec_to_pspec(spec))
                return jax.device_put(v, sharding)
            return v
        return shard

    def make_gather(spec):
        def gather(x):
            return np.asarray(jax.device_get(x))
        return gather

    is_spec = lambda s: isinstance(s, tuple)  # noqa: E731
    shard_fns = jax.tree_util.tree_map(make_shard, partition_specs,
                                       is_leaf=is_spec)
    gather_fns = jax.tree_util.tree_map(make_gather, partition_specs,
                                        is_leaf=is_spec)
    return shard_fns, gather_fns


def resolve_shard_rules(mesh, label: str = "model") -> Tuple[str, str]:
    """Resolve the engine mode from MMLSPARK_TPU_SHARD_RULES + mesh.

    Returns ``(mode, reason)``: mode is ``rules`` (rule-table
    shardings over the mesh), ``replicate`` (mesh present but without
    a dp axis — params replicated, batch unsharded), or ``serial``
    (single-device). Downgrades warn once; the pair is recorded in
    model metadata and surfaced by bench/serving so every measurement
    names its placement.
    """
    from mmlspark_tpu.core.env import env_str

    knob = (env_str("MMLSPARK_TPU_SHARD_RULES", "auto") or "auto")
    knob = knob.strip().lower() or "auto"
    if knob not in ("auto", "on", "off"):
        warn_once("shard_rules.knob.unknown",
                  "MMLSPARK_TPU_SHARD_RULES=%r not in auto|on|off; "
                  "using auto", knob)
        knob = "auto"
    if knob == "off":
        return "serial", "disabled by MMLSPARK_TPU_SHARD_RULES=off"
    if mesh is None:
        if knob == "on":
            warn_once(f"shard_rules.no_mesh.{label}",
                      "MMLSPARK_TPU_SHARD_RULES=on but %s carries no "
                      "mesh; serial single-device fallback", label)
            return "serial", "requested on, but no mesh attached"
        return "serial", "no mesh attached"
    if DATA_AXIS not in mesh.axis_names:
        warn_once(f"shard_rules.no_dp.{label}",
                  "shard_rules: mesh for %s has no %r axis; params "
                  "replicate and the batch stays unsharded",
                  label, DATA_AXIS)
        return "replicate", f"mesh lacks the {DATA_AXIS!r} axis"
    return "rules", f"rule table over {mesh.devices.size}-device mesh"


def resolve_train_shard(mesh, label: str = "fit") -> Tuple[str, str]:
    """Resolve the training-state mode from MMLSPARK_TPU_TRAIN_SHARD +
    the fit mesh.

    Returns ``(mode, reason)``: ``sharded`` (ZeRO-1 — optimizer
    moments partitioned over dp via DL_TRAIN_RULES, grads
    reduce-scattered, params all-gathered after the owned-shard
    update) or ``replicated`` (the legacy fully replicated update).
    Downgrades warn once and the pair lands in the fitted model's
    ``shard_metadata()`` — same contract as :func:`resolve_shard_rules`.
    """
    from mmlspark_tpu.core.env import env_str

    knob = (env_str("MMLSPARK_TPU_TRAIN_SHARD", "auto") or "auto")
    knob = knob.strip().lower() or "auto"
    if knob not in ("auto", "on", "off"):
        warn_once("train_shard.knob.unknown",
                  "MMLSPARK_TPU_TRAIN_SHARD=%r not in auto|on|off; "
                  "using auto", knob)
        knob = "auto"
    if knob == "off":
        return "replicated", "disabled by MMLSPARK_TPU_TRAIN_SHARD=off"
    if mesh is None:
        if knob == "on":
            warn_once(f"train_shard.no_mesh.{label}",
                      "MMLSPARK_TPU_TRAIN_SHARD=on but %s carries no "
                      "mesh; training state stays replicated", label)
            return "replicated", "requested on, but no mesh attached"
        return "replicated", "no mesh attached"
    if DATA_AXIS not in mesh.axis_names:
        if knob == "on":
            warn_once(f"train_shard.no_dp.{label}",
                      "MMLSPARK_TPU_TRAIN_SHARD=on but the mesh for %s "
                      "has no %r axis; training state stays replicated",
                      label, DATA_AXIS)
        return "replicated", f"mesh lacks the {DATA_AXIS!r} axis"
    return "sharded", (f"ZeRO-1 over dp={axis_size(mesh, DATA_AXIS)} "
                       f"({mesh.devices.size}-device mesh)")


def train_state_shardings(state, mesh, label: str = "train_state",
                          family: str = "dl"):
    """NamedSharding pytree for a training-state pytree (params, grads,
    or optimizer state) under the family's *_TRAIN_RULES table with the
    training-state small-leaf threshold. Built leaf-wise (optax states
    are namedtuples, so spec tuples cannot live as pytree leaves)."""
    import jax

    specs = _match_rules_flat(TRAIN_FAMILY_RULES[family], state, mesh,
                              label, TRAIN_SMALL_LEAF_NUMEL)
    shardings = [jax.sharding.NamedSharding(mesh, spec_to_pspec(s))
                 for s in specs]
    treedef = jax.tree_util.tree_structure(state)
    return jax.tree_util.tree_unflatten(treedef, shardings)


def train_state_bytes_per_device(state, mesh, label: str = "train_state",
                                 family: str = "dl") -> int:
    """Analytic per-device bytes of ``state`` under its *_TRAIN_RULES
    placement: sharded leaves contribute nbytes / (product of their
    axis sizes), replicated leaves full nbytes — the optimizer-state
    memory model the train-shard metadata and MULTICHIP row report.
    ``mesh=None`` gives the fully replicated total."""
    named = _leaf_paths(state)
    specs = (_match_rules_flat(TRAIN_FAMILY_RULES[family], state, mesh,
                               label, TRAIN_SMALL_LEAF_NUMEL)
             if mesh is not None else [() for _ in named])
    total = 0
    for (_, leaf), spec in zip(named, specs):
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or (1,))
                     * np.dtype(getattr(leaf, "dtype",
                                        np.float32)).itemsize)
        denom = 1
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None and mesh is not None \
                        and ax in mesh.axis_names:
                    denom *= axis_size(mesh, ax)
        total += nbytes // denom
    return total


class ShardedScorer:
    """Shared pjit scoring engine for transform/inference.

    ``apply_fn(params, batch)`` plus a params pytree (or a pre-jitted
    closure ``fn(batch)`` with ``params=None`` — the GBDT boosters
    keep their arrays as jit constants). The batch is an ndarray or a
    dict of ndarrays sharing the leading row dim.

    On construction the params shard once onto the mesh under their
    family rule table and stay resident — no per-batch ``device_put``
    of model state. Each call picks a per-device rung from the pow2
    ladder (by row count only), pads with zero rows, and dispatches
    ``dp x rung`` rows sharded over ``dp``; compile count is bounded
    by the ladder and counted under graftsan. Input buffers are
    donated on non-CPU backends (XLA:CPU device_put aliases host
    numpy, so donation there could hand the user's buffer to XLA).
    """

    def __init__(self, apply_fn: Callable, params=None,
                 family: str = "gbdt", mesh=None, *,
                 max_batch: int = 1024, label: str = "scorer"):
        import jax

        from mmlspark_tpu.parallel.inference import bucket_ladder

        if family not in FAMILY_RULES:
            raise ValueError(f"unknown model family {family!r}; "
                             f"known: {sorted(FAMILY_RULES)}")
        self.family = family
        self.label = label
        self.mode, self.reason = resolve_shard_rules(mesh, label=label)
        self._mesh = mesh if self.mode in ("rules", "replicate") else None
        self._dp = (axis_size(self._mesh, DATA_AXIS)
                    if self.mode == "rules" else 1)
        self._ladder = bucket_ladder(max(int(max_batch), 1))
        self._seen_rungs: set = set()
        self.autocast = resolve_infer_autocast()
        dtype = None
        if self.autocast == "bf16":
            import jax.numpy as jnp
            dtype = jnp.bfloat16
        if params is not None:
            specs = match_partition_rules(
                FAMILY_RULES[family], params, mesh=self._mesh,
                label=f"{family}:{label}")
            shard_fns, _ = make_shard_and_gather_fns(
                specs, mesh=self._mesh, dtype_specs=dtype)
            self._params = jax.tree_util.tree_map(
                lambda f, x: f(x), shard_fns, params)
            donate = (1,) if jax.default_backend() != "cpu" else ()
            self._call = jax.jit(lambda p, x: apply_fn(p, x),
                                 donate_argnums=donate)
        else:
            self._params = None
            self._call = apply_fn  # caller supplies a jitted closure

    # -- dispatch ------------------------------------------------------

    def _rung(self, n: int) -> int:
        from mmlspark_tpu.parallel.inference import bucket_for

        return bucket_for(max(n, 1), self._ladder)

    def _row_sharding(self, ndim: int):
        import jax

        spec = [None] * ndim
        if self.mode == "rules":
            spec[0] = DATA_AXIS
        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(*spec))

    def _put(self, arr: np.ndarray):
        import jax

        if self._mesh is not None:
            return jax.device_put(arr, self._row_sharding(arr.ndim))
        return jax.device_put(arr)

    def _dispatch(self, group):
        if self._params is not None:
            return self._call(self._params, group)
        return self._call(group)

    def __call__(self, x):
        """Score rows; returns host numpy with the same tree structure
        as ``apply_fn``'s output, batch-dim outputs sliced to the true
        row count."""
        import jax

        from mmlspark_tpu.core import sanitizer

        is_dict = isinstance(x, dict)
        cols = ({k: np.asarray(v) for k, v in x.items()} if is_dict
                else {"__x__": np.asarray(x)})
        n = next(iter(cols.values())).shape[0]
        r = self._rung(n)
        step = self._dp * r
        if r not in self._seen_rungs:
            self._seen_rungs.add(r)
            sanitizer.count_recompile(
                f"shard_rules {self.family}:{self.label} rung {r} "
                f"(global {step})")
        chunks = []
        for g in range(0, max(n, 1), step):
            group = {}
            for k, v in cols.items():
                gv = v[g:g + step]
                if gv.shape[0] < step:
                    fill = np.zeros((step - gv.shape[0],) + gv.shape[1:],
                                    dtype=gv.dtype)
                    gv = np.concatenate([gv, fill]) if gv.shape[0] \
                        else fill
                group[k] = self._put(gv)
            chunks.append(self._dispatch(
                group if is_dict else group["__x__"]))
        def fetch(a):
            if getattr(a, "is_fully_addressable", True):
                return np.asarray(jax.device_get(a))
            # process-spanning mesh (multi-host): the global value
            # is not locally addressable; allgather it to every host
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(a, tiled=True))

        flat0, treedef = jax.tree_util.tree_flatten(chunks[0])
        gathered = []
        for i in range(len(flat0)):
            leaves = [fetch(jax.tree_util.tree_flatten(c)[0][i])
                      for c in chunks]
            a = leaves[0]
            if a.ndim >= 1 and a.shape[0] == step:
                gathered.append(np.concatenate(leaves)[:n])
            else:
                gathered.append(a)  # non-batch output: first chunk's
        return jax.tree_util.tree_unflatten(treedef, gathered)

    # -- metadata ------------------------------------------------------

    def metadata(self) -> Dict[str, Any]:
        return {"shard_rules": self.mode,
                "shard_rules_reason": self.reason,
                "shard_rules_family": self.family,
                "infer_autocast": self.autocast,
                "shard_rules_dp": self._dp}
