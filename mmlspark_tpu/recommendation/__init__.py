"""Recommendation: SAR + ranking adapters/evaluation.

Parity surface: reference ``recommendation`` package
(recommendation/SAR.scala:36, SARModel.scala:23, RankingAdapter.scala:1,
RankingEvaluator.scala:1, RankingTrainValidationSplit.scala:1).
"""

from mmlspark_tpu.recommendation.ranking import (
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    RankingTrainValidationSplit,
)
from mmlspark_tpu.recommendation.sar import SAR, SARModel

__all__ = ["SAR", "SARModel", "RankingAdapter", "RankingAdapterModel",
           "RankingEvaluator", "RankingTrainValidationSplit"]
