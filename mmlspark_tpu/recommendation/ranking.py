"""Ranking adapter + evaluator + train/validation split.

Parity: recommendation/RankingAdapter.scala:70 (wrap a recommender so
generic evaluation sees per-user predicted item lists vs actual item
lists), RankingEvaluator.scala:1 (map / ndcgAt / precisionAtk /
recallAtK / mrr over (prediction, label) list pairs),
RankingTrainValidationSplit.scala:1 (per-user stratified split + param
grid search on a ranking metric).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    Param, Params, gt, in_range, one_of, to_float, to_int, to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model


class _RankingParams(Params):
    userCol = Param("userCol", "user column", to_str, default="user")
    itemCol = Param("itemCol", "item column", to_str, default="item")
    ratingCol = Param("ratingCol", "rating column", to_str, default="rating")
    labelCol = Param("labelCol", "actual-items column", to_str, default="label")
    k = Param("k", "recommendation list length", to_int, gt(0), default=10)


class RankingAdapter(Estimator, _RankingParams):
    recommender = Param("recommender", "wrapped recommender estimator",
                        is_complex=True)
    mode = Param("mode", "recommendation mode", to_str, one_of("allUsers"),
                 default="allUsers")
    minRatingsPerUser = Param("minRatingsPerUser", "min ratings per user",
                              to_int, gt(0), default=1)
    minRatingsPerItem = Param("minRatingsPerItem", "min ratings per item",
                              to_int, gt(0), default=1)

    def _fit(self, dataset: DataFrame) -> "RankingAdapterModel":
        rec_model = self.get("recommender").fit(dataset)
        model = RankingAdapterModel(
            **{p.name: v for p, v in self.iter_set_params()
               if p.name != "recommender"})
        model._set(recommenderModel=rec_model)
        return model


class RankingAdapterModel(Model, _RankingParams):
    """transform(df) → one row per user: ``prediction`` (recommended item
    list) and ``label`` (actual items, rating-desc) —
    RankingAdapter.scala:132-151."""

    recommenderModel = Param("recommenderModel", "fitted recommender",
                             is_complex=True)
    mode = Param("mode", "recommendation mode", to_str, default="allUsers")
    minRatingsPerUser = Param("minRatingsPerUser", "min ratings per user",
                              to_int, default=1)
    minRatingsPerItem = Param("minRatingsPerItem", "min ratings per item",
                              to_int, default=1)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        rec = self.get("recommenderModel")
        k = self.get("k")
        recs = rec.recommend_for_user_subset(dataset, k)
        user_col, item_col = self.get("userCol"), self.get("itemCol")
        rating_col = self.get("ratingCol")

        pred_of: Dict[Any, List[Any]] = {}
        for row in recs.iter_rows():
            pred_of[row[user_col]] = [m["item"] for m in row["recommendations"]]

        ratings = dataset.col(rating_col) if rating_col in dataset else \
            np.ones(dataset.num_rows)
        items = dataset.col(item_col)
        actual_of: Dict[Any, List[Tuple[float, Any]]] = {}
        for u, it, r in zip(dataset.col(user_col), items, ratings):
            actual_of.setdefault(u, []).append((-float(r), it))

        users = sorted(actual_of.keys())
        preds = np.empty(len(users), dtype=object)
        actuals = np.empty(len(users), dtype=object)
        for i, u in enumerate(users):
            preds[i] = list(pred_of.get(u, []))
            actuals[i] = [it for _, it in sorted(actual_of[u])]
        return DataFrame({user_col: np.asarray(users),
                          "prediction": preds, self.get("labelCol"): actuals})


class RankingEvaluator(Params):
    """Metrics over per-user (predicted list, actual list) pairs."""

    metricName = Param("metricName", "ndcgAt|map|precisionAtk|recallAtK|mrr",
                       to_str, one_of("ndcgAt", "map", "precisionAtk",
                                      "recallAtK", "mrr"),
                       default="ndcgAt")
    k = Param("k", "cutoff", to_int, gt(0), default=10)
    labelCol = Param("labelCol", "actual-items column", to_str, default="label")
    predictionCol = Param("predictionCol", "predicted-items column", to_str,
                          default="prediction")

    def _pairs(self, dataset: DataFrame):
        preds = dataset.col(self.get("predictionCol"))
        labels = dataset.col(self.get("labelCol"))
        return [(list(p), list(l)) for p, l in zip(preds, labels) if len(l)]

    def evaluate(self, dataset: DataFrame) -> float:
        return self.match_metric(self.get("metricName"), dataset)

    def match_metric(self, name: str, dataset: DataFrame) -> float:
        pairs = self._pairs(dataset)
        if not pairs:
            return 0.0
        k = self.get("k")
        vals = []
        for pred, actual in pairs:
            actual_set = set(actual)
            if name == "ndcgAt":
                dcg = sum(1.0 / np.log2(i + 2)
                          for i, p in enumerate(pred[:k]) if p in actual_set)
                idcg = sum(1.0 / np.log2(i + 2)
                           for i in range(min(k, len(actual))))
                vals.append(dcg / idcg if idcg > 0 else 0.0)
            elif name == "map":
                hits, score = 0, 0.0
                for i, p in enumerate(pred):
                    if p in actual_set:
                        hits += 1
                        score += hits / (i + 1.0)
                vals.append(score / len(actual))
            elif name == "precisionAtk":
                vals.append(len(set(pred[:k]) & actual_set) / float(k))
            elif name == "recallAtK":
                vals.append(len(set(pred[:k]) & actual_set)
                            / float(len(actual)))
            elif name == "mrr":
                rank = next((i + 1 for i, p in enumerate(pred)
                             if p in actual_set), None)
                vals.append(1.0 / rank if rank else 0.0)
            else:
                raise ValueError(f"unknown metric {name!r}")
        return float(np.mean(vals))

    def get_all_metrics(self, dataset: DataFrame) -> Dict[str, float]:
        return {m: self.match_metric(m, dataset)
                for m in ("map", "ndcgAt", "precisionAtk", "recallAtK", "mrr")}

    def is_larger_better(self) -> bool:
        return True


class RankingTrainValidationSplit(Estimator, _RankingParams):
    """Per-user chronology-free stratified split + grid search.

    Parity: RankingTrainValidationSplit.scala:1 — trainRatio split keeps
    every user present in train; candidate estimators (or param maps)
    are evaluated with RankingEvaluator on the validation half.
    """

    estimator = Param("estimator", "recommender estimator", is_complex=True)
    estimatorParamMaps = Param("estimatorParamMaps", "list of param dicts",
                               is_complex=True)
    evaluator = Param("evaluator", "RankingEvaluator", is_complex=True)
    trainRatio = Param("trainRatio", "fraction of each user's events in "
                       "train", to_float, in_range(0.0, 1.0,
                                                   lo_inclusive=False,
                                                   hi_inclusive=False),
                       default=0.75)
    seed = Param("seed", "rng seed", to_int, default=0)

    def split(self, dataset: DataFrame) -> Tuple[DataFrame, DataFrame]:
        rng = np.random.default_rng(self.get("seed"))
        groups = dataset.group_indices(self.get("userCol"))
        train_idx, valid_idx = [], []
        ratio = self.get("trainRatio")
        for _, idx in groups.items():
            perm = rng.permutation(idx)
            n_train = max(1, int(round(len(idx) * ratio)))
            train_idx.append(perm[:n_train])
            valid_idx.append(perm[n_train:])
        return (dataset.take_rows(np.concatenate(train_idx)),
                dataset.take_rows(np.concatenate(valid_idx))
                if any(len(v) for v in valid_idx)
                else dataset.take_rows(np.asarray([], dtype=np.int64)))

    def _fit(self, dataset: DataFrame) -> "RankingTrainValidationSplitModel":
        train_df, valid_df = self.split(dataset)
        evaluator = self.get("evaluator") or RankingEvaluator()
        param_maps = self.get("estimatorParamMaps") or [{}]
        base = self.get("estimator")

        best_model, best_metric, metrics = None, -np.inf, []
        for pm in param_maps:
            adapter = RankingAdapter(
                recommender=base.copy(**pm), k=self.get("k"),
                userCol=self.get("userCol"), itemCol=self.get("itemCol"),
                ratingCol=self.get("ratingCol"))
            fitted = adapter.fit(train_df)
            scored = fitted.transform(valid_df)
            m = evaluator.evaluate(scored)
            metrics.append(m)
            if m > best_metric:
                best_metric, best_model = m, fitted
        out = RankingTrainValidationSplitModel()
        out._set(bestModel=best_model)
        out.validation_metrics = metrics
        return out


class RankingTrainValidationSplitModel(Model):
    bestModel = Param("bestModel", "best fitted ranking adapter",
                      is_complex=True)
    validation_metrics: List[float] = []

    def get_best_model(self) -> RankingAdapterModel:
        return self.get("bestModel")

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return self.get("bestModel").transform(dataset)
