"""SAR — Smart Adaptive Recommendations.

Parity: recommendation/SAR.scala:36 —

- **user-item affinity** (calculateUserItemAffinities, SAR.scala:86-121):
  affinity = rating * 2^(-Δt / (timeDecayCoeff days)) summed per
  (user, item); rating and/or time optional, both absent → 1.
- **item-item similarity** (calculateItemItemSimilarity, SAR.scala:152-208):
  distinct-user co-occurrence counts, thresholded at supportThreshold,
  normalized by ``jaccard`` (default) / ``lift`` / raw co-occurrence.

TPU-first: both matrices are dense device matmuls — the co-occurrence
matrix is ``Bᵀ B`` of the binary user×item interaction matrix, and
recommendation scoring is ``affinity @ similarity`` + top-k, instead of
the reference's per-row UDFs over broadcast sparse matrices.
"""

from __future__ import annotations

from datetime import datetime
from typing import Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import Param, Params, gt, one_of, to_int, to_str
from mmlspark_tpu.core.pipeline import Estimator, Model


class _SARParams(Params):
    userCol = Param("userCol", "user id column (integer ids)", to_str,
                    default="user")
    itemCol = Param("itemCol", "item id column (integer ids)", to_str,
                    default="item")
    ratingCol = Param("ratingCol", "rating column (optional)", to_str,
                      default="rating")
    timeCol = Param("timeCol", "activity timestamp column (optional)", to_str,
                    default="time")
    similarityFunction = Param("similarityFunction",
                               "jaccard|lift|cooccurrence", to_str,
                               one_of("jaccard", "lift", "cooccurrence"),
                               default="jaccard")
    supportThreshold = Param("supportThreshold", "min co-occurrence count",
                             to_int, gt(0), default=4)
    timeDecayCoeff = Param("timeDecayCoeff", "half-life in days", to_int,
                           gt(0), default=30)
    startTime = Param("startTime", "reference 'now' time (ISO format) for "
                      "time decay", to_str)
    activityTimeFormat = Param("activityTimeFormat", "strptime format for "
                               "timeCol strings", to_str,
                               default="%Y/%m/%dT%H:%M:%S")


class SAR(Estimator, _SARParams):
    def _parse_times(self, values) -> np.ndarray:
        fmt = self.get("activityTimeFormat")
        out = np.empty(len(values), np.float64)
        for i, v in enumerate(values):
            if isinstance(v, str):
                out[i] = datetime.strptime(v, fmt).timestamp()
            else:
                out[i] = float(v)
        return out

    def _fit(self, dataset: DataFrame) -> "SARModel":
        users = np.asarray(dataset.col(self.get("userCol"))).astype(np.int64)
        items = np.asarray(dataset.col(self.get("itemCol"))).astype(np.int64)
        n_users = int(users.max()) + 1
        n_items = int(items.max()) + 1

        # -- affinity weights ------------------------------------------------
        weights = np.ones(len(users))
        if self.get("ratingCol") in dataset:
            weights = np.asarray(dataset.col(self.get("ratingCol")),
                                 np.float64)
        if self.get("timeCol") in dataset:
            t = self._parse_times(dataset.col(self.get("timeCol")))
            if self.is_set("startTime"):
                ref = datetime.fromisoformat(self.get("startTime")).timestamp()
            else:
                ref = float(t.max())
            dt_minutes = (ref - t) / 60.0
            decay = 2.0 ** (-dt_minutes / (self.get("timeDecayCoeff") * 24 * 60))
            weights = weights * decay

        affinity = np.zeros((n_users, n_items), np.float64)
        np.add.at(affinity, (users, items), weights)

        # -- item-item similarity (device matmul) ----------------------------
        import jax.numpy as jnp

        interacted = np.zeros((n_users, n_items), np.float32)
        interacted[users, items] = 1.0
        b = jnp.asarray(interacted)
        cooccur = np.asarray(b.T @ b, np.float64)  # distinct users per pair
        occ = np.diag(cooccur).copy()
        thresholded = np.where(cooccur >= self.get("supportThreshold"),
                               cooccur, 0.0)
        fn = self.get("similarityFunction")
        if fn == "jaccard":
            denom = occ[:, None] + occ[None, :] - cooccur
            sim = np.where(denom > 0, thresholded / np.maximum(denom, 1e-12), 0.0)
        elif fn == "lift":
            denom = occ[:, None] * occ[None, :]
            sim = np.where(denom > 0, thresholded / np.maximum(denom, 1e-12), 0.0)
        else:
            sim = thresholded

        model = SARModel(**{p.name: v for p, v in self.iter_set_params()})
        model._init_state(affinity, sim, interacted)
        return model


class SARModel(Model, _SARParams):
    """Fitted SAR. ``user_data_frame`` / ``item_data_frame`` views match the
    reference's userDataFrame/itemDataFrame params (SARModel.scala:30-43)."""

    _affinity: np.ndarray    # (users, items)
    _similarity: np.ndarray  # (items, items)
    _seen: np.ndarray        # (users, items) binary

    def _init_state(self, affinity, similarity, seen):
        self._affinity = affinity
        self._similarity = similarity
        self._seen = seen
        return self

    def _get_state(self):
        return {"affinity": self._affinity, "similarity": self._similarity,
                "seen": self._seen}

    def _set_state(self, state):
        self._affinity = np.asarray(state["affinity"])
        self._similarity = np.asarray(state["similarity"])
        self._seen = np.asarray(state["seen"])

    @property
    def user_data_frame(self) -> DataFrame:
        return DataFrame({self.get("userCol"): np.arange(len(self._affinity)),
                          "flatList": self._affinity})

    @property
    def item_data_frame(self) -> DataFrame:
        return DataFrame({self.get("itemCol"): np.arange(len(self._similarity)),
                          "itemAffinities": self._similarity})

    def _scores(self, user_ids: np.ndarray, remove_seen: bool) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(aff, sim, seen):
            s = aff @ sim
            return jnp.where(seen > 0, -jnp.inf, s) if remove_seen else s

        return np.asarray(score(jnp.asarray(self._affinity[user_ids], jnp.float32),
                                jnp.asarray(self._similarity, jnp.float32),
                                jnp.asarray(self._seen[user_ids], jnp.float32)))

    def recommend_for_all_users(self, num_items: int,
                                remove_seen: bool = True) -> DataFrame:
        users = np.arange(len(self._affinity))
        return self._recommend(users, num_items, remove_seen)

    def recommend_for_user_subset(self, dataset: DataFrame, num_items: int,
                                  remove_seen: bool = True) -> DataFrame:
        users = np.unique(np.asarray(dataset.col(self.get("userCol")),
                                     np.int64))
        return self._recommend(users, num_items, remove_seen)

    def _recommend(self, users: np.ndarray, k: int,
                   remove_seen: bool) -> DataFrame:
        scores = self._scores(users, remove_seen)
        k = min(k, scores.shape[1])
        top = np.argsort(-scores, axis=1)[:, :k]
        recs = np.empty(len(users), dtype=object)
        for r in range(len(users)):
            recs[r] = [{"item": int(i), "rating": float(scores[r, i])}
                       for i in top[r] if np.isfinite(scores[r, i])]
        return DataFrame({self.get("userCol"): users,
                          "recommendations": recs})

    def _transform(self, dataset: DataFrame) -> DataFrame:
        """Score explicit (user, item) pairs — parity with
        SARModel.transform's rating prediction."""
        users = np.asarray(dataset.col(self.get("userCol")), np.int64)
        items = np.asarray(dataset.col(self.get("itemCol")), np.int64)
        scores = self._scores(np.unique(users), remove_seen=False)
        row_of = {u: i for i, u in enumerate(np.unique(users))}
        pred = np.asarray([scores[row_of[u], it] for u, it in zip(users, items)])
        return dataset.with_column("prediction", pred)
