"""Generic pipeline stages (parity: reference core `stages` package)."""

from mmlspark_tpu.stages.basic import (Cacher, DropColumns, Explode, Lambda,
                                       MultiColumnAdapter, RenameColumn,
                                       Repartition, SelectColumns,
                                       UDFTransformer, UnicodeNormalize)
from mmlspark_tpu.stages.balance import (ClassBalancer, ClassBalancerModel,
                                         StratifiedRepartition)
from mmlspark_tpu.stages.batching import (DynamicMiniBatchTransformer,
                                          FixedMiniBatchTransformer,
                                          FlattenBatch, PartitionConsolidator,
                                          TimeIntervalMiniBatchTransformer)
from mmlspark_tpu.stages.summarize import SummarizeData
from mmlspark_tpu.stages.text import EnsembleByKey, TextPreprocessor
from mmlspark_tpu.stages.timer import Timer, TimerModel

__all__ = [
    "Cacher", "ClassBalancer", "ClassBalancerModel", "DropColumns",
    "DynamicMiniBatchTransformer", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "FlattenBatch", "Lambda",
    "MultiColumnAdapter", "PartitionConsolidator", "RenameColumn",
    "Repartition", "SelectColumns", "StratifiedRepartition", "SummarizeData",
    "TextPreprocessor", "TimeIntervalMiniBatchTransformer", "Timer",
    "TimerModel", "UDFTransformer", "UnicodeNormalize",
]
