"""Class balancing + stratified resharding.

Parity: stages/ClassBalancer.scala:44-57 (weight = maxCount/count per
label) and stages/StratifiedRepartition.scala:50-84 (resample per label
so every shard sees every label — required by distributed GBDT multiclass
where each worker must hold at least one instance of each class).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (HasInputCol, HasLabelCol, HasOutputCol,
                                     Param, one_of, to_bool, to_int, to_str)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Computes per-class weights maxCount/count as a new column
    (stages/ClassBalancer.scala:44-57)."""

    outputCol = Param("outputCol", "weight column", to_str, default="weight")
    broadcastJoin = Param("broadcastJoin", "broadcast the mapping (parity)",
                          to_bool, default=True)

    def _fit(self, dataset: DataFrame) -> "ClassBalancerModel":
        labels = dataset.col(self.get("inputCol"))
        values, counts = np.unique(labels, return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        model = ClassBalancerModel(
            inputCol=self.get("inputCol"), outputCol=self.get("outputCol"))
        model.weights = {v: w for v, w in zip(values.tolist(), weights)}
        return model


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    outputCol = Param("outputCol", "weight column", to_str, default="weight")

    weights: Dict[Any, float]

    def _get_state(self):
        return {"weights": [[k, v] for k, v in self.weights.items()]}

    def _set_state(self, state):
        self.weights = {k: v for k, v in state["weights"]}

    def _transform(self, dataset: DataFrame) -> DataFrame:
        labels = dataset.col(self.get("inputCol"))
        w = np.array([self.weights[v] for v in labels.tolist()],
                     dtype=np.float64)
        return dataset.with_column(self.get("outputCol"), w)


class StratifiedRepartition(Transformer, HasLabelCol):
    """Resamples (with replacement) per label, then orders rows so that
    any contiguous equal sharding contains every label
    (stages/StratifiedRepartition.scala:50-84). Modes: ``equal`` equalizes
    label counts, ``original`` keeps ratios, ``mixed`` is the reference's
    heuristic between the two."""

    mode = Param("mode", "equal | original | mixed", to_str,
                 one_of("equal", "original", "mixed"), default="mixed")
    seed = Param("seed", "sampling seed", to_int, default=0)
    numShards = Param("numShards", "target shard count (defaults to device count)",
                      to_int)

    def _num_shards(self, dataset: DataFrame) -> int:
        if self.is_set("numShards"):
            return self.get("numShards")
        hint = dataset.metadata("__shards__").get("n")
        if hint:
            return int(hint)
        import jax
        return jax.device_count()

    def _transform(self, dataset: DataFrame) -> DataFrame:
        labels = dataset.col(self.get("labelCol"))
        values, counts = np.unique(labels, return_counts=True)
        n_shards = self._num_shards(dataset)

        def equal_fracs():
            max_count = max(counts.max(), n_shards)
            return {v: max_count / c for v, c in zip(values.tolist(), counts)}

        mode = self.get("mode")
        if mode == "equal":
            fracs = equal_fracs()
        elif mode == "original":
            fracs = {v: 1.0 for v in values.tolist()}
        else:
            # mixed: geometric mean of equal and original — upsamples
            # rare labels partway toward balance without exploding the
            # common ones (the reference's heuristic middle ground)
            eq = equal_fracs()
            fracs = {v: float(np.sqrt(eq[v])) for v in values.tolist()}

        rng = np.random.default_rng(self.get("seed"))
        picked = []
        for v, c in zip(values.tolist(), counts):
            idx = np.nonzero(labels == v)[0]
            # every label must land in every shard — the transformer's
            # whole purpose (StratifiedRepartition.scala:28-31)
            target = max(int(round(c * fracs[v])), n_shards, 1)
            if target <= c:
                picked.append(rng.choice(idx, size=target, replace=False))
            else:
                picked.append(rng.choice(idx, size=target, replace=True))
        # interleave labels round-robin so each contiguous shard gets all
        # labels (the RangePartitioner-on-index analog)
        order = np.concatenate(picked)
        # fractional position within each label group: labels interleave
        # evenly, so every contiguous shard sees every label
        keys = np.concatenate([np.arange(len(p)) / max(len(p), 1)
                               for p in picked])
        out = dataset.take_rows(order[np.argsort(keys, kind="stable")])
        return out.with_metadata("__shards__", {"n": n_shards})
