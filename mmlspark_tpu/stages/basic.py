"""Generic column/row DataFrame transformers.

Parity targets: the reference's ``stages`` package of ~20 small
transformers (SURVEY.md §2.1): DropColumns.scala:1, SelectColumns.scala:1,
RenameColumn.scala:1, Cacher.scala:1, Repartition.scala:1, Explode.scala:1,
Lambda.scala:1, UDFTransformer.scala:1, MultiColumnAdapter.scala:1,
UnicodeNormalize.scala:1. On the TPU-native columnar DataFrame most of
these are thin; "partitions" map to device shards (a shard-count hint
consumed by ``DataFrame.to_device``), not physical RDD partitions.
"""

from __future__ import annotations

import unicodedata
from typing import Any, Callable, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (HasInputCol, HasOutputCol, Param,
                                     ParamValidationError, gt, one_of,
                                     to_bool, to_int, to_list, to_str)
from mmlspark_tpu.core.pipeline import Transformer


class DropColumns(Transformer):
    """Drops the listed columns (stages/DropColumns.scala:1)."""

    cols = Param("cols", "columns to drop", to_list(to_str))

    def __init__(self, cols: Optional[Sequence[str]] = None, **kwargs: Any):
        super().__init__(**({"cols": list(cols)} if cols else {}), **kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        cols = self.get("cols") or []
        missing = [c for c in cols if c not in dataset]
        if missing:
            raise KeyError(f"DropColumns: no such columns {missing}")
        return dataset.drop(*cols)


class SelectColumns(Transformer):
    """Keeps only the listed columns (stages/SelectColumns.scala:1)."""

    cols = Param("cols", "columns to keep", to_list(to_str))

    def __init__(self, cols: Optional[Sequence[str]] = None, **kwargs: Any):
        super().__init__(**({"cols": list(cols)} if cols else {}), **kwargs)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return dataset.select(*(self.get("cols") or []))


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """Renames inputCol to outputCol (stages/RenameColumn.scala:1)."""

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return dataset.rename({self.get("inputCol"): self.get("outputCol")})


class Cacher(Transformer):
    """Materializes the dataset. The columnar DataFrame is already eager,
    so this pins device copies of numeric columns when requested
    (stages/Cacher.scala:1; `disable` param kept for parity)."""

    disable = Param("disable", "whether to disable caching", to_bool,
                    default=False)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return dataset


class Repartition(Transformer):
    """Records a target shard count consumed by the device path; with
    ``disable=False`` and n > 0 also re-spreads rows round-robin so any
    contiguous device sharding sees an even row mix
    (stages/Repartition.scala:1)."""

    n = Param("n", "number of shards", to_int, gt(0))
    disable = Param("disable", "do nothing if true", to_bool, default=False)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        if self.get("disable") or not self.is_set("n"):
            return dataset
        n = self.get("n")
        num = dataset.num_rows
        # round-robin order: row i goes to shard i % n, shards contiguous
        order = np.argsort(np.arange(num) % n, kind="stable")
        out = dataset.take_rows(order)
        return out.with_metadata("__shards__", {"n": n})


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explodes a list/array column into one row per element
    (stages/Explode.scala:1)."""

    def _transform(self, dataset: DataFrame) -> DataFrame:
        in_col, out_col = self.get("inputCol"), self.get("outputCol")
        col = dataset.col(in_col)
        lens = np.array([len(v) for v in col], dtype=np.int64)
        row_idx = np.repeat(np.arange(dataset.num_rows), lens)
        flat = [x for v in col for x in v]
        exploded = dataset.take_rows(row_idx)
        return exploded.with_column(out_col, flat)


class Lambda(Transformer):
    """Applies an arbitrary DataFrame -> DataFrame function
    (stages/Lambda.scala:1)."""

    transformFunc = Param("transformFunc", "df -> df function", is_complex=True)

    def __init__(self, transformFunc: Optional[Callable[[DataFrame], DataFrame]] = None,
                 **kwargs: Any):
        super().__init__(**kwargs)
        if transformFunc is not None:
            self._paramMap["transformFunc"] = transformFunc

    def _transform(self, dataset: DataFrame) -> DataFrame:
        fn = self.get("transformFunc")
        if fn is None:
            raise ParamValidationError("Lambda requires transformFunc")
        return fn(dataset)


class UDFTransformer(Transformer, HasInputCol, HasOutputCol):
    """Applies a per-row (or vectorized) function to one or more columns
    (stages/UDFTransformer.scala:1). ``udf`` receives one value per input
    column; if ``vectorized`` it receives whole column arrays instead —
    the TPU-friendly path (wrap a jitted function)."""

    inputCols = Param("inputCols", "multiple input columns", to_list(to_str))
    udf = Param("udf", "the function to apply", is_complex=True)
    vectorized = Param("vectorized", "call udf on whole columns", to_bool,
                       default=False)

    def __init__(self, udf: Optional[Callable] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if udf is not None:
            self._paramMap["udf"] = udf

    def _transform(self, dataset: DataFrame) -> DataFrame:
        fn = self.get("udf")
        if fn is None:
            raise ParamValidationError("UDFTransformer requires udf")
        if self.is_set("inputCols"):
            cols = [dataset.col(c) for c in self.get("inputCols")]
        else:
            cols = [dataset.col(self.get("inputCol"))]
        if self.get("vectorized"):
            result = fn(*cols)
        else:
            result = [fn(*vals) for vals in zip(*cols)]
        return dataset.with_column(self.get("outputCol"), np.asarray(result))


class MultiColumnAdapter(Transformer):
    """Applies a single-column stage to several columns
    (stages/MultiColumnAdapter.scala:1). The base stage must have
    inputCol/outputCol params."""

    inputCols = Param("inputCols", "input columns", to_list(to_str))
    outputCols = Param("outputCols", "output columns", to_list(to_str))
    baseStage = Param("baseStage", "stage to replicate per column",
                      is_complex=True)

    def __init__(self, baseStage=None, **kwargs: Any):
        super().__init__(**kwargs)
        if baseStage is not None:
            self._paramMap["baseStage"] = baseStage

    def _transform(self, dataset: DataFrame) -> DataFrame:
        ins, outs = self.get("inputCols"), self.get("outputCols")
        if not ins or not outs or len(ins) != len(outs):
            raise ParamValidationError(
                "MultiColumnAdapter needs equal-length inputCols/outputCols")
        base = self.get("baseStage")
        df = dataset
        for i, o in zip(ins, outs):
            stage = base.copy(inputCol=i, outputCol=o)
            df = stage.transform(df)
        return df


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode-normalizes a string column (stages/UnicodeNormalize.scala:1)."""

    form = Param("form", "unicode normal form", to_str,
                 one_of("NFC", "NFD", "NFKC", "NFKD"), default="NFKD")
    lower = Param("lower", "lowercase the text", to_bool, default=True)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        form, lower = self.get("form"), self.get("lower")
        col = dataset.col(self.get("inputCol"))
        out = [None if v is None else
               (unicodedata.normalize(form, v).lower() if lower
                else unicodedata.normalize(form, v))
               for v in col]
        return dataset.with_column(self.get("outputCol"),
                                   np.asarray(out, dtype=object))
