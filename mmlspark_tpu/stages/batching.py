"""Mini-batching transformers.

Parity: stages/MiniBatchTransformer.scala:153,189 (Fixed/Dynamic/
TimeInterval mini-batchers + FlattenBatch) and
stages/PartitionConsolidator.scala:22. Batched rows hold one array/list
per cell — the shape the ONNX scorer and HTTP transformer consume — and
``FlattenBatch`` undoes it. On TPU the fixed batcher is the important
one: static batch sizes keep XLA shapes stable; the final ragged batch is
either emitted short (host paths) or padded by the consumer.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import Param, gt, to_bool, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer


def _batch_column(arr: np.ndarray, bounds: List[int]) -> np.ndarray:
    """Slice a column into per-batch cells (object array of arrays)."""
    out = np.empty(len(bounds) - 1, dtype=object)
    for i in range(len(bounds) - 1):
        out[i] = arr[bounds[i]:bounds[i + 1]]
    return out


def _batch_df(dataset: DataFrame, bounds: List[int]) -> DataFrame:
    meta = {name: dataset.metadata(name) for name in dataset.columns
            if dataset.metadata(name)}
    return DataFrame({name: _batch_column(dataset.col(name), bounds)
                      for name in dataset.columns}, meta)


class FixedMiniBatchTransformer(Transformer):
    """Groups rows into fixed-size batches
    (stages/MiniBatchTransformer.scala:153). ``buffered`` and
    ``maxBufferSize`` are accepted for parity; the columnar engine always
    has the full column in host memory so buffering is moot."""

    batchSize = Param("batchSize", "rows per batch", to_int, gt(0), default=16)
    buffered = Param("buffered", "buffer batches (parity no-op)", to_bool,
                     default=False)
    maxBufferSize = Param("maxBufferSize", "max buffered batches", to_int,
                          default=2147483647)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        bs = self.get("batchSize")
        n = dataset.num_rows
        bounds = list(range(0, n, bs)) + [n]
        return _batch_df(dataset, bounds)


class DynamicMiniBatchTransformer(Transformer):
    """Batches all currently-available rows up to maxBatchSize
    (stages/MiniBatchTransformer.scala:189). Eager-columnar semantics:
    one batch of everything, capped."""

    maxBatchSize = Param("maxBatchSize", "max rows per batch", to_int, gt(0),
                         default=2147483647)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        cap = self.get("maxBatchSize")
        n = dataset.num_rows
        bounds = list(range(0, n, cap)) + [n]
        bounds = sorted(set(bounds))
        return _batch_df(dataset, bounds)


class TimeIntervalMiniBatchTransformer(Transformer):
    """Time-interval batcher (stages/MiniBatchTransformer.scala): rows
    arriving within one ``millisToWait`` window form a batch.

    The reference batches by ARRIVAL time off a stream; the columnar
    analog batches by EVENT time: ``timestampCol`` (epoch millis, or any
    monotone numeric clock) assigns each row to the window
    ``(ts - ts[0]) // millisToWait``, consecutive same-window rows
    group into one batch, and ``maxBatchSize`` splits oversized
    windows — identical batch boundaries to replaying the rows against
    a wall clock. Without a timestamp column a bounded frame has a
    single arrival instant, so everything lands in one capped batch
    (the documented degenerate)."""

    millisToWait = Param("millisToWait", "window length (ms)", to_int,
                         gt(0), default=1000)
    maxBatchSize = Param("maxBatchSize", "max rows per batch", to_int,
                         gt(0), default=2147483647)
    timestampCol = Param("timestampCol", "event-time column (epoch ms)",
                         to_str)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        cap = self.get("maxBatchSize")
        n = dataset.num_rows
        if not self.is_set("timestampCol") or n == 0:
            return DynamicMiniBatchTransformer(
                maxBatchSize=cap).transform(dataset)
        ts = np.asarray(dataset.col(self.get("timestampCol")),
                        dtype=np.float64)
        window = np.floor((ts - ts[0]) / self.get("millisToWait"))
        bounds = [0]
        for i in range(1, n):
            if (window[i] != window[i - 1]
                    or i - bounds[-1] >= cap):
                bounds.append(i)
        bounds.append(n)
        return _batch_df(dataset, sorted(set(bounds)))


class FlattenBatch(Transformer):
    """Explodes batched rows back into single rows
    (stages/MiniBatchTransformer.scala:189 FlattenBatch)."""

    def _transform(self, dataset: DataFrame) -> DataFrame:
        if dataset.num_rows == 0:
            return dataset
        names = dataset.columns
        cols: dict = {}
        for name in names:
            cells = dataset.col(name)
            parts = [np.asarray(c) for c in cells]
            if parts and all(p.dtype != object for p in parts):
                cols[name] = np.concatenate(parts)
            else:
                cols[name] = np.asarray(
                    [x for c in cells for x in c], dtype=object)
        lengths = {name: len(v) for name, v in cols.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged batch columns: {lengths}")
        meta = {name: dataset.metadata(name) for name in names
                if dataset.metadata(name)}
        return DataFrame(cols, meta)


class PartitionConsolidator(Transformer):
    """Funnels data to fewer shards (stages/PartitionConsolidator.scala:22).
    Reference semantics: move all rows onto as few executors as have data,
    for resource-constrained stages (one HTTP client per node). Columnar
    analog: collapse the shard hint to 1."""

    def _transform(self, dataset: DataFrame) -> DataFrame:
        return dataset.with_metadata("__shards__", {"n": 1})
