"""SummarizeData: per-column summary statistics table.

Parity: stages/SummarizeData.scala — feature column plus count / basic /
sample / percentile stat groups, toggled by boolean params. Quantiles are
exact (`errorThreshold` kept for parity; numpy quantiles are already
exact on host columns).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import Param, to_bool, to_float
from mmlspark_tpu.core.pipeline import Transformer


class SummarizeData(Transformer):
    counts = Param("counts", "compute count statistics", to_bool, default=True)
    basic = Param("basic", "compute basic statistics", to_bool, default=True)
    sample = Param("sample", "compute sample statistics", to_bool, default=True)
    percentiles = Param("percentiles", "compute percentiles", to_bool,
                        default=True)
    errorThreshold = Param("errorThreshold",
                           "quantile error threshold - 0 is exact", to_float,
                           default=0.0)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        out: dict = {"Feature": []}
        want_counts = self.get("counts")
        want_basic = self.get("basic")
        want_sample = self.get("sample")
        want_pct = self.get("percentiles")
        if want_counts:
            out.update({"Count": [], "Unique Value Count": [],
                        "Missing Value Count": []})
        if want_basic:
            out.update({"Min": [], "1st Quartile": [], "Median": [],
                        "3rd Quartile": [], "Max": [], "Mean": [],
                        "Range": []})
        if want_sample:
            out.update({"Sample Variance": [], "Sample Standard Deviation": [],
                        "Sample Skewness": [], "Sample Kurtosis": []})
        if want_pct:
            out.update({f"P{p}": [] for p in (0.5, 1, 5, 30, 70, 95, 99, 99.5)})

        for name in dataset.columns:
            arr = dataset.col(name)
            if arr.ndim != 1:
                continue
            out["Feature"].append(name)
            is_numeric = np.issubdtype(arr.dtype, np.number) or arr.dtype == bool
            numeric = arr.astype(np.float64) if is_numeric else None
            valid = numeric[~np.isnan(numeric)] if is_numeric else None

            if want_counts:
                out["Count"].append(float(len(arr)))
                if is_numeric:
                    out["Unique Value Count"].append(float(len(np.unique(valid))))
                    out["Missing Value Count"].append(float(np.isnan(numeric).sum()))
                else:
                    vals = [v for v in arr if v is not None]
                    out["Unique Value Count"].append(float(len(set(vals))))
                    out["Missing Value Count"].append(float(len(arr) - len(vals)))

            nan = float("nan")
            if want_basic:
                if is_numeric and len(valid):
                    q1, med, q3 = np.quantile(valid, [0.25, 0.5, 0.75])
                    out["Min"].append(float(valid.min()))
                    out["1st Quartile"].append(float(q1))
                    out["Median"].append(float(med))
                    out["3rd Quartile"].append(float(q3))
                    out["Max"].append(float(valid.max()))
                    out["Mean"].append(float(valid.mean()))
                    out["Range"].append(float(valid.max() - valid.min()))
                else:
                    for k in ("Min", "1st Quartile", "Median", "3rd Quartile",
                              "Max", "Mean", "Range"):
                        out[k].append(nan)
            if want_sample:
                if is_numeric and len(valid) > 1:
                    var = float(valid.var(ddof=1))
                    sd = float(np.sqrt(var))
                    centered = valid - valid.mean()
                    m2 = float((centered ** 2).mean())
                    skew = (float((centered ** 3).mean()) / m2 ** 1.5
                            if m2 > 0 else nan)
                    kurt = (float((centered ** 4).mean()) / m2 ** 2 - 3.0
                            if m2 > 0 else nan)
                    out["Sample Variance"].append(var)
                    out["Sample Standard Deviation"].append(sd)
                    out["Sample Skewness"].append(skew)
                    out["Sample Kurtosis"].append(kurt)
                else:
                    for k in ("Sample Variance", "Sample Standard Deviation",
                              "Sample Skewness", "Sample Kurtosis"):
                        out[k].append(nan)
            if want_pct:
                for p in (0.5, 1, 5, 30, 70, 95, 99, 99.5):
                    if is_numeric and len(valid):
                        out[f"P{p}"].append(float(np.quantile(valid, p / 100)))
                    else:
                        out[f"P{p}"].append(nan)

        return DataFrame({k: np.asarray(v, dtype=object) if k == "Feature"
                          else np.asarray(v) for k, v in out.items()})
