"""Text preprocessing stages.

Parity: stages/TextPreprocessor.scala (trie-backed longest-match,
left-to-right substring replacement with a normalization function) and
stages/EnsembleByKey.scala (grouped vector/scalar aggregation).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (HasInputCol, HasOutputCol, Param,
                                     ParamValidationError, one_of, to_bool,
                                     to_list, to_str)
from mmlspark_tpu.core.pipeline import Transformer

_NORM_FUNCS = {
    "identity": lambda c: c,
    "lowerCase": str.lower,
    "upperCase": str.upper,
}


class _Trie:
    """Character trie with longest-match scan, mirroring the matching
    semantics of TextPreprocessor.scala:18-88: longest key wins, matches
    scanned left to right, and after a replacement any immediately
    following word characters are skipped."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "_Trie"] = {}
        self.value: Optional[str] = None

    def put(self, key: str, value: str, norm) -> None:
        node = self
        for ch in key:
            ch = norm(ch)
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def map_text(self, text: str, norm) -> str:
        out = []
        i, n = 0, len(text)
        while i < n:
            node, j = self, i
            best_end, best_val = -1, None
            while j < n:
                child = node.children.get(norm(text[j]))
                if child is None:
                    break
                node, j = child, j + 1
                if node.value is not None:
                    best_end, best_val = j, node.value
            if best_val is not None:
                out.append(best_val)
                i = best_end
                while i < n and (text[i].isalnum() or text[i] == "_"):
                    i += 1  # skip trailing word chars after a match
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Replaces substrings per a map, longest match first
    (stages/TextPreprocessor.scala:96-)."""

    map = Param("map", "substring -> replacement map", is_complex=True)
    normFunc = Param("normFunc", "identity | lowerCase | upperCase", to_str,
                     one_of(*_NORM_FUNCS), default="identity")

    def __init__(self, map: Optional[Dict[str, str]] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if map is not None:
            self._paramMap["map"] = dict(map)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        mapping = self.get("map") or {}
        norm = _NORM_FUNCS[self.get("normFunc")]
        trie = _Trie()
        for k, v in mapping.items():
            trie.put(k, v, norm)
        col = dataset.col(self.get("inputCol"))
        out = [None if v is None else trie.map_text(v, norm) for v in col]
        return dataset.with_column(self.get("outputCol"),
                                   np.asarray(out, dtype=object))


class EnsembleByKey(Transformer):
    """Aggregates scalar/vector columns grouped by key columns
    (stages/EnsembleByKey.scala:1). ``strategy`` is mean (the only
    reference strategy); ``collapseGroup`` controls one-row-per-key vs.
    joining the aggregate back onto every row."""

    keys = Param("keys", "grouping key columns", to_list(to_str))
    cols = Param("cols", "columns to aggregate", to_list(to_str))
    colNames = Param("colNames", "output column names", to_list(to_str))
    strategy = Param("strategy", "aggregation strategy", to_str,
                     one_of("mean"), default="mean")
    collapseGroup = Param("collapseGroup", "one row per key", to_bool,
                          default=True)
    vectorDims = Param("vectorDims", "expected vector dims (parity)",
                       is_complex=True)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        keys = self.get("keys") or []
        cols = self.get("cols") or []
        if not keys or not cols:
            raise ParamValidationError("EnsembleByKey requires keys and cols")
        names = self.get("colNames") or [f"mean({c})" for c in cols]
        if len(names) != len(cols):
            raise ParamValidationError("colNames must match cols")

        # build a composite group key
        if len(keys) == 1:
            group_map = dataset.group_indices(keys[0])
        else:
            composite = np.asarray(
                [tuple(dataset.col(k)[i] for k in keys)
                 for i in range(dataset.num_rows)], dtype=object)
            tmp = dataset.with_column("__gkey__", composite)
            group_map = tmp.group_indices("__gkey__")

        group_keys = list(group_map.keys())
        agg: Dict[str, list] = {n: [] for n in names}
        for gk in group_keys:
            idx = group_map[gk]
            for c, n in zip(cols, names):
                arr = dataset.col(c)
                agg[n].append(np.asarray(arr[idx]).mean(axis=0))

        if self.get("collapseGroup"):
            out_cols: Dict[str, Any] = {}
            for j, k in enumerate(keys):
                if len(keys) == 1:
                    out_cols[k] = np.asarray(group_keys)
                else:
                    out_cols[k] = np.asarray([gk[j] for gk in group_keys])
            for n in names:
                vals = agg[n]
                out_cols[n] = (np.stack(vals)
                               if np.asarray(vals[0]).ndim else np.asarray(vals))
            key_meta = {k: dataset.metadata(k) for k in keys
                        if dataset.metadata(k)}
            return DataFrame(out_cols, key_meta)

        index_of = {gk: i for i, gk in enumerate(group_keys)}
        if len(keys) == 1:
            row_groups = [index_of[v] for v in dataset.col(keys[0]).tolist()]
        else:
            row_groups = [index_of[tuple(dataset.col(k)[i] for k in keys)]
                          for i in range(dataset.num_rows)]
        df = dataset
        for n in names:
            vals = agg[n]
            stacked = (np.stack(vals)
                       if np.asarray(vals[0]).ndim else np.asarray(vals))
            df = df.with_column(n, stacked[np.asarray(row_groups)])
        return df
