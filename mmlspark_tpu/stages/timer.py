"""Timer stage: wraps another stage and records wall-clock timing.

Parity: stages/Timer.scala — an Estimator whose fit times the inner
stage's fit (and optionally its transform), logging through the
framework's structured telemetry (core/timer.py StopWatch).
"""

from __future__ import annotations

from typing import Any, Optional

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logging_utils import logger
from mmlspark_tpu.core.param import Param, to_bool
from mmlspark_tpu.core.pipeline import Estimator, Model, PipelineStage, Transformer
from mmlspark_tpu.core.timer import StopWatch


class Timer(Estimator):
    stage = Param("stage", "the stage to time", is_complex=True)
    logToScala = Param("logToScala", "log to framework logger (vs print)",
                       to_bool, default=True)
    disableMaterialization = Param(
        "disableMaterialization",
        "whether to skip materializing the output before stopping the clock",
        to_bool, default=True)

    def __init__(self, stage: Optional[PipelineStage] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if stage is not None:
            self._paramMap["stage"] = stage

    def _log(self, message: str) -> None:
        if self.get("logToScala"):
            logger.info(message)
        else:
            print(message)

    def _fit(self, dataset: DataFrame) -> "TimerModel":
        inner = self.get("stage")
        watch = StopWatch()
        if isinstance(inner, Estimator):
            with watch.measure():
                fitted = inner.fit(dataset)
            self._log(f"{type(inner).__name__}.fit took {watch.elapsed:.4f}s")
        else:
            fitted = inner
        model = TimerModel(stage=self)
        model.fitted_stage = fitted
        return model


class TimerModel(Model):
    stage = Param("stage", "the owning Timer", is_complex=True)
    fittedStage = Param("fittedStage", "the fitted inner stage",
                        is_complex=True)

    def __init__(self, stage: Optional[Timer] = None, **kwargs: Any):
        super().__init__(**kwargs)
        if stage is not None:
            self._paramMap["stage"] = stage

    @property
    def fitted_stage(self) -> Transformer:
        return self.get("fittedStage")

    @fitted_stage.setter
    def fitted_stage(self, value: Transformer) -> None:
        self._paramMap["fittedStage"] = value

    def _transform(self, dataset: DataFrame) -> DataFrame:
        timer: Timer = self.get("stage")
        watch = StopWatch()
        with watch.measure():
            out = self.fitted_stage.transform(dataset)
        msg = (f"{type(self.fitted_stage).__name__}.transform took "
               f"{watch.elapsed:.4f}s")
        if timer is not None:
            timer._log(msg)
        else:
            logger.info(msg)
        return out
