"""AutoML-lite train/eval: wrap any learner + metrics computation.

Parity surface: the reference's ``train`` package
(core/src/main/scala/.../train/TrainClassifier.scala:52,
TrainRegressor.scala:1, ComputeModelStatistics.scala:58,
ComputePerInstanceStatistics.scala:1).
"""

from mmlspark_tpu.train.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    MetricConstants,
)
from mmlspark_tpu.train.trainers import (
    TrainClassifier,
    TrainedClassifierModel,
    TrainedRegressorModel,
    TrainRegressor,
)

__all__ = [
    "TrainClassifier", "TrainRegressor",
    "TrainedClassifierModel", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "MetricConstants",
]
