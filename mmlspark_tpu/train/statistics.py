"""Model-quality metrics as pipeline stages.

Parity: ``ComputeModelStatistics`` (reference
core/src/main/scala/.../train/ComputeModelStatistics.scala:58) computes
classification metrics (accuracy/precision/recall/AUC + confusion
matrix) or regression metrics (mse/rmse/r2/mae) from a scored
DataFrame; ``ComputePerInstanceStatistics`` (ComputePerInstanceStatistics.scala:1)
emits per-row losses. Metric names follow the reference's
``MetricConstants`` (core/metrics/MetricConstants.scala:7-40).

TPU-first: the reductions are jit-compiled jnp; the confusion matrix is
a one-hot matmul (MXU-friendly) rather than a per-row loop.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasLabelCol, Param, one_of, to_str,
)
from mmlspark_tpu.core.pipeline import Transformer


class MetricConstants:
    # regression
    Mse = "mse"
    Rmse = "rmse"
    R2 = "r2"
    Mae = "mae"
    RegressionMetricsName = "regression"
    RegressionMetrics = {Mse, Rmse, R2, Mae, RegressionMetricsName}
    # classification
    Accuracy = "accuracy"
    Precision = "precision"
    Recall = "recall"
    Auc = "AUC"
    ClassificationMetricsName = "classification"
    ClassificationMetrics = {Accuracy, Precision, Recall, Auc,
                             ClassificationMetricsName}
    AllSparkMetrics = "all"
    ConfusionMatrix = "confusion_matrix"
    EvaluationType = "evaluation_type"


def _classification_metrics(labels: np.ndarray, preds: np.ndarray,
                            scores: Optional[np.ndarray]) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models.gbdt.metrics import auc as auc_metric

    classes = np.unique(np.concatenate([labels, preds]))
    k = int(classes.max()) + 1 if len(classes) else 1
    k = max(k, 2)

    @jax.jit
    def stats(y, p):
        oh_y = jax.nn.one_hot(y.astype(jnp.int32), k)
        oh_p = jax.nn.one_hot(p.astype(jnp.int32), k)
        # confusion[i, j] = #(label==i, pred==j): one matmul on the MXU
        confusion = oh_y.T @ oh_p
        correct = jnp.trace(confusion)
        total = jnp.sum(confusion)
        accuracy = correct / jnp.maximum(total, 1.0)
        tp = jnp.diag(confusion)
        per_class_prec = tp / jnp.maximum(jnp.sum(confusion, axis=0), 1.0)
        per_class_rec = tp / jnp.maximum(jnp.sum(confusion, axis=1), 1.0)
        return confusion, accuracy, per_class_prec, per_class_rec

    confusion, accuracy, prec_c, rec_c = stats(jnp.asarray(labels), jnp.asarray(preds))
    confusion = np.asarray(confusion)
    out: Dict[str, Any] = {
        MetricConstants.Accuracy: float(accuracy),
        MetricConstants.ConfusionMatrix: confusion,
    }
    if k == 2:
        # binary: precision/recall on the positive class (reference uses
        # Spark MulticlassMetrics.precision(1.0)/recall(1.0) semantics)
        out[MetricConstants.Precision] = float(prec_c[1])
        out[MetricConstants.Recall] = float(rec_c[1])
        if scores is not None:
            import jax.numpy as jnp
            out[MetricConstants.Auc] = float(
                auc_metric(jnp.asarray(scores), jnp.asarray(labels)))
    else:
        # multiclass: micro-averaged (== accuracy) + macro averages, as the
        # reference's addAllClassificationMetrics does
        # (ComputeModelStatistics.scala:234-247)
        out[MetricConstants.Precision] = float(accuracy)
        out[MetricConstants.Recall] = float(accuracy)
        present = np.isin(np.arange(k), classes.astype(int))
        out["average_accuracy"] = float(accuracy)
        out["macro_averaged_precision"] = float(np.mean(np.asarray(prec_c)[present]))
        out["macro_averaged_recall"] = float(np.mean(np.asarray(rec_c)[present]))
    return out


def _regression_metrics(labels: np.ndarray, preds: np.ndarray) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    @jax.jit
    def stats(y, p):
        err = p - y
        mse = jnp.mean(err ** 2)
        mae = jnp.mean(jnp.abs(err))
        var = jnp.mean((y - jnp.mean(y)) ** 2)
        r2 = 1.0 - mse / jnp.maximum(var, 1e-30)
        return mse, jnp.sqrt(mse), r2, mae

    mse, rmse, r2, mae = stats(jnp.asarray(labels), jnp.asarray(preds))
    return {MetricConstants.Mse: float(mse), MetricConstants.Rmse: float(rmse),
            MetricConstants.R2: float(r2), MetricConstants.Mae: float(mae)}


class ComputeModelStatistics(Transformer, HasLabelCol):
    """Compute classification/regression metrics from a scored DataFrame.

    Returns a one-row DataFrame of metric columns, mirroring the
    reference transform (ComputeModelStatistics.scala:75-166).
    """

    evaluationMetric = Param(
        "evaluationMetric", "metric to compute: all|classification|regression"
        "|accuracy|precision|recall|AUC|mse|rmse|r2|mae", to_str,
        one_of("all", "classification", "regression", "accuracy", "precision",
               "recall", "AUC", "mse", "rmse", "r2", "mae"),
        default="all")
    scoresCol = Param("scoresCol", "raw score / probability column for AUC",
                      to_str)
    scoredLabelsCol = Param("scoredLabelsCol", "predicted-label column", to_str,
                            default="prediction")

    def _infer_kind(self, labels: np.ndarray) -> str:
        metric = self.get("evaluationMetric")
        if metric in MetricConstants.RegressionMetrics and \
                metric != MetricConstants.AllSparkMetrics:
            return "regression"
        if metric in MetricConstants.ClassificationMetrics:
            return "classification"
        # "all": infer from the label column the way the reference infers
        # from schema categorical metadata — integer-valued small-cardinality
        # labels are classification
        as_int = labels.astype(np.int64, copy=False) if labels.dtype.kind in "iu" \
            else None
        if labels.dtype.kind in "iu":
            return "classification"
        if labels.dtype.kind == "f" and np.all(labels == np.round(labels)) \
                and len(np.unique(labels)) <= 100:
            return "classification"
        del as_int
        return "regression"

    def _transform(self, dataset: DataFrame) -> DataFrame:
        labels = np.asarray(dataset.col(self.get("labelCol")), dtype=np.float64)
        preds = np.asarray(dataset.col(self.get("scoredLabelsCol")),
                           dtype=np.float64)
        kind = self._infer_kind(np.asarray(dataset.col(self.get("labelCol"))))
        if kind == "regression":
            metrics: Dict[str, Any] = _regression_metrics(labels, preds)
        else:
            scores = None
            sc = self.get("scoresCol")
            if sc and sc in dataset:
                s = dataset.col(sc)
                scores = np.asarray(s[:, -1] if s.ndim == 2 else s,
                                    dtype=np.float64)
            metrics = _classification_metrics(labels, preds, scores)
            metrics[MetricConstants.EvaluationType] = "Classification"
        want = self.get("evaluationMetric")
        if want not in (MetricConstants.AllSparkMetrics,
                        MetricConstants.ClassificationMetricsName,
                        MetricConstants.RegressionMetricsName):
            keep = {want, MetricConstants.ConfusionMatrix,
                    MetricConstants.EvaluationType}
            metrics = {k: v for k, v in metrics.items() if k in keep}
        cols = {}
        for k, v in metrics.items():
            if isinstance(v, np.ndarray):
                cell = np.empty(1, dtype=object)
                cell[0] = v
                cols[k] = cell
            else:
                cols[k] = np.asarray([v])
        return DataFrame(cols)


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Per-row loss columns (L1/L2 for regression, log-loss for
    classification), parity with ComputePerInstanceStatistics.scala:1."""

    scoresCol = Param("scoresCol", "probability/score column", to_str)
    scoredLabelsCol = Param("scoredLabelsCol", "predicted-label column", to_str,
                            default="prediction")

    def _transform(self, dataset: DataFrame) -> DataFrame:
        labels = np.asarray(dataset.col(self.get("labelCol")), dtype=np.float64)
        preds = np.asarray(dataset.col(self.get("scoredLabelsCol")),
                           dtype=np.float64)
        sc = self.get("scoresCol")
        if sc and sc in dataset:
            probs = dataset.col(sc)
            if probs.ndim == 2:
                idx = labels.astype(np.int64)
                idx = np.clip(idx, 0, probs.shape[1] - 1)
                p = probs[np.arange(len(labels)), idx]
            else:
                p = np.where(labels > 0, probs, 1.0 - probs)
            logloss = -np.log(np.clip(p.astype(np.float64), 1e-15, 1.0))
            return dataset.with_column("log_loss", logloss)
        err = preds - labels
        return dataset.with_columns({"L1_loss": np.abs(err),
                                     "L2_loss": err ** 2})
