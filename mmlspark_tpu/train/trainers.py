"""TrainClassifier / TrainRegressor: auto-featurizing learner wrappers.

Parity: reference ``TrainClassifier`` (train/TrainClassifier.scala:52)
and ``TrainRegressor`` (train/TrainRegressor.scala:1) — featurize all
non-label columns into one vector column, optionally reindex the label,
fit the inner learner, and return a model that scores + maps indexed
labels back (``TrainedClassifierModel.transform``).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.param import (
    HasFeaturesCol, HasLabelCol, Param, to_bool, to_int, to_list, to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.featurize.featurize import Featurize
from mmlspark_tpu.featurize.indexer import ValueIndexer


class _AutoTrainer(Estimator, HasFeaturesCol, HasLabelCol):
    """Shared base of TrainClassifier/TrainRegressor (AutoTrainer.scala:1)."""

    model = Param("model", "inner learner to run", is_complex=True)
    numFeatures = Param("numFeatures", "number of hashed features (0 = no "
                        "hashing)", to_int, default=0)

    def _featurize(self, dataset: DataFrame, feature_cols: List[str]) -> Transformer:
        feat = Featurize(inputCols=feature_cols,
                         outputCol=self.get("featuresCol"),
                         numFeatures=self.get("numFeatures") or None)
        return feat.fit(dataset)

    def _feature_columns(self, dataset: DataFrame) -> List[str]:
        label = self.get("labelCol")
        return [c for c in dataset.columns if c != label]


class TrainClassifier(_AutoTrainer):
    """Featurize + (optionally) reindex labels + fit a classifier.

    reindexLabel/labels interaction follows the reference contract
    (TrainClassifier.scala:24-41).
    """

    reindexLabel = Param("reindexLabel", "re-index the label column", to_bool,
                         default=True)
    labels = Param("labels", "sorted label values for the label column",
                   to_list(to_str))

    def _fit(self, dataset: DataFrame) -> "TrainedClassifierModel":
        label_col = self.get("labelCol")
        levels: Optional[List[Any]] = None
        df = dataset

        labels_arr = df.col(label_col)
        # drop rows with missing labels (convertLabel parity)
        if labels_arr.dtype.kind == "f":
            keep = ~np.isnan(labels_arr)
            if not keep.all():
                df = df.filter(keep)
                labels_arr = df.col(label_col)

        if self.is_set("labels"):
            levels = list(self.get("labels"))
            lookup = {v: i for i, v in enumerate(levels)}
            idx = np.asarray([lookup[str(v)] for v in labels_arr], np.float64)
            df = df.with_column(label_col, idx)
        elif self.get("reindexLabel"):
            indexer = ValueIndexer(inputCol=label_col, outputCol=label_col)
            model = indexer.fit(df)
            levels = list(model.levels)
            df = model.transform(df)

        feature_cols = self._feature_columns(dataset)
        feat_model = self._featurize(df, feature_cols)
        featurized = feat_model.transform(df)

        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
            inner = LightGBMClassifier()
        inner = inner.copy(featuresCol=self.get("featuresCol"),
                           labelCol=label_col)
        fitted = inner.fit(featurized)
        return TrainedClassifierModel(
            featuresCol=self.get("featuresCol"), labelCol=label_col,
            )._init_state(feat_model, fitted, levels)


class TrainRegressor(_AutoTrainer):
    def _fit(self, dataset: DataFrame) -> "TrainedRegressorModel":
        label_col = self.get("labelCol")
        df = dataset
        labels_arr = df.col(label_col)
        if labels_arr.dtype.kind == "f":
            keep = ~np.isnan(labels_arr)
            if not keep.all():
                df = df.filter(keep)

        feature_cols = self._feature_columns(dataset)
        feat_model = self._featurize(df, feature_cols)
        featurized = feat_model.transform(df)

        inner = self.get("model")
        if inner is None:
            from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor
            inner = LightGBMRegressor()
        inner = inner.copy(featuresCol=self.get("featuresCol"),
                           labelCol=label_col)
        fitted = inner.fit(featurized)
        return TrainedRegressorModel(
            featuresCol=self.get("featuresCol"), labelCol=label_col,
            )._init_state(feat_model, fitted)


class _TrainedBase(Model, HasFeaturesCol, HasLabelCol):
    featurizer = Param("featurizer", "fitted featurization model",
                       is_complex=True)
    innerModel = Param("innerModel", "fitted inner model", is_complex=True)

    def _transform(self, dataset: DataFrame) -> DataFrame:
        featurized = self.get("featurizer").transform(dataset)
        return self.get("innerModel").transform(featurized)


class TrainedClassifierModel(_TrainedBase):
    levels = Param("levels", "original label values, index order",
                   is_complex=True)

    def _init_state(self, featurizer, inner, levels):
        self._set(featurizer=featurizer, innerModel=inner, levels=levels)
        return self

    def _transform(self, dataset: DataFrame) -> DataFrame:
        scored = super()._transform(dataset)
        levels = self.get("levels")
        if levels is not None:
            pred_col = self.get("innerModel").get("predictionCol")
            idx = np.asarray(scored.col(pred_col)).astype(np.int64)
            idx = np.clip(idx, 0, len(levels) - 1)
            mapped = np.asarray([levels[i] for i in idx])
            scored = scored.with_column("scored_labels", mapped)
        return scored


class TrainedRegressorModel(_TrainedBase):
    def _init_state(self, featurizer, inner):
        self._set(featurizer=featurizer, innerModel=inner)
        return self
