// Native data plane: CSV/libsvm ingest, murmur3 feature hashing, and
// quantile binning.
//
// Role parity: the reference's hot data paths live in native engines —
// LightGBM's Dataset construction/binning (lightgbmlib LGBM_Dataset*),
// VW's murmur feature hashing (vw-jni), and the row marshaling loops
// (StreamingPartitionTask.scala:203-277). Here the same stages run as a
// multithreaded C++ library feeding numpy buffers that go straight to
// the TPU via jnp.asarray; Python fallbacks exist for environments
// without a compiler (mmlspark_tpu/native/__init__.py).
//
// Exposed via a plain C ABI for ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

int hardware_threads() {
  unsigned n = std::thread::hardware_concurrency();
  return n ? static_cast<int>(n) : 4;
}

// parallel-for over [0, n) in contiguous chunks
template <typename F>
void parallel_chunks(int64_t n, F&& fn) {
  int workers = std::min<int64_t>(hardware_threads(), std::max<int64_t>(n, 1));
  std::vector<std::thread> threads;
  int64_t chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int64_t lo = w * chunk;
    int64_t hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back([lo, hi, &fn] { fn(lo, hi); });
  }
  for (auto& t : threads) t.join();
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// murmur3_32 (public algorithm; VW-compatible hashing of feature names)
// ---------------------------------------------------------------------------
uint32_t mmls_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
  uint32_t h = seed;
  const int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; ++i) {
    uint32_t k;
    std::memcpy(&k, data + i * 4, 4);
    k *= c1;
    k = (k << 15) | (k >> 17);
    k *= c2;
    h ^= k;
    h = (h << 13) | (h >> 19);
    h = h * 5 + 0xe6546b64u;
  }
  uint32_t k = 0;
  const uint8_t* tail = data + nblocks * 4;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8; [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= c1;
      k = (k << 15) | (k >> 17);
      k *= c2;
      h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

// hash a batch of NUL-separated strings; offsets[i] is the byte offset of
// string i in `blob`, offsets[n] the total length
void mmls_murmur3_batch(const uint8_t* blob, const int64_t* offsets,
                        int64_t n, uint32_t seed, uint32_t* out) {
  parallel_chunks(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      out[i] = mmls_murmur3_32(blob + offsets[i],
                               offsets[i + 1] - offsets[i], seed);
    }
  });
}

// Branchless lower_bound (first index with u[i] >= v): the classic
// halving form where the compiler turns the select into cmov, removing
// the 8 unpredictable branches per lookup that dominate binning time on
// random data (measured ~60ns/element with std::lower_bound on one
// core; ~2x faster branchless).
static inline int32_t bin_lower_bound(const double* u, int32_t n,
                                      double v) {
  if (n <= 0) return 0;
  const double* base = u;
  int32_t len = n;
  while (len > 1) {
    int32_t half = len >> 1;
    base = (base[half] < v) ? base + half : base;
    len -= half;
  }
  return static_cast<int32_t>(base - u) + (*base < v ? 1 : 0);
}

// ---------------------------------------------------------------------------
// quantile binning: values -> bin ids via upper-edge binary search
// (the reference's LGBM_DatasetCreateFromSampledColumn bin mapping role)
// ---------------------------------------------------------------------------
void mmls_bin_column(const double* vals, int64_t n, const double* uppers,
                     int32_t n_bins, int32_t* out) {
  parallel_chunks(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      double v = vals[i];
      int32_t b = bin_lower_bound(uppers, n_bins, v);
      out[i] = std::min(b, n_bins - 1);
    }
  });
}

// bin a whole (n, f) column-major-agnostic matrix: vals row-major,
// uppers (f, n_bins) row-major
void mmls_bin_matrix(const double* vals, int64_t n, int64_t f,
                     const double* uppers, int32_t n_bins, int32_t* out) {
  parallel_chunks(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      for (int64_t j = 0; j < f; ++j) {
        double v = vals[i * f + j];
        const double* u = uppers + j * n_bins;
        int32_t b = bin_lower_bound(u, n_bins, v);
        out[i * f + j] = std::min(b, n_bins - 1);
      }
    }
  });
}

// ---------------------------------------------------------------------------
// CSV ingest (double matrix). Two-pass: size, then parallel parse by
// line index. Returns 0 on success.
// ---------------------------------------------------------------------------
int mmls_csv_dims(const char* path, int skip_header, int64_t* n_rows,
                  int64_t* n_cols) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return 1;
  std::fseek(fp, 0, SEEK_END);
  long size = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(size);
  if (size && std::fread(buf.data(), 1, size, fp) != (size_t)size) {
    std::fclose(fp);
    return 2;
  }
  std::fclose(fp);
  int64_t rows = 0, cols = 1;
  bool counted_cols = false;
  bool in_first_data_line = true;
  int skipped = 0;
  for (long i = 0; i < size; ++i) {
    if (skipped < skip_header) {
      if (buf[i] == '\n') ++skipped;
      continue;
    }
    if (!counted_cols && buf[i] == ',') ++cols;
    if (buf[i] == '\n') {
      counted_cols = true;
      ++rows;
    }
  }
  if (size > 0 && buf[size - 1] != '\n' && skipped >= skip_header) ++rows;
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

int mmls_csv_parse(const char* path, int skip_header, double* out,
                   int64_t n_rows, int64_t n_cols) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return 1;
  std::fseek(fp, 0, SEEK_END);
  long size = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(size + 1);
  if (size && std::fread(buf.data(), 1, size, fp) != (size_t)size) {
    std::fclose(fp);
    return 2;
  }
  std::fclose(fp);
  buf[size] = '\0';

  // index line starts
  std::vector<const char*> lines;
  lines.reserve(n_rows);
  const char* p = buf.data();
  const char* end = buf.data() + size;
  int skipped = 0;
  while (p < end && skipped < skip_header) {
    if (*p == '\n') ++skipped;
    ++p;
  }
  while (p < end && static_cast<int64_t>(lines.size()) < n_rows) {
    lines.push_back(p);
    while (p < end && *p != '\n') ++p;
    ++p;
  }
  if (static_cast<int64_t>(lines.size()) != n_rows) return 3;

  std::atomic<int> err{0};
  parallel_chunks(n_rows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* q = lines[r];
      for (int64_t c = 0; c < n_cols; ++c) {
        char* next = nullptr;
        out[r * n_cols + c] = std::strtod(q, &next);
        if (next == q && !(*q == ',' || *q == '\n')) {
          err.store(4);
        }
        q = next;
        while (*q == ',' || *q == ' ') ++q;
      }
    }
  });
  return err.load();
}

// ---------------------------------------------------------------------------
// libsvm ingest -> dense matrix ("label idx:val idx:val ...")
// ---------------------------------------------------------------------------
int mmls_libsvm_parse(const char* path, double* x, double* y,
                      int64_t n_rows, int64_t n_cols) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return 1;
  std::fseek(fp, 0, SEEK_END);
  long size = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(size + 1);
  if (size && std::fread(buf.data(), 1, size, fp) != (size_t)size) {
    std::fclose(fp);
    return 2;
  }
  std::fclose(fp);
  buf[size] = '\0';

  std::vector<const char*> lines;
  lines.reserve(n_rows);
  const char* p = buf.data();
  const char* end = buf.data() + size;
  while (p < end && static_cast<int64_t>(lines.size()) < n_rows) {
    lines.push_back(p);
    while (p < end && *p != '\n') ++p;
    ++p;
  }
  if (static_cast<int64_t>(lines.size()) != n_rows) return 3;

  std::memset(x, 0, sizeof(double) * n_rows * n_cols);
  std::atomic<int> err{0};
  parallel_chunks(n_rows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const char* q = lines[r];
      char* next = nullptr;
      y[r] = std::strtod(q, &next);
      q = next;
      while (*q && *q != '\n') {
        while (*q == ' ') ++q;
        if (*q == '\n' || *q == '\0') break;
        long idx = std::strtol(q, &next, 10);
        if (*next != ':') {
          err.store(4);
          break;
        }
        q = next + 1;
        double val = std::strtod(q, &next);
        q = next;
        if (idx >= 1 && idx <= n_cols) x[r * n_cols + (idx - 1)] = val;
      }
    }
  });
  return err.load();
}

}  // extern "C"

// ---------------------------------------------------------------------------
// GBDT per-level histogram (the flagship hot op; the CPU twin of
// hist_pallas.py's VMEM restructuring, applied to the cache hierarchy).
//
// (N, F) bin ids + per-row grad/hess/live + per-row node id ->
// (width, F, B, 3) grad/hess/count sums. Layout matches
// trainer._level_histogram exactly, so the ctypes caller returns the
// buffer straight into a jax.pure_callback.
//
// Structure (Booster accelerator paper, arxiv 2011.02022: the pass is
// bandwidth-bound and wins come from keeping the accumulation window
// cache-resident):
//   - each worker thread owns a private (width, F, B, 4) float tile
//     (4th lane pads the grad/hess/count triple to one 16-byte vector
//     so the inner update is a single SIMD add), merged into the
//     3-channel output ONCE per level in fixed worker order — the
//     merge order is deterministic, so a given thread count reproduces
//     bit-identical float sums;
//   - while the tile stays cache-resident rows are accumulated
//     directly in one pass; once the tile outgrows the budget
//     (width x F x B x 16B beyond ~4 MiB) each worker first
//     counting-sorts its row chunk by tree node into node-pure
//     segments (a stable 1-pass bucket scatter of the bin rows plus
//     the packed update vector), then accumulates segment by segment —
//     the active tile slice is one node's (F, B, 4) block (~100 KiB at
//     bench shape) regardless of level width. Both paths add into a
//     given (node, feature, bin) cell in ascending row order, so they
//     produce bit-identical sums and the crossover is purely a speed
//     knob (at 2M x 28 x 255 the direct pass wins through width 32 —
//     76 ms vs 87 ms sorted — because the random-bin scatter already
//     misses L2 either way and the sort staging is pure overhead; the
//     sorted pass only pays off once the tile spills last-level cache);
//   - the quantized variants (mmls_level_hist_q16_* / _q8_*) take
//     int16/int8 grad+hess with a shared power-of-two scale and a
//     uint8 0/1 live gate, accumulate into per-worker int32 SIMD tiles,
//     and periodically fold the tile into exact int64 accumulators
//     (every 2^16 live rows for int16, 2^24 for int8 — chosen so a
//     single cell can never reach INT32_MAX between folds). The merge
//     multiplies the exact int64 sums by the inverse scales in double
//     and rounds to f32 once, so the result is bit-identical to an
//     int64 bincount reference regardless of worker count or path;
//   - live == 0 rows are skipped before their bin row is touched
//     (direct path) or dropped at partition time (sorted path), which
//     is what makes the histogram-subtraction trick cheap here: the
//     trainer masks the larger sibling's rows instead of compacting
//     them (no gather materialization on the host path).
// ---------------------------------------------------------------------------

typedef float v4sf __attribute__((vector_size(16)));
typedef int32_t v4si __attribute__((vector_size(16)));

namespace {

// direct-path crossover: above this tile size the node-partitioned
// pass wins. Measured at 2M x 28 x 255 rows on one core: direct beats
// sorted at every level width up to 32 (3.6 MiB tile), so the budget
// sits above that; sorted only helps once the tile spills LLC.
constexpr int64_t kHistL2Budget = 1 << 22;

template <typename BinT>
void level_hist_chunk_direct(const BinT* binned, int64_t lo, int64_t hi,
                             int64_t f, const float* grad,
                             const float* hess, const float* live,
                             const int32_t* local, int32_t n_bins,
                             v4sf* tile) {
  for (int64_t i = lo; i < hi; ++i) {
    const float lv = live[i];
    if (lv == 0.0f) continue;
    const BinT* brow = binned + i * f;
    const v4sf upd = {grad[i] * lv, hess[i] * lv, lv, 0.0f};
    v4sf* nbase = tile + static_cast<int64_t>(local[i]) * f * n_bins;
    for (int64_t j = 0; j < f; ++j) {
      nbase[j * n_bins + static_cast<int64_t>(brow[j])] += upd;
    }
  }
}

template <typename BinT>
void level_hist_chunk_sorted(const BinT* binned, int64_t lo, int64_t hi,
                             int64_t f, const float* grad,
                             const float* hess, const float* live,
                             const int32_t* local, int32_t width,
                             int32_t n_bins, v4sf* tile) {
  const int64_t n = hi - lo;
  // stable counting sort by node; dead rows dropped here. Buffers are
  // thread_local so the steady-state boosting loop reuses the pages
  // instead of re-faulting ~50 MB per level.
  static thread_local std::vector<BinT> bins_buf;
  static thread_local std::vector<v4sf> upd_buf;
  if (static_cast<int64_t>(bins_buf.size()) < n * f) bins_buf.resize(n * f);
  if (static_cast<int64_t>(upd_buf.size()) < n) upd_buf.resize(n);
  std::vector<int64_t> offsets(width + 1, 0);
  for (int64_t i = lo; i < hi; ++i) {
    if (live[i] != 0.0f) ++offsets[local[i] + 1];
  }
  for (int32_t w = 0; w < width; ++w) offsets[w + 1] += offsets[w];
  std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
  for (int64_t i = lo; i < hi; ++i) {
    const float lv = live[i];
    if (lv == 0.0f) continue;
    const int64_t pos = cursor[local[i]]++;
    std::memcpy(bins_buf.data() + pos * f, binned + i * f,
                sizeof(BinT) * f);
    upd_buf[pos] = v4sf{grad[i] * lv, hess[i] * lv, lv, 0.0f};
  }
  for (int32_t w = 0; w < width; ++w) {
    v4sf* nbase = tile + static_cast<int64_t>(w) * f * n_bins;
    for (int64_t p = offsets[w]; p < offsets[w + 1]; ++p) {
      const BinT* brow = bins_buf.data() + p * f;
      const v4sf upd = upd_buf[p];
      for (int64_t j = 0; j < f; ++j) {
        nbase[j * n_bins + static_cast<int64_t>(brow[j])] += upd;
      }
    }
  }
}

template <typename BinT>
void level_hist_typed(const BinT* binned, int64_t n, int64_t f,
                      const float* grad, const float* hess,
                      const float* live, const int32_t* local,
                      int32_t width, int32_t n_bins, float* out) {
  const int64_t cells = static_cast<int64_t>(width) * f * n_bins;
  std::memset(out, 0, sizeof(float) * cells * 3);
  if (n <= 0 || cells <= 0) return;
  // one worker per ~128K rows: below that the private-tile zero/merge
  // costs more than the accumulation it parallelizes
  int workers = static_cast<int>(std::min<int64_t>(
      hardware_threads(), std::max<int64_t>(n / 131072, 1)));
  const bool sorted_path = cells * 16 > kHistL2Budget;

  std::vector<std::vector<v4sf>> tiles(workers);
  std::vector<std::thread> threads;
  int64_t chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int64_t lo = w * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) continue;
    tiles[w].assign(cells, v4sf{0.0f, 0.0f, 0.0f, 0.0f});
    threads.emplace_back([&, w, lo, hi] {
      if (sorted_path) {
        level_hist_chunk_sorted(binned, lo, hi, f, grad, hess, live,
                                local, width, n_bins, tiles[w].data());
      } else {
        level_hist_chunk_direct(binned, lo, hi, f, grad, hess, live,
                                local, n_bins, tiles[w].data());
      }
    });
  }
  for (auto& t : threads) t.join();
  for (int w = 0; w < workers; ++w) {
    if (tiles[w].empty()) continue;
    const v4sf* tile = tiles[w].data();
    for (int64_t c = 0; c < cells; ++c) {
      out[c * 3 + 0] += tile[c][0];
      out[c * 3 + 1] += tile[c][1];
      out[c * 3 + 2] += tile[c][2];
    }
  }
}

// --- quantized variants -----------------------------------------------------
//
// grad/hess arrive pre-scaled to int16 (|q| <= 32511) or int8
// (|q| <= 126) by the trainer; live is a 0/1 uint8 gate (the trainer
// keeps live binary — GOSS amplification is folded into grad/hess
// before quantization). Accumulation runs in int32 SIMD tiles folded
// into exact int64 accumulators every kFlushRows live rows, so no cell
// can exceed INT32_MAX between folds: 2^16 * 32511 and 2^24 * 126 both
// stay under 2^31.

inline void hist_q_flush(v4si* tile, int64_t cells, int64_t* gacc,
                         int64_t* hacc, int64_t* cacc) {
  for (int64_t c = 0; c < cells; ++c) {
    gacc[c] += tile[c][0];
    hacc[c] += tile[c][1];
    cacc[c] += tile[c][2];
  }
  std::memset(tile, 0, sizeof(v4si) * cells);
}

template <typename BinT, typename QT>
void level_hist_q_chunk_direct(const BinT* binned, int64_t lo, int64_t hi,
                               int64_t f, const QT* grad_q,
                               const QT* hess_q, const uint8_t* live,
                               const int32_t* local, int32_t n_bins,
                               int64_t flush_rows, int64_t cells,
                               v4si* tile, int64_t* gacc, int64_t* hacc,
                               int64_t* cacc) {
  int64_t since_flush = 0;
  for (int64_t i = lo; i < hi; ++i) {
    if (!live[i]) continue;
    const BinT* brow = binned + i * f;
    const v4si upd = {grad_q[i], hess_q[i], 1, 0};
    v4si* nbase = tile + static_cast<int64_t>(local[i]) * f * n_bins;
    for (int64_t j = 0; j < f; ++j) {
      nbase[j * n_bins + static_cast<int64_t>(brow[j])] += upd;
    }
    if (++since_flush == flush_rows) {
      hist_q_flush(tile, cells, gacc, hacc, cacc);
      since_flush = 0;
    }
  }
  hist_q_flush(tile, cells, gacc, hacc, cacc);
}

template <typename BinT, typename QT>
void level_hist_q_chunk_sorted(const BinT* binned, int64_t lo, int64_t hi,
                               int64_t f, const QT* grad_q,
                               const QT* hess_q, const uint8_t* live,
                               const int32_t* local, int32_t width,
                               int32_t n_bins, int64_t flush_rows,
                               int64_t cells, v4si* tile, int64_t* gacc,
                               int64_t* hacc, int64_t* cacc) {
  const int64_t n = hi - lo;
  static thread_local std::vector<BinT> bins_buf;
  static thread_local std::vector<v4si> upd_q_buf;
  if (static_cast<int64_t>(bins_buf.size()) < n * f) bins_buf.resize(n * f);
  if (static_cast<int64_t>(upd_q_buf.size()) < n) upd_q_buf.resize(n);
  std::vector<int64_t> offsets(width + 1, 0);
  for (int64_t i = lo; i < hi; ++i) {
    if (live[i]) ++offsets[local[i] + 1];
  }
  for (int32_t w = 0; w < width; ++w) offsets[w + 1] += offsets[w];
  std::vector<int64_t> cursor(offsets.begin(), offsets.end() - 1);
  for (int64_t i = lo; i < hi; ++i) {
    if (!live[i]) continue;
    const int64_t pos = cursor[local[i]]++;
    std::memcpy(bins_buf.data() + pos * f, binned + i * f,
                sizeof(BinT) * f);
    upd_q_buf[pos] = v4si{grad_q[i], hess_q[i], 1, 0};
  }
  int64_t since_flush = 0;
  for (int32_t w = 0; w < width; ++w) {
    v4si* nbase = tile + static_cast<int64_t>(w) * f * n_bins;
    for (int64_t p = offsets[w]; p < offsets[w + 1]; ++p) {
      const BinT* brow = bins_buf.data() + p * f;
      const v4si upd = upd_q_buf[p];
      for (int64_t j = 0; j < f; ++j) {
        nbase[j * n_bins + static_cast<int64_t>(brow[j])] += upd;
      }
      if (++since_flush == flush_rows) {
        hist_q_flush(tile, cells, gacc, hacc, cacc);
        since_flush = 0;
      }
    }
  }
  hist_q_flush(tile, cells, gacc, hacc, cacc);
}

template <typename BinT, typename QT>
void level_hist_q_typed(const BinT* binned, int64_t n, int64_t f,
                        const QT* grad_q, const QT* hess_q,
                        const uint8_t* live, const int32_t* local,
                        int32_t width, int32_t n_bins, float gscale_inv,
                        float hscale_inv, float* out) {
  const int64_t cells = static_cast<int64_t>(width) * f * n_bins;
  std::memset(out, 0, sizeof(float) * cells * 3);
  if (n <= 0 || cells <= 0) return;
  const int64_t flush_rows =
      sizeof(QT) == 1 ? (int64_t{1} << 24) : (int64_t{1} << 16);
  int workers = static_cast<int>(std::min<int64_t>(
      hardware_threads(), std::max<int64_t>(n / 131072, 1)));
  const bool sorted_path = cells * 16 > kHistL2Budget;

  std::vector<std::vector<v4si>> tiles(workers);
  std::vector<std::vector<int64_t>> accs(workers);
  std::vector<std::thread> threads;
  int64_t chunk = (n + workers - 1) / workers;
  for (int w = 0; w < workers; ++w) {
    int64_t lo = w * chunk;
    int64_t hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) continue;
    tiles[w].assign(cells, v4si{0, 0, 0, 0});
    accs[w].assign(cells * 3, 0);
    threads.emplace_back([&, w, lo, hi] {
      int64_t* gacc = accs[w].data();
      int64_t* hacc = gacc + cells;
      int64_t* cacc = gacc + 2 * cells;
      if (sorted_path) {
        level_hist_q_chunk_sorted(binned, lo, hi, f, grad_q, hess_q,
                                  live, local, width, n_bins, flush_rows,
                                  cells, tiles[w].data(), gacc, hacc,
                                  cacc);
      } else {
        level_hist_q_chunk_direct(binned, lo, hi, f, grad_q, hess_q,
                                  live, local, n_bins, flush_rows, cells,
                                  tiles[w].data(), gacc, hacc, cacc);
      }
    });
  }
  for (auto& t : threads) t.join();
  // int64 partials sum exactly (|sum| < 2^53 at any realistic n); the
  // power-of-two inverse scales make the double product exact, so the
  // f32 cast below is the single rounding step — bit-identical to an
  // int64 bincount reference for any worker count or path.
  std::vector<int64_t> total(cells * 3, 0);
  for (int w = 0; w < workers; ++w) {
    if (accs[w].empty()) continue;
    const int64_t* acc = accs[w].data();
    for (int64_t c = 0; c < cells * 3; ++c) total[c] += acc[c];
  }
  const double gs = static_cast<double>(gscale_inv);
  const double hs = static_cast<double>(hscale_inv);
  for (int64_t c = 0; c < cells; ++c) {
    out[c * 3 + 0] = static_cast<float>(total[c] * gs);
    out[c * 3 + 1] = static_cast<float>(total[cells + c] * hs);
    out[c * 3 + 2] = static_cast<float>(total[2 * cells + c]);
  }
}

}  // namespace

extern "C" {

void mmls_level_hist_u8(const uint8_t* binned, int64_t n, int64_t f,
                        const float* grad, const float* hess,
                        const float* live, const int32_t* local,
                        int32_t width, int32_t n_bins, float* out) {
  level_hist_typed(binned, n, f, grad, hess, live, local, width, n_bins,
                   out);
}

void mmls_level_hist_i32(const int32_t* binned, int64_t n, int64_t f,
                         const float* grad, const float* hess,
                         const float* live, const int32_t* local,
                         int32_t width, int32_t n_bins, float* out) {
  level_hist_typed(binned, n, f, grad, hess, live, local, width, n_bins,
                   out);
}

void mmls_level_hist_q16_u8(const uint8_t* binned, int64_t n, int64_t f,
                            const int16_t* grad_q, const int16_t* hess_q,
                            const uint8_t* live, const int32_t* local,
                            int32_t width, int32_t n_bins,
                            float gscale_inv, float hscale_inv,
                            float* out) {
  level_hist_q_typed(binned, n, f, grad_q, hess_q, live, local, width,
                     n_bins, gscale_inv, hscale_inv, out);
}

void mmls_level_hist_q16_i32(const int32_t* binned, int64_t n, int64_t f,
                             const int16_t* grad_q, const int16_t* hess_q,
                             const uint8_t* live, const int32_t* local,
                             int32_t width, int32_t n_bins,
                             float gscale_inv, float hscale_inv,
                             float* out) {
  level_hist_q_typed(binned, n, f, grad_q, hess_q, live, local, width,
                     n_bins, gscale_inv, hscale_inv, out);
}

void mmls_level_hist_q8_u8(const uint8_t* binned, int64_t n, int64_t f,
                           const int8_t* grad_q, const int8_t* hess_q,
                           const uint8_t* live, const int32_t* local,
                           int32_t width, int32_t n_bins,
                           float gscale_inv, float hscale_inv,
                           float* out) {
  level_hist_q_typed(binned, n, f, grad_q, hess_q, live, local, width,
                     n_bins, gscale_inv, hscale_inv, out);
}

void mmls_level_hist_q8_i32(const int32_t* binned, int64_t n, int64_t f,
                            const int8_t* grad_q, const int8_t* hess_q,
                            const uint8_t* live, const int32_t* local,
                            int32_t width, int32_t n_bins,
                            float gscale_inv, float hscale_inv,
                            float* out) {
  level_hist_q_typed(binned, n, f, grad_q, hess_q, live, local, width,
                     n_bins, gscale_inv, hscale_inv, out);
}

int64_t mmls_libsvm_dims(const char* path, int64_t* n_rows,
                         int64_t* max_index) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return 1;
  std::fseek(fp, 0, SEEK_END);
  long size = std::ftell(fp);
  std::fseek(fp, 0, SEEK_SET);
  std::vector<char> buf(size + 1);
  if (size && std::fread(buf.data(), 1, size, fp) != (size_t)size) {
    std::fclose(fp);
    return 2;
  }
  std::fclose(fp);
  buf[size] = '\0';
  int64_t rows = 0, maxi = 0;
  const char* p = buf.data();
  const char* end = buf.data() + size;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    if (line_end > p) ++rows;
    const char* q = p;
    while (q < line_end) {
      if (*q == ':') {
        const char* b = q;
        while (b > p && (b[-1] >= '0' && b[-1] <= '9')) --b;
        long idx = std::strtol(b, nullptr, 10);
        if (idx > maxi) maxi = idx;
      }
      ++q;
    }
    p = line_end + 1;
  }
  *n_rows = rows;
  *max_index = maxi;
  return 0;
}

}  // extern "C"
