"""Accuracy-regression harness.

Parity: core test ``Benchmarks`` trait
(core/src/test/scala/.../benchmarks/Benchmarks.scala:15-70): named
metric values are compared against a committed CSV with per-metric
tolerance; on mismatch the observed values are written next to the
expected file as ``new_benchmarks_<name>.csv`` so a human can diff and
promote them.
"""

from __future__ import annotations

import csv
import os
from typing import Dict, List, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_RESOURCES = os.path.join(_HERE, "resources")


class Benchmarks:
    def __init__(self, name: str):
        self.name = name
        self.rows: List[Tuple[str, float, float]] = []  # (key, value, tol)

    def add(self, key: str, value: float, tolerance: float = 1e-6
            ) -> "Benchmarks":
        self.rows.append((key, float(value), float(tolerance)))
        return self

    @property
    def expected_path(self) -> str:
        return os.path.join(_RESOURCES, f"benchmarks_{self.name}.csv")

    @property
    def observed_path(self) -> str:
        return os.path.join(_RESOURCES, f"new_benchmarks_{self.name}.csv")

    def _write(self, path: str) -> None:
        os.makedirs(_RESOURCES, exist_ok=True)
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["key", "value", "tolerance"])
            for key, value, tol in self.rows:
                w.writerow([key, f"{value:.6f}", tol])

    def verify(self) -> None:
        """Compare against the committed CSV; write observed values and
        raise on drift. A missing expected file writes it and fails so
        the author commits it deliberately (Benchmarks.scala semantics)."""
        if not os.path.exists(self.expected_path):
            self._write(self.expected_path)
            raise AssertionError(
                f"no committed benchmark for {self.name}; wrote "
                f"{self.expected_path} — review and commit it")
        expected: Dict[str, Tuple[float, float]] = {}
        with open(self.expected_path, newline="") as f:
            for row in csv.DictReader(f):
                expected[row["key"]] = (float(row["value"]),
                                        float(row["tolerance"]))
        errors = []
        for key, value, _ in self.rows:
            if key not in expected:
                errors.append(f"unexpected new metric {key!r}")
                continue
            want, tol = expected[key]
            if abs(value - want) > tol:
                errors.append(
                    f"{key}: got {value:.6f}, expected {want:.6f} ±{tol}")
        missing = set(expected) - {k for k, _, _ in self.rows}
        errors.extend(f"metric {k!r} not produced" for k in missing)
        if errors:
            self._write(self.observed_path)
            raise AssertionError(
                f"benchmark drift for {self.name} (observed values written "
                f"to {self.observed_path}):\n  " + "\n  ".join(errors))
