"""Accuracy regression vs committed CSVs, patterned on the reference's
benchmarks_VerifyLightGBMClassifier*.csv /
benchmarks_VerifyVowpalWabbitRegressor.csv suites (SURVEY.md §4.3).

Datasets are deterministic synthetics, so the metric values are exact
fingerprints of the training algorithms: any numerical change to
histogram building, split selection, objectives or SGD shows up here.
"""

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import (
    LightGBMClassifier,
    LightGBMRegressor,
)
from mmlspark_tpu.models.vw.learners import VowpalWabbitRegressor
from mmlspark_tpu.train.statistics import ComputeModelStatistics

from .benchmarks import Benchmarks


def _cls_data(n=400, seed=11):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6))
    logit = 1.2 * x[:, 0] - 0.8 * x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (logit + rng.normal(size=n) * 0.4 > 0).astype(np.float64)
    return DataFrame({"features": x, "label": y})


def _reg_data(n=400, seed=13):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6))
    y = 2.0 * x[:, 0] - x[:, 1] + 0.3 * x[:, 2] ** 2 \
        + rng.normal(size=n) * 0.2
    return DataFrame({"features": x, "label": y})


def _auc(model, df) -> float:
    scored = model.transform(df)
    stats = ComputeModelStatistics(
        labelCol="label", scoresCol="probability",
        evaluationMetric="AUC").transform(scored)
    return float(stats.col("AUC")[0])


def _l2(model, df) -> float:
    pred = model.transform(df).col("prediction")
    return float(np.mean((pred - df.col("label")) ** 2))


def test_lightgbm_classifier_benchmarks():
    df = _cls_data()
    bench = Benchmarks("VerifyLightGBMClassifier")
    for boosting in ("gbdt", "rf", "dart", "goss"):
        clf = LightGBMClassifier(numIterations=10, numLeaves=15, maxBin=64,
                                 boostingType=boosting, seed=3,
                                 baggingFraction=0.8, baggingFreq=1)
        bench.add(f"auc_{boosting}", _auc(clf.fit(df), df), tolerance=0.01)
    bench.verify()


# Round-4 note: the regressor CSV was re-pinned after the seed-family
# rework (dedicated bagging/feature-fraction/drop RNG streams) and the
# LightGBM-default weighted DART drop. The l2_dart move (1.03 -> 1.40)
# was verified to be pure RNG-stream reshuffle on this 10-tree/400-row
# fixture: uniform vs weighted drop produce identical values here, and
# changing dropSeed alone swings l2 between 1.05 and 1.40.
def test_lightgbm_regressor_benchmarks():
    df = _reg_data()
    bench = Benchmarks("VerifyLightGBMRegressor")
    for boosting in ("gbdt", "rf", "dart", "goss"):
        reg = LightGBMRegressor(numIterations=10, numLeaves=15, maxBin=64,
                                boostingType=boosting, seed=3,
                                baggingFraction=0.8, baggingFreq=1)
        bench.add(f"l2_{boosting}", _l2(reg.fit(df), df), tolerance=0.05)
    bench.verify()


def test_lightgbm_classifier_real_dataset_benchmarks():
    """Real-dataset accuracy pins (VERDICT r4 weak #7), mirroring the
    reference's benchmarks_VerifyLightGBMClassifierBulkBasic.csv rows
    (BreastTissue etc. — its CSVs pin real-data AUC per boosting type).
    The reference's datasets are CI downloads; sklearn's breast_cancer
    is the in-image stand-in, same family of small real tabular data."""
    from sklearn.datasets import load_breast_cancer

    X, y = load_breast_cancer(return_X_y=True)
    df = DataFrame({"features": X, "label": y.astype(np.float64)})
    bench = Benchmarks("VerifyLightGBMClassifierBreastCancer")
    for boosting in ("gbdt", "rf", "dart", "goss"):
        clf = LightGBMClassifier(numIterations=10, numLeaves=15, maxBin=64,
                                 boostingType=boosting, seed=3,
                                 baggingFraction=0.8, baggingFreq=1)
        bench.add(f"auc_{boosting}", _auc(clf.fit(df), df),
                  tolerance=0.005)
    bench.verify()


def test_lightgbm_regressor_real_dataset_benchmarks():
    """Diabetes L2 per boosting type — the energyefficiency-row analog
    (benchmarks_VerifyLightGBMRegressor*.csv in the reference)."""
    from sklearn.datasets import load_diabetes

    X, y = load_diabetes(return_X_y=True)
    df = DataFrame({"features": X, "label": y.astype(np.float64)})
    base_var = float(np.var(y))
    bench = Benchmarks("VerifyLightGBMRegressorDiabetes")
    for boosting in ("gbdt", "rf", "dart", "goss"):
        reg = LightGBMRegressor(numIterations=10, numLeaves=15, maxBin=64,
                                boostingType=boosting, seed=3,
                                baggingFraction=0.8, baggingFreq=1)
        # pin the variance-normalized L2 so the tolerance is scale-free
        bench.add(f"l2_rel_{boosting}", _l2(reg.fit(df), df) / base_var,
                  tolerance=0.01)
    bench.verify()


def test_vw_regressor_benchmarks():
    df = _reg_data()
    bench = Benchmarks("VerifyVowpalWabbitRegressor")
    base = VowpalWabbitRegressor(numPasses=6, learningRate=0.5, seed=5,
                                 batchSize=16)
    bench.add("l2_default", _l2(base.fit(df), df), tolerance=0.05)
    adaptive = base.copy(adaptive=True)
    bench.add("l2_adaptive", _l2(adaptive.fit(df), df), tolerance=0.05)
    bench.verify()
