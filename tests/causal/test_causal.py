"""causal tests, patterned on the reference's VerifyDoubleMLEstimator /
VerifySyntheticDiffInDiffEstimator suites."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.causal import (
    DiffInDiffEstimator,
    DoubleMLEstimator,
    OrthoForestDMLEstimator,
    ResidualTransformer,
    SyntheticControlEstimator,
    SyntheticDiffInDiffEstimator,
    constrained_least_square,
    mirror_descent,
)
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor


def _dml_data(n=600, effect=2.5, seed=0):
    """Y = effect*T + confounding(X) + noise; T depends on X."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    propensity = 1 / (1 + np.exp(-x[:, 0]))
    t = (rng.random(n) < propensity).astype(np.float64)
    y = effect * t + 2.0 * x[:, 0] + x[:, 1] + rng.normal(size=n) * 0.3
    return DataFrame({"features": x, "treatment": t, "outcome": y})


class TestMirrorDescent:
    def test_simplex_solution(self):
        # b is exactly A @ [0.3, 0.7]
        rng = np.random.default_rng(0)
        a = rng.normal(size=(50, 2))
        w_true = np.asarray([0.3, 0.7])
        b = a @ w_true
        w = mirror_descent(a, b)
        assert w.sum() == pytest.approx(1.0, abs=1e-5)
        assert (w >= 0).all()
        assert np.allclose(w, w_true, atol=0.01)

    def test_constrained_with_intercept(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=(60, 3))
        w_true = np.asarray([0.2, 0.5, 0.3])
        b = a @ w_true + 4.0
        w, c = constrained_least_square(a, b)
        assert np.allclose(w, w_true, atol=0.02)
        assert c == pytest.approx(4.0, abs=0.1)


class TestDoubleML:
    def test_recovers_effect(self):
        df = _dml_data()
        est = DoubleMLEstimator(
            treatmentModel=LightGBMRegressor(numIterations=20, numLeaves=7),
            outcomeModel=LightGBMRegressor(numIterations=20, numLeaves=7),
            maxIter=1)
        model = est.fit(df)
        assert model.get_avg_treatment_effect() == pytest.approx(2.5, abs=0.5)

    def test_bootstrap_ci_brackets_effect(self):
        df = _dml_data(400)
        est = DoubleMLEstimator(
            treatmentModel=LightGBMRegressor(numIterations=10, numLeaves=7),
            outcomeModel=LightGBMRegressor(numIterations=10, numLeaves=7),
            maxIter=6, parallelism=2)
        model = est.fit(df)
        lo, hi = model.get_confidence_interval()
        # generous slop: 6 bootstrap draws + underfit nuisance models bias
        # the small-sample interval
        assert lo - 0.7 < 2.5 < hi + 0.7
        assert lo <= hi
        assert len(model.get("rawTreatmentEffects")) == 6
        assert model.get_pvalue() <= 0.5

    def test_residual_transformer(self):
        df = DataFrame({"obs": np.asarray([1.0, 2.0]),
                        "pred": np.asarray([0.5, 2.5])})
        out = ResidualTransformer(observedCol="obs", predictedCol="pred",
                                  outputCol="res").transform(df)
        assert np.allclose(out.col("res"), [0.5, -0.5])


class TestOrthoForest:
    def test_heterogeneous_effect_direction(self):
        rng = np.random.default_rng(3)
        n = 800
        x = rng.normal(size=(n, 3))
        h = rng.normal(size=(n, 1))  # heterogeneity driver
        tau = np.where(h[:, 0] > 0, 3.0, 1.0)
        t = (rng.random(n) < 1 / (1 + np.exp(-x[:, 0]))).astype(np.float64)
        y = tau * t + x[:, 0] + rng.normal(size=n) * 0.3
        df = DataFrame({"features": x, "heterogeneityVector": h,
                        "treatment": t, "outcome": y})
        est = OrthoForestDMLEstimator(
            treatmentModel=LightGBMRegressor(numIterations=10, numLeaves=7),
            outcomeModel=LightGBMRegressor(numIterations=10, numLeaves=7),
            numTrees=10, maxDepth=3)
        model = est.fit(df)
        out = model.transform(df)
        cate = out.col("EffectAverage")
        hi_group = cate[h[:, 0] > 0.5].mean()
        lo_group = cate[h[:, 0] < -0.5].mean()
        assert hi_group > lo_group + 0.5
        assert (out.col("EffectLowerBound") <= out.col("EffectUpperBound")).all()


class TestDiffInDiff:
    def test_two_by_two(self):
        rng = np.random.default_rng(4)
        n = 2000
        treat = rng.integers(0, 2, n).astype(np.float64)
        post = rng.integers(0, 2, n).astype(np.float64)
        y = 1.0 + 0.5 * treat + 0.8 * post + 2.0 * treat * post \
            + rng.normal(size=n) * 0.2
        df = DataFrame({"treatment": treat, "postTreatment": post,
                        "outcome": y})
        model = DiffInDiffEstimator().fit(df)
        assert model.treatment_effect == pytest.approx(2.0, abs=0.1)
        assert model.standard_error < 0.05

    def _panel(self, effect=3.0, seed=5):
        rng = np.random.default_rng(seed)
        units, times = 12, 10
        unit_fe = rng.normal(size=units)
        time_fe = np.linspace(0, 1, times)
        rows = []
        for u in range(units):
            treated = u < 3
            for t in range(times):
                post = t >= 6
                y = unit_fe[u] + time_fe[t] + rng.normal() * 0.05
                if treated and post:
                    y += effect
                rows.append({"unit": u, "time": t, "outcome": y,
                             "treatment": float(treated),
                             "postTreatment": float(post)})
        return DataFrame.from_rows(rows)

    def test_synthetic_control(self):
        model = SyntheticControlEstimator().fit(self._panel())
        assert model.treatment_effect == pytest.approx(3.0, abs=0.5)
        w = np.asarray(model.summary["unitWeights"])
        assert w.sum() == pytest.approx(1.0, abs=1e-4)

    def test_synthetic_diff_in_diff(self):
        model = SyntheticDiffInDiffEstimator().fit(self._panel())
        assert model.treatment_effect == pytest.approx(3.0, abs=0.4)
        assert "timeWeights" in model.summary
