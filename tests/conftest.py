"""Test configuration: force an 8-device virtual CPU platform.

The reference tests multi-node behavior on a single JVM via ``local[*]``
(SURVEY.md §4.4); the analog here is an 8-device CPU mesh via
``xla_force_host_platform_device_count`` so shard_map/psum paths execute
for real without TPU hardware. Must run before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize imports jax (axon TPU plugin) before conftest
# runs, so the env vars above may be read too late — force via config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from mmlspark_tpu.parallel.mesh import create_mesh
    return create_mesh()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
