"""Test configuration: force an 8-device virtual CPU platform.

The reference tests multi-node behavior on a single JVM via ``local[*]``
(SURVEY.md §4.4); the analog here is an 8-device CPU mesh via
``xla_force_host_platform_device_count`` so shard_map/psum paths execute
for real without TPU hardware. Must run before jax initializes.
"""

from mmlspark_tpu.core.compile_cache import enable_persistent_cache
from mmlspark_tpu.core.virtual_devices import force_cpu_devices

force_cpu_devices(8)
enable_persistent_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from mmlspark_tpu.parallel.mesh import create_mesh
    return create_mesh()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
