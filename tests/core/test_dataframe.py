import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame


def make_df():
    return DataFrame({
        "x": np.arange(10, dtype=np.float32),
        "v": np.arange(20, dtype=np.float64).reshape(10, 2),
        "s": [f"row{i}" for i in range(10)],
    })


def test_schema_and_access():
    df = make_df()
    assert df.num_rows == 10
    assert set(df.columns) == {"x", "v", "s"}
    assert df.schema()["v"].startswith("vector[2")
    assert df["s"][3] == "row3"


def test_mismatched_lengths_raise():
    with pytest.raises(ValueError):
        DataFrame({"a": [1, 2], "b": [1, 2, 3]})


def test_with_column_select_drop_rename():
    df = make_df()
    df2 = df.with_column("y", df["x"] * 2)
    assert np.allclose(df2["y"], df["x"] * 2)
    assert "y" not in df.columns  # original untouched
    assert df2.select("x", "y").columns == ["x", "y"]
    assert "x" not in df2.drop("x").columns
    assert "z" in df2.rename({"y": "z"}).columns


def test_filter_sort_sample():
    df = make_df()
    f = df.filter(df["x"] > 4)
    assert f.num_rows == 5 and f["s"][0] == "row5"
    srt = df.sort("x", ascending=False)
    assert srt["x"][0] == 9
    assert 0 < df.sample(0.5, seed=1).num_rows < 10


def test_random_split_partitions_all_rows():
    df = make_df()
    parts = df.random_split([0.5, 0.3, 0.2], seed=7)
    assert sum(p.num_rows for p in parts) == 10
    all_s = sorted(s for p in parts for s in p["s"])
    assert all_s == sorted(df["s"])


def test_concat_and_group_indices():
    df = make_df()
    both = DataFrame.concat([df, df])
    assert both.num_rows == 20
    g = DataFrame({"k": [1, 2, 1, 2, 1], "v": [1., 2., 3., 4., 5.]})
    groups = g.group_indices("k")
    assert np.allclose(g["v"][groups[1]], [1., 3., 5.])


def test_pandas_roundtrip():
    df = make_df()
    back = DataFrame.from_pandas(df.to_pandas())
    assert back.num_rows == 10
    assert np.allclose(back["v"], df["v"])


def test_metadata():
    df = make_df().with_metadata("s", {"categorical": True})
    assert df.metadata("s")["categorical"] is True
    assert df.metadata("x") == {}


def test_to_device_sharded(mesh8):
    df = DataFrame({"x": np.arange(13, dtype=np.float32)})
    arrs, n = df.to_device(["x"], mesh=mesh8)
    assert n == 13
    assert arrs["x"].shape[0] % 8 == 0
    assert float(arrs["x"][:13].sum()) == sum(range(13))


def test_concat_empty_list_and_filter_list_mask():
    assert DataFrame.concat([]).num_rows == 0
    df = DataFrame({"x": np.arange(3.0)})
    assert df.filter(lambda d: [True, False, True]).num_rows == 2


def test_with_column_replacement_drops_stale_metadata():
    df = DataFrame({"a": np.arange(3.0)}).with_metadata("a", {"levels": ["x"]})
    replaced = df.with_column("a", np.zeros(3))
    assert replaced.metadata("a") == {}
