"""core/env.py: typed env helpers + the one registry."""

from __future__ import annotations

import warnings

import pytest

from mmlspark_tpu.core import env as env_mod
from mmlspark_tpu.core.env import (REGISTRY, env_flag, env_float,
                                   env_int, env_override, env_raw,
                                   env_str)

VAR = "MMLSPARK_TPU_TEST_ONLY_KNOB"


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv(VAR, raising=False)
    env_mod.reset_warnings()
    yield
    env_mod.reset_warnings()


def test_env_flag_truthy_falsey(monkeypatch):
    assert env_flag(VAR) is False
    assert env_flag(VAR, default=True) is True
    for v in ("1", "true", "YES", " On "):
        monkeypatch.setenv(VAR, v)
        assert env_flag(VAR) is True
        assert env_flag(VAR, default=True) is True
    for v in ("0", "false", "OFF", " no "):
        monkeypatch.setenv(VAR, v)
        assert env_flag(VAR) is False
        assert env_flag(VAR, default=True) is False


def test_env_flag_garbage_warns_once_and_defaults(monkeypatch):
    monkeypatch.setenv(VAR, "maybe")
    with pytest.warns(UserWarning, match=VAR):
        assert env_flag(VAR, default=True) is True
    # second read: warned already, silent
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert env_flag(VAR) is False


def test_env_int(monkeypatch):
    assert env_int(VAR, 7) == 7
    monkeypatch.setenv(VAR, " 42 ")
    assert env_int(VAR, 7) == 42
    monkeypatch.setenv(VAR, "zero?")
    with pytest.warns(UserWarning, match="not an integer"):
        assert env_int(VAR, 7) == 7
    env_mod.reset_warnings()
    monkeypatch.setenv(VAR, "-3")
    with pytest.warns(UserWarning, match="below the minimum"):
        assert env_int(VAR, 7, minimum=1) == 7


def test_env_float(monkeypatch):
    assert env_float(VAR, 0.2) == 0.2
    monkeypatch.setenv(VAR, " 0.35 ")
    assert env_float(VAR, 0.2) == 0.35
    monkeypatch.setenv(VAR, "lots")
    with pytest.warns(UserWarning, match="not a number"):
        assert env_float(VAR, 0.2) == 0.2
    env_mod.reset_warnings()
    monkeypatch.setenv(VAR, "-0.5")
    with pytest.warns(UserWarning, match="below the minimum"):
        assert env_float(VAR, 0.2, minimum=0.0) == 0.2


def test_env_str_and_raw(monkeypatch):
    assert env_str(VAR) is None
    assert env_str(VAR, "d") == "d"
    assert env_raw(VAR) is None
    monkeypatch.setenv(VAR, "  value ")
    assert env_str(VAR) == "  value "        # unstripped by contract
    assert env_raw(VAR) == "  value "


def test_env_override_restores(monkeypatch):
    import os
    monkeypatch.setenv(VAR, "orig")
    with env_override(VAR, "0"):
        assert os.environ[VAR] == "0"
        with env_override(VAR, None):
            assert VAR not in os.environ
        assert os.environ[VAR] == "0"
    assert os.environ[VAR] == "orig"
    monkeypatch.delenv(VAR)
    with env_override(VAR, "x"):
        assert os.environ[VAR] == "x"
    assert VAR not in os.environ


def test_env_override_restores_on_exception():
    import os
    with pytest.raises(RuntimeError):
        with env_override(VAR, "armed"):
            assert os.environ[VAR] == "armed"
            raise RuntimeError("boom")
    assert VAR not in os.environ


def test_registry_shape():
    assert len(REGISTRY) >= 14
    for name, var in REGISTRY.items():
        assert name.startswith("MMLSPARK_TPU_")
        assert var.name == name
        assert var.kind in ("flag", "int", "float", "str")
        assert var.description
    # the 5 knobs PR 3's audit found undocumented must stay declared
    for name in ("MMLSPARK_TPU_COMPILE_CACHE",
                 "MMLSPARK_TPU_FABRIC_ENDPOINT",
                 "MMLSPARK_TPU_FABRIC_TOKEN",
                 "MMLSPARK_TPU_FLASH",
                 "MMLSPARK_TPU_PALLAS_FORCE_COMPILE"):
        assert name in REGISTRY


def test_utils_env_flag_alias(monkeypatch):
    from mmlspark_tpu.core.utils import env_flag as legacy
    monkeypatch.setenv(VAR, "1")
    assert legacy(VAR) is True
    monkeypatch.setenv(VAR, "0")
    assert legacy(VAR) is False
