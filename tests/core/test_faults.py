"""Fault-injection harness + shared retry policy unit tests."""

import time

import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.faults import FaultInjected, fault_point, injected
from mmlspark_tpu.core.logging_utils import (SINK, reset_warn_once,
                                             warn_once)
from mmlspark_tpu.core.retries import (RetryPolicy, backoff_schedule,
                                       with_retries)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class TestFaultPoint:
    def test_disabled_is_passthrough(self):
        assert fault_point("serving.score") is None
        assert fault_point("serving.score", 42) == 42
        # the fast path does not even count hits
        assert faults.hits("serving.score") == 0

    def test_unknown_point_refused(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.arm("no.such.point")
        with pytest.raises(ValueError, match="action must be one of"):
            faults.arm("io.http", "explode")

    def test_raise_on_nth_hit_once(self):
        faults.arm("io.http", "raise", nth=3, count=1)
        fault_point("io.http")
        fault_point("io.http")
        with pytest.raises(FaultInjected):
            fault_point("io.http")
        # count=1: the fault fired, later hits pass through
        fault_point("io.http")
        assert faults.hits("io.http") == 4

    def test_raise_custom_exception(self):
        faults.arm("checkpoint.write", "raise", exc=OSError("disk full"))
        with pytest.raises(OSError, match="disk full"):
            fault_point("checkpoint.write")

    def test_unbounded_count(self):
        faults.arm("io.http", "raise", nth=1, count=None)
        for _ in range(3):
            with pytest.raises(FaultInjected):
                fault_point("io.http")

    def test_delay(self):
        faults.arm("serving.score", "delay", delay_s=0.05)
        t0 = time.perf_counter()
        fault_point("serving.score")
        assert time.perf_counter() - t0 >= 0.05

    def test_corrupt_transforms_value(self):
        faults.arm("gbdt.level_hist", "corrupt",
                   corrupt=lambda v: v * 0, count=None)
        assert fault_point("gbdt.level_hist", 7) == 0
        faults.disarm("gbdt.level_hist")
        assert fault_point("gbdt.level_hist", 7) == 7

    def test_injected_context_disarms_on_error(self):
        with pytest.raises(FaultInjected):
            with injected("io.http", "raise"):
                fault_point("io.http")
        assert fault_point("io.http", "fine") == "fine"

    def test_arm_from_env(self):
        faults.arm_from_env("io.http:raise:2,serving.score:delay:1:0.01")
        fault_point("io.http")  # hit 1 < nth
        with pytest.raises(FaultInjected):
            fault_point("io.http")
        fault_point("serving.score")  # delays 0.01s, no raise

    def test_arm_from_env_rejects_garbage(self):
        with pytest.raises(ValueError, match="MMLSPARK_TPU_FAULTS"):
            faults.arm_from_env("just-a-name")

    def test_registry_reexported_for_fuzzing(self):
        from tests.fuzzing.registry import fault_point_registry
        reg = fault_point_registry()
        assert reg == faults.KNOWN_POINTS
        assert "serving.score" in reg


class TestWithRetries:
    def test_succeeds_after_transient_failures(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("blip")
            return "ok"

        out = with_retries(flaky, policy=RetryPolicy(
            max_attempts=4, base_delay=0.0), sleep=lambda s: None)
        assert out == "ok" and calls["n"] == 3

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def fails():
            calls["n"] += 1
            raise ValueError("bad arg")

        with pytest.raises(ValueError):
            with_retries(fails, should_retry=lambda e: not isinstance(
                e, ValueError), sleep=lambda s: None)
        assert calls["n"] == 1

    def test_exhaustion_raises_last_and_warns_once(self):
        reset_warn_once()
        SINK.drain()

        def always():
            raise ConnectionError("down")

        for _ in range(2):
            with pytest.raises(ConnectionError):
                with_retries(always, policy=RetryPolicy(
                    max_attempts=2, base_delay=0.0),
                    describe="test.exhaust", sleep=lambda s: None)
        degradations = [e for e in SINK.drain()
                        if e.get("event") == "degradation"
                        and "test.exhaust" in e.get("key", "")]
        assert len(degradations) == 1  # once per process, not per call

    def test_backoff_schedule_uses_fixed_delays(self):
        slept = []

        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            with_retries(always, policy=backoff_schedule([0.1, 0.7]),
                         describe="test.sched", sleep=slept.append)
        assert slept == [0.1, 0.7]

    def test_min_delay_override_floors(self):
        slept = []

        def always():
            raise ConnectionError("429ish")

        with pytest.raises(ConnectionError):
            with_retries(always, policy=backoff_schedule([0.01]),
                         min_delay_override=lambda e: 0.5,
                         describe="test.floor", sleep=slept.append)
        assert slept == [0.5]

    def test_deadline_caps_total_wait(self):
        slept = []

        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            with_retries(
                always,
                policy=RetryPolicy(max_attempts=10, base_delay=100.0,
                                   jitter=0.0, deadline=0.0),
                describe="test.deadline", sleep=slept.append)
        assert slept == []  # deadline already spent -> no retries

    def test_exponential_backoff_growth(self):
        slept = []

        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            with_retries(
                always,
                policy=RetryPolicy(max_attempts=4, base_delay=0.1,
                                   multiplier=2.0, jitter=0.0,
                                   max_delay=10.0),
                describe="test.growth", sleep=slept.append)
        assert slept == pytest.approx([0.1, 0.2, 0.4])


class TestWarnOnce:
    def test_emits_once_and_records_telemetry(self):
        reset_warn_once()
        SINK.drain()
        assert warn_once("test.key.abc", "degraded %s", "now")
        assert not warn_once("test.key.abc", "degraded %s", "again")
        events = [e for e in SINK.drain()
                  if e.get("key") == "test.key.abc"]
        assert len(events) == 1
        assert events[0]["event"] == "degradation"
