import pytest

from mmlspark_tpu.core.param import (
    HasInputCol,
    Param,
    ParamValidationError,
    Params,
    ge,
    in_range,
    one_of,
    to_float,
    to_int,
    to_list,
    to_str,
)


class Demo(HasInputCol):
    alpha = Param("alpha", "learning rate", to_float, in_range(0, 1), default=0.1)
    iters = Param("iters", "iterations", to_int, ge(1), default=10)
    mode = Param("mode", "mode", to_str, one_of("a", "b"), default="a")
    names = Param("names", "names", to_list(to_str), default=None)


def test_defaults_and_set():
    d = Demo()
    assert d.get("alpha") == 0.1
    assert d.get("iters") == 10
    d2 = Demo(alpha=0.5, iters=3, names=["x", "y"])
    assert d2.get("alpha") == 0.5
    assert d2.get("names") == ["x", "y"]
    assert not d.is_set("alpha") and d2.is_set("alpha")


def test_validation_errors():
    with pytest.raises(ParamValidationError):
        Demo(alpha=2.0)
    with pytest.raises(ParamValidationError):
        Demo(iters=0)
    with pytest.raises(ParamValidationError):
        Demo(mode="c")
    with pytest.raises(ParamValidationError):
        Demo(alpha="x")


def test_int_converter_rejects_bool():
    with pytest.raises(ParamValidationError):
        Demo(iters=True)


def test_inherited_params_and_copy():
    d = Demo(inputCol="feat")
    assert d.get("inputCol") == "feat"
    c = d.copy(alpha=0.9)
    assert c.get("alpha") == 0.9 and d.get("alpha") == 0.1
    assert c.get("inputCol") == "feat"


def test_unknown_param_raises():
    with pytest.raises(KeyError):
        Demo(bogus=1)


def test_explain_params_mentions_all():
    text = Demo().explain_params()
    for name in ("alpha", "iters", "mode", "inputCol"):
        assert name in text


def test_numpy_scalars_accepted():
    import numpy as np
    d = Demo(alpha=np.float32(0.5), iters=np.int64(3))
    assert d.get("alpha") == 0.5 and d.get("iters") == 3


def test_set_none_clears_and_validates_name():
    d = Demo(alpha=0.7)
    d.set("alpha", None)
    assert d.get("alpha") == 0.1 and not d.is_set("alpha")
    with pytest.raises(KeyError):
        d.set("weigthCol", None)
