import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logging_utils import SINK, scrub
from mmlspark_tpu.core.param import HasInputCol, HasOutputCol, Param, to_float
from mmlspark_tpu.core.pipeline import Estimator, Model, Pipeline, PipelineModel, Transformer


class AddConst(Transformer, HasInputCol, HasOutputCol):
    value = Param("value", "constant to add", to_float, default=1.0)

    def _transform(self, df):
        return df.with_column(self.get("outputCol"),
                              df.col(self.get("inputCol")) + self.get("value"))


class MeanCenter(Estimator, HasInputCol, HasOutputCol):
    def _fit(self, df):
        m = MeanCenterModel(inputCol=self.get("inputCol"),
                            outputCol=self.get("outputCol"))
        m.mean = float(np.mean(df.col(self.get("inputCol"))))
        return m


class MeanCenterModel(Model, HasInputCol, HasOutputCol):
    mean: float = 0.0

    def _get_state(self):
        return {"mean": self.mean}

    def _set_state(self, state):
        self.mean = state["mean"]

    def _transform(self, df):
        return df.with_column(self.get("outputCol"),
                              df.col(self.get("inputCol")) - self.mean)


def test_transformer_and_estimator():
    df = DataFrame({"x": np.array([1.0, 2.0, 3.0])})
    out = AddConst(inputCol="x", outputCol="y", value=2.0).transform(df)
    assert np.allclose(out["y"], [3, 4, 5])
    model = MeanCenter(inputCol="x", outputCol="c").fit(df)
    assert np.allclose(model.transform(df)["c"], [-1, 0, 1])


def test_pipeline_fit_transform():
    df = DataFrame({"x": np.array([1.0, 2.0, 3.0])})
    pipe = Pipeline([
        AddConst(inputCol="x", outputCol="y", value=10.0),
        MeanCenter(inputCol="y", outputCol="z"),
    ])
    pm = pipe.fit(df)
    assert isinstance(pm, PipelineModel)
    assert np.allclose(pm.transform(df)["z"], [-1, 0, 1])


def test_save_load_roundtrip(tmp_path):
    df = DataFrame({"x": np.array([1.0, 2.0, 3.0])})
    pipe = Pipeline([
        AddConst(inputCol="x", outputCol="y", value=10.0),
        MeanCenter(inputCol="y", outputCol="z"),
    ])
    pm = pipe.fit(df)
    expected = pm.transform(df)["z"]
    path = str(tmp_path / "pm")
    pm.save(path)
    loaded = PipelineModel.load(path)
    assert np.allclose(loaded.transform(df)["z"], expected)
    # estimator itself round-trips too
    pipe.save(str(tmp_path / "pipe"))
    pipe2 = Pipeline.load(str(tmp_path / "pipe"))
    assert np.allclose(pipe2.fit(df).transform(df)["z"], expected)


def test_telemetry_records_fit_and_transform():
    SINK.drain()
    df = DataFrame({"x": np.array([1.0, 2.0])})
    MeanCenter(inputCol="x").fit(df).transform(df)
    events = SINK.drain()
    methods = [e["method"] for e in events]
    assert "fit" in methods and "transform" in methods
    assert all("seconds" in e for e in events)


def test_scrubber():
    assert "REDACTED" in scrub("https://h/?sig=abc123&x=1")
    assert "hello" in scrub("hello")
