"""graftsan unit tests: the zero-overhead-when-disabled contract, the
NaN/Inf boundary guard, the collective-sequence recorder/cross-check,
and the recompilation budget (ISSUE: SPMD correctness suite)."""

import time

import numpy as np
import pytest

from mmlspark_tpu.core import sanitizer as san
from mmlspark_tpu.core.env import SAN, SAN_RECOMPILE_BUDGET, env_override


@pytest.fixture(autouse=True)
def _sanitizer_off():
    """Every test starts and ends disabled with clean state."""
    san.disable()
    san.reset()
    san.set_recompile_budget(0)
    yield
    san.disable()
    san.reset()
    san.set_recompile_budget(0)


# --- disabled: strict no-op ----------------------------------------------

def test_disabled_check_finite_passes_nan_through_identically():
    x = np.array([1.0, np.nan, np.inf])
    assert san.check_finite("boundary", x) is x


def test_disabled_recorder_and_counter_stay_empty():
    san.record_collective("psum", "dp", (4,), "float32")
    san.count_recompile("step")
    assert len(san.recorder()) == 0
    assert san.recompile_count() == 0
    assert san.step_boundary() == ""


def test_disabled_overhead_is_noise():
    """The guard sits unconditionally on production hot paths: the
    disabled cost must stay within the fault_point noise band (~100ns
    class, generous bound for shared CI machines)."""
    x = np.zeros(8, np.float32)
    reps = 50_000
    san.check_finite("warm", x)
    t0 = time.perf_counter()
    for _ in range(reps):
        san.check_finite("bench", x)
    per_call_ns = (time.perf_counter() - t0) / reps * 1e9
    assert per_call_ns < 5_000, f"{per_call_ns:.0f}ns per disabled call"


# --- NaN/Inf guard --------------------------------------------------------

def test_nan_guard_names_boundary_and_counts():
    san.enable()
    bad = {"w": [np.ones(3), np.array([1.0, np.nan, np.inf, np.nan])]}
    with pytest.raises(san.NonFiniteError) as ei:
        san.check_finite("gbdt.train_scan.entry", bad)
    msg = str(ei.value)
    assert "graftsan" in msg
    assert "'gbdt.train_scan.entry'" in msg
    assert "2 NaN / 1 Inf" in msg
    assert "value['w'][1]" in msg


def test_guard_accepts_finite_and_non_float_leaves():
    san.enable()
    ok = {"i": np.arange(5), "f": np.ones(3), "s": "name",
          "n": None, "b": True, "t": (1.5, np.zeros(2))}
    assert san.check_finite("b", ok) is ok


def test_guard_skips_extension_dtypes():
    jax = pytest.importorskip("jax")
    san.enable()
    key = jax.random.key(0)  # PRNG key arrays have a non-numpy dtype
    san.check_finite("b", {"key": key, "x": np.ones(2)})


def test_guard_catches_python_float_nan():
    san.enable()
    with pytest.raises(san.NonFiniteError):
        san.check_finite("b", {"lr": float("nan")})


# --- collective recorder / divergence cross-check -------------------------

def test_recorder_hash_is_order_and_content_sensitive():
    san.enable()
    a, b = san.CollectiveRecorder(), san.CollectiveRecorder()
    for r in (a, b):
        with san.use_recorder(r):
            san.record_collective("psum", "dp", (4, 2), "float32")
            san.record_collective("all_gather", "fp", (8,), "int32")
    assert a.sequence_hash() == b.sequence_hash()
    with san.use_recorder(b):
        san.record_collective("psum", "dp", (4, 2), "float32")
    assert a.sequence_hash() != b.sequence_hash()


def test_crosscheck_raises_naming_divergent_rank():
    san.enable()
    rank0, rank1 = san.CollectiveRecorder(), san.CollectiveRecorder()
    with san.use_recorder(rank0):
        san.record_collective("psum", "dp", (4,), "float32")
    with san.use_recorder(rank1):
        # the `if rank == 0: psum` class: rank 1 skipped the psum
        san.record_collective("all_gather", "dp", (4,), "float32")
    hashes = [rank0.sequence_hash(), rank1.sequence_hash()]
    with pytest.raises(san.CollectiveDivergence) as ei:
        san.crosscheck_hashes(hashes, tag="iteration 3")
    msg = str(ei.value)
    assert "rank 1" in msg and "'iteration 3'" in msg


def test_crosscheck_agreeing_ranks_pass():
    san.crosscheck_hashes(["abcd", "abcd", "abcd"])


def test_step_boundary_single_process_returns_local_hash():
    san.enable()
    san.record_collective("psum", "dp", (4,), "float32")
    h = san.step_boundary()
    assert h == san.recorder().sequence_hash() and len(h) == 16


# --- recompilation budget -------------------------------------------------

def test_recompile_budget_raises_past_limit():
    san.enable()
    san.set_recompile_budget(2)
    san.count_recompile("step A")
    san.count_recompile("step B")
    with pytest.raises(san.RecompileBudgetExceeded) as ei:
        san.count_recompile("step C")
    msg = str(ei.value)
    assert "3 compilations" in msg and "budget of 2" in msg
    assert "step C" in msg


def test_recompile_budget_zero_counts_only():
    san.enable()
    for i in range(10):
        san.count_recompile(f"step {i}")
    assert san.recompile_count() == 10


# --- env registration -----------------------------------------------------

def test_refresh_from_env_flips_enabled_and_budget():
    with env_override(SAN, "1"), env_override(SAN_RECOMPILE_BUDGET, "7"):
        san.refresh_from_env()
        try:
            assert san.enabled()
            san.set_recompile_budget(0)  # reset below re-checks budget
            san.refresh_from_env()
            san.count_recompile("x")  # budget 7: no raise
        finally:
            pass
    san.refresh_from_env()
    assert not san.enabled()


# --- lock-discipline recorder (graftlock runtime twin) ---------------------

@pytest.mark.lock_smoke
class TestSanLock:
    def test_abba_drill_aborts_attributed_with_san_on(self):
        """The seeded two-thread ABBA drill: t1 takes A then B, t2
        takes B then A. With SAN on the second thread's inner acquire
        raises LockOrderViolation (naming thread, held set, both call
        sites) BEFORE blocking, so the drill finishes in well under a
        second instead of deadlocking."""
        import threading

        san.enable()
        a = san.san_lock("drill.A")
        b = san.san_lock("drill.B")
        errors = []

        def t1():
            with a:
                with b:
                    pass

        def t2(ready):
            ready.wait(5.0)
            try:
                with b:
                    with a:       # reverse order: must be rejected
                        pass
            except san.LockOrderViolation as e:
                errors.append(e)

        t0 = time.perf_counter()
        ready = threading.Event()
        th1 = threading.Thread(target=t1, name="mmlspark-drill-1")
        th2 = threading.Thread(target=t2, args=(ready,),
                               name="mmlspark-drill-2")
        th1.start()
        th1.join(5.0)
        ready.set()
        th2.start()
        th2.join(5.0)
        wall = time.perf_counter() - t0
        assert wall < 1.0, f"drill took {wall:.2f}s"
        assert len(errors) == 1
        err = errors[0]
        assert err.thread == "mmlspark-drill-2"
        assert tuple(err.held) == ("drill.B",)
        assert err.acquiring == "drill.A"
        msg = str(err)
        assert "ABBA" in msg
        assert "'drill.A'" in msg and "'drill.B'" in msg
        # both call sites are named: the earlier-recorded A->B order
        # and this acquire, all in this test file
        assert msg.count("test_sanitizer.py") >= 2

    def test_abba_drill_completes_with_san_off(self):
        """SAN off (the default): the same sequential drill is two
        plain nested acquisitions and completes normally."""
        import threading

        a = san.san_lock("offdrill.A")
        b = san.san_lock("offdrill.B")
        done = []

        def t1():
            with a:
                with b:
                    done.append("ab")

        def t2():
            with b:
                with a:
                    done.append("ba")

        th1 = threading.Thread(target=t1, name="mmlspark-offdrill-1")
        th1.start()
        th1.join(5.0)
        th2 = threading.Thread(target=t2, name="mmlspark-offdrill-2")
        th2.start()
        th2.join(5.0)
        assert done == ["ab", "ba"]
        assert san.lock_order_edges() == {}

    def test_consistent_order_records_edges_without_raising(self):
        san.enable()
        a = san.san_lock("ord.A")
        b = san.san_lock("ord.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        edges = san.lock_order_edges()
        assert ("ord.A", "ord.B") in edges
        held_site, acq_site = edges[("ord.A", "ord.B")]
        assert "test_sanitizer.py" in held_site
        assert "test_sanitizer.py" in acq_site

    def test_hold_time_warning_names_acquire_site(self):
        import warnings

        san.enable()
        san.set_lock_hold_budget_ms(5.0)
        lk = san.san_lock("hold.slow")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with lk:
                time.sleep(0.03)
        hold = [w for w in caught
                if issubclass(w.category, san.SanLockHoldWarning)]
        assert len(hold) == 1
        msg = str(hold[0].message)
        assert "'hold.slow'" in msg
        assert "MMLSPARK_TPU_SAN_LOCK_HOLD_MS=5" in msg
        assert "test_sanitizer.py" in msg
        assert "GL012" in msg
        # under budget: no warning
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with lk:
                pass
        assert not [w for w in caught
                    if issubclass(w.category, san.SanLockHoldWarning)]

    def test_condition_wait_does_not_count_parked_time(self):
        """A Condition.wait parks without holding the lock, so a long
        timed wait under a small hold budget must not warn."""
        import warnings

        san.enable()
        san.set_lock_hold_budget_ms(5.0)
        cond = san.san_lock("hold.cond", kind="condition")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with cond:
                cond.wait(0.03)    # parked 30ms > 5ms budget: fine
        assert not [w for w in caught
                    if issubclass(w.category, san.SanLockHoldWarning)]

    def test_rlock_reentry_is_not_an_order_edge(self):
        san.enable()
        r = san.san_lock("reent.R", kind="rlock")
        with r:
            with r:
                pass
        assert san.lock_order_edges() == {}

    def test_disabled_acquire_overhead_within_budget(self):
        """Acceptance bound: the disabled san_lock with-pass costs
        <=200ns over a raw threading.Lock with-pass (one module-global
        boolean plus delegation). Best-of-trials delta to shed CI
        scheduler noise."""
        import threading

        raw = threading.Lock()
        wrapped = san.san_lock("bench.disabled")
        reps = 200_000

        def probe(lk):
            t0 = time.perf_counter()
            for _ in range(reps):
                with lk:
                    pass
            return (time.perf_counter() - t0) / reps * 1e9

        probe(raw), probe(wrapped)          # warm
        deltas = []
        for _ in range(3):
            deltas.append(probe(wrapped) - probe(raw))
        best = min(deltas)
        assert best <= 200.0, f"disabled san_lock adds {best:.0f}ns"

    def test_reset_clears_order_graph_and_held_state(self):
        san.enable()
        a = san.san_lock("reset.A")
        b = san.san_lock("reset.B")
        with a:
            with b:
                pass
        assert san.lock_order_edges()
        san.reset()
        assert san.lock_order_edges() == {}
        # after reset the reverse order is legal again (fresh graph)
        with b:
            with a:
                pass

    def test_san_lock_rejects_unknown_kind(self):
        with pytest.raises(ValueError, match="unknown kind"):
            san.san_lock("x", kind="semaphore")


# --- dtype contracts (graftdtype runtime twin) ------------------------------

@pytest.mark.dtype_smoke
class TestDtypeContract:
    def test_drift_drill_aborts_attributed_on_the_crossing(self):
        """THE acceptance drill: flip one leaf's width mid-run and the
        very next crossing raises, naming the boundary and the leaf —
        not a later step, not an unattributed numerics divergence."""
        san.enable()
        payload = {"scores": np.zeros(4, np.float32),
                   "bins": np.zeros(4, np.uint8)}
        san.check_dtype_contract("gbdt.train_scan.exit", payload)
        payload["scores"] = payload["scores"].astype(np.float16)
        with pytest.raises(san.DtypeDrift) as ei:
            san.check_dtype_contract("gbdt.train_scan.exit", payload)
        msg = str(ei.value)
        assert "'gbdt.train_scan.exit'" in msg
        assert "value['scores']" in msg
        assert "float32" in msg and "float16" in msg
        assert ei.value.boundary == "gbdt.train_scan.exit"
        assert ei.value.leaf == "value['scores']"
        assert ei.value.before == "float32"
        assert ei.value.after == "float16"

    def test_disabled_arm_passes_drifted_values_through(self):
        """SAN off: the same drill completes, values untouched (the
        identity return is the bitwise contract)."""
        a = {"w": np.zeros(3, np.float32)}
        b = {"w": np.zeros(3, np.float16)}
        assert san.check_dtype_contract("b", a) is a
        assert san.check_dtype_contract("b", b) is b
        assert san.dtype_contracts() == {}

    def test_matching_crossings_record_once_and_pass(self):
        san.enable()
        x = {"w": np.ones(2, np.float32)}
        assert san.check_dtype_contract("b", x) is x
        assert san.check_dtype_contract("b", x) is x
        assert san.dtype_contracts() == {
            "b": {"value['w']": "float32"}}

    def test_arity_tolerance_compares_common_leaves_only(self):
        """Optional payloads (a probe batch without labels, a carry
        that grows a slot) must not false-positive: only leaves present
        in both signatures are compared, and new leaves join the
        recorded contract."""
        san.enable()
        san.check_dtype_contract("probe", {"a": np.zeros(1, np.float32)})
        san.check_dtype_contract(
            "probe", {"a": np.zeros(1, np.float32),
                      "lbl": np.zeros(1, np.int8)})
        san.check_dtype_contract("probe", {"a": np.zeros(1, np.float32)})
        # ... but the joined leaf is now held to its width
        with pytest.raises(san.DtypeDrift):
            san.check_dtype_contract(
                "probe", {"lbl": np.zeros(1, np.int32)})

    def test_scalars_and_extension_leaves_carry_no_contract(self):
        san.enable()
        san.check_dtype_contract(
            "b", {"n": 3, "f": 0.5, "s": "x", "none": None,
                  "obj": object()})
        assert san.dtype_contracts() == {"b": {}}

    def test_reset_clears_contracts(self):
        san.enable()
        san.check_dtype_contract("b", np.zeros(1, np.float32))
        san.reset()
        assert san.dtype_contracts() == {}
        # fresh contract: the other width is legal again
        san.check_dtype_contract("b", np.zeros(1, np.float16))

    def test_env_gate_turns_only_the_dtype_check_off(self):
        from mmlspark_tpu.core.env import SAN_DTYPE
        with env_override(SAN, "1"), env_override(SAN_DTYPE, "0"):
            san.refresh_from_env()
            assert san.enabled()
            san.check_dtype_contract("b", np.zeros(1, np.float32))
            san.check_dtype_contract("b", np.zeros(1, np.float16))
            assert san.dtype_contracts() == {}
            # the rest of the sanitizer is still live
            with pytest.raises(san.NonFiniteError):
                san.check_finite("b", np.array([np.nan]))
        san.refresh_from_env()

    def test_disabled_call_overhead_within_budget(self):
        """Acceptance bound: the disabled check_dtype_contract call
        costs <=200ns over a no-op passthrough (one module-global
        boolean). Best-of-trials delta to shed CI scheduler noise."""
        payload = {"p": 1.0}

        def passthrough(boundary, value):
            return value

        reps = 200_000

        def probe(fn):
            t0 = time.perf_counter()
            for _ in range(reps):
                fn("bench", payload)
            return (time.perf_counter() - t0) / reps * 1e9

        probe(passthrough), probe(san.check_dtype_contract)   # warm
        deltas = []
        for _ in range(3):
            deltas.append(probe(san.check_dtype_contract)
                          - probe(passthrough))
        best = min(deltas)
        assert best <= 200.0, f"disabled dtype contract adds {best:.0f}ns"
