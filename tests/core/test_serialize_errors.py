"""Error-path coverage for stage persistence and the crash-safe
checkpoint protocol (extends the fuzzing round-trip suite, which only
exercises the happy path): truncated manifests, missing array payloads,
and config-hash mismatches on resume must fail loudly or fall back
safely — never load garbage."""

import json
import os

import numpy as np
import pytest

from mmlspark_tpu.core import serialize
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logging_utils import reset_warn_once
from mmlspark_tpu.core.serialize import (load_latest_checkpoint,
                                         load_stage, save_checkpoint,
                                         save_stage)


@pytest.fixture()
def vw_model(rng):
    from mmlspark_tpu.models.vw.learners import VowpalWabbitRegressor
    x = rng.normal(size=(40, 3))
    y = x[:, 0] - 0.5 * x[:, 1]
    df = DataFrame({"features": x, "label": y})
    return VowpalWabbitRegressor(numPasses=1).fit(df)


class TestStageErrorPaths:
    def test_roundtrip_baseline(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        loaded = load_stage(path)
        np.testing.assert_array_equal(loaded.weights, vw_model.weights)

    def test_truncated_metadata_raises(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        meta = os.path.join(path, "metadata.json")
        with open(meta) as fh:
            text = fh.read()
        with open(meta, "w") as fh:
            fh.write(text[: len(text) // 2])  # torn mid-write
        with pytest.raises(json.JSONDecodeError):
            load_stage(path)

    def test_missing_arrays_file_raises(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        os.remove(os.path.join(path, "arrays.npz"))
        with pytest.raises((KeyError, FileNotFoundError)):
            load_stage(path)

    def test_missing_metadata_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_stage(str(tmp_path / "nope"))


class TestCheckpointProtocol:
    STATE = {"weights": np.arange(6, dtype=np.float32), "bias": 0.5,
             "passLosses": [1.0, 0.5]}

    def test_roundtrip_picks_latest_tag(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, {"weights": np.zeros(3), "bias": 0.0}, "h1")
        save_checkpoint(d, 2, self.STATE, "h1")
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 2
        np.testing.assert_array_equal(state["weights"],
                                      self.STATE["weights"])
        assert state["bias"] == 0.5
        assert state["passLosses"] == [1.0, 0.5]

    def test_empty_or_missing_dir(self, tmp_path):
        assert load_latest_checkpoint(str(tmp_path / "none")) is None
        assert load_latest_checkpoint(str(tmp_path)) is None

    def test_wrong_config_hash_refused(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        with pytest.raises(ValueError,
                           match="different config or dataset"):
            load_latest_checkpoint(d, "OTHER")

    def test_truncated_manifest_falls_back(self, tmp_path):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(2), "bias": 9.0}, "h1")
        manifest = os.path.join(d, "ckpt_00000002.json")
        with open(manifest) as fh:
            text = fh.read()
        with open(manifest, "w") as fh:
            fh.write(text[: len(text) // 3])
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 1  # torn tag 2 skipped, earlier one recovered

    def test_missing_payload_falls_back(self, tmp_path):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(2), "bias": 9.0}, "h1")
        os.remove(os.path.join(d, "ckpt_00000002.npz"))
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 1

    def test_tmp_debris_is_invisible(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        # a writer SIGKILLed before the manifest commit point
        with open(os.path.join(d, "ckpt_00000002.npz"), "wb") as fh:
            fh.write(b"half an npz")
        with open(os.path.join(d, "ckpt_00000002.json.tmp"), "w") as fh:
            fh.write('{"tag": 2')
        tag, _ = load_latest_checkpoint(d, "h1")
        assert tag == 1

    def test_atomic_write_never_tears(self, tmp_path):
        p = str(tmp_path / "f.txt")
        serialize.atomic_write(p, "hello")
        serialize.atomic_write(p, "world")
        with open(p) as fh:
            assert fh.read() == "world"
        assert not os.path.exists(p + ".tmp")

    def test_checkpoint_write_fault_degrades(self, tmp_path):
        """An armed checkpoint.write OSError surfaces to the caller —
        the training loops catch it and continue (checkpoint skip)."""
        from mmlspark_tpu.core import faults
        faults.reset()
        try:
            with faults.injected("checkpoint.write", "raise",
                                 exc=OSError("disk full")):
                with pytest.raises(OSError, match="disk full"):
                    save_checkpoint(str(tmp_path), 1, self.STATE, "h1")
        finally:
            faults.reset()
        # nothing half-written got committed
        assert load_latest_checkpoint(str(tmp_path), "h1") is None


class TestCheckpointIntegrity:
    """Payload digests in the manifest: bit-rot (not just torn writes)
    is detected at load and the loader falls back one committed
    generation with an attributed warning."""

    STATE = {"weights": np.arange(8, dtype=np.float32), "bias": 1.5}

    def test_manifest_records_payload_digest(self, tmp_path):
        save_checkpoint(str(tmp_path), 1, self.STATE, "h1")
        with open(tmp_path / "ckpt_00000001.json") as fh:
            manifest = json.load(fh)
        assert isinstance(manifest["payloadCrc32"], int)
        assert manifest["payloadBytes"] == os.path.getsize(
            tmp_path / "ckpt_00000001.npz")

    def test_bitflip_falls_back_one_generation(self, tmp_path, caplog):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(4), "bias": 9.0}, "h1")
        # flip one payload byte: np.load would still succeed, only the
        # digest can catch this
        npz = os.path.join(d, "ckpt_00000002.npz")
        with open(npz, "r+b") as fh:
            fh.seek(-7, os.SEEK_END)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([b[0] ^ 0xFF]))
        with caplog.at_level("WARNING"):
            tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 1
        np.testing.assert_array_equal(state["weights"],
                                      self.STATE["weights"])
        msgs = " ".join(r.getMessage() for r in caplog.records)
        assert "crc32" in msgs or "bit-rot" in msgs

    def test_verify_off_skips_digest(self, tmp_path, monkeypatch):
        monkeypatch.setenv("MMLSPARK_TPU_SPILL_VERIFY", "off")
        d = str(tmp_path)
        save_checkpoint(d, 2, self.STATE, "h1")
        npz = os.path.join(d, "ckpt_00000002.npz")
        with open(npz, "r+b") as fh:
            fh.seek(-7, os.SEEK_END)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_CUR)
            fh.write(bytes([b[0] ^ 0xFF]))
        # trust-the-disk mode: the digest is not consulted; the load
        # either returns (possibly garbage) data or trips np.load's own
        # structural checks — never the CheckpointCorrupt digest path
        try:
            out = load_latest_checkpoint(d, "h1")
        except Exception as e:  # noqa: BLE001 — zip-level damage
            assert "crc32" not in str(e)
        else:
            assert out is None or out[0] == 2

    def test_validate_hook_rejection_falls_back(self, tmp_path):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(4), "bias": 9.0}, "h1")

        def validate(tag, state):
            return "model dir digest mismatch" if tag == 2 else None

        tag, _ = load_latest_checkpoint(d, "h1", validate=validate)
        assert tag == 1
