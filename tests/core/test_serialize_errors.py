"""Error-path coverage for stage persistence and the crash-safe
checkpoint protocol (extends the fuzzing round-trip suite, which only
exercises the happy path): truncated manifests, missing array payloads,
and config-hash mismatches on resume must fail loudly or fall back
safely — never load garbage."""

import json
import os

import numpy as np
import pytest

from mmlspark_tpu.core import serialize
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.logging_utils import reset_warn_once
from mmlspark_tpu.core.serialize import (load_latest_checkpoint,
                                         load_stage, save_checkpoint,
                                         save_stage)


@pytest.fixture()
def vw_model(rng):
    from mmlspark_tpu.models.vw.learners import VowpalWabbitRegressor
    x = rng.normal(size=(40, 3))
    y = x[:, 0] - 0.5 * x[:, 1]
    df = DataFrame({"features": x, "label": y})
    return VowpalWabbitRegressor(numPasses=1).fit(df)


class TestStageErrorPaths:
    def test_roundtrip_baseline(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        loaded = load_stage(path)
        np.testing.assert_array_equal(loaded.weights, vw_model.weights)

    def test_truncated_metadata_raises(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        meta = os.path.join(path, "metadata.json")
        with open(meta) as fh:
            text = fh.read()
        with open(meta, "w") as fh:
            fh.write(text[: len(text) // 2])  # torn mid-write
        with pytest.raises(json.JSONDecodeError):
            load_stage(path)

    def test_missing_arrays_file_raises(self, vw_model, tmp_path):
        path = str(tmp_path / "stage")
        save_stage(vw_model, path)
        os.remove(os.path.join(path, "arrays.npz"))
        with pytest.raises((KeyError, FileNotFoundError)):
            load_stage(path)

    def test_missing_metadata_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_stage(str(tmp_path / "nope"))


class TestCheckpointProtocol:
    STATE = {"weights": np.arange(6, dtype=np.float32), "bias": 0.5,
             "passLosses": [1.0, 0.5]}

    def test_roundtrip_picks_latest_tag(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, {"weights": np.zeros(3), "bias": 0.0}, "h1")
        save_checkpoint(d, 2, self.STATE, "h1")
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 2
        np.testing.assert_array_equal(state["weights"],
                                      self.STATE["weights"])
        assert state["bias"] == 0.5
        assert state["passLosses"] == [1.0, 0.5]

    def test_empty_or_missing_dir(self, tmp_path):
        assert load_latest_checkpoint(str(tmp_path / "none")) is None
        assert load_latest_checkpoint(str(tmp_path)) is None

    def test_wrong_config_hash_refused(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        with pytest.raises(ValueError,
                           match="different config or dataset"):
            load_latest_checkpoint(d, "OTHER")

    def test_truncated_manifest_falls_back(self, tmp_path):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(2), "bias": 9.0}, "h1")
        manifest = os.path.join(d, "ckpt_00000002.json")
        with open(manifest) as fh:
            text = fh.read()
        with open(manifest, "w") as fh:
            fh.write(text[: len(text) // 3])
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 1  # torn tag 2 skipped, earlier one recovered

    def test_missing_payload_falls_back(self, tmp_path):
        reset_warn_once()
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        save_checkpoint(d, 2, {"weights": np.ones(2), "bias": 9.0}, "h1")
        os.remove(os.path.join(d, "ckpt_00000002.npz"))
        tag, state = load_latest_checkpoint(d, "h1")
        assert tag == 1

    def test_tmp_debris_is_invisible(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, 1, self.STATE, "h1")
        # a writer SIGKILLed before the manifest commit point
        with open(os.path.join(d, "ckpt_00000002.npz"), "wb") as fh:
            fh.write(b"half an npz")
        with open(os.path.join(d, "ckpt_00000002.json.tmp"), "w") as fh:
            fh.write('{"tag": 2')
        tag, _ = load_latest_checkpoint(d, "h1")
        assert tag == 1

    def test_atomic_write_never_tears(self, tmp_path):
        p = str(tmp_path / "f.txt")
        serialize.atomic_write(p, "hello")
        serialize.atomic_write(p, "world")
        with open(p) as fh:
            assert fh.read() == "world"
        assert not os.path.exists(p + ".tmp")

    def test_checkpoint_write_fault_degrades(self, tmp_path):
        """An armed checkpoint.write OSError surfaces to the caller —
        the training loops catch it and continue (checkpoint skip)."""
        from mmlspark_tpu.core import faults
        faults.reset()
        try:
            with faults.injected("checkpoint.write", "raise",
                                 exc=OSError("disk full")):
                with pytest.raises(OSError, match="disk full"):
                    save_checkpoint(str(tmp_path), 1, self.STATE, "h1")
        finally:
            faults.reset()
        # nothing half-written got committed
        assert load_latest_checkpoint(str(tmp_path), "h1") is None
