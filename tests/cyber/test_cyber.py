"""cyber tests, patterned on the reference's explore_access_anomalies /
test_scalers / test_indexers python suites."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.cyber import (
    AccessAnomaly,
    ComplementAccessTransformer,
    IdIndexer,
    PartitionedMinMaxScaler,
    PartitionedStandardScaler,
)


class TestFeature:
    def test_id_indexer_per_partition(self):
        df = DataFrame({"tenant": np.asarray(["a", "a", "b", "b", "b"],
                                             dtype=object),
                        "user": np.asarray(["u1", "u2", "u1", "u3", "u1"],
                                           dtype=object)})
        model = IdIndexer(inputCol="user", outputCol="uidx",
                          partitionKey="tenant").fit(df)
        out = model.transform(df)
        # ids restart per tenant, 1-based
        assert out.col("uidx").tolist() == [1, 2, 1, 2, 1]
        back = model.undo_transform(out)
        assert back.col("user").tolist() == ["u1", "u2", "u1", "u3", "u1"]

    def test_standard_scaler_per_partition(self):
        df = DataFrame({"t": np.asarray(["a"] * 3 + ["b"] * 3, dtype=object),
                        "v": np.asarray([1.0, 2.0, 3.0, 10.0, 20.0, 30.0])})
        model = PartitionedStandardScaler(inputCol="v", outputCol="z",
                                          partitionKey="t").fit(df)
        z = model.transform(df).col("z")
        assert z[:3].mean() == pytest.approx(0.0, abs=1e-9)
        assert z[3:].mean() == pytest.approx(0.0, abs=1e-9)

    def test_minmax_scaler_range(self):
        df = DataFrame({"v": np.asarray([1.0, 3.0, 5.0])})
        model = PartitionedMinMaxScaler(inputCol="v", outputCol="s",
                                        minRequiredValue=5.0,
                                        maxRequiredValue=10.0).fit(df)
        s = model.transform(df).col("s")
        assert s.min() == pytest.approx(5.0)
        assert s.max() == pytest.approx(10.0)


class TestComplement:
    def test_complement_avoids_observed(self):
        rng = np.random.default_rng(0)
        n = 60
        df = DataFrame({"tenant": np.zeros(n, np.int64),
                        "user_idx": rng.integers(1, 10, n),
                        "res_idx": rng.integers(1, 10, n)})
        seen = set(zip(df.col("user_idx").tolist(),
                       df.col("res_idx").tolist()))
        comp = ComplementAccessTransformer(
            tenantCol="tenant", complementsetFactor=1).transform(df)
        assert comp.num_rows > 0
        for u, r in zip(comp.col("user_idx"), comp.col("res_idx")):
            assert (u, r) not in seen


class TestAccessAnomaly:
    def test_cross_clique_access_is_anomalous(self):
        """Users access resources in their own clique; an access across
        cliques must score higher than in-clique accesses."""
        rng = np.random.default_rng(1)
        rows = []
        for u in range(20):
            clique = u % 2
            for _ in range(12):
                r = int(rng.integers(0, 10)) + clique * 10
                rows.append({"tenant": 0, "user": f"u{u}", "res": f"r{r}",
                             "likelihood": 1.0 + rng.random()})
        df = DataFrame.from_rows(rows)
        model = AccessAnomaly(maxIter=300, rankParam=8, seed=2).fit(df)

        in_clique = DataFrame.from_rows(
            [{"tenant": 0, "user": "u0", "res": "r3", "likelihood": 1.0},
             {"tenant": 0, "user": "u1", "res": "r13", "likelihood": 1.0}])
        cross = DataFrame.from_rows(
            [{"tenant": 0, "user": "u0", "res": "r13", "likelihood": 1.0},
             {"tenant": 0, "user": "u1", "res": "r3", "likelihood": 1.0}])
        s_in = model.transform(in_clique).col("anomaly_score")
        s_cross = model.transform(cross).col("anomaly_score")
        assert s_cross.mean() > s_in.mean() + 0.5

    def test_unseen_user_neutral(self):
        rows = [{"tenant": 0, "user": f"u{i}", "res": "r0",
                 "likelihood": 1.0} for i in range(5)]
        model = AccessAnomaly(maxIter=50).fit(DataFrame.from_rows(rows))
        out = model.transform(DataFrame.from_rows(
            [{"tenant": 0, "user": "stranger", "res": "r0",
              "likelihood": 1.0}]))
        assert out.col("anomaly_score")[0] == 0.0
