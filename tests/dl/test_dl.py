"""dl tests, patterned on the reference's test_deep_vision_classifier /
test_deep_text_classifier python suites (deep-learning/src/test/python)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.dl import (
    DeepTextClassifier,
    DeepVisionClassifier,
    SentenceEmbedder,
)


def _image_df(n=64, seed=0):
    """Two classes: bright-top vs bright-bottom images."""
    rng = np.random.default_rng(seed)
    imgs = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        img = rng.uniform(0, 0.2, (12, 12, 3)).astype(np.float32)
        cls = i % 2
        if cls == 0:
            img[:6] += 0.7
        else:
            img[6:] += 0.7
        imgs[i] = img
        labels[i] = cls
    return DataFrame({"image": imgs, "label": labels})


def _text_df(n=80, seed=0):
    rng = np.random.default_rng(seed)
    pos = ["great wonderful fantastic", "excellent amazing great",
           "wonderful superb fantastic"]
    neg = ["terrible awful horrible", "bad dreadful terrible",
           "horrible awful poor"]
    texts, labels = [], []
    for i in range(n):
        cls = i % 2
        texts.append((pos if cls else neg)[rng.integers(3)])
        labels.append(cls)
    return DataFrame({"text": np.asarray(texts, dtype=object),
                      "label": np.asarray(labels, np.float64)})


class TestDeepVision:
    def test_learns_separable_images(self):
        df = _image_df()
        est = DeepVisionClassifier(backbone="simple_cnn", batchSize=16,
                                   maxEpochs=8, learningRate=3e-3,
                                   labelCol="label", imageCol="image")
        model = est.fit(df)
        out = model.transform(df)
        acc = (out.col("prediction") == df.col("label")).mean()
        assert acc > 0.9
        assert out.col("probability").shape == (64, 2)
        assert model.train_seconds > 0

    def test_save_load_roundtrip(self, tmp_path):
        df = _image_df(32)
        model = DeepVisionClassifier(backbone="simple_cnn", batchSize=16,
                                     maxEpochs=2, labelCol="label").fit(df)
        model.save(str(tmp_path / "dv"))
        from mmlspark_tpu.core.pipeline import PipelineStage
        loaded = PipelineStage.load(str(tmp_path / "dv"))
        a = model.transform(df).col("probability")
        b = loaded.transform(df).col("probability")
        assert np.allclose(a, b, atol=1e-5)

    def test_unknown_backbone_raises(self):
        with pytest.raises(ValueError, match="unknown backbone"):
            DeepVisionClassifier(backbone="resnet999",
                                 labelCol="label").fit(_image_df(8))


class TestDeepText:
    def test_learns_sentiment_words(self):
        df = _text_df()
        est = DeepTextClassifier(batchSize=16, maxEpochs=10,
                                 learningRate=3e-3, labelCol="label",
                                 maxLength=8, embeddingDim=32, numLayers=1,
                                 numHeads=2)
        model = est.fit(df)
        out = model.transform(df)
        acc = (out.col("prediction") == df.col("label")).mean()
        assert acc > 0.9

    def test_embedder_from_model_and_fresh(self):
        df = _text_df(20)
        model = DeepTextClassifier(batchSize=10, maxEpochs=2,
                                   labelCol="label", maxLength=8,
                                   embeddingDim=32, numLayers=1,
                                   numHeads=2).fit(df)
        emb = SentenceEmbedder.from_text_model(model)
        out = emb.transform(df)
        assert out.col("embeddings").shape == (20, 32)
        # same text -> same embedding; different texts differ
        e = out.col("embeddings")
        texts = df.col("text")
        same = [i for i in range(1, 20) if texts[i] == texts[0]]
        if same:
            assert np.allclose(e[0], e[same[0]], atol=1e-5)

        fresh = SentenceEmbedder(inputCol="text", outputCol="embeddings",
                                 allowRandomEncoder=True,
                                 maxLength=8, embeddingDim=16, numLayers=1,
                                 numHeads=2)
        out2 = fresh.transform(df)
        assert out2.col("embeddings").shape == (20, 16)


class TestNonContiguousLabels:
    def test_labels_not_zero_based(self):
        """Regression: labels {1, 2} must round-trip through prediction."""
        df = _image_df(32)
        df = df.with_column("label", df.col("label") + 1.0)  # {1.0, 2.0}
        model = DeepVisionClassifier(backbone="simple_cnn", batchSize=16,
                                     maxEpochs=6, learningRate=3e-3,
                                     labelCol="label").fit(df)
        out = model.transform(df)
        assert set(np.unique(out.col("prediction"))) <= {1.0, 2.0}
        acc = (out.col("prediction") == df.col("label")).mean()
        assert acc > 0.9
