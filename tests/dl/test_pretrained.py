"""Pretrained ONNX checkpoints as fine-tunable backbones (VERDICT r2 #6;
reference fine-tunes torchvision/HF checkpoints,
dl/DeepVisionClassifier.py:7-31, hf/HuggingFaceSentenceEmbedder.py:26-60)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from tests.onnx.test_onnx import _model, _node, _tensor, _vi

H = W = 8
FDIM = H * W


def _make_filter(rng):
    """A fixed discriminative image filter: the 'pretrained knowledge'."""
    f = rng.normal(size=(FDIM,)).astype(np.float32)
    return f / np.linalg.norm(f)


def _backbone_onnx(filt):
    """(N,H,W,1) -> flatten -> Gemm(64->8, first unit = the filter) ->
    Relu features. The checkpoint carries the task's solution."""
    w = np.zeros((FDIM, 8), np.float32)
    w[:, 0] = filt * 4.0
    w[:, 1] = -filt * 4.0
    b = np.zeros((8,), np.float32)
    shape = np.asarray([-1, FDIM], np.int64)
    nodes = [
        _node("Reshape", ["x", "shape"], ["flat"]),
        _node("Gemm", ["flat", "w", "b"], ["h"]),
        _node("Relu", ["h"], ["feats"]),
    ]
    return _model(nodes, [_vi("x", [None, H, W, 1])],
                  [_vi("feats", [None, 8])],
                  [_tensor("w", w), _tensor("b", b),
                   _tensor("shape", shape)])


def _image_dataset(rng, filt, n=256):
    # uniform in [-1, 1]: values above 2 would trip the raw-pixel /255
    # normalization heuristic in _stack_images
    imgs = rng.uniform(-1, 1, size=(n, H, W, 1)).astype(np.float32)
    proj = imgs.reshape(n, FDIM) @ filt
    y = (proj > 0).astype(np.float64)
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = imgs[i]
    return DataFrame({"image": col, "label": y}), imgs, y


def test_convert_trainable_lifts_float_weights(rng):
    from mmlspark_tpu.onnx.convert import OnnxGraph, load_model

    filt = _make_filter(rng)
    graph = OnnxGraph(load_model(_backbone_onnx(filt)))
    fn, weights = graph.convert_trainable()
    assert set(weights) == {"w", "b"}  # int shape tensor stays static
    import jax
    import jax.numpy as jnp

    x = rng.normal(size=(4, H, W, 1)).astype(np.float32)
    grads = jax.grad(
        lambda p: jnp.sum(fn(p, {"x": x})["feats"]))(
            {k: jnp.asarray(v) for k, v in weights.items()})
    assert float(jnp.abs(grads["w"]).sum()) > 0


def test_finetune_from_pretrained_beats_scratch(rng):
    from mmlspark_tpu.dl.vision import DeepVisionClassifier

    filt = _make_filter(rng)
    df, imgs, y = _image_dataset(rng, filt)
    path = "/tmp/backbone_test.onnx"
    with open(path, "wb") as f:
        f.write(_backbone_onnx(filt))

    kw = dict(batchSize=32, maxEpochs=8, learningRate=3e-2,
              labelCol="label")
    pre = DeepVisionClassifier(backboneFile=path, **kw).fit(df)
    scratch = DeepVisionClassifier(backbone="simple_cnn", **kw).fit(df)
    acc_pre = float((pre.transform(df)["prediction"] == y).mean())
    acc_scratch = float((scratch.transform(df)["prediction"] == y).mean())
    assert acc_pre > 0.95
    assert acc_pre >= acc_scratch

    # persistence: the checkpoint travels WITH the saved model — delete
    # the original file before loading to prove no path dependence
    pre.save("/tmp/pre_model_stage")
    want = np.asarray(list(pre.transform(df)["probability"]), np.float64)
    os.remove(path)
    from mmlspark_tpu.core.pipeline import PipelineStage
    loaded = PipelineStage.load("/tmp/pre_model_stage")
    np.testing.assert_allclose(
        want,
        np.asarray(list(loaded.transform(df)["probability"]), np.float64),
        rtol=1e-5, atol=1e-6)


def test_frozen_backbone_keeps_imported_weights(rng):
    import jax

    from mmlspark_tpu.dl.vision import DeepVisionClassifier

    filt = _make_filter(rng)
    df, _, _ = _image_dataset(rng, filt, n=128)
    path = "/tmp/backbone_frozen.onnx"
    with open(path, "wb") as f:
        f.write(_backbone_onnx(filt))
    model = DeepVisionClassifier(
        backboneFile=path, freezeBackbone=True, batchSize=32, maxEpochs=1,
        labelCol="label").fit(df)
    flat = jax.tree_util.tree_flatten_with_path(model._params)[0]
    got_w = next(np.asarray(v) for path_k, v in flat
                 if any("onnx/w" in str(p) for p in path_k))
    want_w = np.zeros((FDIM, 8), np.float32)
    want_w[:, 0] = filt * 4.0
    want_w[:, 1] = -filt * 4.0
    np.testing.assert_allclose(got_w, want_w, atol=1e-6)


def test_embedder_requires_weights_or_optin(rng):
    from mmlspark_tpu.dl.embedder import SentenceEmbedder

    df = DataFrame({"text": np.asarray(["a b", "c d"], dtype=object)})
    with pytest.raises(ValueError, match="no weights"):
        SentenceEmbedder(inputCol="text", outputCol="emb").transform(df)
    out = SentenceEmbedder(inputCol="text", outputCol="emb", maxLength=4,
                           allowRandomEncoder=True).transform(df)
    assert out["emb"].shape[0] == 2


def test_embedder_onnx_checkpoint_deterministic(rng):
    from mmlspark_tpu.dl.embedder import SentenceEmbedder

    L, D = 6, 5
    w = rng.normal(size=(L, D)).astype(np.float32)
    nodes = [_node("MatMul", ["ids", "w"], ["proj"]),
             _node("Tanh", ["proj"], ["emb"])]
    payload = _model(nodes, [_vi("ids", [None, L])], [_vi("emb", [None, D])],
                     [_tensor("w", w)])
    path = "/tmp/embedder_enc.onnx"
    with open(path, "wb") as f:
        f.write(payload)
    df = DataFrame({"text": np.asarray(
        ["alpha beta", "gamma delta epsilon", "alpha beta"], dtype=object)})
    emb = SentenceEmbedder(inputCol="text", outputCol="emb", maxLength=L,
                           modelFile=path)
    out1 = emb.transform(df)["emb"]
    out2 = SentenceEmbedder(inputCol="text", outputCol="emb", maxLength=L,
                            modelFile=path).transform(df)["emb"]
    assert out1.shape == (3, D)
    np.testing.assert_allclose(out1, out2, atol=0)     # checkpoint-determined
    np.testing.assert_allclose(out1[0], out1[2], atol=0)  # same text, same emb
