"""Real pretrained checkpoints end-to-end (VERDICT r3 #7).

The committed hub models (mmlspark_tpu/resources/hub/) were genuinely
trained by tools/train_tiny_encoders.py: the text encoder with InfoNCE
over a topic corpus, the vision backbone on rendered shapes. These
tests assert the SEMANTICS — and that random weights fail the same
assertions — not just that the plumbing runs.
"""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.dl.embedder import SentenceEmbedder
from mmlspark_tpu.onnx.model import ONNXHub

HUB_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))),
    "mmlspark_tpu", "resources", "hub")

SENTENCES = {
    "animals": ["the dog chased a cat near the otter",
                "a hawk and an eagle watched the rabbit"],
    "finance": ["the stock dividend raised the portfolio yield",
                "broker issued an invoice with credit and margin"],
    "weather": ["rain and thunder with heavy fog tonight",
                "a blizzard brought frost snow and gale winds"],
}


def _pairwise_margin(embs):
    """mean same-topic cosine minus mean cross-topic cosine."""
    z = np.asarray(embs, np.float64)
    z = z / np.linalg.norm(z, axis=1, keepdims=True)
    sims = z @ z.T
    same = np.mean([sims[2 * i, 2 * i + 1] for i in range(3)])
    cross = np.mean([sims[i, j] for i in range(6) for j in range(6)
                     if i // 2 != j // 2])
    return same - cross


@pytest.fixture(scope="module")
def hub():
    return ONNXHub(HUB_DIR)


def test_hub_lists_and_verifies_committed_models(hub):
    names = {e["model"] for e in hub.list_models()}
    assert {"tiny-text-encoder", "tiny-vision-encoder"} <= names
    trained = hub.list_models(tags=["trained-in-repo"])
    assert len(trained) >= 2
    payload = hub.get_model("tiny-text-encoder")  # checksum-verified
    assert len(payload) > 1000


def test_sentence_embedder_semantic_neighbors(hub, tmp_path):
    model_file = os.path.join(HUB_DIR, "tiny-text-encoder.onnx")
    texts = [s for topic in sorted(SENTENCES) for s in SENTENCES[topic]]
    df = DataFrame({"text": np.array(texts, dtype=object)})
    emb = SentenceEmbedder(inputCol="text", outputCol="emb",
                           modelFile=model_file, maxLength=16,
                           vocabSize=2048)
    out = emb.transform(df)
    margin = _pairwise_margin(out["emb"])
    # trained encoder: same-topic sentences are clearly nearest
    assert margin > 0.5, f"semantic margin {margin:.3f}"

    # the SAME assertion fails on random weights — the committed
    # checkpoint carries learned semantics, not hashing geometry
    rand = SentenceEmbedder(inputCol="text", outputCol="emb",
                            maxLength=16, vocabSize=2048,
                            allowRandomEncoder=True)
    rand_margin = _pairwise_margin(rand.transform(df)["emb"])
    assert rand_margin < 0.3, f"random margin {rand_margin:.3f}"
    assert margin > rand_margin + 0.3


def test_vision_backbone_linear_probe_beats_random(hub):
    """Frozen pretrained conv features linearly separate shape classes
    far better than the same architecture with random weights — the
    definition of a real pretrained backbone."""
    import jax.numpy as jnp

    from mmlspark_tpu.onnx.convert import OnnxGraph, load_model

    rng = np.random.default_rng(5)
    from tools.train_tiny_encoders import render_shapes
    x, y = render_shapes(rng, 600)

    graph = OnnxGraph(load_model(hub.get_model("tiny-vision-encoder")))
    run = graph.convert()
    feats = np.asarray(run({"image": jnp.asarray(x)})["features"])

    # random-weight control: same graph with re-drawn initializers
    fn, weights = graph.convert_trainable()
    rand_w = {k: rng.normal(0, 0.1, size=np.shape(v)).astype(np.float32)
              for k, v in weights.items()}
    rand_feats = np.asarray(
        fn(rand_w, {"image": jnp.asarray(x)})["features"])

    def probe_acc(f):
        from sklearn.linear_model import LogisticRegression
        tr, te = slice(0, 400), slice(400, 600)
        clf = LogisticRegression(max_iter=2000).fit(f[tr], y[tr])
        return clf.score(f[te], y[te])

    acc = probe_acc(feats)
    rand_acc = probe_acc(rand_feats)
    assert acc > 0.85, f"pretrained probe acc {acc:.3f}"
    assert acc > rand_acc + 0.1, (acc, rand_acc)


def test_deep_vision_fine_tune_from_checkpoint(hub, tmp_path):
    """DeepVisionClassifier fine-tunes from the committed checkpoint
    through the public estimator API (DeepVisionClassifier.py:7-31
    torchvision-weights analog) and reaches high accuracy in a budget
    where training from scratch clearly lags."""
    from mmlspark_tpu.dl.vision import DeepVisionClassifier
    from tools.train_tiny_encoders import render_shapes

    rng = np.random.default_rng(6)
    x, y = render_shapes(rng, 300)
    imgs = np.empty(len(x), dtype=object)
    imgs[:] = list(x)  # CHW arrays per row (the ONNX backbone is NCHW)
    df = DataFrame({"image": imgs, "label": y.astype(np.float64)})
    backbone_file = os.path.join(HUB_DIR, "tiny-vision-encoder.onnx")
    kw = dict(imageCol="image", labelCol="label", batchSize=64,
              maxEpochs=20, learningRate=5e-3)
    tuned = DeepVisionClassifier(backboneFile=backbone_file, **kw).fit(df)
    xt, yt = render_shapes(np.random.default_rng(7), 300)
    timgs = np.empty(len(xt), dtype=object)
    timgs[:] = list(xt)
    tdf = DataFrame({"image": timgs})
    pred = np.asarray(tuned.transform(tdf)["prediction"])
    acc = float((pred == yt).mean())
    assert acc > 0.8, f"fine-tuned acc {acc:.3f}"

    # saved model carries the checkpoint: scores without the file
    path = os.path.join(tmp_path, "m")
    tuned.save(path)
    from mmlspark_tpu.core.pipeline import PipelineStage
    loaded = PipelineStage.load(path)
    np.testing.assert_array_equal(
        np.asarray(loaded.transform(tdf)["prediction"]), pred)


def test_deep_text_fine_tune_from_checkpoint(hub):
    """DeepTextClassifier starts from the committed trained text
    encoder (the HF-checkpoint fine-tune analog,
    hf/HuggingFaceSentenceEmbedder.py:26-60) and classifies topics it
    was never directly trained to label."""
    from mmlspark_tpu.dl.text import DeepTextClassifier
    from tools.train_tiny_encoders import TOPICS, FILLER

    rng = np.random.default_rng(8)
    names = sorted(TOPICS)[:3]
    texts, labels = [], []
    for li, t in enumerate(names):
        for _ in range(60):
            ws = list(rng.choice(TOPICS[t], size=6)) + \
                list(rng.choice(FILLER, size=2))
            rng.shuffle(ws)
            texts.append(" ".join(ws))
            labels.append(float(li))
    df = DataFrame({"text": np.array(texts, dtype=object),
                    "label": np.array(labels)})
    backbone = os.path.join(HUB_DIR, "tiny-text-encoder.onnx")
    clf = DeepTextClassifier(backboneFile=backbone, textCol="text",
                             labelCol="label", maxLength=16,
                             vocabSize=2048, batchSize=32, maxEpochs=6,
                             learningRate=5e-3).fit(df)
    # held-out topic sentences classify correctly
    ht, hy = [], []
    for li, t in enumerate(names):
        for _ in range(20):
            ws = list(rng.choice(TOPICS[t], size=6))
            ht.append(" ".join(ws))
            hy.append(li)
    pred = np.asarray(clf.transform(
        DataFrame({"text": np.array(ht, dtype=object)}))["prediction"])
    acc = float((pred == np.asarray(hy)).mean())
    assert acc > 0.85, f"fine-tuned text acc {acc:.3f}"
