"""Every examples/ script must run end to end (the nbtest analog:
the reference executes its website notebooks in CI,
DatabricksUtilities.scala / build.sbt:365-370 — examples that aren't
executed rot)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
EXAMPLES = os.path.join(REPO, "examples")

SCRIPTS = sorted(f for f in os.listdir(EXAMPLES)
                 if f.endswith(".py") and f[0].isdigit())


def test_all_examples_are_covered():
    # a new example must appear here (picked up by the glob) and run
    assert len(SCRIPTS) >= 5


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_runs(script):
    env = dict(os.environ)
    env["MMLSPARK_TPU_PLATFORM"] = "cpu"
    # examples must not inherit the test process's virtual-device
    # forcing; 05 spawns its own cluster, others run single-device
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)],
        cwd=EXAMPLES, capture_output=True, text=True, timeout=900,
        env=env)
    assert r.returncode == 0, (
        f"{script} failed:\n{r.stdout[-3000:]}\n{r.stderr[-3000:]}")
    assert f"OK {script[:-3]}" in r.stdout