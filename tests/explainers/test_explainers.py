"""explainers tests, patterned on the reference's split1/ LIME + SHAP +
ICE suites (core/src/test/scala/.../explainers/)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.pipeline import Model, Transformer
from mmlspark_tpu.explainers import (
    ICETransformer,
    LassoRegression,
    LeastSquaresRegression,
    TabularLIME,
    TabularSHAP,
    TextLIME,
    TextSHAP,
    VectorLIME,
    VectorSHAP,
)


class _LinearModel(Transformer):
    """Deterministic model: probability = sigmoid(w . x) on inputCols or a
    vector column."""

    def __init__(self, weights, cols=None, **kw):
        super().__init__(**kw)
        self.weights = np.asarray(weights, np.float64)
        self.cols = cols

    def _transform(self, df: DataFrame) -> DataFrame:
        if self.cols:
            x = np.stack([np.asarray(df.col(c), np.float64)
                          for c in self.cols], axis=1)
        else:
            x = np.asarray(df.col("features"), np.float64)
        z = x @ self.weights
        p = 1.0 / (1.0 + np.exp(-z))
        return df.with_column("probability", np.stack([1 - p, p], axis=1))


class _TokenCountModel(Transformer):
    """probability of class 1 rises with occurrences of the word 'good'."""

    def _transform(self, df: DataFrame) -> DataFrame:
        texts = [str(v) for v in df.col("text")]
        score = np.asarray([t.split().count("good") for t in texts],
                           np.float64)
        p = 1.0 / (1.0 + np.exp(-(score - 0.5)))
        return df.with_column("probability", np.stack([1 - p, p], axis=1))


class TestRegressionSolvers:
    def test_lasso_recovers_sparse_signal(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(200, 5))
        y = 3.0 * x[:, 0] - 2.0 * x[:, 2] + 0.5
        res = LassoRegression(alpha=0.01).fit(x, y)
        assert res.coefficients[0] == pytest.approx(3.0, abs=0.1)
        assert res.coefficients[2] == pytest.approx(-2.0, abs=0.1)
        assert abs(res.coefficients[1]) < 0.05
        assert res.intercept == pytest.approx(0.5, abs=0.1)
        assert res.r_squared > 0.98

    def test_lasso_strong_reg_zeroes_out(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(100, 3))
        y = 0.1 * x[:, 0]
        res = LassoRegression(alpha=10.0).fit(x, y)
        assert np.allclose(res.coefficients, 0.0)

    def test_least_squares_weighted(self):
        x = np.asarray([[1.0], [2.0], [3.0], [10.0]])
        y = np.asarray([2.0, 4.0, 6.0, 0.0])
        w = np.asarray([1.0, 1.0, 1.0, 0.0])  # outlier zero-weighted
        res = LeastSquaresRegression().fit(x, y, w)
        assert res.coefficients[0] == pytest.approx(2.0, abs=1e-3)
        assert res.r_squared == pytest.approx(1.0, abs=1e-4)


def _tabular_df(n=30, seed=0):
    rng = np.random.default_rng(seed)
    return DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})


class TestLIME:
    def test_tabular_lime_finds_important_feature(self):
        bg = _tabular_df(200, seed=1)
        df = _tabular_df(5)
        model = _LinearModel([2.0, 0.0], cols=["x1", "x2"])
        lime = TabularLIME(model=model, inputCols=["x1", "x2"],
                           backgroundData=bg, targetClasses=[1],
                           numSamples=300)
        out = lime.transform(df)
        for row_exp in out.col("explanation"):
            coefs = row_exp[0]  # class 1
            assert abs(coefs[0]) > abs(coefs[1]) * 3
        assert all(r[0] > 0.3 for r in out.col("r2"))

    def test_vector_lime(self):
        rng = np.random.default_rng(2)
        bg = DataFrame({"features": rng.normal(size=(150, 3))})
        df = DataFrame({"features": rng.normal(size=(4, 3))})
        model = _LinearModel([0.0, 3.0, 0.0])
        lime = VectorLIME(model=model, backgroundData=bg, targetClasses=[1],
                          numSamples=200)
        out = lime.transform(df)
        for row_exp in out.col("explanation"):
            coefs = row_exp[0]
            assert np.argmax(np.abs(coefs)) == 1

    def test_text_lime(self):
        texts = np.asarray(["good movie really good",
                            "bad film terrible plot"], dtype=object)
        df = DataFrame({"text": texts})
        lime = TextLIME(model=_TokenCountModel(), inputCol="text",
                        targetClasses=[1], numSamples=200)
        out = lime.transform(df)
        toks = out.col("tokens")[0]
        coefs = out.col("explanation")[0][0]
        good_idx = [i for i, t in enumerate(toks) if t == "good"]
        other_idx = [i for i, t in enumerate(toks) if t != "good"]
        assert min(coefs[i] for i in good_idx) > \
            max(abs(coefs[i]) for i in other_idx)


class TestSHAP:
    def test_tabular_shap_additivity(self):
        bg = _tabular_df(100, seed=3)
        df = _tabular_df(3, seed=4)
        model = _LinearModel([1.5, -1.0], cols=["x1", "x2"])
        shap = TabularSHAP(model=model, inputCols=["x1", "x2"],
                           backgroundData=bg, targetClasses=[1])
        out = shap.transform(df)
        scored = model.transform(df)
        for i, row_exp in enumerate(out.col("explanation")):
            v = row_exp[0]  # [base, shap1, shap2]
            assert len(v) == 3
            fx = scored.col("probability")[i, 1]
            # additivity: base + sum(shap) == f(x)
            assert v.sum() == pytest.approx(fx, abs=0.05)
        assert all(r[0] > 0.5 for r in out.col("r2"))

    def test_vector_shap_importance_order(self):
        rng = np.random.default_rng(5)
        bg = DataFrame({"features": rng.normal(size=(100, 4))})
        df = DataFrame({"features": rng.normal(size=(3, 4)) + 1.0})
        model = _LinearModel([4.0, 0.0, 0.0, 0.0])
        shap = VectorSHAP(model=model, backgroundData=bg, targetClasses=[1])
        out = shap.transform(df)
        for row_exp in out.col("explanation"):
            shap_vals = row_exp[0][1:]
            assert np.argmax(np.abs(shap_vals)) == 0

    def test_text_shap(self):
        df = DataFrame({"text": np.asarray(["good good movie plot"],
                                           dtype=object)})
        shap = TextSHAP(model=_TokenCountModel(), inputCol="text",
                        targetClasses=[1], numSamples=40)
        out = shap.transform(df)
        toks = out.col("tokens")[0]
        vals = out.col("explanation")[0][0][1:]
        good = [vals[i] for i, t in enumerate(toks) if t == "good"]
        rest = [vals[i] for i, t in enumerate(toks) if t != "good"]
        assert min(good) > max(rest)


class TestICE:
    def test_pdp_average_monotone(self):
        df = _tabular_df(50, seed=6)
        model = _LinearModel([2.0, 0.0], cols=["x1", "x2"])
        ice = ICETransformer(model=model, kind="average", targetClasses=[1],
                             numericFeatures=[{"name": "x1", "numSplits": 4},
                                              {"name": "x2", "numSplits": 4}])
        out = ice.transform(df)
        dep = out.col("x1_dependence")[0]
        keys = sorted(dep.keys())
        vals = [float(dep[k][0]) for k in keys]
        assert vals == sorted(vals)  # monotone in x1
        dep2 = out.col("x2_dependence")[0]
        v2 = [float(v[0]) for v in dep2.values()]
        assert max(v2) - min(v2) < 1e-6  # flat in x2

    def test_ice_individual_shape(self):
        df = _tabular_df(7, seed=7)
        model = _LinearModel([1.0, 1.0], cols=["x1", "x2"])
        ice = ICETransformer(model=model, kind="individual",
                             targetClasses=[1],
                             numericFeatures=[{"name": "x1", "numSplits": 3}])
        out = ice.transform(df)
        assert out.num_rows == 7
        assert len(out.col("x1_dependence")[0]) == 4

    def test_feature_importance_ranks(self):
        df = _tabular_df(60, seed=8)
        model = _LinearModel([3.0, 0.2], cols=["x1", "x2"])
        ice = ICETransformer(model=model, kind="feature", targetClasses=[1],
                             numericFeatures=[{"name": "x1"},
                                              {"name": "x2"}])
        out = ice.transform(df)
        imp = {r["featureNames"]: float(np.asarray(r["pdpBasedDependence"])[0])
               for r in out.iter_rows()}
        assert imp["x1_dependence"] > imp["x2_dependence"] * 2
