"""Balance-measure parity on the reference suite's worked example.

The 9-row Gender/Ethnicity dataset and the independent metric
calculators mirror the reference's test base
(core/src/test/scala/.../exploratory/DataBalanceTestBase.scala:31-149);
expected values are recomputed here in plain numpy/scipy-free Python so
the module under test is checked against independent math.
"""

import math

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.exploratory import (AggregateBalanceMeasure,
                                      DistributionBalanceMeasure,
                                      FeatureBalanceMeasure)


@pytest.fixture()
def sensitive_df():
    rows = [
        (0, "Male", "Asian"),
        (0, "Male", "White"),
        (1, "Male", "Other"),
        (1, "Male", "Black"),
        (0, "Female", "White"),
        (0, "Female", "Black"),
        (1, "Female", "Black"),
        (0, "Other", "Asian"),
        (0, "Other", "White"),
    ]
    return DataFrame({
        "Label": np.array([r[0] for r in rows]),
        "Gender": np.array([r[1] for r in rows], dtype=object),
        "Ethnicity": np.array([r[2] for r in rows], dtype=object),
    })


def _assoc_gap(num_rows, p_y, p_x1, p_x1y, p_x2, p_x2y):
    """DataBalanceTestBase.scala:50-81 AssociationMetricsCalculator."""
    p_y_given_x1 = p_x1y / p_x1
    p_y_given_x2 = p_x2y / p_x2
    krc = []
    for pf, pxy in ((p_x1, p_x1y), (p_x2, p_x2y)):
        a = num_rows ** 2 * (1 - 2 * pf - 2 * p_y + 2 * pxy + 2 * pf * p_y)
        b = num_rows * (2 * pf + 2 * p_y - 4 * pxy - 1)
        c = num_rows ** 2 * math.sqrt((pf - pf ** 2) * (p_y - p_y ** 2))
        krc.append((a + b) / c)
    return {
        "dp": p_y_given_x1 - p_y_given_x2,
        "sdc": p_x1y / (p_x1 + p_y) - p_x2y / (p_x2 + p_y),
        "ji": (p_x1y / (p_x1 + p_y - p_x1y)
               - p_x2y / (p_x2 + p_y - p_x2y)),
        "llr": math.log(p_x1y / p_y) - math.log(p_x2y / p_y),
        "pmi": math.log(p_y_given_x1) - math.log(p_y_given_x2),
        "n_pmi_y": (math.log(p_y_given_x1) / math.log(p_y)
                    - math.log(p_y_given_x2) / math.log(p_y)),
        "n_pmi_xy": (math.log(p_y_given_x1) / math.log(p_x1y)
                     - math.log(p_y_given_x2) / math.log(p_x2y)),
        "s_pmi": (math.log(p_x1y ** 2 / (p_x1 * p_y))
                  - math.log(p_x2y ** 2 / (p_x2 * p_y))),
        "krc": krc[0] - krc[1],
        "t_test": ((p_x1y - p_x1 * p_y) / math.sqrt(p_x1 * p_y)
                   - (p_x2y - p_x2 * p_y) / math.sqrt(p_x2 * p_y)),
    }


def test_feature_balance_gender_male_vs_female(sensitive_df):
    out = FeatureBalanceMeasure(
        sensitiveCols=["Gender"], labelCol="Label").transform(sensitive_df)
    rows = {(out["ClassA"][i], out["ClassB"][i]): i
            for i in range(out.num_rows)}
    assert set(rows) == {("Male", "Female"), ("Other", "Male"),
                         ("Other", "Female")}
    # 9 rows, 3 positive; Male: 4 rows 2 pos; Female: 3 rows 1 pos
    want = _assoc_gap(9.0, 3 / 9, 4 / 9, 2 / 9, 3 / 9, 1 / 9)
    i = rows[("Male", "Female")]
    for m, v in want.items():
        assert out[m][i] == pytest.approx(v, abs=1e-8), m


def test_feature_balance_pair_count_and_verbose(sensitive_df):
    out = FeatureBalanceMeasure(
        sensitiveCols=["Gender", "Ethnicity"], labelCol="Label",
        verbose=True).transform(sensitive_df)
    # C(3,2) gender pairs + C(4,2) ethnicity pairs
    assert out.num_rows == 3 + 6
    assert "prA" in out.columns and "prB" in out.columns
    eth = out.filter(out["FeatureName"] == "Ethnicity")
    assert eth.num_rows == 6


def test_distribution_balance_uniform(sensitive_df):
    out = DistributionBalanceMeasure(
        sensitiveCols=["Gender", "Ethnicity"]).transform(sensitive_df)
    assert out.num_rows == 2
    gi = list(out["FeatureName"]).index("Gender")
    # Gender: Male 4/9, Female 3/9, Other 2/9 vs uniform 1/3
    obs = np.array([3 / 9, 4 / 9, 2 / 9])  # sorted: Female, Male, Other
    ref = np.full(3, 1 / 3)
    kl = float(np.sum(obs * np.log(obs / ref)))
    assert out["kl_divergence"][gi] == pytest.approx(kl, abs=1e-8)
    avg = (obs + ref) / 2
    js = math.sqrt((np.sum(ref * np.log(ref / avg))
                    + np.sum(obs * np.log(obs / avg))) / 2)
    assert out["js_dist"][gi] == pytest.approx(js, abs=1e-8)
    diff = np.abs(obs - ref)
    assert out["inf_norm_dist"][gi] == pytest.approx(diff.max(), abs=1e-8)
    assert out["total_variation_dist"][gi] == pytest.approx(
        diff.sum() / 2, abs=1e-8)
    assert out["wasserstein_dist"][gi] == pytest.approx(
        diff.mean(), abs=1e-8)
    chi = float(np.sum((obs * 9 - ref * 9) ** 2 / (ref * 9)))
    assert out["chi_sq_stat"][gi] == pytest.approx(chi, abs=1e-8)
    from scipy.stats import chi2
    assert out["chi_sq_p_value"][gi] == pytest.approx(
        1 - chi2.cdf(chi, df=2), abs=1e-6)


def test_distribution_balance_custom_reference(sensitive_df):
    ref = {"Male": 0.5, "Female": 0.3, "Other": 0.2}
    out = DistributionBalanceMeasure(
        sensitiveCols=["Gender"],
        referenceDistribution=[ref]).transform(sensitive_df)
    obs = {"Female": 3 / 9, "Male": 4 / 9, "Other": 2 / 9}
    diff = [abs(obs[v] - ref[v]) for v in ("Female", "Male", "Other")]
    assert out["inf_norm_dist"][0] == pytest.approx(max(diff), abs=1e-8)
    # mismatched length must raise
    with pytest.raises(ValueError):
        DistributionBalanceMeasure(
            sensitiveCols=["Gender", "Ethnicity"],
            referenceDistribution=[ref]).transform(sensitive_df)


def test_aggregate_balance_measures(sensitive_df):
    out = AggregateBalanceMeasure(
        sensitiveCols=["Gender"]).transform(sensitive_df)
    probs = np.array([4 / 9, 3 / 9, 2 / 9])
    norm = probs / probs.mean()
    # epsilon=1 -> alpha=0 -> geometric-mean branch
    atkinson = 1 - float(np.prod(norm)) ** (1 / 3)
    theil_l = float(np.sum(-np.log(norm))) / 3
    theil_t = float(np.sum(norm * np.log(norm))) / 3
    assert out["atkinson_index"][0] == pytest.approx(atkinson, abs=1e-8)
    assert out["theil_l_index"][0] == pytest.approx(theil_l, abs=1e-8)
    assert out["theil_t_index"][0] == pytest.approx(theil_t, abs=1e-8)
    # joint grouping over two sensitive cols
    out2 = AggregateBalanceMeasure(
        sensitiveCols=["Gender", "Ethnicity"],
        epsilon=0.5).transform(sensitive_df)
    # 8 distinct (gender, ethnicity) combos of 9 rows; F-Black has 2
    counts = np.array([1, 1, 1, 1, 1, 2, 1, 1], np.float64)
    probs = counts / 9.0
    norm = probs / probs.mean()
    power_mean = float(np.sum(norm ** 0.5)) / 8
    assert out2["atkinson_index"][0] == pytest.approx(
        1 - power_mean ** 2, abs=1e-8)


def test_feature_balance_zero_positive_group():
    # a group with no positive labels: pmi/llr/s_pmi hit log(0) = -inf
    # on the A side, so the gap is -inf (reference keeps the -inf)
    df = DataFrame({
        "Label": np.array([0, 0, 1, 1]),
        "g": np.array(["a", "a", "b", "b"], dtype=object),
    })
    out = FeatureBalanceMeasure(sensitiveCols=["g"],
                                labelCol="Label").transform(df)
    i = {(out["ClassA"][k], out["ClassB"][k]): k
         for k in range(out.num_rows)}[("b", "a")]
    # A=b has all positives, B=a has none: gap = finite - (-inf) = +inf
    assert out["pmi"][i] == math.inf
    assert out["s_pmi"][i] == math.inf
    assert out["llr"][i] == math.inf


def test_feature_balance_rejects_bad_columns(sensitive_df):
    df = sensitive_df.with_column("fval", np.ones(9))
    with pytest.raises(TypeError):
        FeatureBalanceMeasure(sensitiveCols=["fval"],
                              labelCol="Label").transform(df)
    with pytest.raises(TypeError):
        FeatureBalanceMeasure(sensitiveCols=["Gender"],
                              labelCol="Gender").transform(sensitive_df)
