"""Featurize module tests (parity: VerifyCleanMissingData,
VerifyValueIndexer, VerifyTextFeaturizer, VerifyFeaturize suites)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.featurize import (CleanMissingData, CountSelector,
                                    DataConversion, Featurize, IndexToValue,
                                    MultiNGram, PageSplitter, TextFeaturizer,
                                    ValueIndexer, VectorAssembler)


def test_clean_missing_mean_median_custom():
    df = DataFrame({"a": np.array([1.0, np.nan, 3.0]),
                    "b": np.array([np.nan, 4.0, 8.0])})
    m = CleanMissingData(inputCols=["a", "b"], outputCols=["a", "b"]).fit(df)
    out = m.transform(df)
    np.testing.assert_allclose(out.col("a"), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(out.col("b"), [6.0, 4.0, 8.0])

    m = CleanMissingData(inputCols=["a"], outputCols=["a"],
                         cleaningMode="Median").fit(df)
    np.testing.assert_allclose(m.transform(df).col("a"), [1.0, 2.0, 3.0])

    m = CleanMissingData(inputCols=["a"], outputCols=["a"],
                         cleaningMode="Custom", customValue=-1.0).fit(df)
    np.testing.assert_allclose(m.transform(df).col("a"), [1.0, -1.0, 3.0])


def test_value_indexer_roundtrip():
    df = DataFrame({"c": ["b", "a", "b", None]})
    model = ValueIndexer(inputCol="c", outputCol="i").fit(df)
    out = model.transform(df)
    # levels sorted ascending, null last (ValueIndexer.scala NullOrdering)
    assert model.levels == ["a", "b", None]
    np.testing.assert_array_equal(out.col("i"), [1, 0, 1, 2])
    back = IndexToValue(inputCol="i", outputCol="c2").transform(out)
    assert list(back.col("c2")) == ["b", "a", "b", None]
    with pytest.raises(ValueError):
        model.transform(DataFrame({"c": ["unseen"]}))


def test_value_indexer_numeric():
    df = DataFrame({"c": np.array([5, 3, 5, 9])})
    model = ValueIndexer(inputCol="c", outputCol="i").fit(df)
    assert model.levels == [3, 5, 9]
    np.testing.assert_array_equal(model.transform(df).col("i"), [1, 0, 1, 2])


def test_data_conversion():
    df = DataFrame({"a": np.array([1.5, 2.5]), "s": ["1", "2"]})
    out = DataConversion(cols=["a"], convertTo="integer").transform(df)
    assert out.col("a").dtype == np.int32
    out = DataConversion(cols=["s"], convertTo="double").transform(df)
    np.testing.assert_allclose(out.col("s"), [1.0, 2.0])
    out = DataConversion(cols=["a"], convertTo="string").transform(df)
    assert list(out.col("a")) == ["1.5", "2.5"]
    cat = DataConversion(cols=["s"], convertTo="toCategorical").transform(df)
    assert cat.metadata("s")["categorical"]


def test_count_selector():
    df = DataFrame({"f": np.array([[1.0, 0.0, 2.0], [3.0, 0.0, 0.0]])})
    model = CountSelector(inputCol="f", outputCol="o").fit(df)
    assert model.indices == [0, 2]
    out = model.transform(df)
    assert out.col("o").shape == (2, 2)


def test_vector_assembler():
    df = DataFrame({"x": np.array([1.0, 2.0]),
                    "v": np.array([[3.0, 4.0], [5.0, 6.0]])})
    out = VectorAssembler(inputCols=["x", "v"], outputCol="f").transform(df)
    np.testing.assert_allclose(out.col("f"), [[1, 3, 4], [2, 5, 6]])
    assert out.metadata("f")["slots"] == ["x", "v_0", "v_1"]


def test_text_featurizer_tf_idf():
    df = DataFrame({"t": ["the cat sat", "the dog sat", "a bird flew"]})
    model = TextFeaturizer(inputCol="t", outputCol="f", numFeatures=64,
                           useIDF=True).fit(df)
    out = model.transform(df)
    assert out.col("f").shape == (3, 64)
    # idf of a term in all docs < idf of a rarer term
    assert out.col("f").sum() > 0

    nostop = TextFeaturizer(inputCol="t", outputCol="f", numFeatures=64,
                            useStopWordsRemover=True, useIDF=False).fit(df)
    o2 = nostop.transform(df)
    # "the"/"a" removed -> fewer nonzero counts
    assert o2.col("f").sum() < out.col("f").astype(bool).sum() + 100


def test_text_featurizer_ngrams():
    df = DataFrame({"t": ["a b c d"]})
    model = TextFeaturizer(inputCol="t", outputCol="f", numFeatures=32,
                           useNGram=True, nGramLength=2, useIDF=False).fit(df)
    out = model.transform(df)
    assert out.col("f").sum() == 3  # "a b", "b c", "c d"


def test_multi_ngram():
    df = DataFrame({"toks": np.array([["a", "b", "c"]], dtype=object)})
    out = MultiNGram(inputCol="toks", outputCol="ng",
                     lengths=[1, 2, 3]).transform(df)
    assert out.col("ng")[0] == ["a", "b", "c", "a b", "b c", "a b c"]


def test_page_splitter():
    text = "word " * 100  # 500 chars
    df = DataFrame({"t": [text.strip(), None]})
    out = PageSplitter(inputCol="t", outputCol="p", maximumPageLength=100,
                       minimumPageLength=80).transform(df)
    pages = out.col("p")[0]
    assert all(len(p) <= 100 for p in pages)
    assert "".join(pages) == text.strip()
    assert out.col("p")[1] is None
    # a word longer than a page gets hard-split
    long_word = "x" * 250
    out = PageSplitter(inputCol="t", outputCol="p", maximumPageLength=100,
                       minimumPageLength=80).transform(
        DataFrame({"t": [long_word]}))
    assert "".join(out.col("p")[0]) == long_word


def test_featurize_end_to_end():
    df = DataFrame({
        "num": np.array([1.0, np.nan, 3.0, 4.0]),
        "cat": ["r", "g", "r", "b"],
        "y": np.array([0, 1, 0, 1]),
    })
    model = Featurize(inputCols=["num", "cat"], outputCol="features").fit(df)
    out = model.transform(df)
    feats = out.col("features")
    assert feats.shape[0] == 4
    # 1 numeric + 3 one-hot slots
    assert feats.shape[1] == 4
    assert not np.isnan(feats).any()
