"""TestObject registry: one entry per pipeline stage.

Parity: the reference's fuzzing backbone (core test
fuzzing/Fuzzing.scala:604-631) — every stage registers TestObjects that
drive serialization round-trips, fit/transform smoke runs and
getter/setter checks; a completeness test asserts no stage is missing
(FuzzingTest.scala:19-80).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.pipeline import Estimator, PipelineStage, Transformer

_rng = np.random.default_rng(7)


def _obj_col(values) -> np.ndarray:
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def _tabular(n=60):
    x1 = _rng.normal(size=n)
    x2 = _rng.normal(size=n)
    y = (x1 + 0.5 * x2 > 0).astype(np.float64)
    return DataFrame({
        "x1": x1, "x2": x2,
        "features": np.stack([x1, x2], axis=1),
        "label": y,
        "cat": np.asarray([("a", "b", "c")[i % 3] for i in range(n)],
                          dtype=object),
        "text": _obj_col([("good great fine", "bad awful poor")[i % 2]
                          for i in range(n)]),
    })


def _images(n=4):
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = _rng.uniform(0, 255, (12, 12, 3)).astype(np.float32)
    return DataFrame({"image": col, "label": np.asarray(
        [float(i % 2) for i in range(n)])})


def _interactions():
    users = np.repeat(np.arange(12), 5)
    items = np.concatenate([(np.arange(5) + (u % 2) * 5) for u in range(12)])
    return DataFrame({"user": users.astype(np.int64),
                      "item": items.astype(np.int64),
                      "rating": np.ones(len(users))})


@dataclass
class TestObject:
    """A stage instance + the dataset(s) to exercise it with."""

    __test__ = False  # dataclass, not a pytest collection target

    stage: PipelineStage
    fit_df: DataFrame
    transform_df: Optional[DataFrame] = None
    compare_cols: Optional[List[str]] = None   # None = all new columns
    skip_serialization: bool = False
    approx: float = 1e-6

    @property
    def df_for_transform(self) -> DataFrame:
        return self.transform_df if self.transform_df is not None \
            else self.fit_df


def _linear_model():
    class _Probe(Transformer):
        def _transform(self, df):
            # read named columns OR a features vector, whichever exists
            if "x1" in df:
                z = np.asarray(df.col("x1"), np.float64)
            else:
                z = np.asarray(df.col("features"), np.float64)[:, 0]
            p = 1 / (1 + np.exp(-z))
            return df.with_column("probability",
                                  np.stack([1 - p, p], axis=1))
    return _Probe()


def build_registry() -> Dict[str, TestObject]:
    """stage-class-name -> TestObject. Import inside so discovery sees
    every module."""
    from mmlspark_tpu.automl.search import FindBestModel, TuneHyperparameters
    from mmlspark_tpu.causal.diff_in_diff import (
        DiffInDiffEstimator, SyntheticControlEstimator,
        SyntheticDiffInDiffEstimator)
    from mmlspark_tpu.causal.dml import DoubleMLEstimator, ResidualTransformer
    from mmlspark_tpu.causal.ortho_forest import OrthoForestDMLEstimator
    from mmlspark_tpu.cyber.anomaly import (AccessAnomaly,
                                            ComplementAccessTransformer)
    from mmlspark_tpu.cyber.feature import (IdIndexer,
                                            PartitionedMinMaxScaler,
                                            PartitionedStandardScaler)
    from mmlspark_tpu.dl.text import DeepTextClassifier
    from mmlspark_tpu.dl.vision import DeepVisionClassifier
    from mmlspark_tpu.dl.embedder import SentenceEmbedder
    from mmlspark_tpu.exploratory.balance import (AggregateBalanceMeasure,
                                                  DistributionBalanceMeasure,
                                                  FeatureBalanceMeasure)
    from mmlspark_tpu.explainers.ice import ICETransformer
    from mmlspark_tpu.explainers.lime import (TabularLIME, TextLIME,
                                              VectorLIME)
    from mmlspark_tpu.explainers.shap import (TabularSHAP, TextSHAP,
                                              VectorSHAP)
    from mmlspark_tpu.featurize.assemble import VectorAssembler
    from mmlspark_tpu.featurize.clean import CleanMissingData
    from mmlspark_tpu.featurize.convert import DataConversion
    from mmlspark_tpu.featurize.featurize import Featurize
    from mmlspark_tpu.featurize.indexer import IndexToValue, ValueIndexer
    from mmlspark_tpu.featurize.select import CountSelector
    from mmlspark_tpu.featurize.text import (MultiNGram, PageSplitter,
                                             TextFeaturizer)
    from mmlspark_tpu.image.transformer import (ImageSetAugmenter,
                                                ImageTransformer, UnrollImage)
    from mmlspark_tpu.image.superpixel import SuperpixelTransformer
    from mmlspark_tpu.isolationforest.iforest import IsolationForest
    from mmlspark_tpu.models.gbdt.estimators import (LightGBMClassifier,
                                                     LightGBMRanker,
                                                     LightGBMRegressor)
    from mmlspark_tpu.models.vw.bandit import VowpalWabbitContextualBandit
    from mmlspark_tpu.models.vw.cse import (VowpalWabbitCSETransformer,
                                            VowpalWabbitDSJsonTransformer)
    from mmlspark_tpu.models.vw.featurizer import (VowpalWabbitFeaturizer,
                                                   VowpalWabbitInteractions)
    from mmlspark_tpu.models.vw.learners import (VowpalWabbitClassifier,
                                                 VowpalWabbitGeneric,
                                                 VowpalWabbitGenericProgressive,
                                                 VowpalWabbitRegressor)
    from mmlspark_tpu.nn.knn import KNN, ConditionalKNN
    from mmlspark_tpu.onnx.model import ONNXModel
    from mmlspark_tpu.recommendation.ranking import (
        RankingAdapter, RankingTrainValidationSplit)
    from mmlspark_tpu.recommendation.sar import SAR
    from mmlspark_tpu.stages.balance import (ClassBalancer,
                                             StratifiedRepartition)
    from mmlspark_tpu.stages.basic import (Cacher, DropColumns, Explode,
                                           Lambda, MultiColumnAdapter,
                                           RenameColumn, Repartition,
                                           SelectColumns, UDFTransformer,
                                           UnicodeNormalize)
    from mmlspark_tpu.stages.batching import (DynamicMiniBatchTransformer,
                                              FixedMiniBatchTransformer,
                                              FlattenBatch,
                                              PartitionConsolidator,
                                              TimeIntervalMiniBatchTransformer)
    from mmlspark_tpu.stages.text import EnsembleByKey
    from mmlspark_tpu.stages.summarize import SummarizeData
    from mmlspark_tpu.stages.text import TextPreprocessor
    from mmlspark_tpu.stages.timer import Timer
    from mmlspark_tpu.train.statistics import (ComputeModelStatistics,
                                               ComputePerInstanceStatistics)
    from mmlspark_tpu.train.trainers import TrainClassifier, TrainRegressor

    tab = _tabular()
    small_gbdt = dict(numIterations=3, numLeaves=4, maxBin=16)
    scored = tab.with_columns({
        "prediction": tab.col("label"),
        "probability": np.stack([1 - tab.col("label"),
                                 tab.col("label")], axis=1)})
    panel = DataFrame.from_rows([
        {"unit": u, "time": t, "outcome": float(u + t + 2.0 * (u < 2 and t > 2)),
         "treatment": float(u < 2), "postTreatment": float(t > 2)}
        for u in range(6) for t in range(6)])
    dsjson = DataFrame({"value": _obj_col([
        '{"EventId":"e1","_label_probability":0.5,"_label_cost":-1.0,'
        '"_labelIndex":0,"p":[0.6,0.4],"a":[1,2]}'] * 6)})
    cb_df = DataFrame({
        "features": _rng.normal(size=(20, 3)),
        "chosenAction": (np.arange(20) % 2 + 1).astype(np.float64),
        "label": _rng.random(20),
        "probability": np.full(20, 0.5),
    })
    access = DataFrame.from_rows([
        {"tenant": 0, "user": f"u{i % 6}", "res": f"r{(i % 6) // 2}",
         "likelihood": 1.0} for i in range(30)])

    onnx_bytes = _tiny_onnx_model()

    reg: Dict[str, TestObject] = {
        # featurize
        "VectorAssembler": TestObject(
            VectorAssembler(inputCols=["x1", "x2"], outputCol="v"), tab),
        "CleanMissingData": TestObject(
            CleanMissingData(inputCols=["x1"], outputCols=["x1c"]), tab),
        "DataConversion": TestObject(
            DataConversion(cols=["x1"], convertTo="double"), tab),
        "Featurize": TestObject(
            Featurize(inputCols=["x1", "cat"], outputCol="f"), tab),
        "ValueIndexer": TestObject(
            ValueIndexer(inputCol="cat", outputCol="cat_idx"), tab),
        "IndexToValue": TestObject(
            IndexToValue(inputCol="cat_idx", outputCol="cat_back"),
            ValueIndexer(inputCol="cat", outputCol="cat_idx").fit(tab)
            .transform(tab)),
        "CountSelector": TestObject(
            CountSelector(inputCol="features", outputCol="sel"), tab),
        "TextFeaturizer": TestObject(
            TextFeaturizer(inputCol="text", outputCol="tf",
                           numFeatures=64), tab),
        "MultiNGram": TestObject(
            MultiNGram(inputCol="text", outputCol="ngrams",
                       lengths=[1, 2]), tab),
        "PageSplitter": TestObject(
            PageSplitter(inputCol="text", outputCol="pages",
                         maximumPageLength=8), tab),
        # stages
        "DropColumns": TestObject(DropColumns(cols=["cat"]), tab),
        "SelectColumns": TestObject(SelectColumns(cols=["x1", "label"]), tab),
        "RenameColumn": TestObject(
            RenameColumn(inputCol="x1", outputCol="x1r"), tab),
        "UDFTransformer": TestObject(
            UDFTransformer(inputCol="x1", outputCol="x1sq",
                           udf=lambda a: np.asarray(a) ** 2), tab,
            skip_serialization=True),  # callables don't round-trip
        "Lambda": TestObject(
            Lambda(transformFunc=lambda df: df.with_column(
                "c", df.col("x1"))), tab, skip_serialization=True),
        "EnsembleByKey": TestObject(
            EnsembleByKey(keys=["cat"], cols=["x1"]), tab),
        "Cacher": TestObject(Cacher(), tab),
        "Repartition": TestObject(Repartition(n=2), tab),
        "Explode": TestObject(
            Explode(inputCol="pages", outputCol="page"),
            PageSplitter(inputCol="text", outputCol="pages",
                         maximumPageLength=8).transform(tab)),
        "UnicodeNormalize": TestObject(
            UnicodeNormalize(inputCol="text", outputCol="norm"), tab),
        "MultiColumnAdapter": TestObject(
            MultiColumnAdapter(inputCols=["text", "cat"],
                               outputCols=["tn", "cn"],
                               baseStage=UnicodeNormalize()), tab),
        "TimeIntervalMiniBatchTransformer": TestObject(
            TimeIntervalMiniBatchTransformer(millisToWait=1,
                                             maxBatchSize=16), tab),
        "ClassBalancer": TestObject(
            ClassBalancer(inputCol="label"), tab),
        "StratifiedRepartition": TestObject(
            StratifiedRepartition(labelCol="label", numShards=2), tab),
        "FixedMiniBatchTransformer": TestObject(
            FixedMiniBatchTransformer(batchSize=16), tab),
        "DynamicMiniBatchTransformer": TestObject(
            DynamicMiniBatchTransformer(maxBatchSize=16), tab),
        "FlattenBatch": TestObject(
            FlattenBatch(),
            FixedMiniBatchTransformer(batchSize=16).transform(
                tab.select("x1", "label"))),
        "PartitionConsolidator": TestObject(PartitionConsolidator(), tab),
        "SummarizeData": TestObject(SummarizeData(), tab.select("x1", "x2")),
        # exploratory (balance measures)
        "FeatureBalanceMeasure": TestObject(
            FeatureBalanceMeasure(sensitiveCols=["cat"], labelCol="label"),
            tab),
        "DistributionBalanceMeasure": TestObject(
            DistributionBalanceMeasure(sensitiveCols=["cat"]), tab),
        "AggregateBalanceMeasure": TestObject(
            AggregateBalanceMeasure(sensitiveCols=["cat"]), tab),
        "TextPreprocessor": TestObject(
            TextPreprocessor(inputCol="text", outputCol="clean",
                             map={"good": "great"}), tab),
        "Timer": TestObject(
            Timer(stage=ValueIndexer(inputCol="cat", outputCol="ci")), tab),
        # gbdt
        "LightGBMClassifier": TestObject(
            LightGBMClassifier(**small_gbdt), tab, approx=1e-5),
        "LightGBMRegressor": TestObject(
            LightGBMRegressor(**small_gbdt), tab, approx=1e-5),
        "LightGBMRanker": TestObject(
            LightGBMRanker(groupCol="group", **small_gbdt),
            tab.with_column("group", np.repeat(np.arange(6), 10)),
            approx=1e-5),
        # vw
        "VowpalWabbitClassifier": TestObject(
            VowpalWabbitClassifier(numPasses=2), tab, approx=1e-5),
        "VowpalWabbitRegressor": TestObject(
            VowpalWabbitRegressor(numPasses=2), tab, approx=1e-5),
        "VowpalWabbitGeneric": TestObject(
            VowpalWabbitGeneric(numPasses=1), tab, approx=1e-5),
        "VowpalWabbitFeaturizer": TestObject(
            VowpalWabbitFeaturizer(inputCols=["x1", "cat"],
                                   outputCol="vwf"), tab),
        "VowpalWabbitInteractions": TestObject(
            VowpalWabbitInteractions(inputCols=["fa", "fb"], outputCol="q",
                                     numBits=10),
            VowpalWabbitFeaturizer(inputCols=["x2"], outputCol="fb",
                                   numBits=10).transform(
                VowpalWabbitFeaturizer(inputCols=["x1"], outputCol="fa",
                                       numBits=10).transform(tab))),
        "VowpalWabbitContextualBandit": TestObject(
            VowpalWabbitContextualBandit(numActions=2, numPasses=1), cb_df,
            approx=1e-5),
        "VowpalWabbitDSJsonTransformer": TestObject(
            VowpalWabbitDSJsonTransformer(), dsjson),
        "VowpalWabbitCSETransformer": TestObject(
            VowpalWabbitCSETransformer(),
            VowpalWabbitDSJsonTransformer().transform(dsjson)
            .with_column("probabilityPredicted", np.full(6, 0.7))),
        # nn / iforest / recommendation
        "KNN": TestObject(
            KNN(k=2), DataFrame({"features": _rng.normal(size=(20, 3)),
                                 "values": np.arange(20)})),
        "ConditionalKNN": TestObject(
            ConditionalKNN(k=2),
            DataFrame({"features": _rng.normal(size=(20, 3)),
                       "values": np.arange(20),
                       "label": _obj_col(["a", "b"] * 10),
                       "conditioner": _obj_col([["a"]] * 20)})),
        "IsolationForest": TestObject(
            IsolationForest(numEstimators=5), tab, approx=1e-5),
        "SAR": TestObject(SAR(supportThreshold=1), _interactions()),
        "RankingAdapter": TestObject(
            RankingAdapter(recommender=SAR(supportThreshold=1), k=3),
            _interactions()),
        "RankingTrainValidationSplit": TestObject(
            RankingTrainValidationSplit(estimator=SAR(supportThreshold=1),
                                        k=3, trainRatio=0.7),
            _interactions(), skip_serialization=True),
        # train / automl
        "TrainClassifier": TestObject(
            TrainClassifier(labelCol="label",
                            model=LightGBMClassifier(**small_gbdt)),
            tab.select("x1", "x2", "label"), approx=1e-5),
        "TrainRegressor": TestObject(
            TrainRegressor(labelCol="label",
                           model=LightGBMRegressor(**small_gbdt)),
            tab.select("x1", "x2", "label"), approx=1e-5),
        "ComputeModelStatistics": TestObject(
            ComputeModelStatistics(labelCol="label"), scored),
        "ComputePerInstanceStatistics": TestObject(
            ComputePerInstanceStatistics(labelCol="label"), scored),
        "TuneHyperparameters": TestObject(
            TuneHyperparameters(models=[LightGBMClassifier(**small_gbdt)],
                                numFolds=2, numRuns=1,
                                evaluationMetric="accuracy"),
            tab.select("features", "label"), skip_serialization=True),
        "FindBestModel": TestObject(
            FindBestModel(models=[LightGBMClassifier(**small_gbdt).fit(tab)],
                          evaluationMetric="accuracy"),
            tab, skip_serialization=True),
        # explainers
        "TabularLIME": TestObject(
            TabularLIME(model=_linear_model(), inputCols=["x1", "x2"],
                        backgroundData=tab, targetClasses=[1],
                        numSamples=40),
            tab.head(2), skip_serialization=True),
        "VectorLIME": TestObject(
            VectorLIME(model=_linear_model(), backgroundData=tab,
                       targetClasses=[1], numSamples=40),
            tab.head(2), skip_serialization=True),
        "TextLIME": TestObject(
            TextLIME(model=_TextProbe(), inputCol="text",
                     targetClasses=[1], numSamples=30),
            tab.head(2), skip_serialization=True),
        "TabularSHAP": TestObject(
            TabularSHAP(model=_linear_model(), inputCols=["x1", "x2"],
                        backgroundData=tab, targetClasses=[1],
                        numSamples=8, backgroundAverages=4),
            tab.head(2), skip_serialization=True),
        "VectorSHAP": TestObject(
            VectorSHAP(model=_linear_model(), backgroundData=tab,
                       targetClasses=[1], numSamples=8,
                       backgroundAverages=4),
            tab.head(2), skip_serialization=True),
        "TextSHAP": TestObject(
            TextSHAP(model=_TextProbe(), inputCol="text", targetClasses=[1],
                     numSamples=8),
            tab.head(2), skip_serialization=True),
        "ICETransformer": TestObject(
            ICETransformer(model=_linear_model(), kind="average",
                           targetClasses=[1],
                           numericFeatures=[{"name": "x1", "numSplits": 3}]),
            tab.head(5), skip_serialization=True),
        # causal
        "ResidualTransformer": TestObject(
            ResidualTransformer(observedCol="label", predictedCol="x1",
                                outputCol="res"), tab),
        "DoubleMLEstimator": TestObject(
            DoubleMLEstimator(
                treatmentModel=LightGBMRegressor(**small_gbdt),
                outcomeModel=LightGBMRegressor(**small_gbdt), maxIter=1),
            DataFrame({"features": _rng.normal(size=(60, 2)),
                       "treatment": (_rng.random(60) > 0.5).astype(float),
                       "outcome": _rng.normal(size=60)}),
            skip_serialization=True),
        "OrthoForestDMLEstimator": TestObject(
            OrthoForestDMLEstimator(
                treatmentModel=LightGBMRegressor(**small_gbdt),
                outcomeModel=LightGBMRegressor(**small_gbdt),
                numTrees=2, maxDepth=2, minSamplesLeaf=2),
            DataFrame({"features": _rng.normal(size=(60, 2)),
                       "heterogeneityVector": _rng.normal(size=(60, 1)),
                       "treatment": (_rng.random(60) > 0.5).astype(float),
                       "outcome": _rng.normal(size=60)}),
            skip_serialization=True),
        "DiffInDiffEstimator": TestObject(DiffInDiffEstimator(), panel),
        "SyntheticControlEstimator": TestObject(
            SyntheticControlEstimator(), panel, approx=1e-3),
        "SyntheticDiffInDiffEstimator": TestObject(
            SyntheticDiffInDiffEstimator(), panel, approx=1e-3),
        # cyber
        "IdIndexer": TestObject(
            IdIndexer(inputCol="user", outputCol="uid",
                      partitionKey="tenant"), access),
        "PartitionedStandardScaler": TestObject(
            PartitionedStandardScaler(inputCol="likelihood",
                                      outputCol="z"), access),
        "PartitionedMinMaxScaler": TestObject(
            PartitionedMinMaxScaler(inputCol="likelihood", outputCol="s"),
            access),
        "ComplementAccessTransformer": TestObject(
            ComplementAccessTransformer(
                tenantCol="tenant", indexedUserCol="user_idx",
                indexedResCol="res_idx"),
            DataFrame({"tenant": np.zeros(20, np.int64),
                       "user_idx": _rng.integers(1, 6, 20),
                       "res_idx": _rng.integers(1, 6, 20)}),
            skip_serialization=True),  # output is random complement draws
        "AccessAnomaly": TestObject(
            AccessAnomaly(maxIter=30, rankParam=4), access, approx=1e-4),
        # dl
        "DeepVisionClassifier": TestObject(
            DeepVisionClassifier(backbone="simple_cnn", batchSize=8,
                                 maxEpochs=1, labelCol="label"),
            _images(), approx=1e-4),
        "DeepTextClassifier": TestObject(
            DeepTextClassifier(batchSize=8, maxEpochs=1, labelCol="label",
                               maxLength=6, embeddingDim=16, numLayers=1,
                               numHeads=2),
            tab.head(16), approx=1e-4),
        "SentenceEmbedder": TestObject(
            SentenceEmbedder(inputCol="text", outputCol="emb", maxLength=6,
                             allowRandomEncoder=True,
                             embeddingDim=16, numLayers=1, numHeads=2),
            tab.head(8), skip_serialization=True),
        # image
        "ImageTransformer": TestObject(
            ImageTransformer(inputCol="image", outputCol="out").resize(8, 8),
            _images()),
        "ImageSetAugmenter": TestObject(
            ImageSetAugmenter(inputCol="image", outputCol="aug"), _images()),
        "UnrollImage": TestObject(
            UnrollImage(inputCol="image", outputCol="vec"), _images()),
        "SuperpixelTransformer": TestObject(
            SuperpixelTransformer(inputCol="image", cellSize=6.0), _images()),
        # onnx
        "ONNXModel": TestObject(
            ONNXModel(modelPayload=onnx_bytes,
                      feedDict={"x": "features"},
                      fetchDict={"out": "y"}), tab),
    }
    return reg


class _TextProbe(Transformer):
    def _transform(self, df):
        texts = [str(v) for v in df.col("text")]
        score = np.asarray([t.split().count("good") for t in texts],
                           np.float64)
        p = 1 / (1 + np.exp(-(score - 0.5)))
        return df.with_column("probability", np.stack([1 - p, p], axis=1))


def _tiny_onnx_model() -> bytes:
    from mmlspark_tpu.onnx.convert import pb

    w = _rng.normal(size=(2, 1)).astype(np.float32)
    t = pb.TensorProto()
    t.name = "w"
    t.dims.extend(w.shape)
    t.data_type = 1
    t.raw_data = np.ascontiguousarray(w).tobytes()
    n = pb.NodeProto()
    n.op_type = "MatMul"
    n.input.extend(["x", "w"])
    n.output.append("y")
    m = pb.ModelProto()
    m.ir_version = 8
    m.opset_import.add().version = 17
    m.graph.name = "g"
    m.graph.node.append(n)
    vi = pb.ValueInfoProto()
    vi.name = "x"
    vi.type.tensor_type.elem_type = 1
    m.graph.input.append(vi)
    vo = pb.ValueInfoProto()
    vo.name = "y"
    m.graph.output.append(vo)
    m.graph.initializer.append(t)
    return m.SerializeToString()


def fault_point_registry() -> Dict[str, str]:
    """Named fault-injection points the fuzzing/chaos suites can arm
    (the robustness analog of TestObject registration): the canonical
    list lives in :mod:`mmlspark_tpu.core.faults` (``KNOWN_POINTS``);
    this re-export keeps fuzzing drivers decoupled from core imports.
    Arm via ``mmlspark_tpu.core.faults.injected(name, action, ...)`` or
    ``MMLSPARK_TPU_FAULTS="name:action[:nth[:param]]"``. The
    completeness test (tests/gbdt/test_fault_injection.py) pins that
    every production ``fault_point("...")`` call site names a
    registered point."""
    from mmlspark_tpu.core.faults import KNOWN_POINTS
    return dict(KNOWN_POINTS)


# Stages with no TestObject, with the reason (FuzzingTest exemption-list
# parity, FuzzingTest.scala:19-80)
EXEMPT: Dict[str, str] = {
    "Pipeline": "exercised via every composite TestObject",
    "HTTPTransformer": "needs a live endpoint; covered by tests/io",
    "SimpleHTTPTransformer": "needs a live endpoint; covered by tests/io",
    "CognitiveServiceTransformer": "abstract base",
    "OpenAIChatCompletion": "needs a live endpoint; covered by tests/io",
    "OpenAIPrompt": "needs a live endpoint; covered by tests/io",
    "OpenAIEmbedding": "needs a live endpoint; covered by tests/io",
    "TextSentiment": "needs a live endpoint; covered by tests/io",
    "KeyPhraseExtractor": "needs a live endpoint; covered by tests/io",
    "LanguageDetector": "needs a live endpoint; covered by tests/io",
    "EntityRecognizer": "needs a live endpoint; covered by tests/io",
    "PIIRecognizer": "needs a live endpoint; covered by tests/io",
    "Translate": "needs a live endpoint; covered by tests/io",
    "DetectLastAnomaly": "needs a live endpoint; covered by tests/io",
    "DetectAnomalies": "needs a live endpoint; covered by tests/io",
    "AnalyzeImage": "needs a live endpoint; covered by tests/io",
    "DescribeImage": "needs a live endpoint; covered by tests/io",
    "OCR": "needs a live endpoint; covered by tests/io",
    "DetectFace": "needs a live endpoint; covered by tests/io",
    "AnalyzeDocument": "needs a live endpoint; covered by tests/io",
    "AnalyzeText": "needs a live endpoint; covered by tests/io",
    "AddDocuments": "needs a live endpoint; covered by tests/io",
    "SpeechToText": "needs a live endpoint; covered by tests/io",
    "SpeechToTextSDK": "needs a live endpoint; covered by tests/io",
    "TextToSpeech": "needs a live endpoint; covered by tests/io",
    "BingImageSearch": "needs a live endpoint; covered by tests/io",
    "AddressGeocoder": "needs a live endpoint; covered by tests/io",
    "ReverseAddressGeocoder": "needs a live endpoint; covered by tests/io",
    "CheckPointInPolygon": "needs a live endpoint; covered by tests/io",
    "FitMultivariateAnomaly": "needs a live endpoint; covered by tests/io",
    "ImageFeaturizer": "covered by tests/onnx with a real graph",
    "ImageLIME": "superpixel loop too slow for fuzzing; tests/explainers",
    "ImageSHAP": "superpixel loop too slow for fuzzing; tests/explainers",
    "LocalExplainer": "abstract base",
    "DeepEstimator": "abstract base",
    "VowpalWabbitGenericProgressive":
        "transform-only progressive mode; covered by tests/vw",
}
