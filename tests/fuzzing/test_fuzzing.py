"""The fuzzing backbone (Fuzzing.scala:604-631 parity):

- ExperimentFuzzing  — every TestObject fits/transforms without error;
- SerializationFuzzing — save/load round-trip + transform equality;
- GetterSetterFuzzing — explicitly-set simple params survive get/set;
- completeness — every Estimator/Transformer in the package has a
  TestObject or a justified exemption (FuzzingTest.scala:19-80).
"""

import importlib
import inspect
import pkgutil

import numpy as np
import pytest

import mmlspark_tpu
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.pipeline import (
    Estimator, Model, PipelineStage, Transformer,
)

from .registry import EXEMPT, TestObject, build_registry

REGISTRY = build_registry()


def _fit_or_self(obj: TestObject):
    stage = obj.stage
    if isinstance(stage, Estimator):
        return stage.fit(obj.fit_df)
    return stage


def _columns_equal(a, b, tol: float) -> bool:
    a = np.asarray(a)
    b = np.asarray(b)
    if a.dtype == object or b.dtype == object:
        return all(_cell_equal(x, y, tol) for x, y in zip(a, b))
    if a.dtype.kind in "fc":
        return np.allclose(a, b, atol=tol, equal_nan=True)
    return np.array_equal(a, b)


def _cell_equal(x, y, tol) -> bool:
    if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.dtype.kind in "fc" and ya.dtype.kind in "fc":
            return np.allclose(xa.astype(np.float64),
                               ya.astype(np.float64), atol=tol,
                               equal_nan=True)
        return np.array_equal(xa, ya)
    return x == y


@pytest.mark.parametrize("name", sorted(REGISTRY), ids=str)
def test_experiment_fuzzing(name):
    """fit + transform smoke (ExperimentFuzzing, Fuzzing.scala:424-440)."""
    obj = REGISTRY[name]
    fitted = _fit_or_self(obj)
    out = fitted.transform(obj.df_for_transform)
    assert isinstance(out, DataFrame)


@pytest.mark.parametrize(
    "name", sorted(k for k, v in REGISTRY.items()
                   if not v.skip_serialization), ids=str)
def test_serialization_fuzzing(name, tmp_path):
    """save/load round-trip + transform equality (SerializationFuzzing,
    Fuzzing.scala:456-504)."""
    obj = REGISTRY[name]
    fitted = _fit_or_self(obj)
    before = fitted.transform(obj.df_for_transform)
    path = str(tmp_path / name)
    fitted.save(path)
    loaded = PipelineStage.load(path)
    after = loaded.transform(obj.df_for_transform)
    cols = obj.compare_cols or [c for c in after.columns
                                if c in before.columns]
    for c in cols:
        assert _columns_equal(before.col(c), after.col(c), obj.approx), \
            f"column {c!r} differs after round-trip"


@pytest.mark.parametrize("name", sorted(REGISTRY), ids=str)
def test_getter_setter_fuzzing(name):
    """explicitly-set simple params survive a get/set cycle
    (GetterSetterFuzzing, Fuzzing.scala:546)."""
    obj = REGISTRY[name]
    stage = obj.stage
    for param, value in list(stage.iter_set_params()):
        if param.is_complex:
            continue
        clone = stage.copy()
        clone.set(param.name, value)
        assert clone.get(param.name) == value


def _all_stage_classes():
    out = {}
    for mod_info in pkgutil.walk_packages(mmlspark_tpu.__path__,
                                          prefix="mmlspark_tpu."):
        try:
            mod = importlib.import_module(mod_info.name)
        except Exception:
            continue
        for _, cls in inspect.getmembers(mod, inspect.isclass):
            if (issubclass(cls, (Estimator, Transformer))
                    and cls.__module__.startswith("mmlspark_tpu")
                    and not cls.__name__.startswith("_")
                    and not issubclass(cls, Model)
                    and cls not in (Estimator, Transformer)):
                out[cls.__name__] = cls
    return out


def test_registry_completeness():
    """Every public stage has a TestObject or a documented exemption
    (the FuzzingTest 'assertFuzzed' contract)."""
    classes = _all_stage_classes()
    missing = [n for n in classes
               if n not in REGISTRY and n not in EXEMPT]
    assert not missing, (
        f"stages without TestObjects or exemptions: {sorted(missing)}")
    stale = [n for n in EXEMPT if n not in classes]
    assert not stale, f"exemptions for unknown stages: {sorted(stale)}"


def test_all_stages_have_uids_and_docs():
    """uid convention + param docs (FuzzingTest's uid/doc assertions)."""
    for name, obj in REGISTRY.items():
        assert obj.stage.uid.startswith(type(obj.stage).__name__), name
        for p in obj.stage.params():
            assert p.doc, f"{name}.{p.name} lacks a doc string"
