"""Binned batch scoring (predict_binned_fn) vs raw-feature scoring.

The reference's inference baseline is the per-row JNI UDF re-comparing
float thresholds (booster/LightGBMBooster.scala:394,520-557). When the
caller holds the binned matrix, routing can compare uint8 bin ids
against the stored threshold_bin — results must be IDENTICAL to raw
scoring because threshold_value is exactly the upper edge of
threshold_bin (VERDICT r4 #4; tools/bench_scoring.py measures the A/B).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import BoosterArrays
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def _fit(rng, n=3000, f=10, max_bin=63, **cfg_kw):
    x = rng.normal(size=(n, f))
    y = (x[:, 0] * x[:, 1] + 0.5 * x[:, 2]
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=max_bin)
    binned = mapper.transform(x)
    kw = dict(objective="binary", num_iterations=8, num_leaves=31,
              max_depth=5, min_data_in_leaf=5, max_bin=max_bin)
    kw.update(cfg_kw)
    cfg = TrainConfig(**kw)
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(max_bin))
    return res.booster, mapper, x, binned


def test_binned_matches_raw_exactly(rng):
    booster, mapper, x, binned = _fit(rng)
    raw = np.asarray(booster.predict_jit()(x))
    via_bins = np.asarray(booster.predict_binned_jit()(binned))
    np.testing.assert_array_equal(raw, via_bins)


def test_binned_matches_raw_on_unseen_rows(rng):
    """Fresh rows binned by the SAME mapper must score identically:
    within a bin, raw comparison against the bin's upper edge and bin-id
    comparison against threshold_bin pick the same side."""
    booster, mapper, x, _ = _fit(rng)
    x_new = rng.normal(size=(500, x.shape[1]))
    raw = np.asarray(booster.predict_jit()(x_new))
    via_bins = np.asarray(booster.predict_binned_jit()(
        mapper.transform(x_new)))
    np.testing.assert_array_equal(raw, via_bins)


def test_binned_nan_routes_left_like_raw(rng):
    booster, mapper, x, _ = _fit(rng)
    x_nan = x[:200].copy()
    x_nan[::3, 0] = np.nan
    x_nan[::5, 2] = np.nan
    raw = np.asarray(booster.predict_jit()(x_nan))
    via_bins = np.asarray(booster.predict_binned_jit()(
        mapper.transform(x_nan)))
    np.testing.assert_array_equal(raw, via_bins)


def test_multiclass_binned(rng):
    booster, mapper, x, binned = _fit(
        rng, objective="multiclass", num_class=3)
    # rebuild labels appropriate for multiclass via a fresh fit
    x = rng.normal(size=(1500, 6))
    y = np.argmax(x[:, :3] + 0.1 * rng.normal(size=(1500, 3)),
                  axis=1).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=31)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="multiclass", num_class=3,
                      num_iterations=4, num_leaves=15, max_depth=4,
                      min_data_in_leaf=5, max_bin=31)
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(31))
    raw = np.asarray(res.booster.predict_jit()(x))
    via_bins = np.asarray(res.booster.predict_binned_jit()(binned))
    assert raw.shape == via_bins.shape == (1500, 3)
    np.testing.assert_array_equal(raw, via_bins)


def test_imported_model_string_refuses_binned(rng):
    booster, mapper, x, binned = _fit(rng)
    reimported = BoosterArrays.load_model_string(booster.save_model_string())
    # raw predictions survive the round trip…
    np.testing.assert_allclose(
        np.asarray(reimported.predict_jit()(x[:100])),
        np.asarray(booster.predict_jit()(x[:100])), rtol=1e-6, atol=1e-6)
    # …but bin thresholds do not exist in the text format
    with pytest.raises(ValueError, match="model string"):
        reimported.predict_binned_fn()


def test_categorical_model_refuses_binned(rng):
    n = 1200
    cat = rng.integers(0, 8, size=n).astype(np.float64)
    x = np.stack([cat, rng.normal(size=n)], axis=1)
    y = (np.isin(cat, [1, 3, 5]).astype(np.float64)
         + 0.05 * rng.normal(size=n) > 0.5).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=31, categorical_features=[0])
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="binary", num_iterations=3, num_leaves=7,
                      max_depth=3, min_data_in_leaf=5, max_bin=31,
                      categorical_features=(0,))
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(31))
    if res.booster.has_categorical:
        with pytest.raises(NotImplementedError, match="categorical"):
            res.booster.predict_binned_fn()
