"""Binned batch scoring (predict_binned_fn) vs raw-feature scoring.

The reference's inference baseline is the per-row JNI UDF re-comparing
float thresholds (booster/LightGBMBooster.scala:394,520-557). When the
caller holds the binned matrix, routing can compare uint8 bin ids
against the stored threshold_bin — results must be IDENTICAL to raw
scoring because threshold_value is exactly the upper edge of
threshold_bin (VERDICT r4 #4; tools/bench_scoring.py measures the A/B).
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import BoosterArrays
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def _fit(rng, n=3000, f=10, max_bin=63, **cfg_kw):
    x = rng.normal(size=(n, f))
    y = (x[:, 0] * x[:, 1] + 0.5 * x[:, 2]
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=max_bin)
    binned = mapper.transform(x)
    kw = dict(objective="binary", num_iterations=8, num_leaves=31,
              max_depth=5, min_data_in_leaf=5, max_bin=max_bin)
    kw.update(cfg_kw)
    cfg = TrainConfig(**kw)
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(max_bin))
    return res.booster, mapper, x, binned


def test_binned_matches_raw_exactly(rng):
    booster, mapper, x, binned = _fit(rng)
    raw = np.asarray(booster.predict_jit()(x))
    via_bins = np.asarray(booster.predict_binned_jit()(binned))
    np.testing.assert_array_equal(raw, via_bins)


def test_binned_matches_raw_on_unseen_rows(rng):
    """Fresh rows binned by the SAME mapper must score identically:
    within a bin, raw comparison against the bin's upper edge and bin-id
    comparison against threshold_bin pick the same side."""
    booster, mapper, x, _ = _fit(rng)
    x_new = rng.normal(size=(500, x.shape[1]))
    raw = np.asarray(booster.predict_jit()(x_new))
    via_bins = np.asarray(booster.predict_binned_jit()(
        mapper.transform(x_new)))
    np.testing.assert_array_equal(raw, via_bins)


def test_binned_nan_routes_left_like_raw(rng):
    booster, mapper, x, _ = _fit(rng)
    x_nan = x[:200].copy()
    x_nan[::3, 0] = np.nan
    x_nan[::5, 2] = np.nan
    raw = np.asarray(booster.predict_jit()(x_nan))
    via_bins = np.asarray(booster.predict_binned_jit()(
        mapper.transform(x_nan)))
    np.testing.assert_array_equal(raw, via_bins)


def test_multiclass_binned(rng):
    booster, mapper, x, binned = _fit(
        rng, objective="multiclass", num_class=3)
    # rebuild labels appropriate for multiclass via a fresh fit
    x = rng.normal(size=(1500, 6))
    y = np.argmax(x[:, :3] + 0.1 * rng.normal(size=(1500, 3)),
                  axis=1).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=31)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="multiclass", num_class=3,
                      num_iterations=4, num_leaves=15, max_depth=4,
                      min_data_in_leaf=5, max_bin=31)
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(31))
    raw = np.asarray(res.booster.predict_jit()(x))
    via_bins = np.asarray(res.booster.predict_binned_jit()(binned))
    assert raw.shape == via_bins.shape == (1500, 3)
    np.testing.assert_array_equal(raw, via_bins)


def test_imported_model_string_refuses_binned(rng):
    booster, mapper, x, binned = _fit(rng)
    reimported = BoosterArrays.load_model_string(booster.save_model_string())
    # raw predictions survive the round trip…
    np.testing.assert_allclose(
        np.asarray(reimported.predict_jit()(x[:100])),
        np.asarray(booster.predict_jit()(x[:100])), rtol=1e-6, atol=1e-6)
    # …but bin thresholds do not exist in the text format
    with pytest.raises(ValueError, match="model string"):
        reimported.predict_binned_fn()


def test_categorical_model_refuses_binned(rng):
    n = 1200
    cat = rng.integers(0, 8, size=n).astype(np.float64)
    x = np.stack([cat, rng.normal(size=n)], axis=1)
    y = (np.isin(cat, [1, 3, 5]).astype(np.float64)
         + 0.05 * rng.normal(size=n) > 0.5).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=31, categorical_features=[0])
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="binary", num_iterations=3, num_leaves=7,
                      max_depth=3, min_data_in_leaf=5, max_bin=31,
                      categorical_features=(0,))
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(31))
    if res.booster.has_categorical:
        with pytest.raises(NotImplementedError, match="categorical"):
            res.booster.predict_binned_fn()


# -- derived binning for imported model strings ---------------------------

def _import_roundtrip(booster):
    return BoosterArrays.load_model_string(booster.save_model_string())


def test_derived_binning_matches_raw_exactly(rng):
    """An imported model string carries raw thresholds only; deriving a
    binning from its own splits must reproduce predict_fn exactly."""
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    with pytest.raises(ValueError, match="no binned thresholds"):
        imported.predict_binned_fn()
    binning, derived = imported.derive_binning()
    raw = np.asarray(imported.predict_jit()(x))
    via = np.asarray(derived.predict_binned_jit()(binning.transform(x)))
    np.testing.assert_array_equal(raw, via)
    # unseen rows too (values beyond every threshold, between thresholds)
    x_new = rng.normal(size=(500, x.shape[1])) * 3
    np.testing.assert_array_equal(
        np.asarray(imported.predict_jit()(x_new)),
        np.asarray(derived.predict_binned_jit()(binning.transform(x_new))))


def test_derived_binning_threshold_boundary_rows(rng):
    """Rows sitting EXACTLY on split thresholds route inclusively
    (x <= t goes left) in both paths."""
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    binning, derived = imported.derive_binning()
    internal = imported.split_feature >= 0
    feats = imported.split_feature[internal]
    thrs = imported.threshold_value[internal]
    x_edge = np.tile(x[:1], (min(64, len(feats)), 1))
    for i in range(x_edge.shape[0]):
        x_edge[i, feats[i]] = thrs[i]
    np.testing.assert_array_equal(
        np.asarray(imported.predict_jit()(x_edge)),
        np.asarray(derived.predict_binned_jit()(
            binning.transform(x_edge))))


def test_derived_binning_nan_policy_uniform(rng):
    """Imported trees carry decision_type; NaN routes per the (uniform)
    per-feature default direction in both paths."""
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    binning, derived = imported.derive_binning()
    x_nan = x[:200].copy()
    x_nan[::3, 0] = np.nan
    x_nan[::5, 2] = np.nan
    raw = np.asarray(imported.predict_jit()(x_nan))
    via = np.asarray(derived.predict_binned_jit()(
        binning.transform(x_nan)))
    np.testing.assert_array_equal(raw, via)


def test_derived_binning_mixed_nan_directions_refused(rng):
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    # force mixed NaN default directions on feature 0's nodes
    dt = np.array(imported.decision_type, copy=True) \
        if imported.decision_type is not None \
        else np.zeros_like(imported.split_feature, dtype=np.int8)
    nodes = np.nonzero(imported.split_feature == 0)
    assert len(nodes[0]) >= 2, "fixture needs >= 2 splits on feature 0"
    # missing_type nan (2 << 2 = 8); alternate default-left bit
    for i, (t, m) in enumerate(zip(*nodes)):
        dt[t, m] = np.int8(8 | (2 if i % 2 == 0 else 0))
    import dataclasses
    mixed = dataclasses.replace(imported, decision_type=dt)
    binning, derived = mixed.derive_binning()
    x_nan = x[:50].copy()
    x_nan[::2, 0] = np.nan
    with pytest.raises(ValueError, match="mixes NaN default directions"):
        binning.transform(x_nan)
    # finite rows still fine and exact
    np.testing.assert_array_equal(
        np.asarray(mixed.predict_jit()(x[:100])),
        np.asarray(derived.predict_binned_jit()(
            binning.transform(x[:100]))))


def test_derived_binning_zero_as_missing(rng):
    """All-nodes zero-as-missing with a uniform direction maps exact
    0.0 to the sentinel bin; both paths agree."""
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    dt = np.zeros_like(imported.split_feature, dtype=np.int8)
    internal = imported.split_feature >= 0
    # missing_type zero (1 << 2 = 4) + default-left (2) on every node
    dt[internal] = np.int8(4 | 2)
    import dataclasses
    zmodel = dataclasses.replace(imported, decision_type=dt)
    binning, derived = zmodel.derive_binning()
    x_z = x[:200].copy()
    x_z[::4, 0] = 0.0
    x_z[::7, 3] = 0.0
    np.testing.assert_array_equal(
        np.asarray(zmodel.predict_jit()(x_z)),
        np.asarray(derived.predict_binned_jit()(binning.transform(x_z))))


def test_derived_binning_dtype_is_narrow(rng):
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    binning, _ = imported.derive_binning()
    assert binning.transform(x[:10]).dtype == np.uint8


def _with_decision(imported, dt_val):
    import dataclasses
    dt = np.zeros_like(imported.split_feature, dtype=np.int8)
    dt[imported.split_feature >= 0] = np.int8(dt_val)
    return dataclasses.replace(imported, decision_type=dt)


def test_derived_binning_nan_right_policy(rng):
    """All nodes NaN-missing + default-RIGHT: NaN maps past every
    threshold (bin k+1) and both paths agree."""
    booster, mapper, x, _ = _fit(rng)
    # missing_type nan (2 << 2 = 8), default-left bit clear
    model = _with_decision(_import_roundtrip(booster), 8)
    binning, derived = model.derive_binning()
    assert (binning.nan_bin[[len(t) > 0 for t in binning.thresholds]]
            > 0).all()
    x_nan = x[:200].copy()
    x_nan[::3, 0] = np.nan
    x_nan[::5, 2] = np.nan
    np.testing.assert_array_equal(
        np.asarray(model.predict_jit()(x_nan)),
        np.asarray(derived.predict_binned_jit()(
            binning.transform(x_nan))))


@pytest.mark.parametrize("dt_val", [0, 12])
def test_derived_binning_nan_compares_as_zero_policy(rng, dt_val):
    """missing_type none (0) — and the out-of-spec bits value 3 (12)
    which _go_left_fn also treats as compare — converts NaN to 0.0
    before the threshold compare; the derived binning maps NaN to
    bin(0.0)."""
    booster, mapper, x, _ = _fit(rng)
    model = _with_decision(_import_roundtrip(booster), dt_val)
    binning, derived = model.derive_binning()
    x_nan = x[:200].copy()
    x_nan[::3, 0] = np.nan
    x_nan[::4, 1] = np.nan
    x_nan[::5, 2] = np.nan
    np.testing.assert_array_equal(
        np.asarray(model.predict_jit()(x_nan)),
        np.asarray(derived.predict_binned_jit()(
            binning.transform(x_nan))))


# -- model-level auto-binned transform ------------------------------------

def _fit_model(rng, n=2500, f=6, **params):
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = (x[:, 0] * x[:, 1] + 0.5 * x[:, 2] > 0).astype(np.float64)
    df = DataFrame({"features": x, "label": y})
    m = LightGBMClassifier(numIterations=6, numLeaves=15,
                           **params).fit(df)
    return m, df, x


def test_model_transform_uses_binned_path_identically(rng):
    m, df, x = _fit_model(rng)
    assert m.bin_mapper is not None
    m.set("binnedScoring", True)
    p_binned = np.asarray(m.transform(df)["probability"])
    m.set("binnedScoring", False)
    p_raw = np.asarray(m.transform(df)["probability"])
    np.testing.assert_array_equal(p_binned, p_raw)


def test_model_transform_binned_survives_save_load(rng, tmp_path):
    from mmlspark_tpu.core.pipeline import PipelineStage
    m, df, x = _fit_model(rng)
    p0 = np.asarray(m.transform(df)["probability"])
    m.set("binnedScoring", True)
    m.save(str(tmp_path / "m"))
    loaded = PipelineStage.load(str(tmp_path / "m"))
    assert loaded.bin_mapper is not None
    assert loaded.get("binnedScoring") is True
    np.testing.assert_array_equal(
        p0, np.asarray(loaded.transform(df)["probability"]))


def test_model_transform_nan_rows_identical(rng):
    from mmlspark_tpu.core.dataframe import DataFrame
    m, df, x = _fit_model(rng)
    x_nan = x[:300].copy()
    x_nan[::3, 0] = np.nan
    dfn = DataFrame({"features": x_nan})
    m.set("binnedScoring", True)
    p_binned = np.asarray(m.transform(dfn)["probability"])
    m.set("binnedScoring", False)
    p_raw = np.asarray(m.transform(dfn)["probability"])
    np.testing.assert_array_equal(p_binned, p_raw)


def test_model_transform_categorical_falls_back(rng):
    """Categorical models can't route by bin compare; transform must
    silently use the raw path and still work."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
    n = 2500
    xc = rng.integers(0, 8, size=n).astype(np.float32)
    xn = rng.normal(size=(n, 2)).astype(np.float32)
    x = np.column_stack([xc, xn])
    y = ((xc % 2 == 0) ^ (xn[:, 0] > 0)).astype(np.float64)
    df = DataFrame({"features": x, "label": y})
    m = LightGBMClassifier(numIterations=6, numLeaves=15,
                           categoricalSlotIndexes=[0]).fit(df)
    if not m.booster.has_categorical:
        pytest.skip("fixture produced no categorical splits")
    out = m.transform(df)
    p = np.asarray(out["probability"])
    assert np.isfinite(p).all()


def test_model_transform_zero_as_missing_identical(rng):
    """zeroAsMissing models premap 0.0 -> NaN at fit; the binned
    scoring gate must apply the same premap (review catch: without it
    zeros bin normally and route differently than predict_fn)."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
    n = 2500
    x = rng.normal(size=(n, 5)).astype(np.float32)
    x[rng.random((n, 5)) < 0.15] = 0.0   # plenty of exact zeros
    y = ((x[:, 0] > 0.3) ^ (x[:, 1] < -0.2)).astype(np.float64)
    df = DataFrame({"features": x, "label": y})
    m = LightGBMClassifier(numIterations=8, numLeaves=15,
                           zeroAsMissing=True).fit(df)
    assert m.booster.zero_premap_mode == "all_left"
    m.set("binnedScoring", True)
    p_binned = np.asarray(m.transform(df)["probability"])
    m.set("binnedScoring", False)
    p_raw = np.asarray(m.transform(df)["probability"])
    np.testing.assert_array_equal(p_binned, p_raw)


def test_zero_premap_mode_mixed_is_unsupported(rng):
    import dataclasses
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    dt = np.zeros_like(imported.split_feature, dtype=np.int8)
    internal = imported.split_feature >= 0
    dt[internal] = np.int8(4 | 2)          # zero-missing, left
    t, mlist = np.nonzero(internal)
    dt[t[0], mlist[0]] = np.int8(4)        # one node: zero-missing, right
    mixed = dataclasses.replace(imported, decision_type=dt)
    assert mixed.zero_premap_mode == "unsupported"


def test_derived_binning_uint16_tier(rng):
    """A model with >255 distinct thresholds on one feature pushes the
    derived binning into the uint16 dtype tier; scoring stays exact."""
    import dataclasses
    booster, mapper, x, _ = _fit(rng)
    imported = _import_roundtrip(booster)
    # widen feature 0's threshold table artificially: give every
    # feature-0 node a distinct threshold and synthesize extras by
    # cloning trees with shifted thresholds
    tv = np.array(imported.threshold_value, copy=True)
    sf = imported.split_feature
    reps = []
    for shift in np.linspace(-3, 3, 40):
        t2 = np.array(tv, copy=True)
        t2[sf == 0] += shift
        reps.append(dataclasses.replace(imported, threshold_value=t2))
    big = dataclasses.replace(
        imported,
        split_feature=np.concatenate([r.split_feature for r in reps]),
        threshold_bin=np.concatenate([r.threshold_bin for r in reps]),
        threshold_value=np.concatenate([r.threshold_value for r in reps]),
        node_value=np.concatenate([r.node_value for r in reps]),
        count=np.concatenate([r.count for r in reps]),
        tree_weights=np.concatenate([r.tree_weights for r in reps]),
        decision_type=(None if imported.decision_type is None else
                       np.concatenate([imported.decision_type] * len(reps))))
    binning, derived = big.derive_binning()
    if binning.num_bins <= 256:
        pytest.skip("fixture did not exceed 256 thresholds")
    xb = binning.transform(x[:500])
    assert xb.dtype == np.uint16
    np.testing.assert_array_equal(
        np.asarray(big.predict_jit()(x[:500])),
        np.asarray(derived.predict_binned_jit()(xb)))
