"""Categorical split handling (VERDICT r2 #4).

Parity target: LightGBM's categorical algorithm surfaced through
``categoricalSlotIndexes`` (params/LightGBMParams.scala categorical
group, core/schema/Categoricals.scala) — per-category histograms,
gradient-ratio sorted subset selection, bitset export in the model
string, set-membership routing.
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import BoosterArrays
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def _cat_dataset(n=4000, k=24, seed=0):
    """Label depends on membership of a scattered category subset, so no
    single ordered threshold separates it."""
    rng = np.random.default_rng(seed)
    cats = rng.integers(0, k, size=n)
    good = np.array([1, 4, 7, 11, 14, 17, 20, 23])
    noise = rng.normal(size=n)
    y = (np.isin(cats, good) & (noise > -1.0)).astype(np.float64)
    x = np.stack([cats.astype(np.float64), noise], axis=1)
    return x, y, good


def _fit(x, y, categorical, num_iterations=20, **kw):
    cat_idx = [0] if categorical else []
    mapper = BinMapper.fit(x, max_bin=64, categorical_features=cat_idx)
    binned = mapper.transform(x)
    # small fixtures have < 100 rows per category, so the LightGBM
    # default min_data_per_group would filter every sorted-scan
    # candidate (test_min_data_per_group pins that behavior)
    cfg = TrainConfig(objective="binary", num_iterations=num_iterations,
                      num_leaves=8, max_depth=3, min_data_in_leaf=5,
                      max_bin=64, categorical_features=tuple(cat_idx),
                      **{"min_data_per_group": 10, **kw})
    result = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(64))
    return result, mapper


def _accuracy(booster, x, y):
    raw = np.asarray(booster.predict_jit()(x))
    return float(((raw > 0) == (y > 0.5)).mean())


class TestCategoricalSplits:
    def test_categorical_beats_ordinal(self):
        x, y, _ = _cat_dataset()
        res_cat, _ = _fit(x, y, categorical=True)
        res_ord, _ = _fit(x, y, categorical=False)
        acc_cat = _accuracy(res_cat.booster, x, y)
        acc_ord = _accuracy(res_ord.booster, x, y)
        # scattered subset: set splits isolate it in depth-3 trees,
        # ordered thresholds cannot
        assert acc_cat > acc_ord + 0.02
        assert acc_cat > 0.9

    def test_decision_type_marks_cat_nodes(self):
        x, y, _ = _cat_dataset()
        res, _ = _fit(x, y, categorical=True, num_iterations=3)
        b = res.booster
        assert b.decision_type is not None and b.cat_bitset is not None
        cat_nodes = (b.decision_type & 1) == 1
        assert cat_nodes.any()
        # cat nodes split on feature 0 and carry a nonempty bitset
        assert (b.split_feature[cat_nodes] == 0).all()
        assert (b.cat_bitset[cat_nodes] != 0).any(axis=-1).all()
        # numerical splits carry default-left + NaN-missing bits (10),
        # never the cat bit
        num_nodes = (b.split_feature == 1)
        assert (b.decision_type[num_nodes] == 10).all()

    def test_binned_and_raw_prediction_agree(self):
        x, y, _ = _cat_dataset(n=1500)
        res, mapper = _fit(x, y, categorical=True, num_iterations=5)
        raw_scores = np.asarray(res.booster.predict_jit()(x))
        # independent numpy walk over the exported arrays
        b = res.booster
        acc = np.full(len(x), b.init_score, dtype=np.float64)
        for t in range(b.num_trees):
            node = np.zeros(len(x), dtype=np.int64)
            for _ in range(b.max_depth):
                feat = b.split_feature[t][node]
                leaf = feat < 0
                fx = x[np.arange(len(x)), np.maximum(feat, 0)]
                is_cat = (b.decision_type[t][node] & 1) == 1
                vi = fx.astype(np.int64)
                w = b.cat_bitset.shape[2]
                ok = (fx >= 0) & (fx < w * 32) & (fx == np.floor(fx))
                member = np.zeros(len(x), dtype=bool)
                iv = np.clip(vi, 0, w * 32 - 1)
                words = b.cat_bitset[t][node, iv // 32]
                member = ((words >> (iv % 32).astype(np.uint32)) & 1) == 1
                go_left = np.where(is_cat, ok & member,
                                   np.isnan(fx) | (fx <= b.threshold_value[t][node]))
                child = np.where(go_left, 2 * node + 1, 2 * node + 2)
                node = np.where(leaf, node, child)
            acc += b.node_value[t][node] * b.tree_weights[t]
        np.testing.assert_allclose(raw_scores, acc, atol=1e-5)

    def test_model_string_roundtrip_with_cats(self):
        x, y, _ = _cat_dataset(n=1200)
        res, _ = _fit(x, y, categorical=True, num_iterations=4)
        text = res.booster.save_model_string()
        assert any(f"num_cat={n}" in text for n in range(1, 20))
        assert "cat_boundaries=" in text and "cat_threshold=" in text
        loaded = BoosterArrays.load_model_string(text)
        assert loaded.has_categorical
        p0 = np.asarray(res.booster.predict_jit()(x))
        p1 = np.asarray(loaded.predict_jit()(x))
        np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-5)

    def test_unseen_and_missing_categories_route_right(self):
        x, y, _ = _cat_dataset(n=1500)
        res, _ = _fit(x, y, categorical=True, num_iterations=5)
        b = res.booster
        # craft rows whose cat value was never seen (or missing)
        x_unseen = x.copy()[:4]
        x_unseen[:, 0] = [999.0, -5.0, 3.5, np.nan]
        # routing must take the right-child path at every cat node: same
        # as any seen value NOT in the left set. Just assert it runs and
        # produces finite outputs (the walk would crash/UB on a bad
        # gather otherwise) and that NaN == unseen-category behavior.
        p = np.asarray(b.predict_jit()(x_unseen))
        assert np.isfinite(p).all()
        assert p[0] == pytest.approx(p[3], abs=1e-6)  # 999 ≡ NaN (both right)

    def test_fractional_category_truncates_like_lightgbm(self):
        """LightGBM's CategoricalDecision does static_cast<int>(fval):
        3.7 scores as category 3, not as unseen (ADVICE r3)."""
        x, y, _ = _cat_dataset(n=1500)
        res, _ = _fit(x, y, categorical=True, num_iterations=5)
        predict = res.booster.predict_jit()
        base = np.asarray(predict(x[:8]))
        x_frac = x[:8].copy()
        x_frac[:, 0] = np.trunc(x_frac[:, 0]) + 0.7
        np.testing.assert_allclose(np.asarray(predict(x_frac)), base,
                                   rtol=1e-6, atol=1e-6)

    def test_onehot_mode_low_cardinality(self):
        rng = np.random.default_rng(3)
        n = 2000
        cats = rng.integers(0, 3, size=n)  # 3 cats <= max_cat_to_onehot
        y = (cats == 1).astype(np.float64)
        x = cats[:, None].astype(np.float64)
        mapper = BinMapper.fit(x, max_bin=16, categorical_features=[0])
        binned = mapper.transform(x)
        cfg = TrainConfig(objective="binary", num_iterations=3,
                          num_leaves=4, max_depth=2, min_data_in_leaf=5,
                          max_bin=16, categorical_features=(0,))
        res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(16))
        b = res.booster
        assert b.has_categorical
        # root must isolate category 1 alone on one side
        root_bits = b.cat_bitset[0, 0]
        vals = [v for v in range(16) if (root_bits[v // 32] >> (v % 32)) & 1]
        assert vals == [1]
        acc = _accuracy(b, x, y)
        assert acc > 0.99

    def test_estimator_api_with_categoricals(self):
        from mmlspark_tpu.core.dataframe import DataFrame
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

        x, y, _ = _cat_dataset(n=1200)
        df = DataFrame({"features": x, "label": y})
        model = LightGBMClassifier(
            numIterations=8, numLeaves=8, maxDepth=3, maxBin=64,
            categoricalSlotIndexes=[0], minDataPerGroup=10).fit(df)
        out = model.transform(df)
        acc = float((out["prediction"] == y).mean())
        assert acc > 0.85
        # native model string round-trips through the model API
        text = model.get_model_string()
        reloaded = type(model).load_native_model_from_string(text)
        out2 = reloaded.transform(df)
        np.testing.assert_allclose(out["prediction"], out2["prediction"])

    def test_voting_mode_rejects_categoricals(self, mesh8):
        x, y, _ = _cat_dataset(n=600)
        mapper = BinMapper.fit(x, max_bin=16, categorical_features=[0])
        binned = mapper.transform(x)
        cfg = TrainConfig(objective="binary", num_iterations=2,
                          num_leaves=4, max_depth=2, max_bin=16,
                          categorical_features=(0,), tree_learner="voting")
        with pytest.raises(NotImplementedError):
            train(binned, y, cfg, bin_upper=mapper.bin_upper_values(16),
                  mesh=mesh8)

    def test_min_data_in_leaf_respected(self):
        x, y, _ = _cat_dataset(n=800)
        res, _ = _fit(x, y, categorical=True, num_iterations=5,
                      min_gain_to_split=0.0)
        b = res.booster
        internal = b.split_feature >= 0
        left = b.count[:, 1::2] if b.num_nodes > 1 else None
        # every realized child of a split has >= min_data_in_leaf rows
        for t in range(b.num_trees):
            for m in np.nonzero(internal[t])[0]:
                assert b.count[t, 2 * m + 1] >= 5
                assert b.count[t, 2 * m + 2] >= 5


class TestCategoricalMetadataPlumbing:
    """Categoricals metadata flows ValueIndexer -> VectorAssembler ->
    LightGBM auto-detection (core/schema/Categoricals.scala analog)."""

    def _pipeline_df(self, rng):
        from mmlspark_tpu.core.dataframe import DataFrame

        n, k = 2000, 16
        cats = rng.integers(0, k, size=n)
        good = np.array([2, 5, 9, 13])
        noise = rng.normal(size=n)
        y = (np.isin(cats, good) & (noise > -1)).astype(np.float64)
        color = np.asarray([f"c{c}" for c in cats], dtype=object)
        return DataFrame({"color": color, "num": noise, "label": y}), y

    def test_auto_detection_via_metadata(self, rng):
        from mmlspark_tpu.featurize.assemble import VectorAssembler
        from mmlspark_tpu.featurize.indexer import ValueIndexer
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

        df, y = self._pipeline_df(rng)
        indexed = ValueIndexer(inputCol="color",
                               outputCol="color_idx").fit(df).transform(df)
        assembled = VectorAssembler(
            inputCols=["color_idx", "num"], outputCol="features"
        ).transform(indexed)
        meta = assembled.metadata("features")
        assert meta["categorical_slots"] == [0]
        assert meta["slots"] == ["color_idx", "num"]

        # no categoricalSlotIndexes set: detected from metadata
        est = LightGBMClassifier(numIterations=10, numLeaves=8, maxDepth=3,
                                 maxBin=32)
        assert est._categorical_indexes(assembled) == [0]
        model = est.fit(assembled)
        assert model.booster.has_categorical
        acc = float((model.transform(assembled)["prediction"] == y).mean())
        assert acc > 0.9

    def test_categorical_slot_names(self, rng):
        from mmlspark_tpu.featurize.assemble import VectorAssembler
        from mmlspark_tpu.featurize.indexer import ValueIndexer
        from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

        df, y = self._pipeline_df(rng)
        indexed = ValueIndexer(inputCol="color",
                               outputCol="color_idx").fit(df).transform(df)
        assembled = VectorAssembler(
            inputCols=["num", "color_idx"], outputCol="features"
        ).transform(indexed)
        est = LightGBMClassifier(categoricalSlotNames=["color_idx"],
                                 numIterations=2, numLeaves=4, maxBin=16)
        assert est._categorical_indexes(assembled) == [1]
        with pytest.raises(ValueError, match="no feature slot named"):
            LightGBMClassifier(categoricalSlotNames=["nope"]
                               )._categorical_indexes(assembled)
