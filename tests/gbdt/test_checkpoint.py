"""Mid-training checkpoints + elastic restart (SURVEY.md §5
checkpoint/resume: the reference has model-string warm start but no
mid-iteration checkpoints; here fit segments through warm starts with
continued RNG streams)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor


@pytest.fixture()
def reg_df(rng):
    x = rng.normal(size=(800, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=800) * 0.1
    return DataFrame({"features": x, "label": y}), x, y


def test_checkpointed_fit_matches_monolithic(reg_df, tmp_path):
    df, x, y = reg_df
    kw = dict(numIterations=12, numLeaves=8, maxBin=32)
    mono = LightGBMRegressor(**kw).fit(df)
    ck = LightGBMRegressor(checkpointDir=str(tmp_path / "ck"),
                           checkpointInterval=5, **kw).fit(df)
    # deterministic config: segmented == monolithic bit-for-bit
    np.testing.assert_allclose(
        np.asarray(mono.transform(df)["prediction"]),
        np.asarray(ck.transform(df)["prediction"]), atol=1e-5)
    # checkpoints at 5, 10, 12 exist (plus the fingerprint sidecar)
    names = sorted(n for n in os.listdir(tmp_path / "ck")
                   if n.endswith(".txt"))
    assert names == ["checkpoint_10.txt", "checkpoint_12.txt",
                     "checkpoint_5.txt"]
    assert (tmp_path / "ck" / "checkpoint_meta.json").exists()


def test_elastic_restart_resumes_from_checkpoint(reg_df, tmp_path):
    df, x, y = reg_df
    ckdir = str(tmp_path / "ck")
    kw = dict(numIterations=12, numLeaves=8, maxBin=32,
              checkpointDir=ckdir, checkpointInterval=4)
    # simulate a crash: run a full fit, then delete later checkpoints so
    # only iteration 4 survives
    LightGBMRegressor(**kw).fit(df)
    for n in ("checkpoint_8.txt", "checkpoint_12.txt"):
        os.remove(os.path.join(ckdir, n))
    # the restarted fit resumes at iteration 4 and reproduces the full run
    resumed = LightGBMRegressor(**kw).fit(df)
    assert resumed.booster.num_trees == 12
    fresh = LightGBMRegressor(numIterations=12, numLeaves=8,
                              maxBin=32).fit(df)
    np.testing.assert_allclose(
        np.asarray(resumed.transform(df)["prediction"]),
        np.asarray(fresh.transform(df)["prediction"]), atol=1e-5)


def test_resume_refuses_mismatched_config(reg_df, tmp_path):
    """A refit with changed params or data must not warm-start from an
    incompatible checkpoint (ADVICE r3: config/data fingerprint)."""
    df, x, y = reg_df
    ckdir = str(tmp_path / "ck")
    kw = dict(numIterations=8, numLeaves=8, maxBin=32,
              checkpointDir=ckdir, checkpointInterval=4)
    LightGBMRegressor(**kw).fit(df)
    # changed hyperparams -> refuse
    with pytest.raises(ValueError, match="different config or dataset"):
        LightGBMRegressor(**{**kw, "numLeaves": 16}).fit(df)
    # changed data -> refuse
    df2 = DataFrame({"features": x + 1.0, "label": y})
    with pytest.raises(ValueError, match="different config or dataset"):
        LightGBMRegressor(**kw).fit(df2)
    # raised iteration budget with same config/data -> allowed
    more = LightGBMRegressor(**{**kw, "numIterations": 12}).fit(df)
    assert more.booster.num_trees == 12


def test_checkpointed_fit_with_sampling_matches(reg_df, tmp_path):
    """iteration_offset continues the device RNG streams, so bagging and
    GOSS segment identically to a monolithic fused run."""
    df, x, y = reg_df
    for extra in (dict(baggingFraction=0.7, baggingFreq=2),
                  dict(boostingType="goss")):
        kw = dict(numIterations=8, numLeaves=8, maxBin=32, **extra)
        mono = LightGBMRegressor(**kw).fit(df)
        ck = LightGBMRegressor(
            checkpointDir=str(tmp_path / f"ck_{list(extra)[0]}"),
            checkpointInterval=3, **kw).fit(df)
        np.testing.assert_allclose(
            np.asarray(mono.transform(df)["prediction"]),
            np.asarray(ck.transform(df)["prediction"]), atol=1e-4)

