"""Classifier accuracy + API parity tests.

Patterned on the reference's benchmark-CSV regression approach
(core/.../benchmarks/Benchmarks.scala:15-70 with
benchmarks_VerifyLightGBMClassifierStreamBasic.csv): named metric values
asserted against committed expectations with tolerance, across boosting
types.
"""

import numpy as np
import pytest
from sklearn.datasets import load_breast_cancer, load_iris
from sklearn.metrics import roc_auc_score
from sklearn.model_selection import train_test_split

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt import (
    LightGBMClassificationModel,
    LightGBMClassifier,
)


def binary_dfs():
    X, y = load_breast_cancer(return_X_y=True)
    Xtr, Xte, ytr, yte = train_test_split(X, y, test_size=0.3, random_state=0)
    return (DataFrame({"features": Xtr, "label": ytr.astype(np.float64)}),
            DataFrame({"features": Xte, "label": yte.astype(np.float64)}))


# committed AUC expectations (tolerance matches the reference's ±0.07 style)
BENCHMARKS = {"gbdt": 0.99, "rf": 0.97, "dart": 0.99, "goss": 0.99}


@pytest.mark.parametrize("boosting", ["gbdt", "rf", "dart", "goss"])
def test_binary_auc_benchmark(boosting):
    train_df, test_df = binary_dfs()
    clf = LightGBMClassifier(
        numIterations=40, numLeaves=31, maxDepth=5, minDataInLeaf=5,
        boostingType=boosting, baggingFraction=0.8 if boosting == "rf" else 1.0,
        baggingFreq=1 if boosting == "rf" else 0, seed=7)
    model = clf.fit(train_df)
    out = model.transform(test_df)
    auc = roc_auc_score(test_df["label"], np.asarray(out["probability"])[:, 1])
    assert auc > BENCHMARKS[boosting] - 0.07, f"{boosting}: AUC {auc}"


def test_output_columns_and_thresholds():
    train_df, test_df = binary_dfs()
    model = LightGBMClassifier(numIterations=10, minDataInLeaf=5).fit(train_df)
    out = model.transform(test_df)
    assert np.asarray(out["probability"]).shape == (test_df.num_rows, 2)
    assert np.asarray(out["rawPrediction"]).shape == (test_df.num_rows, 2)
    probs = np.asarray(out["probability"])
    assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    preds = out["prediction"]
    assert set(np.unique(preds)) <= {0.0, 1.0}
    # heavily biased threshold flips predictions toward class 0
    model2 = model.copy(thresholds=[0.01, 0.99])
    preds2 = model2.transform(test_df)["prediction"]
    assert preds2.sum() <= preds.sum()


def test_multiclass_iris():
    X, y = load_iris(return_X_y=True)
    df = DataFrame({"features": X, "label": y.astype(np.float64)})
    model = LightGBMClassifier(numIterations=25, numLeaves=7, maxDepth=3,
                               minDataInLeaf=3).fit(df)
    out = model.transform(df)
    acc = (out["prediction"] == df["label"]).mean()
    assert acc > 0.95
    assert np.asarray(out["probability"]).shape == (len(y), 3)


def test_validation_and_early_stopping():
    X, y = load_breast_cancer(return_X_y=True)
    is_val = np.zeros(len(y), dtype=bool)
    is_val[::4] = True
    df = DataFrame({"features": X, "label": y.astype(np.float64),
                    "isVal": is_val})
    model = LightGBMClassifier(
        numIterations=200, validationIndicatorCol="isVal",
        earlyStoppingRound=5, minDataInLeaf=5).fit(df)
    assert model.best_iteration >= 0
    assert model.booster.num_trees < 200
    assert any("valid0_binary_logloss" in e for e in model.evals_result)


def test_feature_importances_and_leaf_and_contrib_cols():
    train_df, test_df = binary_dfs()
    model = LightGBMClassifier(numIterations=10, minDataInLeaf=5,
                               leafPredictionCol="leaves",
                               featuresShapCol="contribs").fit(train_df)
    imp = model.get_feature_importances("split")
    assert imp.shape == (30,) and imp.sum() > 0
    gain = model.get_feature_importances("gain")
    assert gain.shape == (30,)
    out = model.transform(test_df)
    assert np.asarray(out["leaves"]).shape == (test_df.num_rows, 10)
    contribs = np.asarray(out["contribs"])
    assert contribs.shape == (test_df.num_rows, 31)
    # contributions sum to raw margin (SHAP efficiency property)
    raw = np.asarray(out["rawPrediction"])[:, 1]
    assert np.allclose(contribs.sum(axis=1), raw, atol=1e-3)


def test_native_model_string_roundtrip(tmp_path):
    train_df, test_df = binary_dfs()
    model = LightGBMClassifier(numIterations=8, minDataInLeaf=5).fit(train_df)
    p = str(tmp_path / "model.txt")
    model.save_native_model(p)
    loaded = LightGBMClassificationModel.load_native_model_from_file(p)
    a = np.asarray(model.transform(test_df)["probability"])
    b = np.asarray(loaded.transform(test_df)["probability"])
    assert np.allclose(a, b, atol=1e-5)


def test_model_save_load(tmp_path):
    train_df, test_df = binary_dfs()
    model = LightGBMClassifier(numIterations=8, minDataInLeaf=5).fit(train_df)
    model.save(str(tmp_path / "m"))
    loaded = LightGBMClassificationModel.load(str(tmp_path / "m"))
    a = np.asarray(model.transform(test_df)["probability"])
    b = np.asarray(loaded.transform(test_df)["probability"])
    assert np.allclose(a, b, atol=1e-6)


def test_warm_start_model_string():
    train_df, test_df = binary_dfs()
    m1 = LightGBMClassifier(numIterations=5, minDataInLeaf=5).fit(train_df)
    m2 = LightGBMClassifier(numIterations=5, minDataInLeaf=5,
                            modelString=m1.get_model_string()).fit(train_df)
    # continued model should fit train better than the 5-tree one
    def logloss(m):
        p = np.asarray(m.transform(train_df)["probability"])[:, 1]
        yy = train_df["label"]
        p = np.clip(p, 1e-12, 1 - 1e-12)
        return -(yy * np.log(p) + (1 - yy) * np.log(1 - p)).mean()
    assert logloss(m2) < logloss(m1)


def test_unbalance_weighting_runs():
    train_df, _ = binary_dfs()
    model = LightGBMClassifier(numIterations=5, isUnbalance=True,
                               minDataInLeaf=5).fit(train_df)
    assert model.booster.num_trees == 5


def test_non_consecutive_labels_multiclass():
    X, _ = load_iris(return_X_y=True)
    rng = np.random.default_rng(0)
    # labels {2, 5, 9}: must be re-encoded internally and decoded back
    y = np.array([2.0, 5.0, 9.0])[rng.integers(0, 3, size=len(X))]
    y[X[:, 0] < 5.5] = 2.0
    y[(X[:, 0] >= 5.5) & (X[:, 0] < 6.5)] = 5.0
    y[X[:, 0] >= 6.5] = 9.0
    df = DataFrame({"features": X, "label": y})
    model = LightGBMClassifier(numIterations=15, numLeaves=7, maxDepth=3,
                               minDataInLeaf=3).fit(df)
    out = model.transform(df)
    assert set(np.unique(out["prediction"])) <= {2.0, 5.0, 9.0}
    assert (out["prediction"] == y).mean() > 0.9


def test_dart_multiclass_trains():
    X, y = load_iris(return_X_y=True)
    df = DataFrame({"features": X, "label": y.astype(np.float64)})
    model = LightGBMClassifier(numIterations=15, boostingType="dart",
                               dropRate=0.5, skipDrop=0.0, numLeaves=7,
                               maxDepth=3, minDataInLeaf=3, seed=1).fit(df)
    out = model.transform(df)
    assert (out["prediction"] == df["label"]).mean() > 0.9


def test_is_unbalance_changes_model():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] + rng.normal(size=600) * 2 > 1.8).astype(np.float64)  # rare positives
    df = DataFrame({"features": X, "label": y})
    plain = LightGBMClassifier(numIterations=10, minDataInLeaf=5).fit(df)
    weighted = LightGBMClassifier(numIterations=10, minDataInLeaf=5,
                                  isUnbalance=True).fit(df)
    p0 = np.asarray(plain.transform(df)["probability"])[:, 1].mean()
    p1 = np.asarray(weighted.transform(df)["probability"])[:, 1].mean()
    assert p1 > p0  # upweighted positives shift probabilities up


def test_high_cardinality_categorical():
    rng = np.random.default_rng(0)
    n = 2000
    cat = rng.integers(0, 500, size=n).astype(np.float64)  # 500 > maxBin
    X = np.stack([cat, rng.normal(size=n)], axis=1)
    y = ((cat % 2) == 0).astype(np.float64)
    df = DataFrame({"features": X, "label": y})
    model = LightGBMClassifier(numIterations=5, minDataInLeaf=5,
                               categoricalSlotIndexes=[0], maxBin=64).fit(df)
    assert model.booster.num_trees == 5


def test_zero_iterations_returns_empty_booster():
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(0)
    x = rng.normal(size=(50, 3))
    y = (x[:, 0] > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=16)
    cfg = TrainConfig(objective="binary", num_iterations=0, max_bin=16)
    res = train(mapper.transform(x), y, cfg)
    assert res.booster.num_trees == 0
    assert res.evals == []


def test_callbacks_called_live_per_iteration():
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(0)
    x = rng.normal(size=(200, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=16)
    cfg = TrainConfig(objective="binary", num_iterations=5, num_leaves=7,
                      max_depth=3, min_data_in_leaf=5, max_bin=16)
    seen = []
    train(mapper.transform(x), y, cfg,
          callbacks=[lambda it, rec: seen.append((it, rec["iteration"]))])
    assert seen == [(i, i) for i in range(5)]


def test_instrumentation_surfaces_from_fitted_model(rng):
    """Users can read per-phase fit timings off the model
    (LightGBMPerformance.scala:11-66 analog; VERDICT r2 weak #10)."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

    x = rng.normal(size=(300, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    model = LightGBMClassifier(numIterations=3, numLeaves=4,
                               maxBin=16).fit(
        DataFrame({"features": x, "label": y}))
    measures = model.get_all_instrumentation()
    assert measures.get("binning", 0) > 0
    assert measures.get("training", 0) > 0
    assert model.train_measures.count("training") >= 3
