"""Distributed-semantics tests on the 8-device CPU mesh.

The reference tests multi-node LightGBM on one JVM via local[*]
(SURVEY.md §4.4); here the data-parallel histogram reduction runs for
real across 8 XLA CPU devices and must produce results consistent with
single-device training.
"""

import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.metrics import roc_auc_score

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt import LightGBMClassifier, TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def test_data_parallel_matches_single_device(mesh8):
    X, y = load_breast_cancer(return_X_y=True)
    # pad rows to a multiple of 8 for even sharding
    n8 = (len(X) // 8) * 8
    X, y = X[:n8], y[:n8].astype(np.float64)
    bm = BinMapper.fit(X, max_bin=63)
    binned = bm.transform(X)
    cfg = TrainConfig(objective="binary", num_iterations=5, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5)
    res_single = train(binned, y, cfg, bin_upper=bm.bin_upper_values(cfg.max_bin))
    res_dp = train(binned, y, cfg, bin_upper=bm.bin_upper_values(cfg.max_bin),
                   mesh=mesh8)
    # cross-device float reduction order can flip near-tie splits, so
    # require structural agreement on nearly all slots and matching loss
    sf_a, sf_b = res_single.booster.split_feature, res_dp.booster.split_feature
    agree = (sf_a == sf_b).mean()
    assert agree > 0.9, f"split agreement {agree}"
    ll_a = res_single.evals[-1]["train_binary_logloss"]
    ll_b = res_dp.evals[-1]["train_binary_logloss"]
    assert abs(ll_a - ll_b) < 1e-4


def test_estimator_with_mesh(mesh8):
    X, y = load_breast_cancer(return_X_y=True)
    n8 = (len(X) // 8) * 8
    df = DataFrame({"features": X[:n8], "label": y[:n8].astype(np.float64)})
    clf = LightGBMClassifier(numIterations=10, minDataInLeaf=5).set_mesh(mesh8)
    model = clf.fit(df)
    out = model.transform(df)
    auc = roc_auc_score(df["label"], np.asarray(out["probability"])[:, 1])
    assert auc > 0.95


def test_data_parallel_exact_on_separated_gains(mesh8, rng):
    """VERDICT r3 #9: with well-separated split gains (each feature's
    signal an order of magnitude apart, thresholds far from ties), any
    float-reduction-order drift is far below the gain gaps, so dp
    training must reproduce the single-device tree STRUCTURE exactly —
    a subtly wrong histogram reduction cannot pass this."""
    n = 4096
    x = np.stack([
        rng.normal(size=n) * 1.0,
        rng.normal(size=n) * 1.0 + 3.0,
        rng.uniform(-1, 1, size=n),
    ], axis=1)
    # XOR-style: the root must split x0, then BOTH children carry a
    # strong x1 signal (opposite directions), so every internal node
    # has one dominant, well-separated gain
    left_y = x[:, 1] > 3.0
    right_y = x[:, 1] <= 3.0
    logit = np.where(x[:, 0] > 0.5, 4.0 * right_y - 2.0,
                     4.0 * left_y - 2.0)
    y = (logit + rng.normal(size=n) * 0.2 > 0).astype(np.float64)
    bm = BinMapper.fit(x, max_bin=63)
    binned = bm.transform(x)
    # depth 2: both levels split on strong, well-separated signals
    # (deeper levels would fit residual noise, where near-ties make
    # reduction-order divergence legitimate)
    cfg = TrainConfig(objective="binary", num_iterations=5, num_leaves=4,
                      max_depth=2, min_data_in_leaf=20)
    bu = bm.bin_upper_values(cfg.max_bin)
    res_single = train(binned, y, cfg, bin_upper=bu)
    res_dp = train(binned, y, cfg, bin_upper=bu, mesh=mesh8)
    np.testing.assert_array_equal(res_single.booster.split_feature,
                                  res_dp.booster.split_feature)
    np.testing.assert_array_equal(res_single.booster.threshold_bin,
                                  res_dp.booster.threshold_bin)
    np.testing.assert_allclose(res_single.booster.node_value,
                               res_dp.booster.node_value, atol=1e-5)
