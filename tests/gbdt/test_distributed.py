"""Distributed-semantics tests on the 8-device CPU mesh.

The reference tests multi-node LightGBM on one JVM via local[*]
(SURVEY.md §4.4); here the data-parallel histogram reduction runs for
real across 8 XLA CPU devices and must produce results consistent with
single-device training.
"""

import numpy as np
from sklearn.datasets import load_breast_cancer
from sklearn.metrics import roc_auc_score

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt import LightGBMClassifier, TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def test_data_parallel_matches_single_device(mesh8):
    X, y = load_breast_cancer(return_X_y=True)
    # pad rows to a multiple of 8 for even sharding
    n8 = (len(X) // 8) * 8
    X, y = X[:n8], y[:n8].astype(np.float64)
    bm = BinMapper.fit(X, max_bin=63)
    binned = bm.transform(X)
    cfg = TrainConfig(objective="binary", num_iterations=5, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5)
    res_single = train(binned, y, cfg, bin_upper=bm.bin_upper_values(cfg.max_bin))
    res_dp = train(binned, y, cfg, bin_upper=bm.bin_upper_values(cfg.max_bin),
                   mesh=mesh8)
    # cross-device float reduction order can flip near-tie splits, so
    # require structural agreement on nearly all slots and matching loss
    sf_a, sf_b = res_single.booster.split_feature, res_dp.booster.split_feature
    agree = (sf_a == sf_b).mean()
    assert agree > 0.9, f"split agreement {agree}"
    ll_a = res_single.evals[-1]["train_binary_logloss"]
    ll_b = res_dp.evals[-1]["train_binary_logloss"]
    assert abs(ll_a - ll_b) < 1e-4


def test_estimator_with_mesh(mesh8):
    X, y = load_breast_cancer(return_X_y=True)
    n8 = (len(X) // 8) * 8
    df = DataFrame({"features": X[:n8], "label": y[:n8].astype(np.float64)})
    clf = LightGBMClassifier(numIterations=10, minDataInLeaf=5).set_mesh(mesh8)
    model = clf.fit(df)
    out = model.transform(df)
    auc = roc_auc_score(df["label"], np.asarray(out["probability"])[:, 1])
    assert auc > 0.95
