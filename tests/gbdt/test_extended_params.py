"""Extended LightGBM param surface: pathSmooth, maxDeltaStep,
pos/negBaggingFraction, extraTrees (params/LightGBMParams.scala)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import (LightGBMClassifier,
                                                 LightGBMRegressor)


@pytest.fixture()
def reg_df(rng):
    x = rng.normal(size=(1200, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=1200) * 0.3
    return DataFrame({"features": x, "label": y}), x, y


def test_max_delta_step_bounds_leaf_outputs(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32, learningRate=1.0)
    free = LightGBMRegressor(**kw).fit(df)
    capped = LightGBMRegressor(maxDeltaStep=0.1, **kw).fit(df)
    # every stored node value (pre-shrinkage output) obeys the cap
    leaf_mask = capped.booster.split_feature < 0
    assert float(np.abs(capped.booster.node_value).max()) <= 0.1 + 1e-6
    assert float(np.abs(free.booster.node_value).max()) > 0.1


def test_path_smooth_shrinks_toward_parent(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    free = LightGBMRegressor(**kw).fit(df)
    smooth = LightGBMRegressor(pathSmooth=1e6, **kw).fit(df)
    # huge smoothing: children barely move off the parent -> predictions
    # hug the base score far more than the free fit
    pf = np.asarray(free.transform(df)["prediction"])
    ps = np.asarray(smooth.transform(df)["prediction"])
    assert np.std(ps) < np.std(pf) * 0.2
    # mild smoothing barely changes quality
    mild = LightGBMRegressor(pathSmooth=1.0, **kw).fit(df)
    pm = np.asarray(mild.transform(df)["prediction"])
    assert np.corrcoef(pm, y)[0, 1] > 0.9


def test_pos_neg_bagging_fraction(rng):
    x = rng.normal(size=(3000, 3))
    y = (x[:, 0] > 1.0).astype(np.float64)  # ~16% positives
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=10, numLeaves=8, maxBin=32, baggingFreq=1)
    # keep all (rare) positives, subsample negatives: still learns
    m = LightGBMClassifier(posBaggingFraction=0.9999,
                           negBaggingFraction=0.3, **kw).fit(df)
    acc = float((m.transform(df)["prediction"] == y).mean())
    assert acc > 0.9
    # per-class rates actually differ from plain bagging
    plain = LightGBMClassifier(baggingFraction=0.5, **kw).fit(df)
    assert not np.allclose(m.booster.node_value, plain.booster.node_value)


def test_extra_trees_randomizes_thresholds(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=10, numLeaves=8, maxBin=64)
    et = LightGBMRegressor(extraTrees=True, **kw).fit(df)
    full = LightGBMRegressor(**kw).fit(df)
    # random single-threshold candidates: different trees, but the
    # ensemble still learns the signal
    assert not np.array_equal(et.booster.threshold_bin,
                              full.booster.threshold_bin)
    pe = np.asarray(et.transform(df)["prediction"])
    assert np.corrcoef(pe, y)[0, 1] > 0.85
    # deterministic under the same seed
    et2 = LightGBMRegressor(extraTrees=True, **kw).fit(df)
    np.testing.assert_array_equal(et.booster.threshold_bin,
                                  et2.booster.threshold_bin)


def test_extra_trees_rejected_in_voting_mode(reg_df, mesh8):
    df, _, _ = reg_df
    with pytest.raises(NotImplementedError, match="extra_trees"):
        LightGBMRegressor(extraTrees=True, parallelism="voting_parallel",
                          numIterations=2, numLeaves=4,
                          maxBin=16).set_mesh(mesh8).fit(df)


# ---- round-4 params audit (VERDICT r3 #5) ---------------------------------

def test_scale_pos_weight_shifts_predictions(rng):
    x = rng.normal(size=(1500, 4))
    y = (x[:, 0] > 1.0).astype(np.float64)  # imbalanced positives
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=10, numLeaves=8, maxBin=32)
    base = LightGBMClassifier(**kw).fit(df)
    up = LightGBMClassifier(scalePosWeight=8.0, **kw).fit(df)
    pb = np.asarray(base.transform(df)["probability"])[:, 1]
    pu = np.asarray(up.transform(df)["probability"])[:, 1]
    # up-weighting positives raises predicted positive probability
    assert pu.mean() > pb.mean() + 0.01
    with pytest.raises(ValueError, match="mutually exclusive"):
        LightGBMClassifier(scalePosWeight=8.0, isUnbalance=True,
                           **kw).fit(df)


def test_init_score_col_offsets_training(reg_df):
    df, x, y = reg_df
    offset = np.full(len(y), 5.0)
    df_off = DataFrame({"features": x, "label": y + 5.0,
                        "init": offset})
    kw = dict(numIterations=20, numLeaves=8, maxBin=32)
    plain = LightGBMRegressor(**kw).fit(
        DataFrame({"features": x, "label": y}))
    shifted = LightGBMRegressor(initScoreCol="init", **kw).fit(df_off)
    # the model learns residuals against the offset: predictions on the
    # shifted problem match the plain fit (offset NOT added at predict,
    # LightGBM init_score semantics)
    np.testing.assert_allclose(
        np.asarray(shifted.transform(df_off)["prediction"]),
        np.asarray(plain.transform(df_off)["prediction"]), atol=0.2)


def test_feature_fraction_by_node(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=6, numLeaves=8, maxBin=32)
    m = LightGBMRegressor(featureFractionByNode=0.5, **kw).fit(df)
    # trains and predicts sanely
    pred = np.asarray(m.transform(df)["prediction"])
    assert np.corrcoef(pred, y)[0, 1] > 0.8
    # per-node sampling: within one tree, different nodes pick features
    # a per-tree mask of 2/4 features could not (>2 distinct features)
    distinct = {int(f) for t in range(m.booster.num_trees)
                for f in m.booster.split_feature[t] if f >= 0}
    assert len(distinct) > 2


def test_improvement_tolerance_direction_semantics(rng):
    """TrainUtils.scala:143-169: for higher-better metrics (auc) an
    improvement must CLEAR the tolerance (stricter -> stops earlier);
    for lower-better ones a score within the tolerance still counts as
    improved (more lenient -> never stops earlier)."""
    x = rng.normal(size=(2000, 4))
    y = (x[:, 0] + rng.normal(size=2000) * 2.0 > 0).astype(np.float64)
    val = np.zeros(2000, dtype=bool)
    val[1500:] = True
    df = DataFrame({"features": x, "label": y, "isVal": val})
    kw = dict(numIterations=60, numLeaves=8, maxBin=32, metric="auc",
              validationIndicatorCol="isVal", earlyStoppingRound=5)
    loose = LightGBMClassifier(**kw).fit(df)
    strict = LightGBMClassifier(improvementTolerance=0.02, **kw).fit(df)
    assert strict.booster.num_trees < loose.booster.num_trees


def test_min_data_per_group_filters_small_categories(rng):
    n, k = 1500, 24  # ~62 rows per category
    cats = rng.integers(0, k, size=n)
    good = np.isin(cats, [1, 4, 7, 11, 14, 17, 20, 23])
    y = (good & (rng.normal(size=n) > -1.0)).astype(np.float64)
    x = np.stack([cats.astype(np.float64), rng.normal(size=n)], axis=1)
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=6, numLeaves=8, maxBin=64,
              categoricalSlotIndexes=[0])
    filtered = LightGBMClassifier(**kw).fit(df)       # default 100
    allowed = LightGBMClassifier(minDataPerGroup=10, **kw).fit(df)
    def n_cat_nodes(m):
        dt = m.booster.decision_type
        return 0 if dt is None else int((dt & 1).sum())
    # all categories are under the default threshold -> no sorted-scan
    # splits survive; lowering the threshold restores them
    assert n_cat_nodes(allowed) > n_cat_nodes(filtered)


def test_dart_drop_controls(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=15, numLeaves=8, maxBin=32,
              boostingType="dart", dropRate=0.9, skipDrop=0.0)
    m_cap = LightGBMRegressor(maxDrop=1, **kw).fit(df)
    m_uni = LightGBMRegressor(uniformDrop=True, **kw).fit(df)
    m_s1 = LightGBMRegressor(dropSeed=11, **kw).fit(df)
    m_s2 = LightGBMRegressor(dropSeed=12, **kw).fit(df)
    for m in (m_cap, m_uni, m_s1, m_s2):
        assert m.booster.num_trees == 15
        assert np.isfinite(np.asarray(m.transform(df)["prediction"])).all()
    # different drop seeds change the ensemble weights
    assert not np.allclose(m_s1.booster.tree_weights,
                           m_s2.booster.tree_weights)


def test_pass_through_args(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    m = LightGBMRegressor(
        passThroughArgs="min_data_in_leaf=40 lambda_l2=5.0", **kw).fit(df)
    explicit = LightGBMRegressor(minDataInLeaf=40, lambdaL2=5.0,
                                 **kw).fit(df)
    np.testing.assert_allclose(
        np.asarray(m.transform(df)["prediction"]),
        np.asarray(explicit.transform(df)["prediction"]), atol=1e-6)
    with pytest.raises(ValueError, match="not a training option"):
        LightGBMRegressor(passThroughArgs="nonsense_key=1", **kw).fit(df)


def test_zero_as_missing(rng):
    x = rng.normal(size=(1200, 3))
    x[:, 0] = np.where(rng.random(1200) < 0.4, 0.0, x[:, 0])
    y = np.where(x[:, 0] == 0.0, 2.0, x[:, 0]) + 0.05 * rng.normal(size=1200)
    df = DataFrame({"features": x, "label": y})
    m = LightGBMRegressor(zeroAsMissing=True, numIterations=15,
                          numLeaves=8, maxBin=32).fit(df)
    # scoring parity: raw zeros route exactly like NaN
    x_nan = x.copy()
    x_nan[x_nan[:, 0] == 0.0, 0] = np.nan
    p0 = np.asarray(m.transform(df)["prediction"])
    p1 = np.asarray(m.transform(DataFrame({"features": x_nan}))["prediction"])
    np.testing.assert_allclose(p0, p1, atol=1e-6)
    # and the zero group is learnable as its own (missing) bucket
    assert abs(p0[x[:, 0] == 0.0].mean() - 2.0) < 0.3


def test_max_bin_by_feature(reg_df):
    df, x, y = reg_df
    m = LightGBMRegressor(numIterations=3, numLeaves=8, maxBin=64,
                          maxBinByFeature=[8, 0, 0, 0]).fit(df)
    # feature 0's thresholds take at most 8-2 distinct boundary values
    sf, tv = m.booster.split_feature, m.booster.threshold_value
    f0_thr = {float(t) for s, t in zip(sf.ravel(), tv.ravel()) if s == 0}
    assert 0 < len(f0_thr) <= 6


def test_custom_objective_fobj_multiclass(rng):
    """fobj must be called with the documented (preds, labels, weights)
    signature even when the resolved objective has extra kwargs
    (r4 review: multiclass leaked num_class into the call)."""
    import jax.numpy as jnp
    x = rng.normal(size=(600, 4))
    y = (x[:, 0] > 0).astype(np.float64) + (x[:, 1] > 0)

    def soft_obj(preds, labels, weights=None):
        import jax
        p = jax.nn.softmax(preds, axis=-1)
        yh = jax.nn.one_hot(labels.astype(jnp.int32), preds.shape[-1])
        return p - yh, 2.0 * p * (1.0 - p)

    df = DataFrame({"features": x, "label": y})
    m = LightGBMClassifier(fobj=soft_obj, numIterations=4, numLeaves=8,
                           maxBin=32).fit(df)
    assert (np.asarray(m.transform(df)["prediction"]) == y).mean() > 0.8


def test_custom_objective_fobj(reg_df):
    df, x, y = reg_df

    def my_l2(preds, labels, weights=None):
        import jax.numpy as jnp
        return preds - labels, jnp.ones_like(preds)

    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    custom = LightGBMRegressor(fobj=my_l2, **kw).fit(df)
    builtin = LightGBMRegressor(**kw).fit(df)
    np.testing.assert_allclose(
        np.asarray(custom.transform(df)["prediction"]),
        np.asarray(builtin.transform(df)["prediction"]), atol=1e-4)


def test_ranker_label_gain_and_max_position(rng):
    from mmlspark_tpu.models.gbdt.estimators import LightGBMRanker
    n = 600
    x = rng.normal(size=(n, 4))
    g = np.repeat(np.arange(n // 10), 10)
    y = np.clip((x[:, 0] + rng.normal(size=n) * 0.3 > 0.5) * 2.0
                + (x[:, 1] > 0), 0, 3).astype(np.float64)
    df = DataFrame({"features": x, "label": y, "group": g})
    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    base = LightGBMRanker(**kw).fit(df)
    gained = LightGBMRanker(labelGain=[0.0, 1.0, 100.0, 1000.0],
                            maxPosition=5, **kw).fit(df)
    pb = np.asarray(base.transform(df)["prediction"])
    pg = np.asarray(gained.transform(df)["prediction"])
    assert np.isfinite(pg).all()
    assert not np.allclose(pb, pg)  # gains change the learned ordering


def test_boost_from_average_flag(reg_df):
    df, x, y = reg_df
    on = LightGBMRegressor(numIterations=1, numLeaves=4, maxBin=32).fit(df)
    off = LightGBMRegressor(numIterations=1, numLeaves=4, maxBin=32,
                            boostFromAverage=False).fit(df)
    assert abs(on.booster.init_score - float(np.mean(y))) < 1e-5
    assert off.booster.init_score == 0.0


def test_max_num_classes_guard(rng):
    x = rng.normal(size=(300, 2))
    y = np.arange(300, dtype=np.float64)  # 300 distinct labels
    df = DataFrame({"features": x, "label": y})
    with pytest.raises(ValueError, match="maxNumClasses"):
        LightGBMClassifier(numIterations=2).fit(df)


def test_pass_through_binning_and_none_default_keys(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=3, numLeaves=8)
    # binning-coupled override applies BEFORE binning (r4 review fix)
    m = LightGBMRegressor(passThroughArgs="max_bin=16", maxBin=255,
                          **kw).fit(df)
    assert int(m.booster.threshold_bin.max()) < 16
    # None-default int field parses as int, not str
    m2 = LightGBMRegressor(passThroughArgs="drop_seed=7",
                           boostingType="dart", **kw).fit(df)
    assert m2.booster.num_trees == 3
    # float list parses
    m3 = LightGBMRegressor(passThroughArgs="label_gain=0,1.5,3",
                           **kw).fit(df)
    assert m3.booster.num_trees == 3
    # single-valued sequence fields coerce to 1-tuples instead of bare
    # scalars that explode in tuple(cfg.label_gain) later (ADVICE r4)
    from mmlspark_tpu.models.gbdt.estimators import _apply_pass_through
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig
    cfg = _apply_pass_through(
        TrainConfig(), "label_gain=1 categorical_features=3 "
        "monotone_constraints=-1")
    assert cfg.label_gain == (1,)
    assert cfg.categorical_features == (3,)
    assert cfg.monotone_constraints == (-1,)
    assert tuple(cfg.label_gain) == (1,)  # the r4 failure mode


def test_max_position_truncates_gradients(rng):
    from mmlspark_tpu.models.gbdt.estimators import LightGBMRanker
    n = 400
    x = rng.normal(size=(n, 3))
    g = np.repeat(np.arange(n // 20), 20)  # groups of 20
    y = np.clip(x[:, 0] + rng.normal(size=n) * 0.5, 0, 3).round()
    df = DataFrame({"features": x, "label": y, "group": g})
    kw = dict(numIterations=4, numLeaves=8, maxBin=32)
    full = LightGBMRanker(maxPosition=30, **kw).fit(df)
    trunc = LightGBMRanker(maxPosition=2, **kw).fit(df)
    # truncating to top-2 positions changes the learned trees
    assert not np.allclose(full.booster.node_value,
                           trunc.booster.node_value)
