"""Extended LightGBM param surface: pathSmooth, maxDeltaStep,
pos/negBaggingFraction, extraTrees (params/LightGBMParams.scala)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import (LightGBMClassifier,
                                                 LightGBMRegressor)


@pytest.fixture()
def reg_df(rng):
    x = rng.normal(size=(1200, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=1200) * 0.3
    return DataFrame({"features": x, "label": y}), x, y


def test_max_delta_step_bounds_leaf_outputs(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32, learningRate=1.0)
    free = LightGBMRegressor(**kw).fit(df)
    capped = LightGBMRegressor(maxDeltaStep=0.1, **kw).fit(df)
    # every stored node value (pre-shrinkage output) obeys the cap
    leaf_mask = capped.booster.split_feature < 0
    assert float(np.abs(capped.booster.node_value).max()) <= 0.1 + 1e-6
    assert float(np.abs(free.booster.node_value).max()) > 0.1


def test_path_smooth_shrinks_toward_parent(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    free = LightGBMRegressor(**kw).fit(df)
    smooth = LightGBMRegressor(pathSmooth=1e6, **kw).fit(df)
    # huge smoothing: children barely move off the parent -> predictions
    # hug the base score far more than the free fit
    pf = np.asarray(free.transform(df)["prediction"])
    ps = np.asarray(smooth.transform(df)["prediction"])
    assert np.std(ps) < np.std(pf) * 0.2
    # mild smoothing barely changes quality
    mild = LightGBMRegressor(pathSmooth=1.0, **kw).fit(df)
    pm = np.asarray(mild.transform(df)["prediction"])
    assert np.corrcoef(pm, y)[0, 1] > 0.9


def test_pos_neg_bagging_fraction(rng):
    x = rng.normal(size=(3000, 3))
    y = (x[:, 0] > 1.0).astype(np.float64)  # ~16% positives
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=10, numLeaves=8, maxBin=32, baggingFreq=1)
    # keep all (rare) positives, subsample negatives: still learns
    m = LightGBMClassifier(posBaggingFraction=0.9999,
                           negBaggingFraction=0.3, **kw).fit(df)
    acc = float((m.transform(df)["prediction"] == y).mean())
    assert acc > 0.9
    # per-class rates actually differ from plain bagging
    plain = LightGBMClassifier(baggingFraction=0.5, **kw).fit(df)
    assert not np.allclose(m.booster.node_value, plain.booster.node_value)


def test_extra_trees_randomizes_thresholds(reg_df):
    df, x, y = reg_df
    kw = dict(numIterations=10, numLeaves=8, maxBin=64)
    et = LightGBMRegressor(extraTrees=True, **kw).fit(df)
    full = LightGBMRegressor(**kw).fit(df)
    # random single-threshold candidates: different trees, but the
    # ensemble still learns the signal
    assert not np.array_equal(et.booster.threshold_bin,
                              full.booster.threshold_bin)
    pe = np.asarray(et.transform(df)["prediction"])
    assert np.corrcoef(pe, y)[0, 1] > 0.85
    # deterministic under the same seed
    et2 = LightGBMRegressor(extraTrees=True, **kw).fit(df)
    np.testing.assert_array_equal(et.booster.threshold_bin,
                                  et2.booster.threshold_bin)


def test_extra_trees_rejected_in_voting_mode(reg_df, mesh8):
    df, _, _ = reg_df
    with pytest.raises(NotImplementedError, match="extra_trees"):
        LightGBMRegressor(extraTrees=True, parallelism="voting_parallel",
                          numIterations=2, numLeaves=4,
                          maxBin=16).set_mesh(mesh8).fit(df)
