"""Fault injection on the training path (SURVEY.md §5 failure handling).

The reference's fault story is Spark task retry + barrier mode; the
analog here is elastic checkpoint/resume: a fit killed WITHOUT warning
(SIGKILL, no atexit, no finally) must resume from its last atomic
checkpoint and reproduce the uninterrupted run bit-for-bit.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

_FIT_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

rng = np.random.default_rng(7)
x = rng.normal(size=(2000, 4))
y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=2000) * 0.1
df = DataFrame({{"features": x, "label": y}})
print("FITTING", flush=True)
LightGBMRegressor(numIterations=40, numLeaves=8, maxBin=32,
                  checkpointDir={ckdir!r}, checkpointInterval=4).fit(df)
print("DONE", flush=True)
"""


def _data():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2000, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=2000) * 0.1
    return DataFrame({"features": x, "label": y}), x, y


def test_sigkill_mid_fit_resumes_bit_exact(tmp_path):
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ,
               PYTHONPATH=os.getcwd() + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-c", _FIT_SCRIPT.format(ckdir=ckdir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
    try:
        # hard-kill the trainer as soon as a mid-training checkpoint
        # lands (no cleanup handlers get to run)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            done = [n for n in os.listdir(ckdir)] if os.path.isdir(ckdir) \
                else []
            if any(n.startswith("checkpoint_") and n.endswith(".txt")
                   for n in done):
                break
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(f"fit finished before kill: {err[-500:]}")
            time.sleep(0.05)
        else:
            proc.kill()
            pytest.skip("no checkpoint appeared within timeout")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL

    # partial state only: some checkpoints, no finished model
    names = sorted(n for n in os.listdir(ckdir) if n.endswith(".txt"))
    assert names, "kill happened after a checkpoint landed"
    assert f"checkpoint_40.txt" not in names

    df, x, y = _data()
    kw = dict(numIterations=40, numLeaves=8, maxBin=32)
    resumed = LightGBMRegressor(checkpointDir=ckdir, checkpointInterval=4,
                                **kw).fit(df)
    fresh = LightGBMRegressor(**kw).fit(df)
    assert resumed.booster.num_trees == 40
    np.testing.assert_allclose(
        np.asarray(resumed.transform(df)["prediction"]),
        np.asarray(fresh.transform(df)["prediction"]), atol=1e-5)


def test_corrupt_partial_checkpoint_is_invisible(tmp_path):
    """The atomic rename protocol: a torn half-written .tmp file from a
    crashed writer must never be picked up on resume."""
    df, x, y = _data()
    ckdir = str(tmp_path / "ck")
    kw = dict(numIterations=8, numLeaves=8, maxBin=32,
              checkpointDir=ckdir, checkpointInterval=4)
    LightGBMRegressor(**kw).fit(df)
    os.remove(os.path.join(ckdir, "checkpoint_8.txt"))
    # a torn write that never reached os.replace
    with open(os.path.join(ckdir, ".checkpoint_8.tmp"), "w") as fh:
        fh.write("tree\nversion=v4\ngarbage")
    resumed = LightGBMRegressor(**{**kw, "numIterations": 12}).fit(df)
    assert resumed.booster.num_trees == 12
