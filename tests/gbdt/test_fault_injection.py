"""Fault injection on the training path (SURVEY.md §5 failure handling).

The reference's fault story is Spark task retry + barrier mode; the
analog here is elastic checkpoint/resume: a fit killed WITHOUT warning
(SIGKILL, no atexit, no finally) must resume from its last atomic
checkpoint and reproduce the uninterrupted run bit-for-bit.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.faults import FaultInjected
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()

_FIT_SCRIPT = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

rng = np.random.default_rng(7)
x = rng.normal(size=(2000, 4))
y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=2000) * 0.1
df = DataFrame({{"features": x, "label": y}})
print("FITTING", flush=True)
LightGBMRegressor(numIterations=40, numLeaves=8, maxBin=32,
                  checkpointDir={ckdir!r}, checkpointInterval=4).fit(df)
print("DONE", flush=True)
"""


def _data():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2000, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=2000) * 0.1
    return DataFrame({"features": x, "label": y}), x, y


def test_sigkill_mid_fit_resumes_bit_exact(tmp_path):
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ,
               PYTHONPATH=os.getcwd() + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-c", _FIT_SCRIPT.format(ckdir=ckdir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
    try:
        # hard-kill the trainer as soon as a mid-training checkpoint
        # lands (no cleanup handlers get to run)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            done = [n for n in os.listdir(ckdir)] if os.path.isdir(ckdir) \
                else []
            if any(n.startswith("checkpoint_") and n.endswith(".txt")
                   for n in done):
                break
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(f"fit finished before kill: {err[-500:]}")
            time.sleep(0.05)
        else:
            proc.kill()
            pytest.skip("no checkpoint appeared within timeout")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL

    # partial state only: some checkpoints, no finished model
    names = sorted(n for n in os.listdir(ckdir) if n.endswith(".txt"))
    assert names, "kill happened after a checkpoint landed"
    assert f"checkpoint_40.txt" not in names

    df, x, y = _data()
    kw = dict(numIterations=40, numLeaves=8, maxBin=32)
    resumed = LightGBMRegressor(checkpointDir=ckdir, checkpointInterval=4,
                                **kw).fit(df)
    fresh = LightGBMRegressor(**kw).fit(df)
    assert resumed.booster.num_trees == 40
    np.testing.assert_allclose(
        np.asarray(resumed.transform(df)["prediction"]),
        np.asarray(fresh.transform(df)["prediction"]), atol=1e-5)


@pytest.mark.faults
def test_armed_fault_kill_and_resume_bitwise(tmp_path):
    """The deterministic in-process twin of the SIGKILL test (the
    tier-1-safe smoke member of the fault suite): a fit interrupted by
    an armed ``gbdt.train_step`` fault mid-training, then resumed from
    the latest checkpoint, reproduces an uninterrupted run BITWISE."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(600, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=600) * 0.1
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=12, numLeaves=8, maxBin=32,
              checkpointInterval=4)

    ref = LightGBMRegressor(checkpointDir=str(tmp_path / "a"),
                            **kw).fit(df)

    # hit 9 = first iteration of the third segment: checkpoints at 4
    # and 8 are committed, iteration 9's work is lost with the process
    ckb = str(tmp_path / "b")
    with faults.injected("gbdt.train_step", "raise", nth=9):
        with pytest.raises(FaultInjected):
            LightGBMRegressor(checkpointDir=ckb, **kw).fit(df)
    names = sorted(n for n in os.listdir(ckb) if n.endswith(".txt"))
    assert names == ["checkpoint_4.txt", "checkpoint_8.txt"]

    resumed = LightGBMRegressor(checkpointDir=ckb, **kw).fit(df)
    assert resumed.booster.num_trees == 12
    ref_pred = np.asarray(ref.transform(df)["prediction"])
    res_pred = np.asarray(resumed.transform(df)["prediction"])
    np.testing.assert_array_equal(ref_pred, res_pred)


@pytest.mark.faults
def test_checkpoint_write_failure_degrades_not_dies(tmp_path):
    """A failing checkpoint store (armed OSError on checkpoint.write)
    must not kill a healthy fit: training completes, the skip is
    logged once per process, and restart depth just shrinks."""
    from mmlspark_tpu.core.logging_utils import SINK, reset_warn_once
    reset_warn_once()
    SINK.drain()
    rng = np.random.default_rng(3)
    x = rng.normal(size=(300, 3))
    y = x[:, 0] + rng.normal(size=300) * 0.1
    df = DataFrame({"features": x, "label": y})
    ckdir = str(tmp_path / "ck")
    with faults.injected("checkpoint.write", "raise", count=None,
                         exc=OSError("disk full")):
        model = LightGBMRegressor(
            numIterations=6, numLeaves=4, maxBin=16,
            checkpointDir=ckdir, checkpointInterval=3).fit(df)
    assert model.booster.num_trees == 6  # fit survived
    assert not [n for n in os.listdir(ckdir) if n.endswith(".txt")]
    keys = [e.get("key") for e in SINK.drain()
            if e.get("event") == "degradation"]
    assert "gbdt.checkpoint_skip" in keys


@pytest.mark.faults
def test_level_hist_corruption_reaches_the_model(monkeypatch):
    """Arming corrupt on ``gbdt.level_hist`` must change the trained
    model — proof the injection point sits on the real data path (a
    zeroed histogram kills every split)."""
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    rng = np.random.default_rng(5)
    x = rng.normal(size=(400, 3))
    y = 2.0 * x[:, 0] + rng.normal(size=400) * 0.1
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=3, numLeaves=4, maxBin=16)
    clean = LightGBMRegressor(**kw).fit(df)
    with faults.injected("gbdt.level_hist", "corrupt", count=None,
                         corrupt=lambda h: np.zeros_like(h)):
        broken = LightGBMRegressor(**kw).fit(df)
    clean_pred = np.asarray(clean.transform(df)["prediction"])
    broken_pred = np.asarray(broken.transform(df)["prediction"])
    assert not np.array_equal(clean_pred, broken_pred)
    # with every histogram zeroed no split clears min_gain: the broken
    # model must be the constant base-score predictor
    assert np.allclose(broken_pred, broken_pred[0])


@pytest.mark.faults
def test_every_fault_point_site_is_registered():
    """Fuzzing.scala-style completeness: every production
    ``fault_point("...")`` call site names a registered point, and the
    points the harness advertises are actually threaded through code."""
    import pathlib
    import re

    import mmlspark_tpu
    from mmlspark_tpu.core.faults import KNOWN_POINTS

    root = pathlib.Path(mmlspark_tpu.__file__).parent
    sites = set()
    for p in root.rglob("*.py"):
        if p.name == "faults.py":  # the harness's own docs/examples
            continue
        sites.update(re.findall(r'fault_point\(\s*"([^"]+)"',
                                p.read_text()))
    unregistered = sites - set(KNOWN_POINTS)
    assert not unregistered, f"unregistered fault points: {unregistered}"
    missing = set(KNOWN_POINTS) - sites
    assert not missing, f"registered but never threaded: {missing}"


def test_corrupt_partial_checkpoint_is_invisible(tmp_path):
    """The atomic rename protocol: a torn half-written .tmp file from a
    crashed writer must never be picked up on resume."""
    df, x, y = _data()
    ckdir = str(tmp_path / "ck")
    kw = dict(numIterations=8, numLeaves=8, maxBin=32,
              checkpointDir=ckdir, checkpointInterval=4)
    LightGBMRegressor(**kw).fit(df)
    os.remove(os.path.join(ckdir, "checkpoint_8.txt"))
    # a torn write that never reached os.replace
    with open(os.path.join(ckdir, ".checkpoint_8.tmp"), "w") as fh:
        fh.write("tree\nversion=v4\ngarbage")
    resumed = LightGBMRegressor(**{**kw, "numIterations": 12}).fit(df)
    assert resumed.booster.num_trees == 12
