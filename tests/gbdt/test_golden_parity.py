"""Golden parity vs the LightGBM native model format and real datasets
(VERDICT r2 #3).

Two legs:

1. A committed LightGBM-format model string
   (``fixtures/lightgbm_golden_model.txt`` — v4 text layout exactly as
   ``LGBM_BoosterSaveModel`` emits it, incl. categorical
   cat_boundaries/cat_threshold bitsets). An *independent* parser+walker
   in this file — structurally different from
   ``BoosterArrays.load_model_string``'s full-layout placement — walks
   the explicit child-pointer arrays; both must produce identical
   predictions.

2. Accuracy regression on real datasets (sklearn's bundled
   breast_cancer / diabetes) against sklearn's
   HistGradientBoosting* — the same histogram-GBDT algorithm family the
   reference wraps — mirroring BASELINE.md's tolerance rows
   (benchmarks_VerifyLightGBMClassifierBulkBasic.csv).
"""

import os

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import BoosterArrays

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "lightgbm_golden_model.txt")


def _parse_trees(text):
    """Minimal independent parser: list of dicts of raw arrays."""
    trees = []
    block = None
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("Tree="):
            block = {}
            trees.append(block)
        elif line == "end of trees":
            block = None
        elif block is not None and "=" in line:
            k, v = line.split("=", 1)
            block[k] = v
    return trees


def _walk(tree, x):
    """Reference walker over LightGBM's child-pointer encoding:
    code >= 0 -> internal node, code < 0 -> leaf ~code."""
    sf = list(map(int, tree["split_feature"].split()))
    thr = list(map(float, tree["threshold"].split()))
    left = list(map(int, tree["left_child"].split()))
    right = list(map(int, tree["right_child"].split()))
    dec = list(map(int, tree["decision_type"].split()))
    leaf_value = list(map(float, tree["leaf_value"].split()))
    bounds = (list(map(int, tree["cat_boundaries"].split()))
              if "cat_boundaries" in tree else [])
    words = (list(map(int, tree["cat_threshold"].split()))
             if "cat_threshold" in tree else [])

    out = np.zeros(len(x))
    for i, row in enumerate(x):
        code = 0
        while code >= 0:
            v = row[sf[code]]
            if dec[code] & 1:
                cat_idx = int(thr[code])
                lo, hi = bounds[cat_idx], bounds[cat_idx + 1]
                iv = int(v) if np.isfinite(v) and v == int(v) and v >= 0 else -1
                in_set = (0 <= iv < (hi - lo) * 32
                          and (words[lo + iv // 32] >> (iv % 32)) & 1)
                code = left[code] if in_set else right[code]
            else:
                d = dec[code]
                mt = (d >> 2) & 3
                v0 = 0.0 if np.isnan(v) else v
                missing = (np.isnan(v) if mt == 2
                           else (mt == 1 and v0 == 0.0))
                go_left = bool(d & 2) if missing else v0 <= thr[code]
                code = left[code] if go_left else right[code]
        out[i] += leaf_value[~code]
    return out


@pytest.fixture(scope="module")
def golden_text():
    with open(FIXTURE) as f:
        return f.read()


def test_fixture_loads_with_categoricals(golden_text):
    b = BoosterArrays.load_model_string(golden_text)
    assert b.num_trees == 2
    assert b.num_features == 5
    assert b.has_categorical
    # tree 1 root splits on the categorical feature 4
    assert (b.decision_type[1] & 1).sum() == 2


def test_golden_predictions_match_independent_walker(golden_text):
    rng = np.random.default_rng(11)
    n = 500
    x = rng.normal(size=(n, 5))
    x[:, 4] = rng.integers(-1, 9, size=n)  # cats incl. unseen -1, 8
    x[:5, 0] = np.nan                      # numerical missing
    x[5:8, 4] = np.nan                     # categorical missing

    trees = _parse_trees(golden_text)
    want = _walk(trees[0], x) + _walk(trees[1], x)

    b = BoosterArrays.load_model_string(golden_text)
    got = np.asarray(b.predict_jit()(x))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_golden_roundtrip_preserves_predictions(golden_text):
    rng = np.random.default_rng(12)
    x = rng.normal(size=(300, 5))
    x[:, 4] = rng.integers(0, 8, size=300)
    b = BoosterArrays.load_model_string(golden_text)
    b2 = BoosterArrays.load_model_string(b.save_model_string())
    np.testing.assert_allclose(np.asarray(b.predict_jit()(x)),
                               np.asarray(b2.predict_jit()(x)),
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# real-dataset accuracy vs sklearn HistGradientBoosting
# ---------------------------------------------------------------------------

def _auc(scores, y):
    order = np.argsort(scores)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0.5
    n1, n0 = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)


@pytest.fixture(scope="module")
def breast_cancer():
    from sklearn.datasets import load_breast_cancer
    d = load_breast_cancer()
    rng = np.random.default_rng(0)
    idx = rng.permutation(len(d.target))
    cut = int(0.75 * len(idx))
    return (d.data[idx[:cut]], d.target[idx[:cut]].astype(np.float64),
            d.data[idx[cut:]], d.target[idx[cut:]].astype(np.float64))


def test_breast_cancer_auc_matches_sklearn_hgb(breast_cancer):
    from sklearn.ensemble import HistGradientBoostingClassifier

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

    xtr, ytr, xte, yte = breast_cancer
    model = LightGBMClassifier(numIterations=100, numLeaves=31,
                               learningRate=0.1).fit(
        DataFrame({"features": xtr, "label": ytr}))
    probs = model.transform(DataFrame({"features": xte, "label": yte}))
    ours = _auc(probs["probability"][:, 1], yte)

    ref = HistGradientBoostingClassifier(
        max_iter=100, learning_rate=0.1, max_leaf_nodes=31,
        early_stopping=False, random_state=0).fit(xtr, ytr)
    theirs = _auc(ref.predict_proba(xte)[:, 1], yte)

    assert ours > 0.95
    # BASELINE.md's AUC rows carry +-0.07; hold a tighter bar vs the
    # measured comparator on the same split
    assert ours >= theirs - 0.02, (ours, theirs)


def test_breast_cancer_goss_tracks_gbdt(breast_cancer):
    """GOSS amplification/min_data semantics: quality must track plain
    gbdt closely (pins VERDICT r2 weak #9)."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier

    xtr, ytr, xte, yte = breast_cancer
    aucs = {}
    for boosting in ("gbdt", "goss"):
        model = LightGBMClassifier(numIterations=60, numLeaves=31,
                                   boostingType=boosting).fit(
            DataFrame({"features": xtr, "label": ytr}))
        probs = model.transform(DataFrame({"features": xte, "label": yte}))
        aucs[boosting] = _auc(probs["probability"][:, 1], yte)
    assert aucs["goss"] > 0.95
    assert abs(aucs["goss"] - aucs["gbdt"]) < 0.03, aucs


def test_diabetes_l2_matches_sklearn_hgb():
    from sklearn.datasets import load_diabetes
    from sklearn.ensemble import HistGradientBoostingRegressor

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

    d = load_diabetes()
    rng = np.random.default_rng(1)
    idx = rng.permutation(len(d.target))
    cut = int(0.75 * len(idx))
    xtr, ytr = d.data[idx[:cut]], d.target[idx[:cut]]
    xte, yte = d.data[idx[cut:]], d.target[idx[cut:]]

    model = LightGBMRegressor(numIterations=200, numLeaves=15,
                              learningRate=0.05).fit(
        DataFrame({"features": xtr, "label": ytr}))
    pred = model.transform(
        DataFrame({"features": xte, "label": yte}))["prediction"]
    ours = float(np.mean((pred - yte) ** 2))

    ref = HistGradientBoostingRegressor(
        max_iter=200, learning_rate=0.05, max_leaf_nodes=15,
        early_stopping=False, random_state=0).fit(xtr, ytr)
    theirs = float(np.mean((ref.predict(xte) - yte) ** 2))

    # energyefficiency L2 rows in BASELINE.md carry +-1.0 on values ~4;
    # the same relative slack vs the measured comparator
    assert ours <= theirs * 1.25, (ours, theirs)


def test_decision_type_missing_bits_honored():
    """Imported numerical decision_type bits: bit 1 default-left, bits
    2-3 missing type (1 = zeros are missing)."""
    text = "\n".join([
        "tree", "version=v4", "num_class=1", "num_tree_per_iteration=1",
        "label_index=0", "max_feature_idx=0", "objective=regression",
        "feature_names=f0", "feature_infos=none", "",
        "Tree=0", "num_leaves=2", "num_cat=0",
        "split_feature=0", "split_gain=1", "threshold=0.5",
        "decision_type=0",  # default RIGHT for missing
        "left_child=-1", "right_child=-2",
        "leaf_value=1.0 2.0", "leaf_weight=0 0", "leaf_count=1 1",
        "internal_value=0", "internal_weight=0", "internal_count=2",
        "is_linear=0", "shrinkage=1", "",
        "Tree=1", "num_leaves=2", "num_cat=0",
        "split_feature=0", "split_gain=1", "threshold=0.5",
        "decision_type=6",  # default left + zeros-are-missing
        "left_child=-1", "right_child=-2",
        "leaf_value=10.0 20.0", "leaf_weight=0 0", "leaf_count=1 1",
        "internal_value=0", "internal_weight=0", "internal_count=2",
        "is_linear=0", "shrinkage=1", "",
        "end of trees", "",
    ])
    b = BoosterArrays.load_model_string(text)
    pred = np.asarray(b.predict_jit()(
        np.array([[0.2], [0.8], [np.nan], [0.0]])))
    # tree0 (missing_type none): NaN converts to 0.0 <= 0.5 -> left (1);
    # 0.2->1, 0.8->2, 0.0->1. tree1 (default left, zeros+NaN missing):
    # 0.2->10, 0.8->20, NaN -> missing -> left (10), 0.0 -> missing -> 10.
    np.testing.assert_allclose(pred, [11.0, 22.0, 11.0, 11.0])
    # re-saving preserves the imported bits
    b2 = BoosterArrays.load_model_string(b.save_model_string())
    np.testing.assert_allclose(
        np.asarray(b2.predict_jit()(np.array([[np.nan], [0.0]]))),
        [11.0, 11.0])
    # a default-RIGHT NaN-missing node (decision_type = 8 | 0 = missing
    # nan, default right) routes NaN right
    text3 = text.replace("decision_type=0", "decision_type=8")
    b3 = BoosterArrays.load_model_string(text3)
    np.testing.assert_allclose(
        np.asarray(b3.predict_jit()(np.array([[np.nan], [0.2]]))),
        [12.0, 11.0])


def test_multiclass_import_interleaving():
    """A hand-written 3-class v4 model string: trees interleave per
    class (tree t -> class t % K), scoring returns (N, K) where each
    class's column comes only from its own trees, and the independent
    walker agrees tree-by-tree."""
    def tree_block(i, leaf_lo, leaf_hi):
        return [
            f"Tree={i}", "num_leaves=2", "num_cat=0",
            "split_feature=0", "split_gain=1", "threshold=0.5",
            "decision_type=2",
            "left_child=-1", "right_child=-2",
            f"leaf_value={leaf_lo} {leaf_hi}", "leaf_weight=3 3",
            "leaf_count=3 3",
            "internal_value=0", "internal_weight=0", "internal_count=6",
            "is_linear=0", "shrinkage=1", "",
        ]

    lines = [
        "tree", "version=v4", "num_class=3", "num_tree_per_iteration=3",
        "label_index=0", "max_feature_idx=0",
        "objective=multiclass num_class:3",
        "feature_names=f0", "feature_infos=none", "",
    ]
    # two boosting iterations x 3 classes; class c leaves = c*10 (+1)
    for it in range(2):
        for c in range(3):
            lines += tree_block(it * 3 + c, c * 10 + it,
                                c * 10 + it + 1)
    lines += ["end of trees", ""]
    text = "\n".join(lines)

    b = BoosterArrays.load_model_string(text)
    assert b.num_class == 3 and b.num_trees == 6
    x = np.array([[0.2], [0.8]])
    pred = np.asarray(b.predict_jit()(x))
    assert pred.shape == (2, 3)
    # class c at x<=0.5: iter0 leaf (c*10+0) + iter1 leaf (c*10+1)
    np.testing.assert_allclose(pred[0], [1.0, 21.0, 41.0])
    np.testing.assert_allclose(pred[1], [3.0, 23.0, 43.0])
    # independent walker agrees per class
    trees = _parse_trees(text)
    for c in range(3):
        walked = sum(_walk(trees[it * 3 + c], x) for it in range(2))
        np.testing.assert_allclose(pred[:, c], walked)
    # per-class SHAP blocks sum to each class margin on import too
    shap = np.asarray(b.contrib_jit()(x)).reshape(2, 3, 2)
    np.testing.assert_allclose(shap.sum(axis=2), pred, atol=1e-5)
