"""Native C++ level-histogram kernel vs the XLA formulations, and the
unified best-available dispatch policy (ISSUE 1 tentpole).

The native kernel (native/data_plane.cpp mmls_level_hist_*) is the CPU
default, so most of the suite exercises it implicitly; these tests pin
it EXPLICITLY against every XLA formulation — with and without the
compiled library (numpy fallback), across empty nodes, subtraction
on/off, and per-shard inside both explicit shard_map tree learners.
"""

import numpy as np
import pytest

import mmlspark_tpu.native.bindings as bindings_mod
from mmlspark_tpu.models.gbdt import trainer as trainer_mod
from mmlspark_tpu.models.gbdt.trainer import (
    TrainConfig,
    _level_histogram,
    resolve_histogram_formulation,
    resolve_subtract,
    train,
)
from mmlspark_tpu.ops.binning import BinMapper


def _case(n, f, b, width, seed=0, integer_stats=False, bin_dtype=np.uint8):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int64)
                         .astype(bin_dtype))
    if integer_stats:
        grad = jnp.asarray(rng.integers(-8, 9, size=n).astype(np.float32))
        hess = jnp.asarray(rng.integers(1, 9, size=n).astype(np.float32))
    else:
        grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
        hess = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    live = jnp.asarray((rng.random(n) < 0.9).astype(np.float32))
    local = jnp.asarray(rng.integers(0, width, size=n, dtype=np.int64)
                        .astype(np.int32))
    return binned, grad, hess, live, local


def _fit_data(n=1500, f=6, max_bin=64, seed=11):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    y = (x[:, 0] * x[:, 1] + 0.3 * x[:, 2]
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=max_bin)
    return x, y, mapper.transform(x), mapper.bin_upper_values(max_bin)


# the XLA formulations agree exactly with each other (pinned by
# test_hist_pallas.py::test_formulation_override_agrees), so the shape
# matrix runs against per_feature only and one case fans out across
# the other formulations — same coverage, ~half the jit compiles
@pytest.mark.parametrize("n,f,b,width,bin_dtype,xla", [
    (2000, 7, 32, 4, np.uint8, "per_feature"),    # generic
    (2000, 7, 32, 4, np.uint8, "separate"),
    (2000, 7, 32, 4, np.uint8, "fused"),
    (999, 3, 255, 8, np.int32, "per_feature"),    # int32, full bin range
    (100, 5, 16, 16, np.uint8, "per_feature"),    # empty nodes
    (4096, 2, 64, 1, np.uint8, "per_feature"),    # root level
    (3000, 4, 63, 32, np.uint8, "per_feature"),   # wide level, many nodes
])
def test_native_matches_xla_formulations(n, f, b, width, bin_dtype, xla,
                                         monkeypatch):
    case = _case(n, f, b, width, bin_dtype=bin_dtype)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    got = np.asarray(_level_histogram(*case, width, f, b,
                                      allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", xla)
    ref = np.asarray(_level_histogram(*case, width, f, b,
                                      allow_pallas=False))
    assert got.shape == ref.shape == (width, f, b, 3)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)
    # counts are integers: exact
    np.testing.assert_array_equal(got[..., 2], ref[..., 2])


def test_bitwise_exact_on_integer_stats(monkeypatch):
    """Integer-valued grad/hess make every f32 add exact, so summation
    order cannot matter: native must be bit-for-bit against XLA."""
    case = _case(3000, 4, 63, 8, integer_stats=True)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    got = np.asarray(_level_histogram(*case, 8, 4, 63,
                                      allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "fused")
    ref = np.asarray(_level_histogram(*case, 8, 4, 63,
                                      allow_pallas=False))
    np.testing.assert_array_equal(got, ref)


def test_numpy_fallback_parity(monkeypatch):
    """Without the compiled library the formulation must still work
    (bincount fallback) and agree with the C++ kernel — the acceptance
    path for compiler-less environments."""
    case = _case(2500, 5, 31, 8, seed=3)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    native = np.asarray(_level_histogram(*case, 8, 5, 31,
                                         allow_pallas=False))
    monkeypatch.setattr(bindings_mod, "ensure_built", lambda: False)
    fallback = np.asarray(_level_histogram(*case, 8, 5, 31,
                                           allow_pallas=False))
    np.testing.assert_allclose(fallback, native, rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(fallback[..., 2], native[..., 2])


@pytest.mark.parametrize("formulation", ["native", "onehot"])
def test_empty_input_returns_zero_histogram(formulation, monkeypatch):
    """ADVICE r5 regression: a zero-row level used to raise
    ZeroDivisionError in the onehot chunk math; native must handle the
    degenerate shape too."""
    case = _case(0, 4, 16, 2)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", formulation)
    out = np.asarray(_level_histogram(*case, 2, 4, 16,
                                      allow_pallas=False))
    assert out.shape == (2, 4, 16, 3)
    assert not out.any()


def test_forced_per_feature_warns_under_shard_map(monkeypatch):
    """ADVICE r5: the forced-per_feature -> separate downgrade inside
    shard_map must warn once (mistyped values already did), so A/B
    measurement labels stay honest."""
    monkeypatch.setattr(trainer_mod, "_WARNED_SHARD_DOWNGRADE", False)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "per_feature")
    with pytest.warns(UserWarning, match="per_feature"):
        choice = resolve_histogram_formulation(31, in_shard_map=True,
                                               allow_pallas=False)
    assert choice == "separate"
    # outside shard_map the forced value is honored, no warning
    assert resolve_histogram_formulation(
        31, in_shard_map=False, allow_pallas=False) == "per_feature"


def test_forced_native_warns_under_gspmd(monkeypatch):
    """allow_native=False models the serial-builder-under-mesh (GSPMD)
    case: a forced native request must downgrade loudly, not silently
    mislabel an A/B run."""
    monkeypatch.setattr(trainer_mod, "_WARNED_NATIVE_DOWNGRADE", False)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    with pytest.warns(UserWarning, match="native"):
        choice = resolve_histogram_formulation(31, allow_native=False,
                                               allow_pallas=False)
    assert choice in ("per_feature", "separate", "fused")


def test_default_resolution_policy(monkeypatch):
    """Best-available on the CPU backend: native when the library
    loads; MMLSPARK_TPU_NATIVE_HIST=0 falls back to the XLA defaults;
    subtraction defaults track the native resolution."""
    if not trainer_mod.native_histogram_available():
        pytest.skip("native library not built in this environment")
    assert resolve_histogram_formulation(255) == "native"
    assert resolve_histogram_formulation(255, in_shard_map=True) == "native"
    assert resolve_subtract("serial", 255) is True
    assert resolve_subtract("voting", 255) is False
    monkeypatch.setenv("MMLSPARK_TPU_NATIVE_HIST", "0")
    assert resolve_histogram_formulation(255) == "per_feature"
    assert resolve_histogram_formulation(255, in_shard_map=True) == "fused"
    assert resolve_subtract("serial", 255) is False
    # the explicit env override still forces subtraction on XLA
    monkeypatch.setenv("MMLSPARK_TPU_HIST_SUB", "1")
    assert resolve_subtract("serial", 255) is True


def test_trainer_routes_native_by_default(monkeypatch):
    """A plain serial fit on the CPU backend must run the C++ kernel
    (ensure_built smoke: a silent numpy/XLA fallback here would undo
    the tentpole), and produce the same model as the XLA formulation."""
    if not trainer_mod.native_histogram_available():
        pytest.skip("native library not built in this environment")
    x, y, binned, bu = _fit_data()
    cfg = TrainConfig(objective="binary", num_iterations=4, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5, max_bin=64)
    calls = {"n": 0}
    orig = bindings_mod.level_histogram

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(bindings_mod, "level_histogram", counting)
    res_native = train(binned, y, cfg, bin_upper=bu)
    assert calls["n"] > 0, "default CPU fit did not use the native kernel"
    monkeypatch.setenv("MMLSPARK_TPU_NATIVE_HIST", "0")
    res_xla = train(binned, y, cfg, bin_upper=bu)
    p0 = np.asarray(res_native.booster.predict_jit()(x))
    p1 = np.asarray(res_xla.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("sub", ["0", "1"])
def test_native_subtraction_parity(sub, monkeypatch):
    """The masked smaller-child pass (native subtract) against the full
    pass, with bagging exercising fractional live masks' 0/1 branches;
    both against the XLA reference."""
    x, y, binned, bu = _fit_data(n=3000)
    # deep-ish trees + bagging exercise dead branches and live masks
    cfg = TrainConfig(objective="binary", num_iterations=6, num_leaves=31,
                      max_depth=5, min_data_in_leaf=10, max_bin=64,
                      bagging_fraction=0.8, bagging_freq=1)
    monkeypatch.setenv("MMLSPARK_TPU_NATIVE_HIST", "0")
    base = train(binned, y, cfg, bin_upper=bu)
    monkeypatch.delenv("MMLSPARK_TPU_NATIVE_HIST")
    monkeypatch.setenv("MMLSPARK_TPU_HIST_SUB", sub)
    got = train(binned, y, cfg, bin_upper=bu)
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(got.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-3, atol=1e-3)
    # well-separated root splits must agree exactly
    assert (base.booster.split_feature[:, 0]
            == got.booster.split_feature[:, 0]).all()


@pytest.mark.parametrize("tree_learner,mesh_cfg", [
    ("voting", dict(dp=8)),
    ("feature", dict(dp=1, fp=8)),
])
def test_native_under_shard_map_modes(monkeypatch, tree_learner, mesh_cfg):
    """The distributed tree learners run the native kernel PER-SHARD
    inside their explicit shard_maps (local rows only; the psum on the
    returned histogram is unchanged) and reproduce the XLA path."""
    if not trainer_mod.native_histogram_available():
        pytest.skip("native library not built in this environment")
    from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(**mesh_cfg))
    x, y, binned, bu = _fit_data(n=512, f=8, max_bin=32, seed=5)
    cfg = TrainConfig(objective="binary", num_iterations=4, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5, max_bin=32,
                      tree_learner=tree_learner, top_k=8)
    monkeypatch.setenv("MMLSPARK_TPU_NATIVE_HIST", "0")
    base = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    monkeypatch.delenv("MMLSPARK_TPU_NATIVE_HIST")

    calls = {"n": 0}
    orig = bindings_mod.level_histogram

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(bindings_mod, "level_histogram", counting)
    swapped = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    assert calls["n"] > 0, "native kernel not selected per-shard"
    # per-shard float sum order differs from the XLA scatter's, so
    # compare predictions to float tolerance, not trees bit-for-bit
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(swapped.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-4)
