"""Pallas histogram kernel vs the XLA formulations (VERDICT r3 #2).

Interpret mode on CPU; the TPU compile + timing runs through
``bench_hist.py``'s ``pallas`` variant on real hardware.
"""

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.hist_pallas import pallas_level_histogram
from mmlspark_tpu.models.gbdt.trainer import _level_histogram


def _case(n, f, b, width, seed=0, integer_stats=False):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int64)
                         .astype(np.uint8))
    if integer_stats:
        grad = jnp.asarray(rng.integers(-8, 9, size=n).astype(np.float32))
        hess = jnp.asarray(rng.integers(1, 9, size=n).astype(np.float32))
    else:
        grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
        hess = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    live = jnp.asarray((rng.random(n) < 0.9).astype(np.float32))
    local = jnp.asarray(rng.integers(0, width, size=n, dtype=np.int64)
                        .astype(np.int32))
    return binned, grad, hess, live, local


@pytest.mark.parametrize("n,f,b,width", [
    (2000, 7, 32, 4),     # generic
    (999, 3, 255, 8),     # n not divisible by block, full bin range
    (100, 5, 16, 16),     # more nodes than fit one row block; empty nodes
    (4096, 2, 64, 1),     # single node (root level)
])
def test_matches_xla_histogram(n, f, b, width):
    binned, grad, hess, live, local = _case(n, f, b, width)
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      width, f, b))
    got = np.asarray(pallas_level_histogram(binned, grad, hess, live,
                                            local, width, f, b,
                                            interpret=True))
    assert got.shape == ref.shape == (width, f, b, 3)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)
    # counts are integers: exact
    np.testing.assert_array_equal(got[..., 2], ref[..., 2])


def test_bitwise_exact_on_integer_stats():
    """With integer-valued grad/hess every f32 add is exact, so block
    order cannot matter: the kernel must be bit-for-bit."""
    binned, grad, hess, live, local = _case(3000, 4, 63, 8,
                                            integer_stats=True)
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 4, 63))
    got = np.asarray(pallas_level_histogram(binned, grad, hess, live,
                                            local, 8, 4, 63,
                                            interpret=True))
    np.testing.assert_array_equal(got, ref)


def test_skewed_node_distribution():
    """One dominant node + several empties exercises the per-node block
    padding and the first-visit zero-init of untouched output tiles."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n, f, b, width = 2500, 3, 32, 8
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int64)
                         .astype(np.uint8))
    grad = jnp.asarray(rng.normal(size=n).astype(np.float32))
    hess = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    live = jnp.ones(n, jnp.float32)
    local = jnp.asarray(np.where(rng.random(n) < 0.95, 3, 6)
                        .astype(np.int32))
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      width, f, b))
    got = np.asarray(pallas_level_histogram(binned, grad, hess, live,
                                            local, width, f, b,
                                            interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-4)
    # empty nodes are exactly zero, not stale VMEM
    for w in (0, 1, 2, 4, 5, 7):
        assert not np.any(got[w])


def test_trainer_env_flag_routes_to_pallas(monkeypatch):
    """MMLSPARK_TPU_PALLAS_HIST=1 swaps the kernel into the training
    path and produces an equivalent model."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(3)
    x = rng.normal(size=(600, 5))
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.1 * rng.normal(size=600) > 0
         ).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="binary", num_iterations=4, num_leaves=8,
                      max_depth=3, min_data_in_leaf=5, max_bin=32)
    bu = mapper.bin_upper_values(32)
    base = train(binned, y, cfg, bin_upper=bu)
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS_HIST", "1")
    # count actual kernel entries: the flag keys the compiled-step
    # cache, so the second train must re-trace through the pallas path
    import mmlspark_tpu.models.gbdt.hist_pallas as hp
    calls = {"n": 0}
    orig = hp.pallas_level_histogram

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(hp, "pallas_level_histogram", counting)
    swapped = train(binned, y, cfg, bin_upper=bu)
    assert calls["n"] > 0, "flag did not route through the pallas kernel"
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(swapped.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("tree_learner,mesh_cfg", [
    ("voting", dict(dp=8)),
    ("feature", dict(dp=1, fp=8)),
])
def test_pallas_under_shard_map_modes(monkeypatch, tree_learner, mesh_cfg):
    """The distributed tree learners run the histogram inside shard_map;
    with MMLSPARK_TPU_PALLAS_HIST=1 the pallas kernel must be selected
    per-shard (local rows only, psum on the returned histogram) and
    reproduce the XLA path's trees exactly (VERDICT r4 weak #3 — without
    this the flagship kernel is single-chip-only)."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper
    from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(**mesh_cfg))
    rng = np.random.default_rng(5)
    x = rng.normal(size=(512, 8))
    logit = 1.5 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2]
    y = (logit + rng.normal(size=512) * 0.3 > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    bu = mapper.bin_upper_values(32)
    cfg = TrainConfig(objective="binary", num_iterations=4, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5, max_bin=32,
                      tree_learner=tree_learner, top_k=8)
    base = train(binned, y, cfg, bin_upper=bu, mesh=mesh)

    monkeypatch.setenv("MMLSPARK_TPU_PALLAS_HIST", "1")
    import mmlspark_tpu.models.gbdt.hist_pallas as hp
    calls = {"n": 0}
    orig = hp.pallas_level_histogram

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(hp, "pallas_level_histogram", counting)
    swapped = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    assert calls["n"] > 0, "flag did not route the shard_map histogram " \
                           "through the pallas kernel"
    # the two paths sum histograms in different orders, so compare
    # predictions to float tolerance (1-ulp histogram drift may flip a
    # near-tied split), not tree structure bit-for-bit
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(swapped.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-4)


def test_dp_serial_with_flag_bypasses_pallas(monkeypatch, rng):
    """The serial builder under a mesh runs via GSPMD, which cannot
    partition Mosaic kernels — with MMLSPARK_TPU_PALLAS_HIST=1 it must
    silently take the XLA formulation (identical trees to flag-off),
    not crash at TPU compile (pinned at lowering level in
    test_mosaic_lowering.py; this is the execution-level twin)."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper
    from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(dp=8))
    x = rng.normal(size=(512, 6))
    y = (x[:, 0] - 0.5 * x[:, 1] > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    bu = mapper.bin_upper_values(32)
    cfg = TrainConfig(objective="binary", num_iterations=3, num_leaves=7,
                      max_depth=3, min_data_in_leaf=5, max_bin=32)
    base = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    monkeypatch.setenv("MMLSPARK_TPU_PALLAS_HIST", "1")
    flagged = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    np.testing.assert_array_equal(base.booster.split_feature,
                                  flagged.booster.split_feature)
    np.testing.assert_array_equal(base.booster.threshold_bin,
                                  flagged.booster.threshold_bin)
    np.testing.assert_array_equal(base.booster.node_value,
                                  flagged.booster.node_value)


def test_histogram_subtraction_matches_full(monkeypatch):
    """MMLSPARK_TPU_HIST_SUB=1 derives sibling histograms by
    subtraction (LightGBM's trick); models must match the full
    formulation to float-cancellation tolerance."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(11)
    x = rng.normal(size=(3000, 6))
    y = (x[:, 0] * x[:, 1] + 0.3 * x[:, 2]
         + 0.1 * rng.normal(size=3000) > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=64)
    binned = mapper.transform(x)
    bu = mapper.bin_upper_values(64)
    # deep-ish trees + bagging exercise dead branches and live masks
    cfg = TrainConfig(objective="binary", num_iterations=6, num_leaves=31,
                      max_depth=5, min_data_in_leaf=10, max_bin=64,
                      bagging_fraction=0.8, bagging_freq=1)
    base = train(binned, y, cfg, bin_upper=bu)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_SUB", "1")
    sub = train(binned, y, cfg, bin_upper=bu)
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(sub.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-3, atol=1e-3)
    # identical structure on well-separated early splits
    assert (base.booster.split_feature[:, 0]
            == sub.booster.split_feature[:, 0]).all()


@pytest.mark.parametrize("forced", ["per_feature", "separate", "fused"])
def test_formulation_override_agrees(forced, monkeypatch):
    """MMLSPARK_TPU_HIST_FORMULATION selects each XLA formulation; all
    must produce identical histograms (the separate branch is the
    production default for shard_map on TPU and is otherwise never
    selected on CPU, so this is its coverage). The unforced default on
    CPU is now the native kernel (pinned to float tolerance in
    test_hist_native.py), so the exact-equality reference here is the
    fused scatter."""
    binned, grad, hess, live, local = _case(3000, 5, 31, 8, seed=3)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "fused")
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 5, 31, allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", forced)
    out = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 5, 31, allow_pallas=False))
    np.testing.assert_array_equal(out, ref)


def test_formulation_override_bogus_value_warns_and_uses_default(
        monkeypatch):
    from mmlspark_tpu.models.gbdt import trainer as trainer_mod
    monkeypatch.setattr(trainer_mod, "_WARNED_BAD_FORMULATION", False)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "perfeature")
    binned, grad, hess, live, local = _case(1000, 3, 15, 4, seed=4)
    with pytest.warns(UserWarning, match="perfeature"):
        ref = np.asarray(_level_histogram(
            binned, grad, hess, live, local, 4, 3, 15,
            allow_pallas=False))
    monkeypatch.delenv("MMLSPARK_TPU_HIST_FORMULATION")
    out = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      4, 3, 15, allow_pallas=False))
    np.testing.assert_array_equal(ref, out)


def test_onehot_formulation_matches_to_tolerance(monkeypatch):
    """The MXU one-hot contraction sums in a different order than
    segment_sum: counts must be exact (integer f32 sums), grad/hess to
    float tolerance."""
    binned, grad, hess, live, local = _case(5000, 7, 31, 8, seed=5)
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 7, 31, allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "onehot")
    out = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 7, 31, allow_pallas=False))
    np.testing.assert_array_equal(out[..., 2], ref[..., 2])
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-4)


def test_onehot_formulation_padded_tail(monkeypatch):
    """n not divisible by the chunk: padded rows must contribute
    nothing."""
    binned, grad, hess, live, local = _case(4999, 3, 15, 4, seed=6)
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      4, 3, 15, allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "onehot")
    out = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      4, 3, 15, allow_pallas=False))
    np.testing.assert_array_equal(out[..., 2], ref[..., 2])
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-4)


@pytest.mark.parametrize("extra,rtol", [
    ({"MMLSPARK_TPU_ONEHOT_CHUNK": "3000"}, 2e-5),  # non-divisor
    ({"MMLSPARK_TPU_ONEHOT_CHUNK": "zero?"}, 2e-5),  # bad: warn + default
    ({"MMLSPARK_TPU_ONEHOT_BF16": "1"}, 1e-2),
])
def test_onehot_tuning_knobs(monkeypatch, extra, rtol):
    """Chunk-size and bf16 knobs (on-window A/Bs) keep counts exact and
    grad/hess within the knob's documented tolerance."""
    binned, grad, hess, live, local = _case(5000, 7, 31, 8, seed=7)
    ref = np.asarray(_level_histogram(binned, grad, hess, live, local,
                                      8, 7, 31, allow_pallas=False))
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "onehot")
    for k, v in extra.items():
        monkeypatch.setenv(k, v)
    bad_chunk = not extra.get("MMLSPARK_TPU_ONEHOT_CHUNK",
                              "1").lstrip("-").isdigit()
    if bad_chunk:
        from mmlspark_tpu.core import env as env_mod
        env_mod.reset_warnings()
        with pytest.warns(UserWarning, match="ONEHOT_CHUNK"):
            out = np.asarray(_level_histogram(
                binned, grad, hess, live, local, 8, 7, 31,
                allow_pallas=False))
    else:
        out = np.asarray(_level_histogram(
            binned, grad, hess, live, local, 8, 7, 31,
            allow_pallas=False))
    np.testing.assert_array_equal(out[..., 2], ref[..., 2])
    np.testing.assert_allclose(out, ref, rtol=rtol, atol=rtol * 10)


@pytest.mark.parametrize("tree_learner,mesh_cfg", [
    ("voting", dict(dp=8)),
    ("feature", dict(dp=1, fp=8)),
])
def test_onehot_under_shard_map_modes(monkeypatch, tree_learner,
                                      mesh_cfg):
    """The onehot formulation is shard_map-safe (the scan carry
    inherits the per-shard varying axes) so multi-chip training can
    select it if it wins the TPU microbench."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper
    from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(**mesh_cfg))
    rng = np.random.default_rng(5)
    x = rng.normal(size=(512, 8))
    logit = 1.5 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2]
    y = (logit + rng.normal(size=512) * 0.3 > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    bu = mapper.bin_upper_values(32)
    cfg = TrainConfig(objective="binary", num_iterations=4, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5, max_bin=32,
                      tree_learner=tree_learner, top_k=8)
    base = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "onehot")
    oh = train(binned, y, cfg, bin_upper=bu, mesh=mesh)
    p0 = np.asarray(base.booster.predict_jit()(x))
    p1 = np.asarray(oh.booster.predict_jit()(x))
    np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-4)
