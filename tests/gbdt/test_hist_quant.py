"""Quantized-gradient histogram parity (MMLSPARK_TPU_HIST_QUANT).

The quantization contract (arXiv:2011.02022 applied to this engine):
grad/hess round to int16/int8 under a shared power-of-two scale, bins
accumulate exactly in integers, and dequantization is one float32
multiply by the inverse (power-of-two) scale. That makes the native
kernel, its numpy fallback, the XLA segment_sum mirror and the Pallas
kernel agree to float32 SUMMATION ORDER only — and bit-for-bit
wherever the sums are exact (counts always; grad/hess when per-cell
int sums fit float32 exactly).

The `quant_smoke` marker is the CI lint-workflow guardrail: small-N
q16-vs-f32 end-to-end parity in well under a minute.
"""

import numpy as np
import pytest

import mmlspark_tpu.native.bindings as bindings_mod
from mmlspark_tpu.core.env import env_override
from mmlspark_tpu.models.gbdt import trainer as trainer_mod
from mmlspark_tpu.models.gbdt.trainer import (
    TrainConfig,
    _level_histogram,
    _level_histogram_quant,
    _pow2_scale,
    resolve_hist_quant,
    train,
)
from mmlspark_tpu.ops.binning import BinMapper


def _quant_case(n=3000, f=5, b=63, width=4, seed=0, qdt=np.int16):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    binned = rng.integers(0, b, size=(n, f)).astype(np.uint8)
    grad = rng.normal(size=n).astype(np.float32)
    hess = rng.uniform(0.1, 1.0, size=n).astype(np.float32)
    live = (rng.random(n) < 0.9).astype(np.float32)
    local = rng.integers(0, width, size=n).astype(np.int32)
    qmax = 120.0 if qdt == np.int8 else 32000.0
    gs, gsi = _pow2_scale(jnp.float32(np.abs(grad * live).max()), qmax)
    hs, hsi = _pow2_scale(jnp.float32(np.abs(hess * live).max()), qmax)
    gq = np.rint(grad * live * float(gs)).astype(qdt)
    hq = np.rint(hess * live * float(hs)).astype(qdt)
    return binned, gq, hq, live, local, float(gsi), float(hsi)


def _exact_reference(binned, gq, hq, live, local, width, b, gsi, hsi):
    """int64-exact bincount reference, one final f32 rounding — the
    contract both the native kernel and its fallback implement."""
    n, f = binned.shape
    out = np.zeros((width, f, b, 3), np.float32)
    gate = live != 0
    idx_base = local.astype(np.int64) * b
    chans = (np.where(gate, gq, 0).astype(np.float64),
             np.where(gate, hq, 0).astype(np.float64),
             gate.astype(np.float64))
    scales = (np.float64(gsi), np.float64(hsi), np.float64(1.0))
    for j in range(f):
        idx = idx_base + binned[:, j]
        for c, (w, s) in enumerate(zip(chans, scales)):
            sums = np.bincount(idx, weights=w, minlength=width * b)
            out[:, j, :, c] = (sums.reshape(width, b) * s).astype(
                np.float32)
    return out


@pytest.mark.parametrize("qdt", [np.int16, np.int8])
def test_native_kernel_bit_identical_to_exact_reference(qdt):
    """int64 worker accumulators + a single f32 rounding by a pow2
    inverse scale: the C++ kernel must reproduce the exact integer
    reference bit-for-bit, any thread count, any path."""
    binned, gq, hq, live, local, gsi, hsi = _quant_case(qdt=qdt, seed=2)
    got = bindings_mod.level_histogram_quant(
        binned, gq, hq, (live != 0).astype(np.uint8), local, 4, 63,
        gsi, hsi)
    ref = _exact_reference(binned, gq, hq, live, local, 4, 63, gsi, hsi)
    np.testing.assert_array_equal(got, ref)


def test_native_and_numpy_fallback_bit_identical(monkeypatch):
    binned, gq, hq, live, local, gsi, hsi = _quant_case(seed=5)
    lv = (live != 0).astype(np.uint8)
    native = bindings_mod.level_histogram_quant(
        binned, gq, hq, lv, local, 4, 63, gsi, hsi)
    monkeypatch.setattr(bindings_mod, "quant_histogram_available",
                        lambda: False)
    fallback = bindings_mod.level_histogram_quant(
        binned, gq, hq, lv, local, 4, 63, gsi, hsi)
    np.testing.assert_array_equal(native, fallback)


@pytest.mark.parametrize("qdt", [np.int16, np.int8])
def test_three_formulations_agree(qdt):
    """native callback vs XLA chunked segment_sum vs Pallas
    (interpret): same dequantized values, f32-sum-order tolerance,
    counts exact."""
    import jax.numpy as jnp

    binned, gq, hq, live, local, gsi, hsi = _quant_case(qdt=qdt, seed=7)
    args = (jnp.asarray(binned), jnp.asarray(gq), jnp.asarray(hq),
            jnp.asarray(live), jnp.asarray(local), 4, 5, 63,
            jnp.float32(gsi), jnp.float32(hsi))
    h_native = np.asarray(_level_histogram_quant(*args, "native"))
    h_xla = np.asarray(_level_histogram_quant(*args, "per_feature"))
    h_pallas = np.asarray(_level_histogram_quant(*args, "pallas"))
    np.testing.assert_allclose(h_xla, h_native, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(h_pallas, h_native, rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(h_xla[..., 2], h_native[..., 2])
    np.testing.assert_array_equal(h_pallas[..., 2], h_native[..., 2])


def test_empty_input_returns_zeros():
    import jax.numpy as jnp

    out = _level_histogram_quant(
        jnp.zeros((0, 3), jnp.int32), jnp.zeros(0, jnp.int16),
        jnp.zeros(0, jnp.int16), jnp.zeros(0, jnp.float32),
        jnp.zeros(0, jnp.int32), 2, 3, 8, jnp.float32(1.0),
        jnp.float32(1.0), "per_feature")
    out = np.asarray(out)
    assert out.shape == (2, 3, 8, 3)
    assert not out.any()


def _fit_case(n=6000, f=8, max_bin=64, seed=11):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    y = (x[:, 0] - 0.5 * x[:, 1] * x[:, 2]
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    return BinMapper.fit(x, max_bin=max_bin).transform(x), y


def _split_agreement(b1, b2):
    m = (b1.split_feature >= 0) | (b2.split_feature >= 0)
    if not m.any():
        return 1.0
    return float(((b1.split_feature == b2.split_feature)
                  & (b1.threshold_bin == b2.threshold_bin))[m].mean())


@pytest.mark.quant_smoke
@pytest.mark.parametrize("quant", ["q16", "q8"])
def test_quantized_fit_parity_vs_f32(quant):
    """End-to-end: a quantized fit must track the f32 fit. q16's
    15-bit grid reproduces near-identical trees, so it is pinned at
    the split level; q8's 7-bit grid legitimately picks different
    (near-tied) splits as rounds compound, so it is pinned at the
    quality level — root splits, prediction drift, and training loss
    within quantization tolerance."""
    binned, y = _fit_case()
    cfg = TrainConfig(objective="binary", num_iterations=15,
                      num_leaves=15, max_depth=5, min_data_in_leaf=20,
                      seed=3)
    with env_override("MMLSPARK_TPU_HIST_QUANT", None):
        r_f32 = train(binned, y, cfg)
    with env_override("MMLSPARK_TPU_HIST_QUANT", quant):
        r_q = train(binned, y, cfg)
    assert r_q.hist_stats["hist_quant"] == quant
    assert r_f32.hist_stats["hist_quant"] == "off"
    p_f32 = np.asarray(r_f32.booster.predict_binned_fn()(binned))
    p_q = np.asarray(r_q.booster.predict_binned_fn()(binned))

    def logloss(p):
        p = np.clip(p, 1e-7, 1 - 1e-7)
        return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())

    if quant == "q16":
        assert _split_agreement(r_f32.booster, r_q.booster) >= 0.98
        assert np.abs(p_f32 - p_q).mean() < 2e-3
    else:
        np.testing.assert_array_equal(r_q.booster.split_feature[:, 0],
                                      r_f32.booster.split_feature[:, 0])
        assert np.abs(p_f32 - p_q).mean() < 0.1
    assert logloss(p_q) <= logloss(p_f32) * 1.05 + 1e-3


@pytest.mark.quant_smoke
def test_quantized_fit_deterministic_and_token_released():
    """Same seed + q16 twice -> bit-identical boosters, and the
    host-binned registry must be empty afterwards (the fit releases
    its token even on the quantized path)."""
    binned, y = _fit_case(n=3000, f=5)
    cfg = TrainConfig(objective="binary", num_iterations=8,
                      num_leaves=7, max_depth=4, seed=5)
    with env_override("MMLSPARK_TPU_HIST_QUANT", "q16"):
        r1 = train(binned, y, cfg)
        r2 = train(binned, y, cfg)
    for fld in ("split_feature", "threshold_bin", "node_value", "count"):
        np.testing.assert_array_equal(getattr(r1.booster, fld),
                                      getattr(r2.booster, fld))
    assert trainer_mod._HOST_BINNED_REG == {}


def test_quant_xla_backend_matches_native_backend_structure(monkeypatch):
    """The same q16 fit through the native callback and through the
    pure-XLA mirror must pick identical trees (dequantized operands
    are identical; only f32 sum order differs)."""
    binned, y = _fit_case(n=4000, f=6, seed=13)
    cfg = TrainConfig(objective="binary", num_iterations=10,
                      num_leaves=15, max_depth=5, seed=1)
    with env_override("MMLSPARK_TPU_HIST_QUANT", "q16"):
        r_native = train(binned, y, cfg)
        with env_override("MMLSPARK_TPU_NATIVE_HIST", "0"):
            r_xla = train(binned, y, cfg)
    assert _split_agreement(r_native.booster, r_xla.booster) == 1.0


def test_bad_quant_value_warns_once_and_downgrades(monkeypatch):
    monkeypatch.setattr(trainer_mod, "_WARNED_BAD_QUANT", False)
    with env_override("MMLSPARK_TPU_HIST_QUANT", "int4"):
        with pytest.warns(UserWarning, match="HIST_QUANT"):
            assert resolve_hist_quant() == "off"
        # second resolution is silent (warn-once)
        assert resolve_hist_quant() == "off"


def test_quant_in_shard_map_downgrades_with_warning(monkeypatch):
    monkeypatch.setattr(trainer_mod, "_WARNED_QUANT_SHARD", False)
    with env_override("MMLSPARK_TPU_HIST_QUANT", "q16"):
        with pytest.warns(UserWarning, match="shard"):
            assert resolve_hist_quant(in_shard_map=True) == "off"
        assert resolve_hist_quant(in_shard_map=False) == "q16"
