"""Reduce-scatter histogram sharding (MMLSPARK_TPU_HIST_SHARD) on the
8-device CPU mesh.

The pinned contract: fits through the sharded data-parallel builder
(psum_scatter feature slices + owned-slice split selection) are
BITWISE-identical — trees and predictions — to the full-psum path, at
every dp that divides the device count, including feature counts the
dp axis does not divide. Plus the policy surface: hist_stats
attribution, forced-on downgrade warnings, and the interactions with
histogram subtraction, the leafwise downgrade, and quantized
histograms (all of which the sharded path must ignore bitwise).
"""

import numpy as np
import pytest

from mmlspark_tpu.core.env import env_override
from mmlspark_tpu.core import sanitizer as san
from mmlspark_tpu.models.gbdt import trainer as trainer_mod
from mmlspark_tpu.models.gbdt.parallel_modes import (
    hist_reduction_bytes, make_build_tree_data_parallel)
from mmlspark_tpu.models.gbdt.trainer import (TrainConfig,
                                              resolve_hist_shard, train)
from mmlspark_tpu.ops.binning import BinMapper
from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh


def _data(n=1024, f=10, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    logit = 1.5 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2]
    y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.float64)
    return x, y


def _fit(x, y, mesh, shard, max_bin=32, **cfg_kw):
    mapper = BinMapper.fit(x, max_bin=max_bin)
    base = dict(objective="binary", num_iterations=4, num_leaves=15,
                max_depth=4, min_data_in_leaf=5, max_bin=max_bin)
    base.update(cfg_kw)
    cfg = TrainConfig(**base)
    with env_override("MMLSPARK_TPU_HIST_SHARD", shard):
        return train(mapper.transform(x), y, cfg,
                     bin_upper=mapper.bin_upper_values(max_bin),
                     mesh=mesh)


def _assert_bitwise_trees(a, b):
    np.testing.assert_array_equal(a.booster.split_feature,
                                  b.booster.split_feature)
    np.testing.assert_array_equal(a.booster.threshold_bin,
                                  b.booster.threshold_bin)
    assert np.array_equal(np.asarray(a.booster.node_value),
                          np.asarray(b.booster.node_value))
    assert np.array_equal(np.asarray(a.booster.count),
                          np.asarray(b.booster.count))


@pytest.fixture(scope="module")
def dp8():
    return create_mesh(MeshConfig(dp=8))


@pytest.fixture(scope="module", params=[2, 4, 8])
def dp_mesh(request):
    import jax
    dp = request.param
    return create_mesh(MeshConfig(dp=dp), devices=jax.devices()[:dp])


class TestBitwiseParity:
    def test_sharded_matches_full_psum_at_every_dp(self, dp_mesh):
        """Trees AND predictions bitwise-equal at dp=2/4/8, with a
        feature count (10) the dp axis does not divide — the padded
        columns must never win a split."""
        x, y = _data()
        on = _fit(x, y, dp_mesh, "on")
        off = _fit(x, y, dp_mesh, "off")
        assert on.hist_stats["hist_shard"] == "on"
        assert off.hist_stats["hist_shard"] == "off"
        _assert_bitwise_trees(on, off)
        assert np.array_equal(np.asarray(on.booster.predict_fn()(x)),
                              np.asarray(off.booster.predict_fn()(x)))

    @pytest.mark.shard_smoke
    def test_auto_resolves_on_and_matches_full_psum(self, dp8):
        """auto (the default) routes dp>1 fits through the sharded
        builder; the CI smoke pins the bitwise contract at dp=8."""
        x, y = _data(n=512, f=8)
        auto = _fit(x, y, dp8, None, num_iterations=3)   # unset -> auto
        off = _fit(x, y, dp8, "off", num_iterations=3)
        assert auto.hist_stats["hist_shard"] == "on"
        _assert_bitwise_trees(auto, off)

    def test_subtraction_interaction(self, dp8):
        """The sharded builder never subtracts (sibling compaction is
        data-dependent): forcing HIST_SUB either way must not change a
        sharded fit's bits."""
        x, y = _data()
        with env_override("MMLSPARK_TPU_HIST_SUB", "1"):
            sub_on = _fit(x, y, dp8, "on")
        with env_override("MMLSPARK_TPU_HIST_SUB", "0"):
            sub_off = _fit(x, y, dp8, "on")
        assert sub_on.hist_stats["hist_shard"] == "on"
        _assert_bitwise_trees(sub_on, sub_off)

    def test_leafwise_downgrade_interaction(self, dp8):
        """GROW_POLICY=leafwise downgrades to depthwise under a mesh;
        the sharded reduction must compose with that downgrade and stay
        bitwise-equal to the full-psum fit of the same downgrade."""
        import warnings as w
        x, y = _data()
        with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"):
            with w.catch_warnings():
                w.simplefilter("ignore")
                on = _fit(x, y, dp8, "on")
                off = _fit(x, y, dp8, "off")
        assert on.hist_stats["grow_policy"] == "depthwise"
        assert on.hist_stats["hist_shard"] == "on"
        _assert_bitwise_trees(on, off)

    def test_quant_downgrade_interaction(self, dp8, monkeypatch):
        """HIST_QUANT under a mesh warns once, records hist_quant=off,
        and leaves the sharded fit's bits untouched."""
        x, y = _data(n=512, f=8)
        plain = _fit(x, y, dp8, "on", num_iterations=3)
        monkeypatch.setattr(trainer_mod, "_WARNED_QUANT_SHARD", False)
        with env_override("MMLSPARK_TPU_HIST_QUANT", "q16"):
            with pytest.warns(UserWarning, match="single-program only"):
                quant = _fit(x, y, dp8, "on", num_iterations=3)
        assert quant.hist_stats["hist_quant"] == "off"
        assert quant.hist_stats["hist_shard"] == "on"
        _assert_bitwise_trees(plain, quant)


class TestShardOwnership:
    def test_uneven_features_builder_twin(self, dp8):
        """Direct builder-level contract for features % dp != 0: the
        psum_scatter path and its full-psum twin produce bitwise-equal
        trees, and no padded feature id (>= F) ever wins a split."""
        import jax.numpy as jnp
        rng = np.random.default_rng(7)
        n, f, b = 512, 10, 16
        binned = rng.integers(0, b, size=(n, f)).astype(np.int32)
        grad = rng.normal(size=n).astype(np.float32)
        hess = np.ones(n, dtype=np.float32)
        valid = np.ones(n, dtype=np.float32)
        feat_mask = np.ones(f, dtype=np.float32)
        cfg = TrainConfig(num_leaves=15, max_depth=4, min_data_in_leaf=5,
                          max_bin=b)
        args = (jnp.asarray(binned), jnp.asarray(grad),
                jnp.asarray(hess), jnp.asarray(valid),
                jnp.asarray(feat_mask), jnp.int32(15))
        sharded = make_build_tree_data_parallel(f, b, cfg, dp8,
                                                shard_hist=True)(*args)
        full = make_build_tree_data_parallel(f, b, cfg, dp8,
                                             shard_hist=False)(*args)
        for s_arr, f_arr in zip(sharded, full):
            assert np.array_equal(np.asarray(s_arr), np.asarray(f_arr))
        sf = np.asarray(sharded[0])
        assert sf.max() < f and sf.min() >= -1
        assert (sf >= 0).any()  # the fit actually split

    def test_reduction_bytes_accounting(self):
        """The analytic payload model behind the MULTICHIP metrics:
        sharded bytes approach full/dp as the combine overhead
        amortizes, and dp=1 sharding is a no-op in the model."""
        full = hist_reduction_bytes(256, 64, 6, 8, sharded=False)
        shard = hist_reduction_bytes(256, 64, 6, 8, sharded=True)
        assert full == sum((2 ** d) * 256 * 64 * 3 * 4 for d in range(6))
        assert full / shard > 6.0   # ~8x minus combine overhead
        assert hist_reduction_bytes(256, 64, 6, 1, sharded=True) >= \
            hist_reduction_bytes(256, 64, 6, 1, sharded=False)


class TestPolicy:
    def test_serial_fit_records_off(self):
        x, y = _data(n=256, f=4)
        res = _fit(x, y, None, None, num_iterations=2)
        assert res.hist_stats["hist_shard"] == "off"
        assert "hist_shard_reason" not in res.hist_stats

    def test_unsupported_learner_records_reason(self, dp8):
        x, y = _data(n=512, f=8)
        res = _fit(x, y, dp8, None, num_iterations=2,
                   tree_learner="voting", top_k=8)
        assert res.hist_stats["hist_shard"] == "off"
        assert "voting" in res.hist_stats["hist_shard_reason"]

    def test_forced_on_downgrade_warns_once(self, dp8, monkeypatch):
        x, y = _data(n=512, f=8)
        monkeypatch.setattr(trainer_mod, "_WARNED_SHARD_DOWNGRADE_DP",
                            False)
        with pytest.warns(UserWarning, match="cannot shard"):
            res = _fit(x, y, dp8, "on", num_iterations=2,
                       tree_learner="voting", top_k=8)
        assert res.hist_stats["hist_shard"] == "off"

    def test_forced_on_without_mesh_warns_once(self, monkeypatch):
        """Forcing =on on a mesh-less fit is still a downgrade the
        user asked not to have — same warn-once contract as the
        unsupported-config case, no silent fallback."""
        x, y = _data(n=256, f=4)
        monkeypatch.setattr(trainer_mod, "_WARNED_SHARD_DOWNGRADE_DP",
                            False)
        with pytest.warns(UserWarning, match="no device mesh"):
            res = _fit(x, y, None, "on", num_iterations=2)
        assert res.hist_stats["hist_shard"] == "off"

    def test_bad_value_warns_and_runs_auto(self, monkeypatch):
        monkeypatch.setattr(trainer_mod, "_WARNED_BAD_SHARD", False)
        with env_override("MMLSPARK_TPU_HIST_SHARD", "bogus"):
            with pytest.warns(UserWarning, match="HIST_SHARD"):
                assert resolve_hist_shard() == "auto"

    def test_sanitizer_records_psum_scatter(self, dp8):
        """The collective protocol the sharded builder compiles must
        show the reduce-scatter to graftsan's divergence cross-check."""
        trainer_mod._CHUNK_CACHE.clear()
        trainer_mod._BUILDER_CACHE.clear()
        san.enable()
        try:
            rec = san.CollectiveRecorder()
            x, y = _data(n=512, f=8)
            with san.use_recorder(rec):
                _fit(x, y, dp8, "on", num_iterations=2)
            ops = [e[0] for e in rec.events]
            assert "psum_scatter" in ops
            assert "all_gather" in ops
        finally:
            san.disable()
            san.reset()
        trainer_mod._CHUNK_CACHE.clear()
        trainer_mod._BUILDER_CACHE.clear()
