"""Leaf-wise (max-gain priority queue) tree growth
(MMLSPARK_TPU_GROW_POLICY=leafwise; arXiv:1706.08359 §2).

Determinism is the load-bearing property: the heap is keyed
(-gain, slot) and split-argmax ties break on the first maximum, so a
repeated fit must be BIT-identical — under every histogram
formulation, since split decisions happen on float64 host math over
f32 histogram sums that each formulation must reproduce.
"""

import numpy as np
import pytest

from mmlspark_tpu.core.env import env_override
from mmlspark_tpu.models.gbdt import trainer as trainer_mod
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper

_BOOSTER_ARRAYS = ("split_feature", "threshold_bin", "node_value",
                   "count", "decision_type")


def _fit_case(n=6000, f=7, seed=17):
    """Gain-skewed data: a strong interaction on one side of the root
    split, so leaf-wise growth genuinely diverges from depth-wise."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    left = x[:, 0] < 0
    signal = np.where(left, x[:, 1] * x[:, 2] + x[:, 3],
                      0.2 * x[:, 4])
    y = (signal + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    return BinMapper.fit(x, max_bin=64).transform(x), y


def _cfg(**kw):
    base = dict(objective="binary", num_iterations=8, num_leaves=10,
                max_depth=8, min_data_in_leaf=20, seed=4)
    base.update(kw)
    return TrainConfig(**base)


def _booster_equal(b1, b2):
    for fld in _BOOSTER_ARRAYS:
        a1, a2 = getattr(b1, fld, None), getattr(b2, fld, None)
        if a1 is None or a2 is None:
            continue
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2),
                                      err_msg=fld)


@pytest.mark.parametrize("formulation", ["", "native", "flat"])
def test_repeated_fits_bit_identical(formulation):
    """Same data + seed + policy -> bit-identical booster, for the
    auto, native-callback, and pure-XLA histogram formulations."""
    binned, y = _fit_case()
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"), \
            env_override("MMLSPARK_TPU_HIST_FORMULATION",
                         formulation or None):
        r1 = train(binned, y, _cfg())
        r2 = train(binned, y, _cfg())
    assert r1.hist_stats["grow_policy"] == "leafwise"
    _booster_equal(r1.booster, r2.booster)


def test_num_leaves_cap_and_actual_divergence_from_depthwise():
    # seed 23's draw is skewed enough that a 10-leaf budget spent
    # greedily picks different splits than level-order growth
    binned, y = _fit_case(seed=23)
    cfg = _cfg(num_leaves=10, max_depth=8)
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"):
        r_leaf = train(binned, y, cfg)
    with env_override("MMLSPARK_TPU_GROW_POLICY", None):
        r_depth = train(binned, y, cfg)
    leaves = r_leaf.booster.num_leaves_per_tree
    assert (leaves <= 10).all()
    assert leaves.max() == 10  # rich signal: the budget is actually used
    assert r_depth.hist_stats["grow_policy"] == "depthwise"
    # the policies must pick genuinely different trees on this data
    assert not np.array_equal(r_leaf.booster.split_feature,
                              r_depth.booster.split_feature)


def test_leafwise_quality_reasonable():
    """Leaf-wise spends the same leaf budget where the gain is; on
    gain-skewed data it must at least match depth-wise training loss
    within a small margin (usually beating it)."""
    binned, y = _fit_case(seed=23)
    cfg = _cfg(num_iterations=12)
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"):
        r_leaf = train(binned, y, cfg)
    with env_override("MMLSPARK_TPU_GROW_POLICY", None):
        r_depth = train(binned, y, cfg)

    def logloss(r):
        p = np.clip(np.asarray(r.booster.predict_binned_fn()(binned)),
                    1e-7, 1 - 1e-7)
        return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())

    assert logloss(r_leaf) <= logloss(r_depth) * 1.02


def test_unsupported_config_downgrades_with_warning(monkeypatch):
    binned, y = _fit_case(n=2000, f=5)
    cfg = _cfg(num_iterations=3,
               monotone_constraints=(1, 0, 0, 0, 0))
    monkeypatch.setattr(trainer_mod, "_WARNED_LEAFWISE_DOWNGRADE", False)
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"):
        with pytest.warns(UserWarning, match="monotone_constraints"):
            r = train(binned, y, cfg)
    assert r.hist_stats["grow_policy"] == "depthwise"
    # warn-once: the second downgraded fit is silent
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"):
        r2 = train(binned, y, cfg)
    assert r2.hist_stats["grow_policy"] == "depthwise"
    _booster_equal(r.booster, r2.booster)


def test_bad_grow_policy_value_warns_once(monkeypatch):
    from mmlspark_tpu.models.gbdt.trainer import resolve_grow_policy

    monkeypatch.setattr(trainer_mod, "_WARNED_BAD_GROW", False)
    with env_override("MMLSPARK_TPU_GROW_POLICY", "lossguide"):
        with pytest.warns(UserWarning, match="GROW_POLICY"):
            assert resolve_grow_policy() == "depthwise"
        assert resolve_grow_policy() == "depthwise"


def test_leafwise_ignores_quant_and_efb():
    """Leaf-wise histograms on the host loop's own matrix: quant/EFB
    requests must be recorded as inactive, and the fit must still be
    deterministic."""
    binned, y = _fit_case(n=3000, f=5)
    with env_override("MMLSPARK_TPU_GROW_POLICY", "leafwise"), \
            env_override("MMLSPARK_TPU_HIST_QUANT", "q16"), \
            env_override("MMLSPARK_TPU_EFB", "on"):
        r = train(binned, y, _cfg(num_iterations=4))
    assert r.hist_stats["grow_policy"] == "leafwise"
    assert r.hist_stats["hist_quant"] == "off"
    assert r.hist_stats["efb_bundles"] == 0
