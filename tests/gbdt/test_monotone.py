"""Monotone constraints (LightGBM monotone_constraints, basic method)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor


def _monotone_violations(model, x, feature, grid=None):
    """Max violation of non-decreasing predictions when sweeping one
    feature over a grid with the other features fixed per row."""
    grid = grid if grid is not None else np.linspace(-3, 3, 41)
    worst = 0.0
    for row in x[:20]:
        probe = np.tile(row, (len(grid), 1))
        probe[:, feature] = grid
        pred = np.asarray(model.booster.predict_jit()(probe))
        worst = max(worst, float(np.max(np.diff(pred) * -1)))
    return worst


@pytest.fixture(scope="module")
def noisy_df():
    rng = np.random.default_rng(0)
    n = 3000
    x = rng.normal(size=(n, 3))
    # increasing in x0 with noise that tempts violating splits
    y = 1.5 * x[:, 0] + np.sin(x[:, 1] * 3) + rng.normal(size=n) * 0.8
    return DataFrame({"features": x, "label": y}), x


def test_constrained_fit_is_monotone(noisy_df):
    df, x = noisy_df
    kw = dict(numIterations=40, numLeaves=15, maxDepth=4, maxBin=64)
    free = LightGBMRegressor(**kw).fit(df)
    mono = LightGBMRegressor(monotoneConstraints=[1, 0, 0], **kw).fit(df)

    # unconstrained model violates monotonicity somewhere on noisy data;
    # the constrained one must not (beyond float noise)
    v_free = _monotone_violations(free, x, 0)
    v_mono = _monotone_violations(mono, x, 0)
    assert v_mono <= 1e-5, v_mono
    assert v_free > v_mono

    # constraint costs little quality on a truly monotone relationship
    y = np.asarray(df.col("label"))
    for m in (free, mono):
        pred = m.transform(df)["prediction"]
        assert float(np.corrcoef(pred, y)[0, 1]) > 0.8


def test_decreasing_constraint(noisy_df):
    df, x = noisy_df
    mono = LightGBMRegressor(monotoneConstraints=[-1, 0, 0],
                             numIterations=20, numLeaves=15,
                             maxDepth=4, maxBin=64).fit(df)
    # sweeping x0 upward must never increase predictions
    grid = np.linspace(-3, 3, 41)
    for row in x[:10]:
        probe = np.tile(row, (len(grid), 1))
        probe[:, 0] = grid
        pred = np.asarray(mono.booster.predict_jit()(probe))
        assert float(np.max(np.diff(pred))) <= 1e-5


def test_unconstrained_config_unchanged(noisy_df):
    """monotone_constraints=() must be byte-identical to the previous
    behavior (the fast path skips all bound bookkeeping)."""
    df, _ = noisy_df
    kw = dict(numIterations=5, numLeaves=8, maxBin=32)
    a = LightGBMRegressor(**kw).fit(df)
    b = LightGBMRegressor(monotoneConstraints=[0, 0, 0], **kw).fit(df)
    np.testing.assert_array_equal(a.booster.node_value, b.booster.node_value)
