"""Out-of-core chunked boosting (models/gbdt/ooc.py).

The contract under test: with shared (sketch-derived) bin edges, the
streamed fit produces IDENTICAL trees to the in-core path — bitwise, not
approximately — while holding only chunk-sized state resident. Plus the
dispatch policy (MMLSPARK_TPU_OOC=auto|off|on), downgrade semantics,
chunk-store label streaming, and resume through segment checkpoints.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.faults import FaultInjected
from mmlspark_tpu.models.gbdt import ooc
from mmlspark_tpu.models.gbdt import trainer as T
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor
from mmlspark_tpu.ops.binning import BinMapper
from mmlspark_tpu.ops.ingest import ChunkStore, SpillWriter, binned_ingest_dtype

_BOOSTER_ARRAYS = ("split_feature", "threshold_bin", "node_value", "count")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def parity_env(monkeypatch):
    """Pin the planes where OOC and in-core are defined to coincide:
    quantized histograms (f32 sums are not chunk-associative) and no
    EFB (bundling decisions see full columns in-core only)."""
    monkeypatch.setenv("MMLSPARK_TPU_HIST_QUANT", "q16")
    monkeypatch.setenv("MMLSPARK_TPU_EFB", "off")
    monkeypatch.setenv("MMLSPARK_TPU_OOC_CHUNK_ROWS", "1024")


def _make_data(rng, n=4000, f=8):
    x = rng.normal(size=(n, f))
    x[:, 3] = rng.integers(0, 5, size=n)  # low-cardinality column
    y = (x[:, 0] * 2 + np.sin(x[:, 1])
         + 0.1 * rng.normal(size=n)).astype(np.float64)
    return x, y


@pytest.mark.ooc_smoke
def test_ooc_parity_bitwise_with_in_core(rng, parity_env, monkeypatch):
    """The tentpole acceptance: streamed fit == in-core fit
    tree-for-tree on a size both can hold, with bin edges from the
    streaming sketch path feeding both."""
    x, y = _make_data(rng)
    bm = BinMapper.fit_streaming(iter([x[:1777], x[1777:3200], x[3200:]]),
                                 max_bin=63)
    binned = bm.transform(x)
    cfg = T.TrainConfig(objective="regression", num_iterations=6,
                        max_depth=4, num_leaves=14, learning_rate=0.2,
                        max_bin=63)

    monkeypatch.setenv("MMLSPARK_TPU_OOC", "off")
    r_in = T.train(binned, y, cfg)
    assert r_in.hist_stats["ooc"] is False
    assert r_in.hist_stats["ooc_reason"] == "MMLSPARK_TPU_OOC=off"

    monkeypatch.setenv("MMLSPARK_TPU_OOC", "on")
    r_ooc = T.train(binned, y, cfg)
    st = r_ooc.hist_stats
    assert st["ooc"] is True and st["ooc_reason"] is None
    assert st["chunk_rows"] == 1024 and st["n_chunks"] == 4
    assert st["hist_quant"] == "q16"

    for name in _BOOSTER_ARRAYS:
        np.testing.assert_array_equal(
            getattr(r_in.booster, name), getattr(r_ooc.booster, name),
            err_msg=f"booster.{name} diverged between in-core and ooc")


@pytest.mark.ooc_smoke
def test_ooc_kill_and_resume_mid_ensemble(rng, parity_env, monkeypatch,
                                          tmp_path):
    """A streamed fit killed mid-ensemble resumes through the PR 2
    segment checkpoints and reproduces the uninterrupted streamed run
    bitwise (the OOC dispatch re-engages per resumed segment)."""
    monkeypatch.setenv("MMLSPARK_TPU_OOC", "on")
    x, y = _make_data(rng, n=2500, f=4)
    df = DataFrame({"features": x, "label": y})
    kw = dict(numIterations=9, numLeaves=8, maxBin=32,
              checkpointInterval=3)

    ref = LightGBMRegressor(checkpointDir=str(tmp_path / "a"), **kw).fit(df)

    # hit 7 = first iteration of the third segment: checkpoints at 3
    # and 6 are committed, iteration 7's work dies with the "process"
    ckb = str(tmp_path / "b")
    with faults.injected("gbdt.train_step", "raise", nth=7):
        with pytest.raises(FaultInjected):
            LightGBMRegressor(checkpointDir=ckb, **kw).fit(df)
    names = sorted(n for n in os.listdir(ckb) if n.endswith(".txt"))
    assert names == ["checkpoint_3.txt", "checkpoint_6.txt"]

    resumed = LightGBMRegressor(checkpointDir=ckb, **kw).fit(df)
    assert resumed.booster.num_trees == 9
    np.testing.assert_array_equal(
        np.asarray(ref.transform(df)["prediction"]),
        np.asarray(resumed.transform(df)["prediction"]))


def test_ooc_auto_threshold_and_reason(rng, parity_env, monkeypatch):
    x, y = _make_data(rng, n=2000, f=4)
    binned = BinMapper.fit(x, max_bin=32).transform(x)
    cfg = T.TrainConfig(objective="regression", num_iterations=2,
                        max_depth=3, max_bin=32)

    monkeypatch.delenv("MMLSPARK_TPU_OOC", raising=False)
    small = T.train(binned, y, cfg)
    assert small.hist_stats["ooc"] is False
    assert "below" in small.hist_stats["ooc_reason"]

    # auto engages once the row count crosses MMLSPARK_TPU_OOC_ROWS
    monkeypatch.setenv("MMLSPARK_TPU_OOC_ROWS", "1000")
    big = T.train(binned, y, cfg)
    assert big.hist_stats["ooc"] is True
    assert big.hist_stats["n_chunks"] == 2


def test_ooc_on_downgrades_unsupported_with_one_warning(
        rng, parity_env, monkeypatch):
    monkeypatch.setenv("MMLSPARK_TPU_OOC", "on")
    monkeypatch.setattr(T, "_WARNED_OOC_DOWNGRADE", False)
    x, y = _make_data(rng, n=1500, f=4)
    binned = BinMapper.fit(x, max_bin=32).transform(x)
    cfg = T.TrainConfig(objective="regression", num_iterations=2,
                        max_depth=3, max_bin=32, feature_fraction=0.5)
    with pytest.warns(UserWarning, match="cannot stream"):
        r = T.train(binned, y, cfg)
    assert r.hist_stats["ooc"] is False
    assert r.hist_stats["ooc_reason"] == "feature sampling"
    # warn-once: the second downgraded fit stays quiet
    import warnings
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        T.train(binned, y, cfg)
    assert not [w for w in rec if "cannot stream" in str(w.message)]


def test_train_ooc_chunk_store_labels_match_array_labels(
        rng, parity_env, tmp_path):
    """A truly larger-than-memory fit passes labels per chunk; the
    streamed weighted-mean base score and every downstream tree must
    be bitwise identical to the array-label path over the same spill."""
    x, y = _make_data(rng, n=3000, f=5)
    bm = BinMapper.fit_streaming(iter([x[:1300], x[1300:]]), max_bin=32)
    cfg = T.TrainConfig(objective="regression", num_iterations=3,
                        max_depth=3, max_bin=32)
    writer = SpillWriter(str(tmp_path / "spill"),
                         dtype=binned_ingest_dtype(cfg.max_bin))
    labels = ChunkStore(str(tmp_path / "labels"), "y")
    for i, (s, e) in enumerate(((0, 1100), (1100, 2150), (2150, 3000))):
        writer.append(bm.transform(x[s:e]))
        labels.put(i, y[s:e].astype(np.float32))
    spill = writer.finalize()

    r_store = ooc.train_ooc(spill, labels, cfg,
                            work_dir=str(tmp_path / "w1"))
    r_array = ooc.train_ooc(spill, y, cfg, work_dir=str(tmp_path / "w2"))
    assert r_store.hist_stats["ooc"] is True
    assert r_store.hist_stats["n_chunks"] == 3
    for name in _BOOSTER_ARRAYS:
        np.testing.assert_array_equal(getattr(r_store.booster, name),
                                      getattr(r_array.booster, name))


def test_train_ooc_rejects_unsupported_and_median_objectives(
        rng, parity_env, tmp_path):
    x, y = _make_data(rng, n=1200, f=4)
    bm = BinMapper.fit(x, max_bin=32)
    writer = SpillWriter(str(tmp_path / "spill"), dtype=np.uint8)
    labels = ChunkStore(str(tmp_path / "labels"), "y")
    writer.append(bm.transform(x[:700]))
    writer.append(bm.transform(x[700:]))
    labels.put(0, y[:700].astype(np.float32))
    labels.put(1, y[700:].astype(np.float32))
    spill = writer.finalize()

    bad = T.TrainConfig(objective="regression", num_iterations=2,
                        max_bin=32, feature_fraction=0.5)
    with pytest.raises(ValueError, match="cannot stream"):
        ooc.train_ooc(spill, y, bad, work_dir=str(tmp_path / "w"))

    # median-based init needs full labels: chunk stores must refuse
    # loudly rather than silently approximating
    l1 = T.TrainConfig(objective="regression_l1", num_iterations=2,
                       max_bin=32)
    with pytest.raises(ValueError, match="median"):
        ooc.train_ooc(spill, labels, l1, work_dir=str(tmp_path / "w2"))
    # ...but full array labels stream fine under the same objective
    r = ooc.train_ooc(spill, y, l1, work_dir=str(tmp_path / "w3"))
    assert r.booster.num_trees == 2


_RSS_SCRIPT = r"""
import resource
import sys

import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

from mmlspark_tpu.models.gbdt import ooc
from mmlspark_tpu.models.gbdt import trainer as T
from mmlspark_tpu.ops.binning import BinMapper
from mmlspark_tpu.ops.ingest import ChunkStore, SpillWriter

mode, spill_dir = sys.argv[1], sys.argv[2]
N, F, CHUNK = 4_000_000, 8, 262_144


def gen(i, rows):
    r = np.random.default_rng(1000 + i)
    return r.normal(size=(rows, F))


def chunks():
    for i, s in enumerate(range(0, N, CHUNK)):
        yield i, s, gen(i, min(CHUNK, N - s))


bm = BinMapper.fit_streaming((c for _, _, c in chunks()), max_bin=32)
cfg = T.TrainConfig(objective="regression", num_iterations=4,
                    max_depth=3, max_bin=32)

if mode == "ooc":
    writer = SpillWriter(spill_dir + "/binned", dtype=np.uint8)
    labels = ChunkStore(spill_dir + "/labels", "y")
    for i, s, c in chunks():
        writer.append(bm.transform(c))
        labels.put(i, (c[:, 0] * 2.0).astype(np.float32))
    spill = writer.finalize()
    base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    marks = []
    cb = lambda t, info: marks.append(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    r = ooc.train_ooc(spill, labels, cfg, work_dir=spill_dir + "/w",
                      callbacks=[cb])
    assert r.hist_stats["ooc"] is True
    # growth after the first TWO full passes (jit compiles land across
    # the first iterations, allocator arenas warm, every per-row store
    # populated): the steady state
    peak0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print("STEADY_KB", peak0 - marks[1], flush=True)
else:
    binned = np.empty((N, F), dtype=np.uint8)
    y = np.empty(N, dtype=np.float32)
    for i, s, c in chunks():
        binned[s:s + len(c)] = bm.transform(c)
        y[s:s + len(c)] = (c[:, 0] * 2.0).astype(np.float32)
    base = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    r = T.train(binned, y, cfg)

peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
print("DELTA_KB", peak - base, flush=True)
"""


def _fit_rss_delta_mb(mode, tmp_path):
    env = dict(os.environ,
               MMLSPARK_TPU_HIST_QUANT="q16", MMLSPARK_TPU_EFB="off",
               MMLSPARK_TPU_OOC="off" if mode == "incore" else "on",
               MMLSPARK_TPU_OOC_CHUNK_ROWS="262144",
               PYTHONPATH=os.getcwd() + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    d = tmp_path / mode
    d.mkdir()
    out = subprocess.run(
        [sys.executable, "-c", _RSS_SCRIPT, mode, str(d)],
        capture_output=True, text=True, timeout=420, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    vals = {}
    for l in out.stdout.splitlines():
        if l.startswith(("DELTA_KB", "STEADY_KB")):
            key, v = l.split()
            vals[key] = int(v) / 1024.0
    return vals


def test_ooc_fit_rss_stays_chunk_bounded(tmp_path):
    """Peak RSS growth during the streamed fit must track the chunk
    working set, not the row count: on the same 4M-row fit the in-core
    path materializes full-N device state (binned + grad/hess/raw,
    ~100MB+) while the OOC loop holds chunk-sized buffers. The total
    OOC delta includes one-time jit-compile/allocator-arena overhead
    (tens of MB, run-to-run noisy), so the sharp bound is on the
    STEADY-state growth after the first two full passes — per-row state
    all lives on disk by then, so further growth can only be
    chunk-scale."""
    got = _fit_rss_delta_mb("ooc", tmp_path)
    # absolute caps first — pressure-robust (memory pressure can only
    # shrink an RSS delta, never inflate it): the streamed fit stays
    # well under the ~300MB full-N in-core working set even counting
    # the one-time warmup overhead...
    assert got["DELTA_KB"] < 224, (
        f"ooc fit grew {got['DELTA_KB']:.0f}MB — full-N scale, "
        "not chunk-bounded")
    # ...and once warm, boosting adds only chunk-scale memory
    # (chunk working set here is ~10MB; in-core-style growth would be
    # full-N scale, 100MB+)
    assert got["STEADY_KB"] < 48, (
        f"steady-state ooc growth {got['STEADY_KB']:.0f}MB is not "
        "chunk-bounded")
    # The relative leg needs a quiet box: under global memory pressure
    # (e.g. the full suite running in the parent) the kernel evicts
    # pages mid-fit and ru_maxrss never rises above the pre-train
    # baseline — the in-core probe reads ~0MB. Compare only when the
    # probe actually saw the full-N working set; the absolute caps
    # above carry the bound either way.
    incore = _fit_rss_delta_mb("incore", tmp_path)["DELTA_KB"]
    if incore > 60:
        assert got["DELTA_KB"] < incore, (
            f"ooc fit grew {got['DELTA_KB']:.0f}MB vs "
            f"in-core {incore:.0f}MB")
