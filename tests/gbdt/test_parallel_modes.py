"""Voting-parallel + feature-parallel tree learners on the 8-device CPU
mesh, compared against the serial builder (LightGBM parallelism modes,
LightGBMParams.scala:25-29)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt.estimators import LightGBMClassifier
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper
from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh


def _data(n=512, f=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    logit = 1.5 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2]
    y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.float64)
    return x, y


def _train(x, y, tree_learner, mesh=None, top_k=20, max_bin=32):
    mapper = BinMapper.fit(x, max_bin=max_bin)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="binary", num_iterations=5, num_leaves=15,
                      max_depth=4, min_data_in_leaf=5, max_bin=max_bin,
                      tree_learner=tree_learner, top_k=top_k)
    return train(binned, y, cfg, bin_upper=mapper.bin_upper_values(max_bin),
                 mesh=mesh)


@pytest.fixture(scope="module")
def dp_mesh():
    return create_mesh(MeshConfig(dp=8))


@pytest.fixture(scope="module")
def fp_mesh():
    return create_mesh(MeshConfig(dp=1, fp=8))


class TestFeatureParallel:
    def test_identical_trees_to_serial(self, fp_mesh):
        x, y = _data()
        serial = _train(x, y, "serial")
        feat = _train(x, y, "feature", mesh=fp_mesh)
        # feature-parallel computes the same global histograms and the
        # same argmax tie-break, so trees must match exactly
        assert np.array_equal(serial.booster.split_feature,
                              feat.booster.split_feature)
        assert np.array_equal(serial.booster.threshold_bin,
                              feat.booster.threshold_bin)
        assert np.allclose(serial.booster.node_value,
                           feat.booster.node_value, atol=1e-4)

    def test_indivisible_features_raise(self, fp_mesh):
        x, y = _data(f=6)  # 6 features, fp=8
        with pytest.raises(ValueError, match="divisible"):
            _train(x, y, "feature", mesh=fp_mesh)


class TestVotingParallel:
    def test_full_topk_matches_data_parallel(self, dp_mesh):
        x, y = _data()
        serial = _train(x, y, "serial")
        # top_k >= F: every feature is a candidate -> same splits as full
        # histogram reduction
        voting = _train(x, y, "voting", mesh=dp_mesh, top_k=8)
        assert np.array_equal(serial.booster.split_feature,
                              voting.booster.split_feature)
        assert np.array_equal(serial.booster.threshold_bin,
                              voting.booster.threshold_bin)

    def test_small_topk_still_learns(self, dp_mesh):
        x, y = _data(n=1024, f=16, seed=3)
        voting = _train(x, y, "voting", mesh=dp_mesh, top_k=2)
        pred = np.asarray(voting.booster.predict_fn()(x))
        acc = ((pred > 0) == (y > 0)).mean()
        assert acc > 0.85  # informative features win the vote


class TestEstimatorWiring:
    def test_parallelism_param_routes(self, dp_mesh):
        x, y = _data(n=256)
        df = DataFrame({"features": x, "label": y})
        clf = LightGBMClassifier(numIterations=3, numLeaves=7,
                                 parallelism="voting_parallel", topK=4,
                                 maxBin=32).set_mesh(dp_mesh)
        model = clf.fit(df)
        out = model.transform(df)
        acc = (out.col("prediction") == y).mean()
        assert acc > 0.8
