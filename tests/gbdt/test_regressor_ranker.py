"""Regressor/ranker accuracy benchmarks.

Energy-efficiency-style L2 regression across boosting types mirrors
benchmarks_VerifyLightGBMRegressorBulk.csv; lambdarank NDCG mirrors the
MSLR barrier-mode config tracked in BASELINE.md.
"""

import numpy as np
import pytest
from sklearn.datasets import fetch_california_housing, make_regression

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt import (
    LightGBMRanker,
    LightGBMRegressionModel,
    LightGBMRegressor,
)


def regression_df(n=800, seed=0):
    X, y = make_regression(n_samples=n, n_features=12, n_informative=8,
                           noise=5.0, random_state=seed)
    y = y / np.abs(y).max() * 10
    return DataFrame({"features": X, "label": y})


@pytest.mark.parametrize("boosting", ["gbdt", "rf", "dart", "goss"])
def test_regression_r2_benchmark(boosting):
    df = regression_df()
    reg = LightGBMRegressor(
        numIterations=60, numLeaves=31, maxDepth=5, minDataInLeaf=5,
        boostingType=boosting,
        baggingFraction=0.8 if boosting == "rf" else 1.0,
        baggingFreq=1 if boosting == "rf" else 0, seed=11)
    pred = reg.fit(df).transform(df)["prediction"]
    y = df["label"]
    r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
    floor = {"gbdt": 0.9, "rf": 0.55, "dart": 0.8, "goss": 0.9}[boosting]
    assert r2 > floor, f"{boosting}: r2={r2}"


@pytest.mark.parametrize("objective", ["regression_l1", "huber", "quantile",
                                       "fair", "mape"])
def test_alt_objectives_train(objective):
    df = regression_df(400)
    reg = LightGBMRegressor(numIterations=20, objective=objective,
                            minDataInLeaf=5, alpha=0.5)
    pred = reg.fit(df).transform(df)["prediction"]
    y = df["label"]
    mae = np.abs(pred - y).mean()
    assert mae < np.abs(y - np.median(y)).mean(), f"{objective}: MAE {mae}"


@pytest.mark.parametrize("objective", ["poisson", "tweedie", "gamma"])
def test_log_link_objectives(objective):
    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 5))
    rate = np.exp(0.4 * X[:, 0] - 0.3 * X[:, 1] + 0.5)
    y = rng.poisson(rate).astype(np.float64) + (0.01 if objective == "gamma" else 0.0)
    df = DataFrame({"features": X, "label": y})
    reg = LightGBMRegressor(numIterations=30, objective=objective,
                            minDataInLeaf=10)
    pred = reg.fit(df).transform(df)["prediction"]
    assert np.all(pred > 0)  # log-link predictions are positive
    corr = np.corrcoef(pred, rate)[0, 1]
    assert corr > 0.5, f"{objective}: corr {corr}"


def test_quantile_crossing():
    df = regression_df(500)
    lo = LightGBMRegressor(numIterations=30, objective="quantile", alpha=0.1,
                           minDataInLeaf=10).fit(df).transform(df)["prediction"]
    hi = LightGBMRegressor(numIterations=30, objective="quantile", alpha=0.9,
                           minDataInLeaf=10).fit(df).transform(df)["prediction"]
    # the 90th-percentile predictor should usually sit above the 10th
    assert (hi >= lo).mean() > 0.8
    y = df["label"]
    assert (y <= hi).mean() > 0.6 and (y >= lo).mean() > 0.6


def test_regressor_save_load(tmp_path):
    df = regression_df(300)
    model = LightGBMRegressor(numIterations=10, minDataInLeaf=5).fit(df)
    model.save(str(tmp_path / "m"))
    loaded = LightGBMRegressionModel.load(str(tmp_path / "m"))
    assert np.allclose(model.transform(df)["prediction"],
                       loaded.transform(df)["prediction"])


def make_ranking(num_groups=30, per_group=12, seed=5):
    rng = np.random.default_rng(seed)
    rows = num_groups * per_group
    X = rng.normal(size=(rows, 6))
    group = np.repeat(np.arange(num_groups), per_group)
    # relevance driven by two features
    score = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=rows)
    rel = np.zeros(rows)
    for g in range(num_groups):
        idx = np.nonzero(group == g)[0]
        order = np.argsort(-score[idx])
        rel[idx[order[:2]]] = 2.0
        rel[idx[order[2:5]]] = 1.0
    return DataFrame({"features": X, "label": rel, "query": group.astype(np.int64)})


def test_ndcg_metric_matches_sklearn_oracle(rng):
    """The in-engine ndcg@k metric against sklearn.metrics.ndcg_score
    (an independent oracle): with linear label_gain both use
    gain=relevance and the log2 discount, so per-query values must
    agree to float tolerance — across skewed group sizes and ties."""
    import jax.numpy as jnp
    from sklearn.metrics import ndcg_score

    from mmlspark_tpu.models.gbdt.metrics import ndcg_at

    sizes = [3, 7, 12, 40, 5, 21, 9, 64]
    gid = np.repeat(np.arange(len(sizes)), sizes)
    n = len(gid)
    scores = rng.normal(size=n)
    labels = rng.integers(0, 5, size=n).astype(np.float64)
    labels[: sizes[0]] = 2.0  # an all-tied group

    k = 10
    ours = float(ndcg_at(k, label_gain=(0.0, 1.0, 2.0, 3.0, 4.0))(
        jnp.asarray(scores), jnp.asarray(labels),
        group_ids=jnp.asarray(gid)))
    per_query = []
    start = 0
    for qs in sizes:
        y = labels[start:start + qs][None, :]
        s = scores[start:start + qs][None, :]
        per_query.append(ndcg_score(y, s, k=k) if y.max() > 0 else 1.0)
        start += qs
    assert abs(ours - float(np.mean(per_query))) < 1e-6, \
        (ours, float(np.mean(per_query)))


def ndcg_at_k(scores, labels, groups, k=5):
    total, count = 0.0, 0
    for g in np.unique(groups):
        idx = np.nonzero(groups == g)[0]
        order = np.argsort(-scores[idx])
        gains = (2 ** labels[idx][order] - 1)[:k]
        dcg = np.sum(gains / np.log2(np.arange(2, len(gains) + 2)))
        ideal = np.sort(2 ** labels[idx] - 1)[::-1][:k]
        idcg = np.sum(ideal / np.log2(np.arange(2, len(ideal) + 2)))
        if idcg > 0:
            total += dcg / idcg
            count += 1
    return total / max(count, 1)


def test_lambdarank_beats_random():
    df = make_ranking()
    ranker = LightGBMRanker(numIterations=30, numLeaves=15, maxDepth=4,
                            minDataInLeaf=3, groupCol="query")
    model = ranker.fit(df)
    scores = model.transform(df)["prediction"]
    groups = df["query"]
    ndcg = ndcg_at_k(scores, df["label"], groups)
    rng = np.random.default_rng(0)
    random_ndcg = ndcg_at_k(rng.normal(size=len(scores)), df["label"], groups)
    assert ndcg > 0.8, f"ndcg={ndcg}"
    assert ndcg > random_ndcg + 0.15


def test_lambdarank_with_validation_split():
    """Regression: group ids must be computed post-validation-split so the
    lambdarank pair masks and the per-valid-set NDCG stay aligned."""
    df = make_ranking()
    # mark two whole queries as validation (groups must not straddle)
    groups = df["query"]
    is_val = np.isin(groups, [0, 1])
    df = df.with_column("isVal", is_val)
    ranker = LightGBMRanker(numIterations=8, numLeaves=7, maxDepth=3,
                            minDataInLeaf=3, groupCol="query",
                            validationIndicatorCol="isVal",
                            earlyStoppingRound=5, evalAt=[3])
    model = ranker.fit(df)
    # eval record must contain a finite valid ndcg for every iteration run
    assert model.evals_result
    for rec in model.evals_result:
        assert np.isfinite(rec["valid0_ndcg@3"])
    scores = model.transform(df)["prediction"]
    assert np.isfinite(scores).all()


def test_quantile_metric_uses_cfg_alpha():
    """Regression: quantile eval metric must use the trained alpha."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(1)
    x = rng.normal(size=(300, 4))
    y = x[:, 0] + rng.normal(size=300)
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective="quantile", alpha=0.9, num_iterations=3,
                      num_leaves=7, max_depth=3, min_data_in_leaf=5,
                      max_bin=32)
    res = train(binned, y, cfg, bin_upper=mapper.bin_upper_values(32))
    import jax.numpy as jnp
    from mmlspark_tpu.models.gbdt import metrics as M
    raw = res.booster.predict_jit()(x)
    expected = float(M.quantile_loss(jnp.asarray(raw), jnp.asarray(y),
                                     alpha=0.9))
    assert res.evals[-1]["train_quantile"] == pytest.approx(expected, rel=1e-4)


def test_custom_objective_host_numpy():
    """Custom objectives may be plain numpy functions (FObjTrait analog,
    lightgbm/.../FObjTrait.scala:1): the eager path must call them with
    concrete arrays, not tracers."""
    from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
    from mmlspark_tpu.ops.binning import BinMapper

    rng = np.random.default_rng(5)
    x = rng.normal(size=(300, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=300) * 0.1

    def np_l2(preds, labels, weights=None):
        p = np.asarray(preds)  # raises on tracers: proves eager call
        return p - np.asarray(labels), np.ones_like(p)

    mapper = BinMapper.fit(x, max_bin=32)
    cfg = TrainConfig(objective="regression", num_iterations=15,
                      num_leaves=15, max_depth=4, min_data_in_leaf=5,
                      max_bin=32)
    res = train(mapper.transform(x), y, cfg,
                bin_upper=mapper.bin_upper_values(32),
                custom_objective=np_l2)
    pred = res.booster.predict_jit()(x)
    r2 = 1 - np.sum((np.asarray(pred) - y) ** 2) / np.sum((y - y.mean()) ** 2)
    assert r2 > 0.8, r2


def test_start_iteration_prediction_slicing():
    """LightGBM predict(start_iteration, num_iteration) analog: models
    score with a sub-range of boosting iterations."""
    rng = np.random.default_rng(21)
    x = rng.normal(size=(800, 4))
    y = 2.0 * x[:, 0] - x[:, 1] + rng.normal(size=800) * 0.1
    df = DataFrame({"features": x, "label": y})
    m = LightGBMRegressor(numIterations=10, numLeaves=8, maxBin=32).fit(df)
    full = np.asarray(m.transform(df)["prediction"])
    # first 4 iterations only
    head = m.copy(numIteration=4)
    p_head = np.asarray(head.transform(df)["prediction"])
    # remaining 6: full = head + tail - init (init counted in both)
    tail = m.copy(startIteration=4)
    p_tail = np.asarray(tail.transform(df)["prediction"])
    np.testing.assert_allclose(p_head + p_tail - m.booster.init_score,
                               full, atol=1e-5)
    assert not np.allclose(p_head, full)
    # sub-range booster slices the tree arrays
    assert m.booster.slice_iterations(4, 3).num_trees == 3
    with pytest.raises(ValueError, match="start_iteration"):
        m.booster.slice_iterations(99)


def test_extreme_values_robustness(rng):
    """±inf and huge magnitudes must survive binning, training,
    scoring and SHAP without NaNs (the reference inherits this
    robustness from LightGBM C++; here it must hold through
    searchsorted binning and f32 device math)."""
    x = rng.normal(size=(800, 4))
    x[::50, 0] = np.inf
    x[1::50, 0] = -np.inf
    x[2::50, 1] = 1e30
    x[3::50, 1] = -1e30
    y = np.where(np.isfinite(x[:, 0]), x[:, 0], 3.0) * 2.0 \
        + rng.normal(size=800) * 0.1
    df = DataFrame({"features": x, "label": y})
    m = LightGBMRegressor(numIterations=8, numLeaves=8, maxBin=32,
                          featuresShapCol="shap").fit(df)
    out = m.transform(df)
    pred = np.asarray(out["prediction"])
    assert np.isfinite(pred).all()
    # inf rows all land in the top bin: one consistent prediction group
    assert np.isfinite(np.asarray(out["shap"])).all()
    # model string round-trips inf thresholds if any were chosen
    from mmlspark_tpu.models.gbdt.booster import BoosterArrays
    reloaded = BoosterArrays.load_model_string(
        m.booster.save_model_string())
    np.testing.assert_allclose(
        np.asarray(reloaded.predict_jit()(x)), pred, atol=1e-5)
