"""graftsan end-to-end on the GBDT training path.

The closed loop the ISSUE demands: the fault harness injects NaNs into
the native histogram callback (``gbdt.level_hist:corrupt``); with
``MMLSPARK_TPU_SAN=1`` the fit must abort with a diagnostic naming that
jit boundary, and with the sanitizer off the same corruption completes
silently (a NaN gain becomes ``-inf`` and just disables splits — the
exact silent-failure mode the guard exists for). Plus the divergence
detector against the real shard_map builders on the 8-device mesh.
"""

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core import sanitizer as san
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.models.gbdt import trainer as trainer_mod
from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper
from mmlspark_tpu.parallel.mesh import MeshConfig, create_mesh

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset()
    san.disable()
    san.reset()
    yield
    faults.reset()
    san.disable()
    san.reset()


def _df(n=400, f=3, seed=5):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, f))
    y = 2.0 * x[:, 0] + rng.normal(size=n) * 0.1
    return DataFrame({"features": x, "label": y})


def _nan_corrupt(h):
    h = np.array(h, copy=True)
    h.flat[0] = np.nan
    return h


_KW = dict(numIterations=3, numLeaves=4, maxBin=16)


def test_injected_hist_nan_caught_at_named_boundary(monkeypatch):
    """SAN=1 + armed NaN corruption on the histogram callback must
    abort the fit with a diagnostic naming the jit boundary. jax wraps
    callback exceptions (XlaRuntimeError in 0.4.x), so match on the
    message, not the type."""
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    san.enable()
    with faults.injected("gbdt.level_hist", "corrupt", count=None,
                         corrupt=_nan_corrupt):
        with pytest.raises(Exception) as ei:
            LightGBMRegressor(**_KW).fit(_df())
    msg = str(ei.value)
    assert "graftsan" in msg, msg
    assert "gbdt.level_hist" in msg, msg
    assert "NaN" in msg, msg


def test_injected_hist_nan_is_silent_with_sanitizer_off(monkeypatch):
    """The control arm: without the sanitizer the NaN histogram is
    absorbed (NaN gain -> -inf -> no split) and the fit completes —
    the silent failure mode the guard closes."""
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    assert not san.enabled()
    with faults.injected("gbdt.level_hist", "corrupt", count=None,
                         corrupt=_nan_corrupt):
        model = LightGBMRegressor(**_KW).fit(_df())
    assert model is not None


def test_clean_fit_has_no_false_positives(monkeypatch):
    """SAN=1 over an uncorrupted native-histogram fit: every boundary
    guard (entry, callback, metrics sync, exit) sees finite data."""
    monkeypatch.setenv("MMLSPARK_TPU_HIST_FORMULATION", "native")
    san.enable()
    model = LightGBMRegressor(**_KW).fit(_df())
    pred = np.asarray(model.transform(_df())["prediction"])
    assert np.isfinite(pred).all()


def _trace_voting(mesh, recorder, top_k, seed=0):
    """Fit the voting-parallel learner with ``recorder`` active,
    clearing the trainer's compile caches first so the shard_map body
    is re-traced (record_collective fires at trace time)."""
    trainer_mod._CHUNK_CACHE.clear()
    trainer_mod._BUILDER_CACHE.clear()
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(512, 8))
    y = (1.5 * x[:, 0] - x[:, 1] > 0).astype(np.float64)
    mapper = BinMapper.fit(x, max_bin=32)
    cfg = TrainConfig(objective="binary", num_iterations=2,
                      num_leaves=7, max_depth=3, min_data_in_leaf=5,
                      max_bin=32, tree_learner="voting", top_k=top_k)
    with san.use_recorder(recorder):
        train(mapper.transform(x), y, cfg,
              bin_upper=mapper.bin_upper_values(32), mesh=mesh)
    return recorder


@pytest.fixture(scope="module")
def dp_mesh():
    return create_mesh(MeshConfig(dp=8))


def test_divergence_detector_flags_rank_divergent_protocol(dp_mesh):
    """Two simulated ranks compile the voting builder with different
    top_k: the candidate-histogram psum shapes differ, so the recorded
    collective protocols diverge and the cross-check must name rank 1.
    This is GL006's runtime counterpart on a real 8-device program."""
    san.enable()
    rank0 = _trace_voting(dp_mesh, san.CollectiveRecorder(), top_k=8)
    rank1 = _trace_voting(dp_mesh, san.CollectiveRecorder(), top_k=2)
    assert len(rank0) > 0 and len(rank1) > 0
    with pytest.raises(san.CollectiveDivergence) as ei:
        san.crosscheck_hashes([rank0.sequence_hash(),
                               rank1.sequence_hash()])
    assert "rank 1" in str(ei.value)


def test_divergence_detector_clean_on_identical_ranks(dp_mesh):
    """No false positive: ranks tracing the SAME program record the
    same (op, axis, shape, dtype) sequence, hashes agree."""
    san.enable()
    rank0 = _trace_voting(dp_mesh, san.CollectiveRecorder(), top_k=8)
    rank1 = _trace_voting(dp_mesh, san.CollectiveRecorder(), top_k=8)
    assert len(rank0) == len(rank1) > 0
    assert rank0.events == rank1.events
    san.crosscheck_hashes([rank0.sequence_hash(),
                           rank1.sequence_hash()])


def test_recompiles_are_counted_through_trainer_caches(dp_mesh):
    san.enable()
    before = san.recompile_count()
    _trace_voting(dp_mesh, san.CollectiveRecorder(), top_k=4, seed=1)
    assert san.recompile_count() > before
