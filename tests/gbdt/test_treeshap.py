"""Exact TreeSHAP contributions (VERDICT r3 #4).

Golden oracle: brute-force Shapley values over the path-dependent
conditional expectation (the estimand of LightGBM's predict_contrib /
the reference's featuresShap, LightGBMBooster.scala:418), enumerated
subset-by-subset on small models — written independently of the
booster's leaf-wise polynomial implementation.
"""

import itertools
import math

import numpy as np
import pytest

from mmlspark_tpu.models.gbdt.booster import BoosterArrays
from mmlspark_tpu.models.gbdt.trainer import TrainConfig, train
from mmlspark_tpu.ops.binning import BinMapper


def _fit(x, y, objective="regression", **kw):
    mapper = BinMapper.fit(x, max_bin=32)
    binned = mapper.transform(x)
    cfg = TrainConfig(objective=objective, num_leaves=8, max_depth=3,
                      min_data_in_leaf=5, max_bin=32,
                      **{"num_iterations": 5, **kw})
    return train(binned, y, cfg, bin_upper=mapper.bin_upper_values(32))


def _cond_exp(b: BoosterArrays, t: int, node: int, x_row, S):
    """Path-dependent conditional expectation of tree t given the
    features in S take their x_row values (split-out features branch by
    train cover)."""
    sf = b.split_feature[t]
    if sf[node] < 0:
        return float(b.node_value[t][node])
    feat = int(sf[node])
    left, right = 2 * node + 1, 2 * node + 2
    if feat in S:
        go_left = (np.isnan(x_row[feat])
                   or x_row[feat] <= b.threshold_value[t][node])
        return _cond_exp(b, t, left if go_left else right, x_row, S)
    cl, cr = float(b.count[t][left]), float(b.count[t][right])
    tot = max(cl + cr, 1e-12)
    return (cl * _cond_exp(b, t, left, x_row, S)
            + cr * _cond_exp(b, t, right, x_row, S)) / tot


def _brute_shap(b: BoosterArrays, x_row):
    """Shapley values over ALL model features (absent ones get 0)."""
    nf = b.num_features
    phi = np.zeros(nf + 1)
    for t in range(b.num_trees):
        w = float(b.tree_weights[t])
        used = sorted({int(f) for f in b.split_feature[t] if f >= 0})
        mm = len(used)
        phi[nf] += w * _cond_exp(b, t, 0, x_row, frozenset())
        for i in used:
            others = [f for f in used if f != i]
            for r in range(mm):
                for S in itertools.combinations(others, r):
                    wt = (math.factorial(len(S))
                          * math.factorial(mm - len(S) - 1)
                          / math.factorial(mm))
                    gain = (_cond_exp(b, t, 0, x_row, frozenset(S) | {i})
                            - _cond_exp(b, t, 0, x_row, frozenset(S)))
                    phi[i] += w * wt * gain
    phi[b.num_features] += b.init_score
    return phi


def test_matches_bruteforce_oracle():
    rng = np.random.default_rng(0)
    n = 400
    x = rng.normal(size=(n, 4))
    y = (2.0 * x[:, 0] - x[:, 1] + 0.5 * x[:, 2] * x[:, 1]
         + 0.1 * rng.normal(size=n))
    res = _fit(x, y)
    contrib = np.asarray(res.booster.contrib_jit()(x[:6]))
    for i in range(6):
        expect = _brute_shap(res.booster, x[i])
        np.testing.assert_allclose(contrib[i], expect, rtol=2e-3,
                                   atol=2e-4)


def test_repeated_feature_paths():
    """A single strong feature forces paths that split it repeatedly —
    the duplicate-merge branch of the polynomial."""
    rng = np.random.default_rng(1)
    n = 500
    x = np.stack([rng.normal(size=n), rng.normal(size=n) * 0.01], axis=1)
    y = np.sin(2.0 * x[:, 0])  # needs several thresholds on feature 0
    res = _fit(x, y, num_iterations=3)
    assert any((res.booster.split_feature[t] == 0).sum() > 1
               for t in range(res.booster.num_trees))
    contrib = np.asarray(res.booster.contrib_jit()(x[:5]))
    for i in range(5):
        expect = _brute_shap(res.booster, x[i])
        np.testing.assert_allclose(contrib[i], expect, rtol=2e-3,
                                   atol=2e-4)


def test_efficiency_property_and_saabas_flag():
    """SHAP contributions sum to the raw margin (efficiency); the
    Saabas approximation stays available and shares the property."""
    rng = np.random.default_rng(2)
    n = 600
    x = rng.normal(size=(n, 6))
    y = (x[:, 0] - x[:, 3] > 0).astype(np.float64)
    res = _fit(x, y, objective="binary", num_iterations=8)
    raw = np.asarray(res.booster.predict_jit()(x))
    shap = np.asarray(res.booster.contrib_jit()(x))
    np.testing.assert_allclose(shap.sum(axis=1), raw, atol=1e-3)
    saabas = np.asarray(res.booster.contrib_saabas_jit()(x))
    np.testing.assert_allclose(saabas.sum(axis=1), raw, atol=1e-3)
    # the two attributions genuinely differ (correlated splits)
    assert not np.allclose(shap, saabas, atol=1e-4)


def test_efficiency_on_imported_golden_model():
    """The committed LightGBM-format fixture (categoricals included)
    scores with SHAP contributions that sum to its raw predictions."""
    import os
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "lightgbm_golden_model.txt")
    with open(fixture) as fh:
        booster = BoosterArrays.load_model_string(fh.read())
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, booster.num_features))
    if booster.has_categorical:
        x[:, 0] = rng.integers(0, 8, size=32)  # plausible category codes
    raw = np.asarray(booster.predict_jit()(x))
    shap = np.asarray(booster.contrib_jit()(x))
    np.testing.assert_allclose(shap.sum(axis=1), raw, atol=1e-3)


def test_multiclass_per_class_blocks():
    """Multi-class contribs return (N, K*(F+1)) per-class blocks, each
    block summing to that class's raw margin (LightGBM layout)."""
    rng = np.random.default_rng(4)
    n, f, k = 600, 4, 3
    x = rng.normal(size=(n, f))
    y = np.argmax(np.stack([x[:, 0], x[:, 1], x[:, 2]]), axis=0
                  ).astype(np.float64)
    res = _fit(x, y, objective="multiclass", num_iterations=4,
               num_class=3)
    raw = np.asarray(res.booster.predict_jit()(x))          # (N, K)
    shap = np.asarray(res.booster.contrib_jit()(x))
    assert shap.shape == (n, k * (f + 1))
    blocks = shap.reshape(n, k, f + 1)
    np.testing.assert_allclose(blocks.sum(axis=2), raw, atol=1e-3)
