"""image module tests, patterned on the reference's ImageTransformerSuite /
SuperpixelSuite (opencv + core image tests)."""

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.image import (
    ImageSetAugmenter,
    ImageTransformer,
    Superpixel,
    SuperpixelTransformer,
    UnrollImage,
)


def _images(n=3, h=32, w=24, c=3, seed=0):
    rng = np.random.default_rng(seed)
    col = np.empty(n, dtype=object)
    for i in range(n):
        col[i] = rng.uniform(0, 255, size=(h, w, c)).astype(np.float32)
    return DataFrame({"image": col})


class TestImageTransformer:
    def test_resize(self):
        df = _images()
        out = ImageTransformer(inputCol="image", outputCol="out") \
            .resize(16, 12).transform(df)
        assert out.col("out")[0].shape == (16, 12, 3)

    def test_crop_and_centercrop(self):
        df = _images()
        t = ImageTransformer(inputCol="image", outputCol="out") \
            .crop(x=2, y=4, height=10, width=8)
        got = t.transform(df).col("out")[0]
        want = df.col("image")[0][4:14, 2:10, :]
        assert np.allclose(got, want)
        cc = ImageTransformer(inputCol="image", outputCol="out") \
            .center_crop(10, 10).transform(df).col("out")[0]
        assert cc.shape == (10, 10, 3)

    def test_flip_gray_threshold(self):
        df = _images()
        src = df.col("image")[0]
        flipped = ImageTransformer(inputCol="image", outputCol="o") \
            .flip(1).transform(df).col("o")[0]
        assert np.allclose(flipped, src[:, ::-1, :])
        gray = ImageTransformer(inputCol="image", outputCol="o") \
            .color_format("gray").transform(df).col("o")[0]
        assert gray.shape == (32, 24, 1)
        th = ImageTransformer(inputCol="image", outputCol="o") \
            .threshold(128.0, 255.0).transform(df).col("o")[0]
        assert set(np.unique(th)) <= {0.0, 255.0}

    def test_blur_reduces_variance(self):
        df = _images()
        blurred = ImageTransformer(inputCol="image", outputCol="o") \
            .blur(5, 5).transform(df).col("o")[0]
        assert blurred.var() < df.col("image")[0].var()
        g = ImageTransformer(inputCol="image", outputCol="o") \
            .gaussian_kernel(5, 1.5).transform(df).col("o")[0]
        assert g.var() < df.col("image")[0].var()

    def test_normalize_and_tensor(self):
        df = _images()
        t = ImageTransformer(inputCol="image", outputCol="o", toTensor=True) \
            .normalize(mean=[0.485, 0.456, 0.406], std=[0.229, 0.224, 0.225],
                       color_scale_factor=1 / 255.0)
        out = t.transform(df).col("o")[0]
        assert out.shape == (3, 32, 24)  # CHW

    def test_stage_chain_and_mixed_shapes(self):
        col = np.empty(2, dtype=object)
        rng = np.random.default_rng(0)
        col[0] = rng.uniform(0, 255, (20, 20, 3)).astype(np.float32)
        col[1] = rng.uniform(0, 255, (30, 40, 3)).astype(np.float32)
        df = DataFrame({"image": col})
        out = ImageTransformer(inputCol="image", outputCol="o") \
            .resize(8, 8).color_format("gray").transform(df)
        assert out.col("o")[0].shape == (8, 8, 1)
        assert out.col("o")[1].shape == (8, 8, 1)

    def test_unsupported_action_raises(self):
        df = _images(1)
        t = ImageTransformer(inputCol="image", outputCol="o")
        t._paramMap["stages"] = [{"action": "sharpen"}]
        with pytest.raises(ValueError, match="unsupported"):
            t.transform(df)


class TestAugmenterUnroll:
    def test_augmenter_doubles(self):
        df = _images(4)
        out = ImageSetAugmenter(inputCol="image", outputCol="aug").transform(df)
        assert out.num_rows == 8
        assert np.allclose(out.col("aug")[4], df.col("image")[0][:, ::-1, :])

    def test_unroll(self):
        df = _images(2, h=4, w=5)
        out = UnrollImage(inputCol="image", outputCol="vec").transform(df)
        assert out.col("vec").shape == (2, 4 * 5 * 3)


class TestSuperpixel:
    def test_cluster_count_and_coverage(self):
        img = np.zeros((32, 32, 3), np.float32)
        img[:, 16:] = 255.0
        labels = Superpixel.cluster(img, cell_size=8.0)
        assert labels.shape == (32, 32)
        k = labels.max() + 1
        assert 4 <= k <= 32
        clusters = Superpixel.get_clusters(labels)
        assert sum(len(c) for c in clusters) == 32 * 32

    def test_mask_image(self):
        img = np.ones((8, 8, 3), np.float32)
        labels = np.zeros((8, 8), np.int64)
        labels[:, 4:] = 1
        states = np.asarray([1.0, 0.0])
        masked = Superpixel.mask_image(img, labels, states)
        assert masked[:, :4].sum() == 8 * 4 * 3
        assert masked[:, 4:].sum() == 0

    def test_transformer(self):
        df = _images(2, h=24, w=24)
        out = SuperpixelTransformer(inputCol="image").transform(df)
        assert out.col("superpixels")[0].shape == (24, 24)
