"""Serving under overload: bounded queues, 503 + Retry-After load
shedding, /healthz degradation, connection caps and per-request
deadlines — driven by armed faults instead of real slow models, so the
overload is deterministic and CI-fast."""

import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.serving import (ContinuousServingServer,
                                     ServingFleet, ServingServer)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class _DoubleModel(Transformer):
    def _transform(self, df):
        return df.with_column("doubled", np.asarray(df.col("x")) * 2.0)


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _get_json(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def test_healthz_baseline_ok():
    with ServingServer(_DoubleModel(), max_latency_ms=2) as server:
        _post(server.url, {"x": 1.0})
        health = _get_json(f"http://{server.host}:{server.port}/healthz")
    assert health["status"] == "ok"
    assert health["served"] >= 1
    assert health["queueDepth"] == 0
    assert health["maxQueue"] == 256


def test_slow_score_sheds_load_with_retry_after_and_degraded_health():
    """Acceptance: under injected slow-score load the server answers
    503 + Retry-After instead of queueing unboundedly, and /healthz
    reflects the degraded state."""
    faults.arm("serving.score", "delay", delay_s=0.25, count=None)
    with ServingServer(_DoubleModel(), max_queue=4, max_batch_size=1,
                       max_latency_ms=1, request_timeout_s=10,
                       retry_after_s=2) as server:
        codes, retry_afters = [], []
        lock = threading.Lock()

        def call(i):
            try:
                status, out, _ = _post(server.url, {"x": float(i)})
                with lock:
                    codes.append(status)
            except urllib.error.HTTPError as e:
                with lock:
                    codes.append(e.code)
                    retry_afters.append(e.headers.get("Retry-After"))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # mid-overload: queue full, scorer sleeping
        health = _get_json(f"http://{server.host}:{server.port}/healthz")
        for t in threads:
            t.join()
    shed = [c for c in codes if c == 503]
    ok = [c for c in codes if c == 200]
    assert shed, f"no load was shed: {codes}"
    assert ok, f"nothing succeeded: {codes}"
    assert all(ra == "2" for ra in retry_afters)
    assert health["status"] == "degraded"
    assert health["rejected"] >= 1


def test_request_deadline_times_out_504():
    faults.arm("serving.score", "delay", delay_s=0.5, count=None)
    with ServingServer(_DoubleModel(), max_batch_size=1,
                       max_latency_ms=1,
                       request_timeout_s=0.1) as server:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server.url, {"x": 1.0})
        assert e.value.code == 504


def test_connection_cap_rejects_with_503():
    """Beyond max_connections, new connections get an immediate 503 +
    Retry-After and are closed — idle keep-alive clients can no longer
    grow server threads without bound."""
    with ServingServer(_DoubleModel(), max_connections=2,
                       max_latency_ms=2) as server:
        held = []
        try:
            for _ in range(2):  # two persistent keep-alive connections
                c = http.client.HTTPConnection(server.host, server.port,
                                               timeout=5)
                c.request("GET", "/healthz")
                r = c.getresponse()
                assert r.status == 200
                r.read()
                held.append(c)  # keep open: each pins one thread
            c3 = http.client.HTTPConnection(server.host, server.port,
                                            timeout=5)
            c3.request("GET", "/healthz")
            r3 = c3.getresponse()
            assert r3.status == 503
            assert r3.headers.get("Retry-After") is not None
            c3.close()
        finally:
            for c in held:
                c.close()


def test_idle_keepalive_timeout_closes_connection():
    """The keep-alive idle timeout is capped: a client that goes idle
    has its connection (and thread) reclaimed."""
    with ServingServer(_DoubleModel(), idle_timeout_s=0.3,
                       max_latency_ms=2) as server:
        s = socket.create_connection((server.host, server.port),
                                     timeout=5)
        try:
            s.sendall(b"GET /healthz HTTP/1.1\r\nHost: x\r\n\r\n")
            s.settimeout(5)
            # drain the whole response (headers + body may arrive in
            # separate segments) up to the closing brace of the JSON
            buf = b""
            while b"}" not in buf:
                chunk = s.recv(4096)
                assert chunk, "connection died before the response"
                buf += chunk
            assert b"200" in buf.split(b"\r\n", 1)[0]
            time.sleep(0.8)  # idle past the cap
            s.settimeout(2)
            leftover = s.recv(4096)
            assert leftover == b"", "idle connection was not closed"
        finally:
            s.close()


def test_continuous_server_bounds_inflight():
    faults.arm("serving.score", "delay", delay_s=0.3, count=None)
    server = ContinuousServingServer(_DoubleModel(), max_queue=1).start()
    try:
        codes = []
        lock = threading.Lock()

        def call(i):
            try:
                status, _, _ = _post(server.url, {"x": float(i)})
                with lock:
                    codes.append(status)
            except urllib.error.HTTPError as e:
                with lock:
                    codes.append(e.code)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert 503 in codes and 200 in codes, codes
    finally:
        server.stop()


def test_fleet_registry_aggregates_health():
    with ServingFleet(_DoubleModel(), num_servers=2,
                      max_latency_ms=2) as fleet:
        url = (f"http://{fleet.registry_host}:{fleet.registry_port}"
               "/healthz")
        health = _get_json(url)
        assert health["status"] == "ok"
        assert len(health["workers"]) == 2
        # per-worker /healthz is also live
        w = fleet.servers[0]
        assert _get_json(
            f"http://{w.host}:{w.port}/healthz")["status"] == "ok"


def test_http_transformer_retries_injected_fault(rng):
    """An armed io.http raise on the first attempt is transparently
    retried by the shared with_retries policy."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from mmlspark_tpu.io.http import HTTPTransformer

    class _Echo(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            body = b'{"ok": true}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Echo)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        host, port = httpd.server_address
        reqs = np.empty(1, dtype=object)
        reqs[0] = {"url": f"http://{host}:{port}/x", "method": "POST",
                   "body": "{}"}
        faults.arm("io.http", "raise", nth=1, count=1)
        out = HTTPTransformer(inputCol="r", outputCol="resp",
                              backoffs=[0.01, 0.01]).transform(
            DataFrame({"r": reqs}))
        assert out.col("resp")[0].status_code == 200
        assert faults.hits("io.http") == 2  # failed attempt + retry
    finally:
        httpd.shutdown()
        httpd.server_close()
