"""Binary file IO, PowerBI writer, fabric telemetry client, cognitive
families (VERDICT r2 #8b smaller absentees)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.binary import (PowerBIWriter, read_binary_files,
                                    read_image_files, write_to_power_bi)


@pytest.fixture()
def canned_server():
    """Local server returning a configurable canned JSON reply and
    recording request bodies."""
    state = {"reply": {}, "bodies": [], "fail_first": 0}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            state["bodies"].append(json.loads(self.rfile.read(n)))
            if state["fail_first"] > 0:
                state["fail_first"] -= 1
                self.send_error(503)
                return
            body = json.dumps(state["reply"]).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/api"
    yield url, state
    httpd.shutdown()
    httpd.server_close()


class TestBinaryIO:
    def test_read_binary_files(self, tmp_path):
        (tmp_path / "sub").mkdir()
        (tmp_path / "a.bin").write_bytes(b"alpha")
        (tmp_path / "sub" / "b.bin").write_bytes(b"beta--")
        (tmp_path / "skip.txt").write_bytes(b"no")
        df = read_binary_files(str(tmp_path), glob="*.bin")
        assert df.num_rows == 2
        assert list(df.col("length")) == [5, 6]
        assert df.col("bytes")[0] == b"alpha"
        flat = read_binary_files(str(tmp_path), glob="*.bin",
                                 recursive=False)
        assert flat.num_rows == 1

    def test_read_image_files(self, tmp_path, rng):
        img = rng.uniform(0, 1, (4, 4, 3)).astype(np.float32)
        np.save(tmp_path / "img0.npy", img)
        df = read_image_files(str(tmp_path))
        assert df.num_rows == 1
        np.testing.assert_array_equal(df.col("image")[0], img)

    def test_power_bi_writer_batches_and_retries(self, canned_server):
        url, state = canned_server
        df = DataFrame({"x": np.arange(7, dtype=np.float64),
                        "name": np.asarray([f"r{i}" for i in range(7)],
                                           dtype=object)})
        state["fail_first"] = 1  # first POST 503s -> retried
        batches = write_to_power_bi(df, url, batch_size=3,
                                    retries=[0.01, 0.02])
        assert batches == 3
        # 4 posts happened (1 failed + 3 ok); rows preserved in order
        sent = [r for b in state["bodies"][1:] for r in b["rows"]]
        assert [r["x"] for r in sent] == list(range(7))

    def test_power_bi_4xx_raises_immediately(self, canned_server):
        url, state = canned_server
        state["fail_first"] = 0

        class _Always400(PowerBIWriter):
            def _post(self, rows):
                raise RuntimeError("simulated")

        with pytest.raises(RuntimeError):
            _Always400(url).write(DataFrame({"x": np.arange(2)}))


class TestFabric:
    def test_emit_to_sink_without_endpoint(self):
        from mmlspark_tpu.core.fabric import FabricClient
        from mmlspark_tpu.core.logging_utils import SINK

        SINK.drain()
        FabricClient(endpoint=None).emit(
            {"method": "fit", "secret": "sig=abc123&x=1"})
        events = [e for e in SINK.drain() if "certifiedEvent" in e]
        assert len(events) == 1
        rec = events[0]["certifiedEvent"]
        assert rec["platform"] in ("unknown", "notebook", "synapse",
                                   "synapse_internal", "databricks")
        assert "abc123" not in rec["secret"]  # SAS scrubbed

    def test_emit_posts_with_token(self, canned_server):
        url, state = canned_server
        from mmlspark_tpu.core.fabric import FabricClient, TokenLibrary

        client = FabricClient(endpoint=url,
                              tokens=TokenLibrary(lambda: "tok123"))
        client.emit({"method": "transform"})
        client.flush()
        assert state["bodies"][-1]["method"] == "transform"


class TestCognitiveFamilies:
    def _run(self, stage, df, reply, server):
        url, state = server
        state["reply"] = reply
        return stage.copy(url=url).transform(df)

    def test_text_sentiment_and_keyphrases(self, canned_server):
        from mmlspark_tpu.io.cognitive_services import (KeyPhraseExtractor,
                                                        TextSentiment)

        df = DataFrame({"text": np.asarray(["great product"], object)})
        out = self._run(
            TextSentiment(outputCol="s"), df,
            {"documents": [{"id": "0", "sentiment": "positive",
                            "confidenceScores": {"positive": 0.99}}]},
            canned_server)
        assert out["s"][0]["sentiment"] == "positive"
        # request carried the documents shape
        assert canned_server[1]["bodies"][-1]["documents"][0]["text"] == \
            "great product"

        out = self._run(
            KeyPhraseExtractor(outputCol="k"), df,
            {"documents": [{"id": "0", "keyPhrases": ["great product"]}]},
            canned_server)
        assert out["k"][0] == ["great product"]

    def test_language_entities_pii(self, canned_server):
        from mmlspark_tpu.io.cognitive_services import (EntityRecognizer,
                                                        LanguageDetector,
                                                        PIIRecognizer)

        df = DataFrame({"text": np.asarray(["bonjour"], object)})
        out = self._run(
            LanguageDetector(outputCol="l"), df,
            {"documents": [{"id": "0", "detectedLanguage":
                            {"name": "French", "iso6391Name": "fr",
                             "confidenceScore": 1.0}}]}, canned_server)
        assert out["l"][0]["iso6391Name"] == "fr"
        out = self._run(
            EntityRecognizer(outputCol="e"), df,
            {"documents": [{"id": "0", "entities":
                            [{"text": "Paris", "category": "Location"}]}]},
            canned_server)
        assert out["e"][0][0]["category"] == "Location"
        out = self._run(
            PIIRecognizer(outputCol="p"), df,
            {"documents": [{"id": "0", "redactedText": "call ***",
                            "entities": [{"category": "Phone"}]}]},
            canned_server)
        assert out["p"][0]["redactedText"] == "call ***"

    def test_translate_anomaly_vision_face(self, canned_server):
        from mmlspark_tpu.io.cognitive_services import (AnalyzeImage,
                                                        DetectAnomalies,
                                                        DetectFace,
                                                        DetectLastAnomaly,
                                                        OCR, Translate)

        df = DataFrame({"text": np.asarray(["hello"], object)})
        out = self._run(
            Translate(outputCol="t"), df,
            [{"translations": [{"text": "bonjour", "to": "fr"}]}],
            canned_server)
        assert out["t"][0] == ["bonjour"]

        series = np.empty(1, object)
        series[0] = [{"timestamp": f"2024-01-0{i+1}T00:00:00Z",
                      "value": float(v)}
                     for i, v in enumerate([1, 1, 9])]
        sdf = DataFrame({"series": series})
        out = self._run(DetectLastAnomaly(outputCol="a"), sdf,
                        {"isAnomaly": True, "expectedValue": 1.0,
                         "upperMargin": 0.1, "lowerMargin": 0.1},
                        canned_server)
        assert out["a"][0]["isAnomaly"] is True
        out = self._run(DetectAnomalies(outputCol="a"), sdf,
                        {"isAnomaly": [False, False, True],
                         "expectedValues": [1, 1, 1]}, canned_server)
        assert out["a"][0]["isAnomaly"] == [False, False, True]

        idf = DataFrame({"url": np.asarray(["http://x/img.png"], object)})
        out = self._run(AnalyzeImage(outputCol="v"), idf,
                        {"categories": [{"name": "outdoor"}],
                         "tags": [{"name": "sky"}],
                         "description": {"captions": [{"text": "a sky"}]}},
                        canned_server)
        assert out["v"][0] == {"categories": ["outdoor"], "tags": ["sky"],
                               "captions": ["a sky"]}
        out = self._run(
            OCR(outputCol="o"), idf,
            {"regions": [{"lines": [{"words": [{"text": "hello"},
                                               {"text": "world"}]}]}]},
            canned_server)
        assert out["o"][0] == "hello world"
        out = self._run(DetectFace(outputCol="f"), idf,
                        [{"faceId": "f1", "faceRectangle": {"top": 1}}],
                        canned_server)
        assert out["f"][0][0]["faceId"] == "f1"


class TestAsyncCognitive:
    """Async long-running-operation protocol (Operation-Location POST +
    status polling) — the form-recognizer / MVAD pattern."""

    @pytest.fixture()
    def async_server(self):
        state = {"polls_until_done": 2, "poll_count": 0,
                 "final": {"status": "succeeded"}, "bodies": []}

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                state["bodies"].append(json.loads(self.rfile.read(n)))
                self.send_response(202)
                host, port = self.server.server_address
                self.send_header("Operation-Location",
                                 f"http://{host}:{port}/op/1")
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                state["poll_count"] += 1
                if state["poll_count"] <= state["polls_until_done"]:
                    body = json.dumps({"status": "running"}).encode()
                else:
                    body = json.dumps(state["final"]).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}/analyze", state
        httpd.shutdown()
        httpd.server_close()

    def test_analyze_document_polls_to_completion(self, async_server):
        from mmlspark_tpu.io.cognitive_services import AnalyzeDocument

        url, state = async_server
        state["final"] = {"status": "succeeded", "analyzeResult": {
            "content": "INVOICE #42", "pages": [{}, {}],
            "keyValuePairs": [{"key": "total", "value": "9.99"}]}}
        df = DataFrame({"url": np.asarray(["http://x/doc.pdf"], object)})
        out = AnalyzeDocument(url=url, outputCol="doc",
                              pollingIntervalSec=0.01).transform(df)
        assert out["errors"][0] is None
        assert out["doc"][0]["content"] == "INVOICE #42"
        assert out["doc"][0]["pages"] == 2
        assert state["poll_count"] == 3  # 2 running + 1 succeeded
        assert state["bodies"][0] == {"urlSource": "http://x/doc.pdf"}

    def test_async_failure_and_timeout_surface(self, async_server):
        from mmlspark_tpu.io.cognitive_services import (
            AnalyzeDocument, FitMultivariateAnomaly)

        url, state = async_server
        state["final"] = {"status": "failed", "error": {"code": "boom"}}
        df = DataFrame({"url": np.asarray(["http://x/doc.pdf"], object)})
        out = AnalyzeDocument(url=url, outputCol="doc",
                              pollingIntervalSec=0.01).transform(df)
        assert out["doc"][0] is None
        assert "operation failed" in out["errors"][0]

        state.update(polls_until_done=10**6, poll_count=0)
        sdf = DataFrame({"source": np.asarray(["wasb://data"], object)})
        out = FitMultivariateAnomaly(
            url=url, outputCol="m", pollingIntervalSec=0.001,
            maxPollRetries=3).transform(sdf)
        assert "did not complete" in out["errors"][0]
