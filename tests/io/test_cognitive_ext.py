"""Round-4 cognitive families against live local mock servers:
AnalyzeText (language/AnalyzeText.scala), the AzureSearch sink
(search/AzureSearch.scala), the speech family (speech/*.scala), bing
image search, and Azure Maps geospatial."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io import (
    AddDocuments,
    AddressGeocoder,
    AnalyzeText,
    AzureSearchWriter,
    BingImageSearch,
    CheckPointInPolygon,
    SpeechToText,
    SpeechToTextSDK,
    TextToSpeech,
)


@pytest.fixture()
def server():
    """Mock handling JSON POST, raw-body POST, GET and PUT, recording
    everything; per-path canned replies."""
    state = {"replies": {}, "requests": []}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def _reply(self):
            path = self.path.split("?")[0]
            n = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(n)
            try:
                body = json.loads(raw)
            except (ValueError, UnicodeDecodeError):
                body = raw
            state["requests"].append(
                {"method": self.command, "path": self.path, "body": body,
                 "headers": dict(self.headers)})
            reply = state["replies"].get(path, {})
            if callable(reply):
                reply = reply(body)
            if isinstance(reply, bytes):
                out = reply
                ctype = "application/octet-stream"
            else:
                out = json.dumps(reply).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        do_POST = do_GET = do_PUT = _reply

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", state
    httpd.shutdown()
    httpd.server_close()


class TestAnalyzeText:
    def test_kinds_and_body_shape(self, server):
        url, state = server
        state["replies"]["/language"] = {
            "kind": "SentimentAnalysisResults",
            "results": {"documents": [
                {"id": "0", "sentiment": "positive"}]}}
        df = DataFrame({"text": np.array(["great stuff"], dtype=object)})
        out = AnalyzeText(url=url + "/language", subscriptionKey="k",
                          kind="SentimentAnalysis",
                          outputCol="res").transform(df)
        assert out["res"][0]["sentiment"] == "positive"
        sent = state["requests"][-1]["body"]
        assert sent["kind"] == "SentimentAnalysis"
        assert sent["analysisInput"]["documents"][0]["text"] == "great stuff"
        assert sent["parameters"]["modelVersion"] == "latest"
        # language detection omits the language hint (service infers it)
        state["replies"]["/language"] = {
            "results": {"documents": [
                {"id": "0", "detectedLanguage": {"name": "French"}}]}}
        out = AnalyzeText(url=url + "/language", kind="LanguageDetection",
                          outputCol="res").transform(df)
        assert out["res"][0]["detectedLanguage"]["name"] == "French"
        assert "language" not in state["requests"][-1]["body"][
            "analysisInput"]["documents"][0]

    def test_invalid_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            AnalyzeText(kind="Nonsense")


class TestAzureSearch:
    def test_add_documents_batches(self, server):
        url, state = server
        state["replies"]["/docs"] = lambda body: {
            "value": [{"key": d.get("id"), "status": True}
                      for d in body["value"]]}
        df = DataFrame({"id": np.array([str(i) for i in range(5)],
                                       dtype=object),
                        "content": np.array(list("abcde"), dtype=object)})
        out = AddDocuments(url=url + "/docs", subscriptionKey="k",
                           batchSize=2, outputCol="st").transform(df)
        assert all(s["status"] for s in out["st"])
        posts = [r for r in state["requests"] if r["path"] == "/docs"]
        assert [len(p["body"]["value"]) for p in posts] == [2, 2, 1]
        # every doc got the default upload action verb
        assert all(d["@search.action"] == "upload"
                   for p in posts for d in p["body"]["value"])

    def test_writer_creates_index_then_uploads(self, server):
        url, state = server
        state["replies"]["/indexes/people"] = {"name": "people"}
        state["replies"]["/indexes/people/docs/index"] = lambda body: {
            "value": [{"key": d["id"], "status": True}
                      for d in body["value"]]}
        df = DataFrame({"id": np.array(["1", "2"], dtype=object)})
        AzureSearchWriter.write(
            df, url, key="k",
            index_json=json.dumps({"name": "people", "fields": [
                {"name": "id", "type": "Edm.String", "key": True}]}))
        methods = [(r["method"], r["path"].split("?")[0])
                   for r in state["requests"]]
        assert ("PUT", "/indexes/people") == methods[0]
        assert methods[1] == ("POST", "/indexes/people/docs/index")

    def test_fatal_errors_raise(self, server):
        url, state = server
        state["replies"]["/docs"] = {"value": [
            {"key": "1", "status": False, "errorMessage": "boom"}]}
        df = DataFrame({"id": np.array(["1"], dtype=object)})
        with pytest.raises(RuntimeError, match="boom"):
            AddDocuments(url=url + "/docs", outputCol="st").transform(df)


class TestSpeech:
    def test_one_shot_recognition(self, server):
        url, state = server
        state["replies"]["/stt"] = {"RecognitionStatus": "Success",
                                    "DisplayText": "hello world"}
        audio = np.sin(np.linspace(0, 1, 1600)).astype(np.float32)
        df = DataFrame({"audio": [audio]})
        out = SpeechToText(url=url + "/stt", subscriptionKey="k",
                           outputCol="t").transform(df)
        assert out["t"][0] == "hello world"
        req = state["requests"][-1]
        assert req["headers"].get("Content-Type") == "audio/wav"
        assert "language=en-US" in req["path"]

    def test_sdk_streams_chunks_and_collects_segments(self, server):
        url, state = server
        counter = {"n": 0}

        def reply(_body):
            counter["n"] += 1
            return {"DisplayText": f"seg{counter['n']}"}
        state["replies"]["/stt"] = reply
        # 2 bytes/sample * 16kHz * 250ms chunks over 1s audio -> 4 chunks
        audio = bytes(2 * 16000)
        df = DataFrame({"audio": np.array([audio], dtype=object)})
        out = SpeechToTextSDK(url=url + "/stt", chunkMs=250,
                              outputCol="segs").transform(df)
        assert out["segs"][0] == ["seg1", "seg2", "seg3", "seg4"]
        joined = SpeechToTextSDK(url=url + "/stt", chunkMs=250,
                                 streamIntermediateResults=False,
                                 outputCol="txt").transform(df)
        assert joined["txt"][0] == "seg5 seg6 seg7 seg8"

    def test_text_to_speech_returns_audio(self, server):
        url, state = server
        state["replies"]["/tts"] = b"RIFFfakeaudio"
        df = DataFrame({"text": np.array(["say this"], dtype=object)})
        out = TextToSpeech(url=url + "/tts", outputCol="audio").transform(df)
        assert out["audio"][0] == b"RIFFfakeaudio"
        body = state["requests"][-1]["body"]
        assert b"say this" in body and b"JennyNeural" in body


class TestBingAndGeospatial:
    def test_bing_image_search(self, server):
        url, state = server
        state["replies"]["/v7.0/images/search"] = {"value": [
            {"contentUrl": "http://img/1.png", "name": "one"},
            {"contentUrl": "http://img/2.png", "name": "two"}]}
        df = DataFrame({"q": np.array(["cats", "dogs"], dtype=object)})
        out = BingImageSearch(url=url + "/v7.0/images/search", count=2,
                              outputCol="imgs").transform(df)
        assert out["imgs"][0][0]["contentUrl"] == "http://img/1.png"
        # rows run concurrently: arrival order is unordered
        queries = {r["path"].split("q=")[1].split("&")[0]
                   for r in state["requests"]}
        assert queries == {"cats", "dogs"}
        urls = BingImageSearch.downloads_from_results(out["imgs"])
        assert len(urls) == 4

    def test_geocoders_and_geofence(self, server):
        url, state = server
        state["replies"]["/geo"] = {"results": [
            {"position": {"lat": 47.6, "lon": -122.1}}]}
        df = DataFrame({"address": np.array(["1 Main St"], dtype=object)})
        out = AddressGeocoder(url=url + "/geo",
                              outputCol="pos").transform(df)
        assert out["pos"][0] == {"lat": 47.6, "lon": -122.1}

        state["replies"]["/rev"] = {"addresses": [
            {"address": {"streetName": "Main St"}}]}
        from mmlspark_tpu.io import ReverseAddressGeocoder
        df2 = DataFrame({"lat": np.array([47.6]),
                         "lon": np.array([-122.1])})
        out2 = ReverseAddressGeocoder(url=url + "/rev",
                                      outputCol="addr").transform(df2)
        assert out2["addr"][0]["streetName"] == "Main St"

        state["replies"]["/fence"] = {"result": {"pointInPolygons": True}}
        out3 = CheckPointInPolygon(url=url + "/fence",
                                   userDataIdentifier="udid-1",
                                   outputCol="inside").transform(df2)
        assert out3["inside"][0] is True
        assert state["requests"][-1]["body"]["udid"] == "udid-1"
