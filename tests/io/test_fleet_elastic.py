"""Elastic serving fleet: supervised autoscaling (scale-up under load,
hysteresis, graceful-drain scale-down), the kill-mid-batch chaos drill
(supervisor detection within the heartbeat budget, FleetClient failover
with replies bitwise-identical to a single-worker run, fleet back to
target size), per-tenant token-bucket admission with attributed
counters, supervised restart of crashed workers, and leak-free
ServingFleet teardown."""

import json
import threading
import time
import urllib.error
import urllib.request as urllib_request

import numpy as np
import pytest

from mmlspark_tpu.core import faults
from mmlspark_tpu.core.env import env_override
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.fleet import FleetSupervisor
from mmlspark_tpu.io.serving import FleetClient, ServingFleet, ServingServer

pytestmark = pytest.mark.fleet_smoke


class _ScaleModel(Transformer):
    def __init__(self, factor):
        super().__init__()
        self.factor = factor

    def _transform(self, df):
        return df.with_column(
            "scaled", np.asarray(df.col("x"), np.float64) * self.factor)


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _post(url, payload, headers=None, timeout=10.0):
    req = urllib_request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib_request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=5.0):
    with urllib_request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _named_serving_threads():
    return {t for t in threading.enumerate()
            if t.name.startswith(("mmlspark-serve", "mmlspark-fleet"))}


def _wait_threads_gone(before, timeout=8.0):
    """Threads born since ``before`` with serving/fleet names must
    exit; returns the stragglers (empty = clean)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaked = _named_serving_threads() - before
        leaked = {t for t in leaked if t.is_alive()}
        if not leaked:
            return set()
        time.sleep(0.05)
    return leaked


# -- autoscaling -------------------------------------------------------------

def test_scale_up_under_load():
    """Offered load pushing the rolling p99 past the threshold must
    grow the fleet toward max, one worker per (streak-satisfied,
    cooled-down) supervision pass — and never past max."""
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=1,
                         max_latency_ms=5.0).start()
    sup = FleetSupervisor(fleet, min_workers=1, max_workers=3,
                          scale_p99_ms=2.0, heartbeat_s=0.1,
                          cooldown_s=0.0, scale_streak=1)
    try:
        url = fleet.worker_urls[0]
        for i in range(6):  # batching waits ~5 ms -> p99 >> 2 ms
            assert _post(url, {"x": float(i)})["scaled"] == 2.0 * i
        sup.tick()
        assert len(fleet.worker_urls) == 2
        sup.tick()
        assert len(fleet.worker_urls) == 3
        sup.tick()  # at max: must NOT grow further
        assert len(fleet.worker_urls) == 3
        assert sup.stats()["scale_ups"] == 2
        assert sup.target == 3
    finally:
        sup.stop()
        fleet.stop()


def test_scale_down_drains_gracefully():
    """A calm fleet shrinks to min via graceful retirement: the
    retired worker drains (counted) and its threads exit; the floor
    holds."""
    before = _named_serving_threads()
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=2,
                         max_latency_ms=1.0).start()
    sup = FleetSupervisor(fleet, min_workers=1, max_workers=2,
                          heartbeat_s=0.1, cooldown_s=0.0,
                          scale_streak=1, drain_timeout_s=5.0)
    try:
        sup.tick()  # no traffic: p99 None + empty queues = calm
        assert len(fleet.worker_urls) == 1
        assert sup.stats()["scale_downs"] == 1
        assert sup.stats()["drained"] == 1
        sup.tick()  # at min: must NOT shrink further
        assert len(fleet.worker_urls) == 1
        # the survivor still serves
        assert _post(fleet.worker_urls[0], {"x": 4.0})["scaled"] == 8.0
    finally:
        sup.stop()
        fleet.stop()
    assert _wait_threads_gone(before) == set()


def test_hysteresis_no_flap():
    """Alternating hot/calm polls must never scale (streak resets),
    the dead band between scale-up and scale-down thresholds counts
    toward neither, and cooldown blocks an immediate reversal."""
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=1,
                         max_latency_ms=1.0)
    sup = FleetSupervisor(fleet, min_workers=1, max_workers=4,
                          scale_p99_ms=100.0, cooldown_s=120.0,
                          scale_streak=2)
    hot = {"p99_ms": 500.0, "queueDepth": 0, "maxQueue": 256}
    calm = {"p99_ms": 0.5, "queueDepth": 0, "maxQueue": 256}
    mid = {"p99_ms": 50.0, "queueDepth": 0, "maxQueue": 256}  # dead band
    for h in (hot, calm, hot, calm, hot, mid, hot):
        sup._decide([h])
        assert sup.target == 1  # no streak ever completes: no flap
    # two consecutive hots complete the streak -> one scale-up ...
    sup._decide([hot])
    sup._decide([hot])
    assert sup.target == 2
    assert sup.stats()["scale_ups"] == 1
    # ... and cooldown then blocks BOTH directions, however calm/hot
    for h in (calm, calm, calm, hot, hot, hot):
        sup._decide([h])
    assert sup.target == 2


# -- graceful retirement -----------------------------------------------------

def test_drain_loses_zero_accepted_requests():
    """The retirement contract: deregister -> drain -> stop loses no
    accepted request — every request in the queue at drain time gets
    its real reply, and new requests are turned away with 503 +
    Retry-After."""
    fleet = ServingFleet(_ScaleModel(3.0), num_servers=2,
                         max_latency_ms=300.0, max_batch_size=64).start()
    try:
        victim = fleet.servers[0]
        results = [None] * 8

        def call(i):
            try:
                results[i] = _post(victim.url, {"x": float(i)})
            except Exception as e:  # pragma: no cover - failure detail
                results[i] = e

        threads = [threading.Thread(target=call, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        # wait until all 8 are ACCEPTED (queued), still unscored
        # because the batcher waits max_latency_ms=300
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with victim._lock:
                depth = sum(len(m.queue)
                            for m in victim._models.values())
            if depth + victim._inflight_batches >= 8:
                break
            time.sleep(0.005)
        assert fleet.remove_worker(victim)
        assert victim.url not in fleet.worker_urls
        assert victim.drain(timeout_s=10.0)
        # a drained worker sheds NEW traffic with a retry hint
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(victim.url, {"x": 99.0})
        assert err.value.code == 503
        assert int(err.value.headers["Retry-After"]) >= 1
        victim.stop()
        for t in threads:
            t.join(timeout=10)
        # zero loss: every accepted request got its true reply
        for i, out in enumerate(results):
            assert isinstance(out, dict) and out["scaled"] == 3.0 * i, \
                f"request {i} lost in scale-down: {out!r}"
    finally:
        fleet.stop()


class _SlowFirstScore(Transformer):
    """Factor-scaling model whose FIRST transform (the swap's
    verification probe) sleeps — stretches the hot-swap's held
    probation window so a drain deadline deterministically expires
    inside it."""

    def __init__(self, factor, first_delay_s):
        super().__init__()
        self.factor = factor
        self.first_delay_s = first_delay_s
        self._calls = 0

    def _transform(self, df):
        self._calls += 1
        if self._calls == 1:
            time.sleep(self.first_delay_s)
        return df.with_column(
            "scaled", np.asarray(df.col("x"), np.float64) * self.factor)


def test_drain_flushes_swap_holding_queue():
    """Regression (PR 17): requests accepted while an in-flight
    hot-swap holds the queue in probation must survive a drain whose
    deadline expires inside the swap window. Pre-fix, drain() returned
    False at its deadline (the held queue never empties until the
    probe resolves) and stop() flushed the held requests as errors —
    now drain outlives the swap, restarts its budget once, and flushes
    the released queue: zero accepted-request loss."""
    srv = ServingServer(_ScaleModel(2.0), max_latency_ms=50.0,
                        max_batch_size=8).start()
    swap_result = {}

    def do_swap():
        swap_result["r"] = srv.swap_model(
            "default", _SlowFirstScore(5.0, first_delay_s=1.2),
            probe_payload={"x": 1.0})

    results = [None] * 4

    def call(i):
        try:
            results[i] = _post(srv.url, {"x": float(i)}, timeout=15.0)
        except Exception as e:  # pragma: no cover - failure detail
            results[i] = e

    swapper = threading.Thread(target=do_swap, daemon=True)
    try:
        swapper.start()
        # wait for the flip: the new model is in the registry, held
        # out of the batch loop while its slow probe runs
        deadline = time.monotonic() + 5.0
        held = False
        while time.monotonic() < deadline and not held:
            with srv._lock:
                held = srv._models["default"].held
            time.sleep(0.002)
        assert held, "swap never reached the held-probation window"
        threads = [threading.Thread(target=call, args=(i,), daemon=True)
                   for i in range(4)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with srv._lock:
                if len(srv._models["default"].queue) >= 4:
                    break
            time.sleep(0.002)
        # this deadline expires INSIDE the 1.2 s probe window — the
        # pre-fix drain gave up right here
        assert srv.drain(timeout_s=0.4)
        swapper.join(timeout=10)
        for t in threads:
            t.join(timeout=10)
        assert swap_result["r"]["model"] == "default"
        # zero loss: every held request was scored by the NEW model
        for i, out in enumerate(results):
            assert isinstance(out, dict) and out["scaled"] == 5.0 * i, \
                f"request {i} lost across drain-during-swap: {out!r}"
    finally:
        srv.stop()


# -- chaos drill: kill mid-batch ---------------------------------------------

def test_kill_mid_batch_failover_and_respawn():
    """The PR's chaos contract end-to-end: a worker dies abruptly
    mid-batch under armed ``serving.worker_kill``; the in-flight
    request fails over through FleetClient's connection-error retry
    and every reply stays bitwise-identical to a single-worker run;
    the supervisor detects the death within the heartbeat budget
    (dead_after_misses passes) and returns the fleet to target size."""
    model = _ScaleModel(1.5)
    payloads = [{"x": float(i) + 0.25} for i in range(8)]
    # reference: the same requests through one untouched worker
    with ServingServer(model, max_latency_ms=1.0) as single:
        reference = [_post(single.url, dict(p)) for p in payloads]

    fleet = ServingFleet(model, num_servers=2, max_latency_ms=1.0).start()
    sup = FleetSupervisor(fleet, min_workers=2, max_workers=2,
                          heartbeat_s=0.1, cooldown_s=60.0,
                          dead_after_misses=2)
    client = FleetClient(fleet.registry_url, timeout=5.0)
    try:
        client.refresh()
        faults.arm("serving.worker_kill", "raise", count=1)
        replies = [client.score(dict(p)) for p in payloads]
        faults.disarm("serving.worker_kill")
        # bitwise contract: failover replies identical to single-worker
        assert replies == reference
        # exactly one worker died abruptly (still registered: the
        # sweep, not the kill, owns eviction)
        dead = [s for s in fleet.servers if s._killed]
        assert len(dead) == 1
        # supervisor: detection within the heartbeat budget =
        # dead_after_misses consecutive sweeps, then respawn to target
        for _ in range(sup.dead_after_misses):
            sup.tick()
        stats = sup.stats()
        assert stats["deaths"] == 1
        assert stats["workers"] == 2  # back to target size
        assert dead[0].url not in fleet.worker_urls
        assert len(set(fleet.worker_urls)) == 2
        # the whole (post-respawn) fleet serves correctly
        client.refresh()
        for p, ref in zip(payloads, reference):
            assert client.score(dict(p)) == ref
    finally:
        sup.stop()
        fleet.stop()


def test_supervisor_restarts_crashed_worker_with_spawn_backoff():
    """A worker crashing outside any batch (hard kill) is detected via
    missed heartbeats and replaced; a transient ``fleet.spawn``
    failure during the replacement is absorbed by the supervisor's
    retry/backoff instead of crashing it."""
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=2,
                         max_latency_ms=1.0).start()
    sup = FleetSupervisor(fleet, min_workers=2, max_workers=2,
                          heartbeat_s=0.1, dead_after_misses=2)
    try:
        dead_url = fleet.servers[1].url
        fleet.servers[1].kill()
        # the respawn's first construction attempt fails (chaos), the
        # with_retries backoff must absorb it
        faults.arm("fleet.spawn", "raise", count=1)
        for _ in range(sup.dead_after_misses):
            sup.tick()
        stats = sup.stats()
        assert stats["deaths"] == 1
        assert stats["workers"] == 2
        assert stats["spawn_failures"] == 0  # retry absorbed the fault
        urls = fleet.worker_urls
        assert dead_url not in urls and len(urls) == 2
        for u in urls:
            assert _post(u, {"x": 2.0})["scaled"] == 4.0
    finally:
        faults.reset()
        sup.stop()
        fleet.stop()


def test_heartbeat_fault_marks_worker_dead():
    """Armed ``fleet.heartbeat`` (probe loss, not worker death) must
    count misses and evict after the budget — the supervisor cannot
    tell a dead worker from an unreachable one, by design."""
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=1,
                         max_latency_ms=1.0).start()
    sup = FleetSupervisor(fleet, min_workers=1, max_workers=1,
                          heartbeat_s=0.1, dead_after_misses=3)
    try:
        old_url = fleet.worker_urls[0]
        faults.arm("fleet.heartbeat", "raise", count=3)
        sup.tick()
        sup.tick()
        assert sup.stats()["deaths"] == 0  # under budget: not yet dead
        sup.tick()
        stats = sup.stats()
        assert stats["deaths"] == 1
        assert stats["workers"] == 1  # replaced
        assert fleet.worker_urls[0] != old_url
    finally:
        faults.reset()
        sup.stop()
        fleet.stop()


# -- admission control -------------------------------------------------------

def test_token_bucket_sheds_hot_tenant_with_counters():
    """An over-budget tenant sheds with 503 + Retry-After while other
    tenants are untouched; ``admitted`` / ``shed_tenant`` counters are
    attributed per tenant in /healthz."""
    with env_override("MMLSPARK_TPU_SERVE_TENANT_RATE", "0.5"), \
            env_override("MMLSPARK_TPU_SERVE_TENANT_BURST", "3"):
        with ServingServer(_ScaleModel(2.0), max_latency_ms=1.0) as srv:
            ok = shed = 0
            for i in range(8):
                try:
                    _post(srv.url, {"x": 1.0, "__tenant__": "hot"})
                    ok += 1
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    assert int(e.headers["Retry-After"]) >= 1
                    shed += 1
            assert ok == 3 and shed == 5  # burst admits, then sheds
            # another tenant (via header this time) is unaffected
            assert _post(srv.url, {"x": 3.0},
                         {"X-Tenant": "cool"})["scaled"] == 6.0
            h = _get(f"http://{srv.host}:{srv.port}"
                     "/models/default/healthz")
            assert h["tenants"]["hot"]["admitted"] == 3
            assert h["tenants"]["hot"]["shed_tenant"] == 5
            assert h["tenants"]["cool"]["admitted"] == 1
            assert h["tenants"]["cool"]["shed_tenant"] == 0
            assert h["shed_tenant"] == 5 and h["admitted"] == 4
            # rolling service percentiles surface for the autoscaler
            assert h["p99_ms"] is not None
            top = _get(f"http://{srv.host}:{srv.port}/healthz")
            assert top["shed_tenant"] == 5
            assert top["p99_ms"] is not None


def test_priority_shedding_at_high_water():
    """Past the queue high-water mark low-priority requests shed (503,
    ``shed_priority`` counted) while high-priority requests keep
    queueing to the hard bound."""
    srv = ServingServer(_ScaleModel(2.0), max_latency_ms=300.0,
                        max_queue=8, queue_high_water=1).start()
    try:
        results = []

        def bg(i):
            results.append(_post(srv.url, {"x": float(i)}))

        # park one admitted request in the queue (the batcher waits
        # 300 ms before scoring it)
        t = threading.Thread(target=bg, args=(0,), daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with srv._lock:
                if sum(len(m.queue)
                       for m in srv._models.values()) >= 1:
                    break
            time.sleep(0.005)
        # queue >= high_water: low-priority sheds ...
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(srv.url, {"x": 5.0, "__priority__": "low"})
        assert err.value.code == 503
        # ... via header too ...
        with pytest.raises(urllib.error.HTTPError):
            _post(srv.url, {"x": 5.0}, {"X-Priority": "low"})
        # ... while high-priority (the default) is still admitted
        t2 = threading.Thread(target=bg, args=(7,), daemon=True)
        t2.start()
        t.join(timeout=10)
        t2.join(timeout=10)
        assert sorted(r["scaled"] for r in results) == [0.0, 14.0]
        h = srv._health()
        assert h["shed_priority"] == 2
        assert h["admitted"] == 2
    finally:
        srv.stop()


# -- teardown hygiene --------------------------------------------------------

def test_fleet_stop_survives_worker_stop_failure():
    """One worker's stop() raising must not leak the registry thread
    or the other workers: everything still tears down, and the error
    re-raises after the sweep."""
    before = _named_serving_threads()
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=3,
                         max_latency_ms=1.0).start()
    bad = fleet.servers[1]
    orig_stop = bad.stop

    def exploding_stop():
        orig_stop()
        raise RuntimeError("injected stop failure")

    bad.stop = exploding_stop
    with pytest.raises(RuntimeError, match="injected stop failure"):
        fleet.stop()
    # registry is down (connection refused, not a hang) ...
    with pytest.raises(Exception):
        _get(fleet.registry_url, timeout=1.0)
    # ... and no serving/fleet thread this test created is left alive
    assert _wait_threads_gone(before) == set()


def test_fleet_stop_idempotent_after_chaos():
    """stop() after a chaos kill() (already-dead worker) is a no-op
    per worker and still leaves zero threads."""
    before = _named_serving_threads()
    fleet = ServingFleet(_ScaleModel(2.0), num_servers=2,
                         max_latency_ms=1.0).start()
    fleet.servers[0].kill()
    fleet.stop()
    fleet.stop()  # idempotent
    assert _wait_threads_gone(before) == set()
