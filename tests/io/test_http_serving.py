"""io tests with real localhost servers, patterned on the reference's
HTTPTransformerSuite / HTTPv2Suite (core io tests run against live local
endpoints, SURVEY.md §4.5)."""

import json
import threading
import time
import urllib.request as urllib_request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io import (
    HTTPTransformer,
    OpenAIChatCompletion,
    OpenAIPrompt,
    ServingServer,
    SimpleHTTPTransformer,
)


class _EchoHandler(BaseHTTPRequestHandler):
    flaky_counter = {"n": 0}

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length)) if length else None
        if self.path == "/echo":
            reply = {"echo": body}
        elif self.path == "/flaky":
            _EchoHandler.flaky_counter["n"] += 1
            if _EchoHandler.flaky_counter["n"] % 2 == 1:
                self.send_error(503)
                return
            reply = {"ok": True, "attempt": _EchoHandler.flaky_counter["n"]}
        elif self.path == "/chat":
            text = body["messages"][-1]["content"]
            reply = {"choices": [{"message": {
                "role": "assistant", "content": f"reply to: {text}"}}]}
        else:
            self.send_error(404)
            return
        data = json.dumps(reply).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture(scope="module")
def echo_server():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _EchoHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    host, port = httpd.server_address
    yield f"http://{host}:{port}"
    httpd.shutdown()
    httpd.server_close()


class TestHTTPTransformer:
    def test_concurrent_requests(self, echo_server):
        reqs = np.empty(6, dtype=object)
        for i in range(6):
            reqs[i] = {"url": f"{echo_server}/echo", "method": "POST",
                       "headers": {"Content-Type": "application/json"},
                       "body": json.dumps({"i": i})}
        df = DataFrame({"request": reqs})
        out = HTTPTransformer(inputCol="request", outputCol="response",
                              concurrency=4).transform(df)
        for i, resp in enumerate(out.col("response")):
            assert resp.status_code == 200
            assert json.loads(resp.entity) == {"echo": {"i": i}}

    def test_retry_on_503(self, echo_server):
        _EchoHandler.flaky_counter["n"] = 0
        reqs = np.empty(1, dtype=object)
        reqs[0] = {"url": f"{echo_server}/flaky", "method": "POST",
                   "body": "{}"}
        out = HTTPTransformer(inputCol="r", outputCol="resp",
                              backoffs=[0.01, 0.01]).transform(
            DataFrame({"r": reqs}))
        assert out.col("resp")[0].status_code == 200

    def test_404_surfaces(self, echo_server):
        reqs = np.empty(1, dtype=object)
        reqs[0] = {"url": f"{echo_server}/nope", "method": "POST",
                   "body": "{}"}
        out = HTTPTransformer(inputCol="r", outputCol="resp",
                              backoffs=[]).transform(DataFrame({"r": reqs}))
        assert out.col("resp")[0].status_code == 404


class TestSimpleHTTPTransformer:
    def test_json_in_out(self, echo_server):
        payloads = np.empty(3, dtype=object)
        for i in range(3):
            payloads[i] = {"value": i}
        df = DataFrame({"input": payloads})
        out = SimpleHTTPTransformer(
            inputCol="input", outputCol="parsed",
            url=f"{echo_server}/echo").transform(df)
        assert out.col("parsed")[1] == {"echo": {"value": 1}}
        assert all(e is None for e in out.col("errors"))

    def test_error_column(self, echo_server):
        payloads = np.empty(1, dtype=object)
        payloads[0] = {"x": 1}
        out = SimpleHTTPTransformer(
            inputCol="input", outputCol="parsed", backoffs=[],
            url=f"{echo_server}/missing").transform(
            DataFrame({"input": payloads}))
        assert out.col("parsed")[0] is None
        assert out.col("errors")[0]["statusCode"] == 404


class TestCognitive:
    def test_chat_completion(self, echo_server):
        msgs = np.empty(2, dtype=object)
        msgs[0] = [{"role": "user", "content": "hello"}]
        msgs[1] = [{"role": "user", "content": "world"}]
        df = DataFrame({"messages": msgs})
        chat = OpenAIChatCompletion(url=f"{echo_server}/chat",
                                    subscriptionKey="k",
                                    outputCol="completion")
        out = chat.transform(df)
        assert out.col("completion")[0] == "reply to: hello"
        assert out.col("completion")[1] == "reply to: world"

    def test_prompt_templating(self, echo_server):
        df = DataFrame({"product": np.asarray(["widget", "gadget"],
                                              dtype=object)})
        prompt = OpenAIPrompt(url=f"{echo_server}/chat",
                              promptTemplate="Describe a {product}",
                              outputCol="description")
        out = prompt.transform(df)
        assert out.col("description")[0] == "reply to: Describe a widget"


class _DoubleModel(Transformer):
    def _transform(self, df):
        return df.with_column("doubled", np.asarray(df.col("x")) * 2.0)


class TestServing:
    def test_model_consuming_id_column(self):
        """A model whose input column is literally named 'id' still gets
        that field as data; correlation uses the reserved __id__ key
        (ADVICE r3)."""
        from mmlspark_tpu.core.param import HasInputCol

        class _IdModel(Transformer, HasInputCol):
            def _transform(self, df):
                col = np.asarray(df.col(self.get("inputCol")), np.float64)
                return df.with_column("doubled", col * 2.0)

        with ServingServer(_IdModel(inputCol="id"),
                           max_latency_ms=5) as server:
            req = urllib_request.Request(
                server.url,
                data=json.dumps({"id": 21.0, "__id__": "r-1"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib_request.urlopen(req, timeout=10) as r:
                out = json.loads(r.read())
        assert out["doubled"] == 42.0
        assert out["id"] == "r-1"

    def test_serve_scores_and_batches(self):
        import urllib.request

        with ServingServer(_DoubleModel(), max_latency_ms=20) as server:
            def call(x):
                req = urllib.request.Request(
                    server.url, data=json.dumps({"x": x}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read())

            # concurrent calls get micro-batched into one device batch
            results = {}
            threads = [threading.Thread(
                target=lambda i=i: results.update({i: call(float(i))}))
                for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i in range(8):
                assert results[i] == {"doubled": 2.0 * i}

    def test_bad_json_400(self):
        import urllib.error
        import urllib.request

        with ServingServer(_DoubleModel()) as server:
            req = urllib.request.Request(server.url, data=b"not json")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400

    def test_scoring_error_500(self):
        import urllib.error
        import urllib.request

        class _Boom(Transformer):
            def _transform(self, df):
                raise RuntimeError("kaboom")

        with ServingServer(_Boom()) as server:
            req = urllib.request.Request(
                server.url, data=json.dumps({"x": 1}).encode())
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 500


class TestDistributedServing:
    """Per-host distributed mode + continuous low-latency mode
    (VERDICT r2 #8; ref DistributedHTTPSource.scala:203,362,
    continuous/HTTPSourceV2.scala:305)."""

    @staticmethod
    def _call(url, payload):
        import urllib.request
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as r:
            return json.loads(r.read())

    def test_fleet_registry_and_load(self):
        import urllib.request
        from concurrent.futures import ThreadPoolExecutor

        from mmlspark_tpu.io.serving import ServingFleet

        with ServingFleet(_DoubleModel(), num_servers=3,
                          max_latency_ms=5) as fleet:
            # registry lists every worker (driver service registry analog)
            with urllib.request.urlopen(fleet.registry_url, timeout=5) as r:
                workers = json.loads(r.read())["workers"]
            assert sorted(workers) == sorted(fleet.worker_urls)
            assert len(set(workers)) == 3

            # structured load sprayed across workers, ids correlated
            def call_one(i):
                url = workers[i % len(workers)]
                out = self._call(url, {"x": float(i), "id": f"req-{i}"})
                return i, out

            with ThreadPoolExecutor(max_workers=12) as ex:
                results = list(ex.map(call_one, range(48)))
            for i, out in results:
                assert out["doubled"] == 2.0 * i
                assert out["id"] == f"req-{i}"

    def test_continuous_latency_budget(self):
        import time

        from mmlspark_tpu.io.serving import ContinuousServingServer

        server = ContinuousServingServer(
            _DoubleModel(), warmup_payload={"x": 0.0}).start()
        try:
            lat = []
            for i in range(30):
                t0 = time.perf_counter()
                out = self._call(server.url, {"x": float(i)})
                lat.append(time.perf_counter() - t0)
                assert out["doubled"] == 2.0 * i
            lat.sort()
            p50 = lat[len(lat) // 2]
            # reference continuous mode cites ~1 ms on a cluster
            # (BASELINE.md); hold a CI-safe bound well under the
            # micro-batch path's max_latency_ms floor
            assert p50 < 0.05, f"p50 latency {p50*1e3:.1f} ms"
        finally:
            server.stop()

    def test_continuous_fleet(self):
        from mmlspark_tpu.io.serving import ServingFleet

        with ServingFleet(_DoubleModel(), num_servers=2,
                          continuous=True) as fleet:
            for j, url in enumerate(fleet.worker_urls):
                out = self._call(url, {"x": float(j), "id": str(j)})
                assert out["doubled"] == 2.0 * j and out["id"] == str(j)

    def test_fleet_batched_device_scoring(self, rng):
        """Workers micro-batch concurrent requests into device batches
        (the executor-listener + device-scoring path)."""
        from concurrent.futures import ThreadPoolExecutor

        from mmlspark_tpu.core.dataframe import DataFrame
        from mmlspark_tpu.io.serving import ServingFleet
        from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

        x = rng.normal(size=(400, 3))
        y = 2.0 * x[:, 0] + x[:, 1]
        model = LightGBMRegressor(numIterations=5, numLeaves=4,
                                  maxBin=16).fit(
            DataFrame({"features": x, "label": y}))
        expected = np.asarray(model.transform(
            DataFrame({"features": x[:16], "label": y[:16]}))["prediction"])

        with ServingFleet(model, num_servers=2, max_latency_ms=10,
                          reply_col="prediction") as fleet:
            def call_one(i):
                url = fleet.worker_urls[i % 2]
                return i, self._call(
                    url, {"features": x[i].tolist(), "label": 0.0})

            with ThreadPoolExecutor(max_workers=8) as ex:
                results = list(ex.map(call_one, range(16)))
        for i, out in results:
            assert out["prediction"] == pytest.approx(expected[i], rel=1e-5)


def test_fleet_client_failover(rng):
    """FleetClient retries a dead worker's request on live workers
    (serving-path fault tolerance, FaultToleranceUtils analog)."""
    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.io.serving import FleetClient, ServingFleet

    class _Double(Transformer):
        def _transform(self, df):
            return df.with_column("doubled",
                                  np.asarray(df.col("x")) * 2.0)

    with ServingFleet(_Double(), num_servers=3, max_latency_ms=5) as fleet:
        client = FleetClient(fleet.registry_url, timeout=5.0)
        assert len(client.refresh()) == 3
        # kill one worker; round-robin requests must still all succeed
        fleet.servers[1].stop()
        outs = [client.score({"x": float(i)}) for i in range(9)]
        assert [o["doubled"] for o in outs] == [2.0 * i for i in range(9)]


def test_continuous_latency_with_real_gbdt_model(rng):
    """The continuous-mode latency budget holds with a real booster,
    not just a toy transformer (VERDICT r3 weak #7; the full-scale
    measurement lives in tools/bench_serving.py — ~1.4 ms p50 for a
    100-tree HIGGS-shaped classifier on this host)."""
    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.io.serving import ContinuousServingServer
    from mmlspark_tpu.models.gbdt.estimators import LightGBMRegressor

    x = rng.normal(size=(2000, 8))
    y = x[:, 0] - x[:, 1]
    model = LightGBMRegressor(numIterations=20, numLeaves=15,
                              maxBin=63).fit(
        DataFrame({"features": x, "label": y}))

    class Wrapper(Transformer):
        def _transform(self, df):
            cols = np.stack([np.asarray(df.col(f"f{i}"), np.float64)
                             for i in range(8)], axis=1)
            return model.transform(DataFrame({"features": cols}))

    payload = {f"f{i}": 0.0 for i in range(8)}
    server = ContinuousServingServer(Wrapper(),
                                     warmup_payload=payload).start()
    try:
        lat = []
        for i in range(30):
            row = {f"f{j}": float(v) for j, v in
                   enumerate(rng.normal(size=8))}
            t0 = time.perf_counter()
            req = urllib_request.Request(
                server.url, data=json.dumps(row).encode(),
                headers={"Content-Type": "application/json"})
            with urllib_request.urlopen(req, timeout=10) as r:
                out = json.loads(r.read())
            lat.append(time.perf_counter() - t0)
        assert "prediction" in out
        lat.sort()
        assert lat[len(lat) // 2] < 0.05, f"p50 {lat[15]*1e3:.1f} ms"
    finally:
        server.stop()


def test_fleet_soak_with_failover(rng):
    """Sustained mixed load on a fleet while a worker dies mid-burst:
    every request must be answered exactly once with the right value
    (the cluster-serving soak the reference claims; scaled to CI)."""
    from concurrent.futures import ThreadPoolExecutor

    from mmlspark_tpu.io.serving import FleetClient, ServingFleet

    with ServingFleet(_DoubleModel(), num_servers=3,
                      max_latency_ms=2) as fleet:
        client = FleetClient(fleet.registry_url, timeout=10.0)
        client.refresh()
        killed = {"done": False}

        def call(i):
            if i == 150 and not killed["done"]:
                killed["done"] = True
                fleet.servers[0].stop()
            return i, client.score({"x": float(i)})["doubled"]

        with ThreadPoolExecutor(max_workers=16) as ex:
            results = dict(ex.map(call, range(400)))
        assert len(results) == 400
        assert all(results[i] == 2.0 * i for i in range(400))
