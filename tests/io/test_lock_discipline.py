"""Lock-discipline regression gate for the threaded serving plane.

Every concurrency fix this rule set forced (condition predicate loops
in serving/refresh, the bindings builder election that hoisted the
make/CDLL work out of the module lock, the unified mmlspark- thread
naming) is pinned here two ways: the per-file graftlint scan stays at
zero findings for GL009-GL012 with the shipped EMPTY baseline, and the
behavioral contracts (builder election under contention, backpressure
wakeup on close) are exercised directly.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from tools.graftlint.core import run_checks

pytestmark = pytest.mark.lock_smoke

REPO = Path(__file__).resolve().parents[2]

# the production files the graftlock rules flagged and this PR fixed —
# each stays clean under the full quartet, per file, no baseline
FIXED_FILES = [
    "mmlspark_tpu/io/serving.py",
    "mmlspark_tpu/io/fleet.py",
    "mmlspark_tpu/io/refresh.py",
    "mmlspark_tpu/parallel/prefetch.py",
    "mmlspark_tpu/parallel/resilience.py",
    "mmlspark_tpu/native/bindings.py",
    "mmlspark_tpu/core/fabric.py",
]


@pytest.mark.parametrize("rel", FIXED_FILES)
def test_fixed_file_stays_clean_under_lock_rules(rel):
    _, findings = run_checks([REPO / rel],
                             select=["GL009", "GL010", "GL011", "GL012"],
                             repo_root=REPO)
    assert findings == [], [f"{f.location()} {f.rule} {f.message}"
                            for f in findings]


def test_bindings_builder_election_under_contention():
    """ensure_built from many threads at once: exactly one caller runs
    the build while the rest park on the build-done event (the make +
    CDLL work no longer happens under the module lock), and every
    caller agrees on the outcome."""
    from mmlspark_tpu.native import bindings

    results = []
    results_lock = threading.Lock()
    start = threading.Barrier(8)

    def call():
        start.wait(5.0)
        ok = bindings.ensure_built()
        with results_lock:
            results.append(ok)

    threads = [threading.Thread(target=call,
                                name=f"mmlspark-buildtest-{i}")
               for i in range(8)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert not [t for t in threads if t.is_alive()], "ensure_built hung"
    assert len(results) == 8
    assert len(set(results)) == 1, f"callers disagreed: {results}"
    # the .so ships prebuilt (or was built by an earlier test): the
    # contended path must be fast-path reads, not serialized rebuilds
    if results[0]:
        assert time.perf_counter() - t0 < 20.0


def test_stream_buffer_close_wakes_blocked_put():
    """The GL011 rewrite of StreamBuffer.put (single timed wait in a
    while-predicate loop): a producer blocked on backpressure must see
    close() promptly instead of sleeping out a poll interval."""
    from mmlspark_tpu.io.refresh import StreamBuffer

    buf = StreamBuffer(capacity=4)
    assert buf.put(np.ones((4, 2)), np.ones(4))

    unblocked = threading.Event()
    outcome = []

    def producer():
        # over capacity with rows pending: parks until close() wakes
        # the wait and the re-tested predicate sees the closed flag
        try:
            outcome.append(buf.put(np.ones((4, 2)), np.ones(4),
                                   timeout=10.0))
        except RuntimeError as e:
            outcome.append(str(e))
        unblocked.set()

    t = threading.Thread(target=producer, name="mmlspark-puttest")
    t.start()
    time.sleep(0.1)
    assert not unblocked.is_set(), "put should be parked on capacity"
    t0 = time.perf_counter()
    buf.close()
    assert unblocked.wait(5.0), "close() did not wake the producer"
    wake = time.perf_counter() - t0
    t.join(5.0)
    assert wake < 2.0, f"wakeup took {wake:.2f}s"
    assert outcome == ["put() on a closed StreamBuffer"]


def test_serving_plane_threads_carry_unified_prefix():
    """Satellite contract: every daemon the serving plane spawns uses
    the mmlspark- prefix (GL010 keys thread discovery off it)."""
    from mmlspark_tpu.core.pipeline import Transformer
    from mmlspark_tpu.io.serving import ServingServer

    class Echo(Transformer):
        def _transform(self, df):
            return df.with_column("prediction",
                                  np.zeros(len(df), np.float32))

    before = {t.name for t in threading.enumerate()}
    srv = ServingServer(Echo(), port=0)
    srv.start()
    try:
        spawned = [t.name for t in threading.enumerate()
                   if t.name not in before]
        assert spawned, "server spawned no threads?"
        offenders = [n for n in spawned if not n.startswith("mmlspark-")]
        assert not offenders, offenders
    finally:
        srv.stop()
